"""Meta-estimator wrappers: ParallelPostFit and Incremental.

Reference: ``dask_ml/wrappers.py`` + ``dask_ml/_partial.py`` (SURVEY.md
§2a Wrappers row, §3.6):

- ``ParallelPostFit``: train on small in-memory data, parallelize
  predict/transform/score over blocks.
- ``Incremental``: out-of-core fit via a sequential ``partial_fit`` chain
  over blocks (optionally shuffled per call).

TPU mapping: "blocks" are the row ranges of a ShardedArray. A wrapped
dask_ml_tpu estimator predicts device-parallel as-is (no wrapper machinery
needed — GSPMD already parallelizes); the wrapper's job is interop with
*host* (sklearn-style) estimators: post-fit ops stream blocks through the
host estimator, and ``Incremental.fit`` is the streamed training loop the
reference builds as a linear task chain (the model no longer hops
worker-to-worker; blocks stream to it).
"""

from __future__ import annotations

import copy as _copy

import numpy as np

from .base import BaseEstimator, clone
from .metrics import accuracy_score, r2_score
from .parallel.sharded import ShardedArray, as_sharded

__all__ = ["ParallelPostFit", "Incremental", "CompiledBatchFn",
           "compiled_batch_fn", "ParamSwapError", "SparseBatchFn",
           "sparse_batch_fn"]


def _data_shards(mesh):
    from .parallel.mesh import data_shards

    return data_shards(mesh)


def _device_headroom_bytes(nbytes, sample, fraction=0.5):
    """True when an extra device allocation of ``nbytes`` (sharded like
    ``sample``) plausibly fits: per-device free bytes (when the runtime
    reports memory_stats — TPU does, CPU returns None and passes) must
    cover the per-device share with ``fraction`` slack."""
    try:
        data = getattr(sample, "data", None)
        if data is None:
            return True  # host sample: no device copy involved
        devs = list(data.devices())
        per_dev = nbytes / max(len(devs), 1)
        for dev in devs:
            stats = dev.memory_stats()
            if not stats:
                continue
            free = stats.get("bytes_limit", 0) - stats.get(
                "bytes_in_use", 0
            )
            if per_dev > fraction * free:
                return False
        return True
    except Exception:
        return True  # no reliable stats: assume fine (host-backed CPU)


def _device_headroom_for_copy(X, fraction=0.5):
    """True when a full second device copy of ``X`` plausibly fits."""
    return _device_headroom_bytes(X.data.nbytes, X, fraction)


def _is_device_estimator(est):
    return est.__class__.__module__.startswith("dask_ml_tpu")


def _host_matrix(X):
    """Host representation supporting arbitrary row slicing: CSR for any
    sparse source (scipy matrix of any format, SparseBlocks), numpy
    otherwise — the ONE sparse/dense coercion point for the block loops."""
    import scipy.sparse as sp

    from .parallel.streaming import SparseBlocks

    if isinstance(X, SparseBlocks) or sp.issparse(X):
        return X.tocsr()
    return X.to_numpy() if isinstance(X, ShardedArray) else np.asarray(X)


def _host_blocks(X, block_size=100_000):
    """Yield host row blocks of a ShardedArray / array. Sparse X stays
    sparse — host (sklearn) estimators consume CSR blocks natively."""
    host = _host_matrix(X)
    for i in range(0, host.shape[0], block_size):
        yield host[i:i + block_size]


class ParallelPostFit(BaseEstimator):
    """Ref: dask_ml/wrappers.py::ParallelPostFit. The ``*_meta``
    parameters are accepted for API parity: the reference uses them to
    declare dask output metadata; here output types are concrete, so they
    only pin the output dtype when given."""

    def __init__(self, estimator=None, scoring=None, predict_meta=None,
                 predict_proba_meta=None, transform_meta=None):
        self.estimator = estimator
        self.scoring = scoring
        self.predict_meta = predict_meta
        self.predict_proba_meta = predict_proba_meta
        self.transform_meta = transform_meta

    # -- fit: plain in-memory fit of the wrapped estimator ---------------
    def fit(self, X, y=None, **kwargs):
        from .parallel.streaming import SparseBlocks

        est = clone(self.estimator)
        if isinstance(X, ShardedArray):
            Xh = X.to_numpy()
        elif isinstance(X, SparseBlocks):
            Xh = X.tocsr()  # host estimators consume CSR, not the view
        else:
            Xh = X
        yh = y.to_numpy() if isinstance(y, ShardedArray) else y
        if yh is None:
            est.fit(Xh, **kwargs)
        else:
            est.fit(Xh, yh, **kwargs)
        self.estimator_ = est
        return self

    @property
    def _est(self):
        # support wrapping an already-fitted estimator without fit()
        return getattr(self, "estimator_", self.estimator)

    @property
    def classes_(self):
        return self._est.classes_

    @property
    def training_profile_(self):
        """The wrapped estimator's per-feature training profile (see
        observability/sketch.py) — so a served `Incremental`/
        `ParallelPostFit` carries its drift baseline exactly like the
        bare estimator. AttributeError when the inner fit recorded
        none (sklearn hasattr semantics)."""
        prof = getattr(self._est, "training_profile_", None)
        if prof is None:
            raise AttributeError("training_profile_")
        return prof

    # -- parallel post-fit ops --------------------------------------------
    def _pin_meta(self, out, method):
        """Pin the output dtype when a *_meta hint was given (the
        reference uses metas to declare dask output metadata; here output
        types are concrete, so only the dtype survives)."""
        import scipy.sparse as sp

        meta = {"predict": self.predict_meta,
                "predict_proba": self.predict_proba_meta,
                "transform": self.transform_meta}.get(method)
        if meta is not None and hasattr(meta, "dtype") \
                and (isinstance(out, np.ndarray) or sp.issparse(out)):
            out = out.astype(meta.dtype, copy=False)
        return out

    def _apply(self, X, method):
        est = self._est
        from .parallel.frames import PartitionedFrame

        if isinstance(X, PartitionedFrame):
            # the reference's dd path: map_partitions(est.<method>) —
            # partitions run concurrently through the frame's thread pool
            parts = X.map_partitions(getattr(est, method))
            if isinstance(parts, PartitionedFrame):  # frame-in, frame-out
                return parts
            return self._pin_meta(
                np.concatenate([np.asarray(p) for p in parts], axis=0),
                method,
            )
        if _is_device_estimator(est):
            return getattr(est, method)(X)
        mesh = X.mesh if isinstance(X, ShardedArray) else None
        # blocks are SLICES of one host buffer (views, not copies), so
        # listing them costs nothing beyond the to_numpy pull a host
        # estimator needs anyway
        blocks = list(_host_blocks(X))
        fn = getattr(est, method)
        if len(blocks) > 1:
            # the reference's map_blocks runs post-fit blocks on parallel
            # workers; here a thread pool over the host estimator's
            # (read-only, GIL-releasing sklearn C kernels) per-block calls
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=min(8, len(blocks))
            ) as pool:
                parts = list(pool.map(fn, blocks))
        else:
            parts = [fn(b) for b in blocks]
        import scipy.sparse as sp

        if any(sp.issparse(p) for p in parts):
            # sparse estimator output (e.g. a transformer): stays sparse
            return self._pin_meta(sp.vstack(parts).tocsr(), method)
        out = self._pin_meta(np.concatenate(parts, axis=0), method)
        return as_sharded(out, mesh=mesh) if mesh is not None else out

    def predict(self, X):
        return self._apply(X, "predict")

    def predict_proba(self, X):
        return self._apply(X, "predict_proba")

    def predict_log_proba(self, X):
        return self._apply(X, "predict_log_proba")

    def decision_function(self, X):
        return self._apply(X, "decision_function")

    def transform(self, X):
        return self._apply(X, "transform")

    def score(self, X, y, compute=True):
        if self.scoring:
            from .metrics.scorer import get_scorer

            return get_scorer(self.scoring)(self, X, y)
        pred = self.predict(X)
        if hasattr(self._est, "classes_") or hasattr(self._est, "predict_proba"):
            return accuracy_score(y, pred)
        return r2_score(y, pred)


# --------------------------------------------------------------------------
# Compiled static-shape predict entry points (the serving subsystem's
# hot-loop contract; see dask_ml_tpu/serving/)
# --------------------------------------------------------------------------

class ParamSwapError(ValueError):
    """A hot-swap was structurally impossible: the new estimator's
    fitted parameters do not match the compiled entry point's shapes /
    family / method semantics. The caller must rebuild the entry point
    (paying fresh compiles) instead of swapping."""


class CompiledBatchFn:
    """A fitted estimator's ``method`` as ONE static-shape batch
    function: ``fn(X)`` takes a host float32 (B, d) block and returns a
    host ndarray with one output row per input row.

    For device estimators the core is a single jitted function of
    ``(params, X)`` — the fitted parameters are a pytree ARGUMENT, not a
    baked-in constant, so the compiled program closes over their SHAPES
    only. That is the hot-swap contract the serving fleet rides:
    :meth:`swap_params` replaces the param pytree under the same
    executable, and because XLA specializes per (param shapes, B), a
    swap to same-shape parameters hits the existing compile cache — ZERO
    new XLA compiles (asserted via the recompile counters in
    tests/test_fleet.py). Callers drawing B from a fixed bucket ladder
    pay a fixed, pre-warmable set of compiles and nothing after, across
    any number of swaps. On backends with real buffer donation
    (TPU/GPU) the input batch is donated (the params never are — they
    are reused every call). ``jitted=False`` marks the host fallback
    (sklearn-style estimators): still batchable and still swappable, no
    compile accounting to speak of.
    """

    __slots__ = ("method", "jitted", "n_features", "donates", "version",
                 "quantize", "_fn", "_state", "_extract", "_sig",
                 "_device", "_prefix", "_inner")

    def __init__(self, fn, method, jitted, n_features, donates=False,
                 params=None, post=None, extract=None, sig=None,
                 device=None, prefix=None, inner=None, quantize=None):
        self._fn = fn
        # pipeline flavor: _state holds the LIVE (prefix, inner) pair —
        # one attribute so a swap publishes both in one assignment.
        # leaf flavor: _state holds (params, post), same single-read
        # contract. _prefix/_inner stay as the flavor flag + debug view.
        self._state = (tuple(prefix), inner) if inner is not None \
            else (params, post)
        self._extract = extract
        self._sig = sig
        self._device = device
        self._prefix = prefix
        self._inner = inner
        self.method = method
        self.jitted = jitted
        self.n_features = n_features
        self.donates = donates
        self.version = 0
        # precision flavor this entry point was BUILT as ("int8" or
        # None = float32); swaps re-extract through the same flavor, so
        # an int8 entry point re-quantizes every published version at
        # publish time
        self.quantize = quantize

    def __call__(self, X):
        if self._inner is not None:
            # pipeline: host prefix transforms feed the final step's
            # compiled fn. ONE read of the live (prefix, inner) pair: a
            # concurrent swap publishes a fresh pair in a single
            # assignment, so a request never runs old transforms into
            # new weights (or vice versa)
            prefix, inner = self._state
            for t in prefix:
                X = _host_out(t.transform(X))
            return inner(np.asarray(X, np.float32))
        # ONE attribute read: a concurrent swap_params either lands
        # before (new params+post) or after (old pair) — never a torn
        # mix of new weights with old classes
        params, post = self._state
        out = self._fn(X) if params is None else self._fn(params, X)
        if self.donates:
            from .observability import record_donation

            record_donation(X.nbytes)
        out = _host_out(out)
        return post(out) if post is not None else out

    def swap_params(self, estimator):
        """Atomically replace the fitted parameters under the compiled
        entry point with ``estimator``'s — the zero-recompile hot-swap.

        The new estimator must map onto the SAME compiled structure:
        same family, same method semantics, same parameter shapes (all
        captured in the build-time signature). Anything else raises
        :class:`ParamSwapError` — the cue to rebuild entry points (and
        pay compiles) rather than swap. In-flight batches finish on the
        old parameters; batches packed after the swap see the new ones.

        ``swap_params`` is prepare+commit in one call; callers swapping
        SEVERAL entry points against one estimator (ModelServer.
        swap_model) run :meth:`prepare_swap` on all of them first so a
        late refusal cannot leave the set half-swapped.
        """
        return self.commit_swap(self.prepare_swap(estimator))

    def prepare_swap(self, estimator):
        """Validate ``estimator`` against this entry point WITHOUT
        touching any live state; returns an opaque token for
        :meth:`commit_swap`. Raises :class:`ParamSwapError` on any
        structural mismatch, leaving the entry point exactly as it was.
        """
        if self._inner is not None:
            if not (hasattr(estimator, "steps")
                    and hasattr(estimator, "named_steps")):
                raise ParamSwapError(
                    "entry point serves a pipeline; the swapped-in "
                    f"estimator {type(estimator).__name__} is not one"
                )
            prefix, inner = self._state
            if len(estimator.steps) != len(prefix) + 1:
                raise ParamSwapError(
                    f"pipeline step count changed: "
                    f"{len(prefix) + 1} -> {len(estimator.steps)}"
                )
            # the inner leaf's signature only sees the PREFIX's output
            # width — the pipeline's own input width must match too, or
            # a swap to a pipeline trained on different-width rows would
            # commit fine and then fail inside the prefix transform on
            # every request instead of refusing typed at publish time
            want = getattr(estimator, "n_features_in_", None)
            if want is None:
                want = getattr(estimator.steps[0][1],
                               "n_features_in_", None)
            if (self.n_features is not None and want is not None
                    and int(want) != self.n_features):
                raise ParamSwapError(
                    f"n_features changed: {self.n_features} -> {want}"
                )
            inner_tok = inner.prepare_swap(estimator.steps[-1][1])
            return ("pipe",
                    tuple(t for _, t in estimator.steps[:-1]),
                    inner_tok)
        if self._extract is None:
            # host fallback: rebind the bound method — no compiled
            # structure to protect, but keep the width contract
            target = getattr(estimator, self.method, None)
            if target is None:
                raise ParamSwapError(
                    f"{type(estimator).__name__} has no method "
                    f"{self.method!r}"
                )
            want = getattr(estimator, "n_features_in_", None)
            if (self.n_features is not None and want is not None
                    and want != self.n_features):
                raise ParamSwapError(
                    f"n_features changed: {self.n_features} -> {want}"
                )
            return ("host", target)
        try:
            built = self._extract(estimator)
        except AttributeError as exc:
            # build-time guards (e.g. predict_proba on a hinge loss)
            # surface as the swap's typed refusal, not a raw attribute
            # error mid-request
            raise ParamSwapError(str(exc)) from exc
        if built is None:
            raise ParamSwapError(
                f"{type(estimator).__name__} does not support "
                f"{self.method!r} on the compiled path"
            )
        params, post, sig = built
        if sig != self._sig:
            raise ParamSwapError(
                "compiled structure mismatch (shapes/family/method "
                f"semantics): built with {self._sig}, swap offers {sig}"
            )
        return ("leaf", params, post)

    def commit_swap(self, token):
        """Apply a :meth:`prepare_swap` token. The request-visible flip
        is ONE attribute assignment per entry point — concurrent calls
        see either the complete old state or the complete new one, never
        a torn mix (for a pipeline, old transforms never feed new
        weights: the (prefix, inner) pair is republished together, with
        the new params living in a CLONE of the inner leaf that shares
        the same jitted executable — same compile cache, no compile)."""
        kind = token[0]
        if kind == "pipe":
            _, prefix, inner_tok = token
            _, inner = self._state
            new_inner = _copy.copy(inner).commit_swap(inner_tok)
            self._state = (prefix, new_inner)
            self._prefix, self._inner = prefix, new_inner
        elif kind == "host":
            target = token[1]
            self._fn = lambda X: target(X)
        else:
            _, params, post = token
            # place the new pytree exactly like the old one (same
            # device / same committedness) so the jit cache key is
            # identical and the swap never mints a compile
            self._state = (_put_params(params, self._device), post)
        self.version += 1
        return self


def _host_out(out):
    import scipy.sparse as sp

    if isinstance(out, ShardedArray):
        return out.to_numpy()
    if sp.issparse(out):
        return out.toarray()
    return np.asarray(out)


def _donate_spec():
    """Donate the batch argument only where the runtime honors it; on
    CPU jax warns per call that donated buffers were unusable. Cores are
    ``(params, X)`` — argnum 1 is the batch; the params pytree is never
    donated (it is reused on every call until a swap replaces it)."""
    import jax

    return (1,) if jax.default_backend() in ("tpu", "gpu") else ()


def _tracked_jit(est, method, core, donate, flavor=None, sig=None):
    """Build a serving core's tracked jitted entry point through the
    plan layer (``plans.ProgramPlan`` — ISSUE 15): cache keying,
    ``track_program`` registration as
    ``serving.<Estimator>.<method>[.<flavor>]``, donation wiring and
    ``compile_cache_dir`` arming all happen there. ``sig`` (the swap
    contract's structural signature) is the plan cache key: two builds
    over same-shaped fitted params return the SAME entry point, so a
    second server's warmup hits warm jit caches instead of re-tracing
    — and the quantized flavor ranks separately in the report CLI's
    programs table."""
    from .plans import ProgramPlan

    name = f"serving.{type(est).__name__}.{method}"
    if flavor:
        name += f".{flavor}"
    return ProgramPlan(
        name=name, body=core, donate=tuple(donate),
        key=("serving", sig) if sig is not None else None,
        ladder="serving-rows", group="serving",
    ).build()


def _put_params(params, device):
    """Host param pytree -> device-resident arrays, committed to
    ``device`` when given (per-replica placement), else the default
    device. Build and every swap go through HERE so the jit cache key
    (shapes + placement) is identical across swaps."""
    import jax

    if device is None:
        return jax.device_put(params)
    return jax.device_put(params, device)


def _shapes(params):
    return tuple(sorted(
        (k, tuple(v.shape), str(v.dtype)) for k, v in params.items()
    ))


def _linear_wb(est):
    """(C, d) weight matrix + (C,) bias from a fitted linear model
    (C=1 encodes the binary/regression row)."""
    coef = np.asarray(est.coef_, np.float32)
    if coef.ndim == 1:
        coef = coef[None, :]
    b = np.ravel(np.asarray(getattr(est, "intercept_", 0.0),
                            np.float32))
    if b.shape[0] != coef.shape[0]:
        b = np.full(coef.shape[0], b[0] if b.size else 0.0, np.float32)
    return coef, b


def _linear_extract(est, method):
    """(host params, post, signature) for a linear-family estimator —
    the swap contract's one source of truth: everything the compiled
    program's STRUCTURE depends on (method semantics, multiclass-ness,
    link family, parameter shapes) lands in the signature; everything
    that may change per version (weights, bias, class labels) lands in
    params/post."""
    W, b = _linear_wb(est)
    multi = W.shape[0] > 1
    classes = getattr(est, "classes_", None)
    family = getattr(est, "family", None)
    if method == "decision_function":
        kind = "margin"
    elif method == "predict_proba":
        if classes is None:
            return None
        # mirror SGDClassifier's guard: sigmoid(margins) of a non-log
        # loss is NOT a probability — the direct method raises, so the
        # compiled path (and any swap onto it) must too
        loss = getattr(est, "_loss", None)
        if callable(loss) and loss() != "log_loss":
            raise AttributeError(
                "predict_proba requires loss='log_loss'"
            )
        kind = "proba"
    elif method == "predict":
        if classes is not None:
            kind = "classify"
        elif family == "poisson":
            kind = "poisson"
        else:
            kind = "regress"
    else:
        return None
    post = None
    if kind == "classify":
        cls = np.asarray(classes)
        post = lambda idx: cls[np.asarray(idx)]  # noqa: E731
    params = {"W": W, "b": b}
    sig = ("linear", kind, multi, _shapes(params))
    return params, post, sig


def _quantize_w(W):
    """Per-output-channel symmetric int8 quantization of a (C, d)
    weight matrix: ``scale[c] = max|W[c]| / 127`` (1.0 for an all-zero
    row), computed at publish/build time. Only W quantizes — biases
    stay f32 (C floats, added post-matmul for free)."""
    amax = np.max(np.abs(W), axis=1)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    Wq = np.clip(np.rint(W / scale[:, None]), -127, 127).astype(np.int8)
    return Wq, scale


def _linear_extract_int8(est, method):
    """The int8 twin of ``_linear_extract``: weights quantized
    per-output-channel at extract (= publish) time, scales/bias f32.
    Kinds whose output passes eta through a nonlinearity return None
    and stay on the higher-precision flavor: "proba" (a sigmoid's tail
    is exactly where int8's ~0.4% weight rounding shows) and "poisson"
    (exp(eta) amplifies the eta error multiplicatively — the >=99.5%
    agreement criterion only holds for sign/argmax/linear outputs).
    The signature leads with "linear-int8" so an f32 entry point can
    never silently accept quantized params (or vice versa)."""
    built = _linear_extract(est, method)
    if built is None:
        return None
    params, post, sig = built
    if sig[1] in ("proba", "poisson"):
        return None
    Wq, scale = _quantize_w(params["W"])
    qparams = {"Wq": Wq, "scale": scale, "b": params["b"]}
    return qparams, post, ("linear-int8", sig[1], sig[2],
                           _shapes(qparams))


def _linear_core(kind, multi, eta=None):
    import jax
    import jax.numpy as jnp

    if eta is None:
        def eta(p, X):
            return X @ p["W"].T + p["b"][None, :]  # (B, C)

    if kind == "margin":
        return (lambda p, X: eta(p, X)) if multi \
            else (lambda p, X: eta(p, X)[:, 0])
    if kind == "proba":
        if multi:
            def core(p, X):
                pr = jax.nn.sigmoid(eta(p, X))  # OvR sigmoids, normed
                return pr / jnp.maximum(
                    jnp.sum(pr, axis=1, keepdims=True), 1e-12
                )
        else:
            def core(p, X):
                p1 = jax.nn.sigmoid(eta(p, X)[:, 0])
                return jnp.stack([1.0 - p1, p1], axis=1)
        return core
    if kind == "classify":
        if multi:
            return lambda p, X: jnp.argmax(eta(p, X), axis=1)
        return lambda p, X: (eta(p, X)[:, 0] > 0).astype(jnp.int32)
    if kind == "poisson":
        return lambda p, X: jnp.exp(eta(p, X)[:, 0])
    return lambda p, X: eta(p, X)[:, 0]            # regression


def _linear_core_int8(kind, multi):
    """Serving core over int8 weights: a dequantize-free mixed
    bf16×int8 matmul (XLA contracts the int8 operand directly; no f32
    copy of W ever materializes) with f32 accumulation, the per-channel
    scales applied to the (B, C) result — int8 keeps the weight
    pytree 4x smaller in HBM and the matmul on the low-precision
    units; prediction agreement vs f32 is >=99.5% on the parity suite
    (tests/test_precision.py)."""
    import jax
    import jax.numpy as jnp

    def eta(p, X):
        acc = jax.lax.dot_general(
            X.astype(jnp.bfloat16), p["Wq"],
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # (B, C) f32
        return acc * p["scale"][None, :] + p["b"][None, :]

    return _linear_core(kind, multi, eta=eta)


def _jit_linear(est, method, device=None, quantize=None):
    """Jitted ``(params, X)`` programs for the linear-model family
    (GLM + SGD): the whole method is one matmul + pointwise tail over
    the swappable param pytree. ``quantize="int8"`` builds the
    weight-quantized flavor for the methods that support it
    (predict / decision_function); unsupported methods fall back to
    the f32 build so a quantized server still serves them."""
    if quantize == "int8":
        built = _linear_extract_int8(est, method)
        if built is not None:
            params, post, sig = built
            donate = _donate_spec()
            core = _linear_core_int8(sig[1], sig[2])
            return CompiledBatchFn(
                _tracked_jit(est, method, core, donate, flavor="int8",
                             sig=sig),
                method, True, params["Wq"].shape[1],
                donates=bool(donate),
                params=_put_params(params, device), post=post,
                extract=lambda e: _linear_extract_int8(e, method),
                sig=sig, device=device, quantize="int8",
            )
    elif quantize:
        raise ValueError(
            f"unknown quantize flavor {quantize!r}; supported: 'int8'"
        )
    built = _linear_extract(est, method)
    if built is None:
        return None
    params, post, sig = built
    donate = _donate_spec()
    core = _linear_core(sig[1], sig[2])
    return CompiledBatchFn(
        _tracked_jit(est, method, core, donate, sig=sig), method, True,
        params["W"].shape[1], donates=bool(donate),
        params=_put_params(params, device), post=post,
        extract=lambda e: _linear_extract(e, method), sig=sig,
        device=device,
    )


def _sparse_linear_extract(est, method):
    """The sparse twin of ``_linear_extract``: same params/post, a
    "linear-sparse"-prefixed signature so a dense entry point can never
    silently accept a sparse swap (or vice versa). predict /
    decision_function only — the sparse serving family is the hashed-
    text linear hot path."""
    if method not in ("predict", "decision_function"):
        return None
    built = _linear_extract(est, method)
    if built is None:
        return None
    params, post, sig = built
    return params, post, ("linear-sparse",) + tuple(sig[1:])


def _sparse_linear_core(kind, multi):
    """Serving core over a packed bucketed-nnz CSR batch: eta via one
    gather of the (C,)-wide weight columns per nonzero + a segment_sum
    over rows (ops/sparse_kernels math inlined on the padded triple) —
    nnz * C cost instead of B * d * C, which is the whole point at
    2**14+ hashed-text widths. ``n_rows`` (the row bucket) is static:
    the compiled set is the warmed (rows, nnz) grid."""
    import jax
    import jax.numpy as jnp

    def eta(p, data, cols, rows, n_rows):
        contrib = data[:, None] * jnp.take(p["W"].T, cols, axis=0)
        return jax.ops.segment_sum(contrib, rows,
                                   num_segments=n_rows) \
            + p["b"][None, :]

    if kind == "margin":
        if multi:
            return eta
        return lambda p, d_, c_, r_, n: eta(p, d_, c_, r_, n)[:, 0]
    if kind == "classify":
        if multi:
            return lambda p, d_, c_, r_, n: jnp.argmax(
                eta(p, d_, c_, r_, n), axis=1
            )
        return lambda p, d_, c_, r_, n: (
            eta(p, d_, c_, r_, n)[:, 0] > 0
        ).astype(jnp.int32)
    if kind == "poisson":
        return lambda p, d_, c_, r_, n: jnp.exp(
            eta(p, d_, c_, r_, n)[:, 0]
        )
    return lambda p, d_, c_, r_, n: eta(p, d_, c_, r_, n)[:, 0]


class SparseBatchFn(CompiledBatchFn):
    """A fitted linear estimator's ``method`` as a static-shape SPARSE
    batch function: ``fn(csr)`` takes a scipy CSR block, packs it to
    the (row-bucket, nnz-bucket) grid — rows padded up the serving
    ladder, the nnz triple padded up the geometric nnz ladder
    (``config.serving_sparse_nnz_per_row`` x the batch ladder's
    min/max, same growth) — and runs ONE compiled program per grid
    cell. Warm the grid (:meth:`warm`) and ragged hashed-text traffic
    pays zero steady-state XLA compiles; a batch whose nnz overflows
    the ladder's top rung raises ``ValueError`` for the caller to spill
    (ModelServer densifies into the already-warm dense rung). Hot-swap
    (prepare/commit) is inherited — the "linear-sparse" signature keys
    the same zero-recompile same-shape contract."""

    __slots__ = ("nnz_ladder",)

    def __init__(self, fn, method, n_features, params=None, post=None,
                 extract=None, sig=None, device=None, nnz_ladder=None):
        super().__init__(fn, method, True, n_features, params=params,
                         post=post, extract=extract, sig=sig,
                         device=device)
        self.nnz_ladder = nnz_ladder

    def nnz_bucket(self, nnz: int) -> int:
        return self.nnz_ladder.bucket_for(max(int(nnz), 1))

    def _pack(self, X):
        import scipy.sparse as sp

        X = X.tocsr() if not sp.isspmatrix_csr(X) else X
        n = int(X.shape[0])
        nnz = int(X.nnz)
        nb = self.nnz_bucket(nnz)
        data = np.zeros(nb, np.float32)
        cols = np.zeros(nb, np.int32)
        rows = np.zeros(nb, np.int32)
        data[:nnz] = X.data
        cols[:nnz] = X.indices
        rows[:nnz] = np.repeat(
            np.arange(n, dtype=np.int32), np.diff(X.indptr)
        )
        return data, cols, rows, n

    def __call__(self, X, n_rows=None):
        """Run the packed batch; ``n_rows`` pins the row bucket (the
        server picks it from the ladder), default = the batch's own
        rows. Returns the LOGICAL rows only (padding sliced off)."""
        params, post = self._state
        data, cols, rows, n = self._pack(X)
        out = self._fn(params, data, cols, rows,
                       int(n_rows if n_rows is not None else n))
        out = _host_out(out)[:n]
        return post(out) if post is not None else out

    def warm(self, row_bucket: int, nnz_bucket: int):
        """Compile one (rows, nnz) grid cell now (zero-filled operands
        — the program depends on shapes only)."""
        params, _ = self._state
        self._fn(params, np.zeros(nnz_bucket, np.float32),
                 np.zeros(nnz_bucket, np.int32),
                 np.zeros(nnz_bucket, np.int32), int(row_bucket))
        return self


def sparse_batch_fn(estimator, method="predict", device=None):
    """Build the sparse (CSR-in) serving entry point for a fitted
    LINEAR estimator's predict / decision_function — the hashed-text
    twin of :func:`compiled_batch_fn`, bucketed by (rows, nnz) instead
    of rows alone. Returns None for estimators/methods without a
    sparse story (pipelines, KMeans/PCA, predict_proba) — callers fall
    back to the dense path (which densifies per batch)."""
    est = estimator
    if not (_is_device_estimator(est) and hasattr(est, "coef_")):
        return None
    built = _sparse_linear_extract(est, method)
    if built is None:
        return None
    params, post, sig = built
    from .config import get_config
    from .serving._buckets import BucketLadder

    cfg = get_config()
    npr = max(int(cfg.serving_sparse_nnz_per_row), 1)
    nnz_ladder = BucketLadder(
        min_rows=max(cfg.serving_min_batch * npr, 1),
        max_rows=max(cfg.serving_max_batch * npr,
                     cfg.serving_min_batch * npr, 1),
        growth=cfg.serving_bucket_growth,
    )
    core = _sparse_linear_core(sig[1], sig[2])
    from .plans import ProgramPlan

    name = f"serving.{type(est).__name__}.{method}.sparse"
    fn = ProgramPlan(
        name=name, body=core, static_argnums=(4,),
        key=("serving-sparse", sig), ladder="serving-nnz",
        group="serving",
    ).build()
    return SparseBatchFn(
        fn, method, params["W"].shape[1],
        params=_put_params(params, device), post=post,
        extract=lambda e: _sparse_linear_extract(e, method), sig=sig,
        device=device, nnz_ladder=nnz_ladder,
    )


def _kmeans_extract(est, method):
    if method not in ("predict", "transform"):
        return None
    centers = np.asarray(est.cluster_centers_, np.float32)
    params = {"centers": centers}
    return params, None, ("kmeans", method, _shapes(params))


def _kmeans_core(method):
    import jax.numpy as jnp

    def dist2(p, X):
        # ||x-c||^2 via the expanded form: one (B,d)x(d,k) MXU matmul
        c = p["centers"]
        xx = jnp.sum(X * X, axis=1, keepdims=True)
        cc = jnp.sum(c * c, axis=1)[None, :]
        return jnp.maximum(xx + cc - 2.0 * (X @ c.T), 0.0)

    if method == "predict":
        return lambda p, X: jnp.argmin(dist2(p, X), axis=1).astype(
            jnp.int32
        )
    return lambda p, X: jnp.sqrt(dist2(p, X))


def _jit_kmeans(est, method, device=None):
    built = _kmeans_extract(est, method)
    if built is None:
        return None
    params, post, sig = built
    donate = _donate_spec()
    return CompiledBatchFn(
        _tracked_jit(est, method, _kmeans_core(method), donate,
                     sig=sig), method,
        True, int(params["centers"].shape[1]), donates=bool(donate),
        params=_put_params(params, device), post=post,
        extract=lambda e: _kmeans_extract(e, method), sig=sig,
        device=device,
    )


def _pca_extract(est, method):
    if method != "transform":
        return None
    params = {"components": np.asarray(est.components_, np.float32)}
    mean = getattr(est, "mean_", None)
    if mean is not None:
        params["mean"] = np.asarray(mean, np.float32)
    if getattr(est, "whiten", False):
        params["scale"] = np.sqrt(np.asarray(
            est.explained_variance_, np.float32
        ))
    # which optional terms exist is structural (the traced graph
    # branches on their presence), so it rides the signature via shapes
    return params, None, ("pca", _shapes(params))


def _pca_core(has_mean, has_scale):
    def core(p, X):
        xc = X - p["mean"][None, :] if has_mean else X
        sc = xc @ p["components"].T
        return sc / p["scale"][None, :] if has_scale else sc

    return core


def _jit_pca(est, method, device=None):
    built = _pca_extract(est, method)
    if built is None:
        return None
    params, post, sig = built
    donate = _donate_spec()
    core = _pca_core("mean" in params, "scale" in params)
    return CompiledBatchFn(
        _tracked_jit(est, method, core, donate, sig=sig), method, True,
        int(params["components"].shape[1]), donates=bool(donate),
        params=_put_params(params, device), post=post,
        extract=lambda e: _pca_extract(e, method), sig=sig,
        device=device,
    )


def _nb_extract(est, method):
    """(host params, post, signature) for a fitted GaussianNB — the
    ISSUE 15 onboarding: the joint-log-likelihood predict is one
    matmul-shaped program over a swappable {theta, var, log_prior}
    pytree, so naive_bayes serves through the same plan-built
    zero-recompile entry points (and hot-swap contract) as the linear
    family."""
    if method not in ("predict", "predict_proba"):
        return None
    theta = np.asarray(est.theta_, np.float32)
    var = np.asarray(est.var_, np.float32)
    prior = np.asarray(est.class_prior_, np.float64)
    params = {"theta": theta, "var": var,
              "log_prior": np.log(prior).astype(np.float32)}
    kind = "classify" if method == "predict" else "proba"
    post = None
    if kind == "classify":
        cls = np.asarray(est.classes_)
        post = lambda idx: cls[np.asarray(idx)]  # noqa: E731
    return params, post, ("nb", kind, _shapes(params))


def _nb_core(kind):
    import jax
    import jax.numpy as jnp

    from .naive_bayes import _jll_math

    def jll(p, X):
        # the ONE jll definition (naive_bayes._jll_math) over the
        # swappable param pytree — served and in-core predictions can
        # never numerically diverge
        return _jll_math(X, p["theta"], p["var"], p["log_prior"])

    if kind == "classify":
        return lambda p, X: jnp.argmax(jll(p, X), axis=1).astype(
            jnp.int32
        )
    return lambda p, X: jax.nn.softmax(jll(p, X), axis=1)


def _jit_nb(est, method, device=None):
    built = _nb_extract(est, method)
    if built is None:
        return None
    params, post, sig = built
    donate = _donate_spec()
    return CompiledBatchFn(
        _tracked_jit(est, method, _nb_core(sig[1]), donate, sig=sig),
        method, True, int(params["theta"].shape[1]),
        donates=bool(donate), params=_put_params(params, device),
        post=post, extract=lambda e: _nb_extract(e, method), sig=sig,
        device=device,
    )


def compiled_batch_fn(estimator, method="predict", device=None,
                      quantize=None):
    """Build the static-shape batch entry point for a fitted estimator
    (or sklearn-style pipeline ending in one) — the serving subsystem's
    per-method compile unit.

    Device estimators (GLM, SGD, KMeans, PCA/TruncatedSVD) lower to one
    jitted ``(params, X)`` program whose fitted parameters are a
    swappable pytree argument (see :meth:`CompiledBatchFn.swap_params`);
    ``device=`` commits the params to a specific device — the fleet's
    per-replica placement knob. A pipeline applies its prefix transforms
    per batch and feeds the final step's compiled fn (prefix outputs are
    shape-deterministic per batch height, so the compile set stays
    bounded by the bucket ladder). Anything else gets the host
    fallback — ``getattr(est, method)`` over the padded batch.

    ``quantize="int8"`` builds the weight-quantized serving flavor for
    linear-family predict / decision_function (per-output-channel
    scales computed here, mixed bf16×int8 matmul core); methods and
    estimator families without an int8 path — predict_proba, KMeans,
    PCA, pipelines, host fallbacks — build their standard
    higher-precision flavor instead (``.quantize`` on the result says
    which one you got).
    """
    est = estimator
    if hasattr(est, "steps") and hasattr(est, "named_steps"):
        inner = compiled_batch_fn(est.steps[-1][1], method,
                                  device=device)
        first = est.steps[0][1]
        return CompiledBatchFn(
            None, method, inner.jitted,
            getattr(first, "n_features_in_", None),
            prefix=tuple(t for _, t in est.steps[:-1]), inner=inner,
        )
    if _is_device_estimator(est):
        built = None
        if hasattr(est, "coef_"):
            built = _jit_linear(est, method, device=device,
                                quantize=quantize)
        elif hasattr(est, "cluster_centers_"):
            built = _jit_kmeans(est, method, device=device)
        elif hasattr(est, "components_"):
            built = _jit_pca(est, method, device=device)
        elif hasattr(est, "theta_"):
            built = _jit_nb(est, method, device=device)
        if built is not None:
            return built
    target = getattr(est, method, None)
    if target is None:
        raise AttributeError(
            f"{type(est).__name__} has no method {method!r}"
        )
    n_feat = getattr(est, "n_features_in_", None)
    return CompiledBatchFn(lambda X: target(X), method, False, n_feat)


class Incremental(ParallelPostFit):
    """Ref: dask_ml/wrappers.py::Incremental +
    dask_ml/_partial.py::fit."""

    def __init__(self, estimator=None, scoring=None, shuffle_blocks=True,
                 random_state=None, assume_equal_chunks=True,
                 predict_meta=None, predict_proba_meta=None,
                 transform_meta=None):
        self.estimator = estimator
        self.scoring = scoring
        self.shuffle_blocks = shuffle_blocks
        self.random_state = random_state
        self.assume_equal_chunks = assume_equal_chunks
        self.predict_meta = predict_meta
        self.predict_proba_meta = predict_proba_meta
        self.transform_meta = transform_meta

    def _partial_fit_pass(self, est, X, y, block_size, rng, **fit_kwargs):
        if _is_device_estimator(est) and isinstance(X, ShardedArray):
            # device estimator + device data: blocks are the fused-epoch
            # grid's contiguous S-row ranges (fused_blocks), so the
            # fused and per-block paths train identical minibatches.
            # Blocks materialize as sharded gathers (take_rows); the
            # dataset never round-trips through host (VERDICT r2 #4 —
            # the reference's partial_fit chain runs on worker-resident
            # chunks the same way, SURVEY §3.6)
            from .models.sgd import fused_blocks
            from .parallel.sharded import take_rows

            ys = y if isinstance(y, ShardedArray) or y is None \
                else np.asarray(y)
            B, S = fused_blocks(X)
            # the last grid block always holds ≥1 real row (padding < D
            # and S*(B-1) is a multiple of D), so B IS the block count
            order = list(range(B))
            if self.shuffle_blocks:
                rng.shuffle(order)
            if (hasattr(est, "_fused_epoch") and ys is not None
                    and B > 1
                    and set(fit_kwargs) <= {"classes"}
                    and _device_headroom_for_copy(X)):
                # fused-epoch fast path: the whole pass compiles into ONE
                # scan program (same updates/order/lr clock as the block
                # loop) — per-block dispatch round trips vanish. The
                # grid is a second device copy of X for the epoch, hence
                # the headroom gate (the loop gathers one block at a
                # time and stays the fallback near HBM capacity).
                est._fused_epoch(
                    X, ys, order, n_blocks=B,
                    classes=fit_kwargs.get("classes"),
                )
                return est
            from .observability.live import publish_progress

            for done, b in enumerate(order):
                idx = np.arange(b * S, min((b + 1) * S, X.n_rows))
                Xb = take_rows(X, idx)
                if ys is None:
                    est.partial_fit(Xb, **fit_kwargs)
                else:
                    yb = take_rows(ys, idx) if isinstance(ys, ShardedArray) \
                        else ys[idx]
                    est.partial_fit(Xb, yb, **fit_kwargs)
                # live pass progress (host ints; no-op without the
                # telemetry server)
                publish_progress(block=done + 1, blocks_total=B)
            return est
        # sparse X blocks stay CSR host-side: a device estimator's
        # partial_fit densifies ONE block at placement (as_sharded), a
        # host estimator consumes the CSR block natively — either way
        # peak memory is O(block), never the dense corpus
        Xh = _host_matrix(X)
        yh = y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y)
        starts = list(range(0, Xh.shape[0], block_size))
        order = np.arange(len(starts))
        if self.shuffle_blocks:
            rng.shuffle(order)
        if (_is_device_estimator(est) and hasattr(est, "_stream_pass")
                and set(fit_kwargs) <= {"classes"}):
            # super-block fast path for device estimators on host data:
            # the pass's per-block partial_fit dispatches collapse into
            # donated-carry scans over K-stacked blocks — identical
            # minibatches, order, and lr clock. Returns False (sparse
            # source, K == 1 opt-out, partition mismatch) -> the
            # per-block loop below.
            if est._stream_pass(Xh, yh, block_size, order=order,
                                classes=fit_kwargs.get("classes")):
                return est
        from .observability.live import publish_progress

        for done, oi in enumerate(order):
            s = starts[int(oi)]
            est.partial_fit(Xh[s:s + block_size], yh[s:s + block_size],
                            **fit_kwargs)
            publish_progress(block=done + 1, blocks_total=len(starts))
        return est

    # -- pass-granular checkpoint/auto-resume (ISSUE 11) -------------------
    # With config.stream_checkpoint_path set, every partial_fit pass of
    # a device SGD-family inner estimator persists (w, lr clock,
    # classes, completed pass count) under a fingerprint token; a FRESH
    # wrapper whose first partial_fit finds a matching checkpoint
    # resumes the inner model and exposes ``completed_passes_`` so a
    # killed pass-driver loop (serve_while_training, chaos harnesses)
    # skips the passes already done. Host estimators and non-numeric
    # class sets opt out; fit() (a fresh one-pass fit) clears any
    # matching slot rather than resuming into it.

    def _pass_checkpoint(self, est, X, y, fit_kwargs):
        from .config import get_config
        from .reliability.stream_ckpt import stream_checkpoint

        if not get_config().stream_checkpoint_path:
            return None   # knobs off: touch nothing, cost one read
        if not (hasattr(est, "_stream_pass") and hasattr(est, "_loss")):
            return None   # device SGD-family only (w/t carry contract)
        if isinstance(X, ShardedArray) or y is None:
            return None
        classes = fit_kwargs.get("classes",
                                 getattr(est, "classes_", None))
        if classes is not None:
            classes = np.asarray(classes)
            if classes.dtype.kind not in "fiub":
                return None   # string labels don't round-trip orbax
        Xh, yh = _host_matrix(X), np.asarray(y)
        parts = (
            "incremental", type(est).__name__,
            repr(sorted(est.get_params().items())),
            self.shuffle_blocks, self.random_state,
            None if classes is None else tuple(classes.tolist()),
            tuple(Xh.shape) if hasattr(Xh, "shape") else len(Xh),
        )
        ckpt = stream_checkpoint("incremental", parts, arrays=(Xh, yh))
        self._pass_ckpt_ = ckpt
        return ckpt

    def _clear_pass_checkpoint(self):
        """Completion hook (serve_while_training calls it): the pass
        sequence is done, the slot must not resume into a future fit."""
        ckpt = getattr(self, "_pass_ckpt_", None)
        if ckpt is not None:
            ckpt.clear()

    def resume_from_checkpoint(self, X, y=None, **fit_kwargs):
        """Restore a matching pass checkpoint into this FRESH wrapper
        WITHOUT training — pass-driver loops (serve_while_training)
        call it before their first pass so a driver killed after its
        final pass resumes to zero remaining work instead of training
        one pass past the target. Returns the completed pass count
        (0 when nothing restored / already fitted / knobs off)."""
        from .config import get_config

        if not get_config().stream_checkpoint_path:
            return 0
        if getattr(self, "estimator_", None) is not None:
            return int(getattr(self, "completed_passes_", 0))
        est = clone(self.estimator)
        ckpt = self._pass_checkpoint(est, X, y, fit_kwargs)
        if ckpt is None:
            return 0
        st = ckpt.restore()
        if st is None:
            return 0
        from .observability._counters import record_stream_checkpoint

        import jax.numpy as jnp

        classes = st.get("classes")
        if classes is not None:
            est._set_classes(np.asarray(classes))
        est._ensure_state(int(st["d"]))
        est._w = jnp.asarray(np.asarray(st["w"], np.float32))
        est._t = int(st["t"])
        est._publish(int(st["d"]))
        self.estimator_ = est
        self.completed_passes_ = int(st["passes"])
        record_stream_checkpoint(resume=True)
        return self.completed_passes_

    def fit(self, X, y=None, **fit_kwargs):
        est = clone(self.estimator)
        if not hasattr(est, "partial_fit"):
            raise ValueError(
                f"{type(est).__name__} has no partial_fit; Incremental "
                "requires a partial_fit-capable estimator"
            )
        # classifiers need `classes` on the first partial_fit; the
        # reference makes callers pass classes= explicitly (y is a lazy
        # dask array there, a global unique is a cluster job) — here y is
        # concrete, so infer it when omitted (explicit classes= still wins)
        from sklearn.base import is_classifier

        if (y is not None and "classes" not in fit_kwargs
                and is_classifier(est)):
            if isinstance(y, ShardedArray):
                # binary: a three-scalar device scan, no column gather
                from .utils.validation import device_classes

                fit_kwargs["classes"] = device_classes(y)
            else:
                fit_kwargs["classes"] = np.unique(np.asarray(y))
        # a fresh fit() must never resume a stale pass sequence
        try:
            ckpt = self._pass_checkpoint(est, X, y, fit_kwargs)
            if ckpt is not None:
                ckpt.clear()
        except Exception:
            pass
        rng = np.random.RandomState(self.random_state)
        self.estimator_ = self._partial_fit_pass(
            est, X, y, self._block_size(X), rng, **fit_kwargs
        )
        return self

    def partial_fit(self, X, y=None, **fit_kwargs):
        if getattr(self, "estimator_", None) is None:
            # fresh wrapper: a matching checkpoint restores the killed
            # driver's inner carry before this pass runs
            self.resume_from_checkpoint(X, y, **fit_kwargs)
        est = getattr(self, "estimator_", None)
        if est is None:
            est = clone(self.estimator)
        ckpt = self._pass_checkpoint(est, X, y, fit_kwargs)
        rng = np.random.RandomState(self.random_state)
        self.estimator_ = self._partial_fit_pass(
            est, X, y, self._block_size(X), rng, **fit_kwargs
        )
        if ckpt is not None:
            self.completed_passes_ = \
                getattr(self, "completed_passes_", 0) + 1
            if ckpt.due(self.completed_passes_):
                inner = self.estimator_
                classes = getattr(inner, "classes_", None)
                ckpt.save(
                    w=np.asarray(inner._w), t=int(inner._t),
                    d=int(np.asarray(inner._w).shape[-1]) - 1,
                    passes=self.completed_passes_,
                    classes=None if classes is None
                    else np.asarray(classes),
                )
        return self

    @staticmethod
    def _block_size(X):
        if isinstance(X, ShardedArray):
            # the device branch of _partial_fit_pass derives its own
            # contiguous fused_blocks partition and ignores this value;
            # report that partition's row count for consistency
            from .models.sgd import fused_blocks

            return max(fused_blocks(X)[1], 1)
        # host inputs: the SAME grid partition the device path uses
        # (capped by the byte budget for sparse/memmap sources), so
        # host- and device-input fits train identical blocks
        from .parallel.streaming import fit_block_rows

        return fit_block_rows(X)
