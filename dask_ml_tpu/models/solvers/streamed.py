"""Out-of-core GLM solvers: gradient/loss/Hessian accumulation over
streamed host blocks.

Reference equivalent: dask's chunk scheduling under ``dask_glm`` — the
optimizer lives on the client and every objective evaluation is a lazy
graph over host-backed chunks (``dask_glm/algorithms.py``, SURVEY.md §3.2
"host-resident optimizer, cluster-resident data"). TPU design (SURVEY.md
§7 B0 / design stance #1): the dataset stays in host RAM or an
``np.memmap``; fixed-shape blocks stream through ``BlockStream``
(prefetched ``device_put``) into per-block jitted kernels that return
partial (loss, gradient[, Hessian]) sums; a small host-side optimizer
(d-vector state) consumes the accumulated totals. One objective
evaluation = one full pass over the data — line searches pay extra
passes, exactly as the reference pays extra cluster round-trips, so the
pass budget per solver is explicit below.

Passes per outer iteration:

- ``lbfgs`` (two-loop recursion): 1 + line-search trials (Armijo)
- ``gradient_descent``: 1 + trials
- ``proximal_grad``: 1 + trials
- ``newton``: 1 (grad+Hessian fused in one pass) + step-halving trials
- ``admm``: exactly 1 (block-local prox solves; the one-pass-friendly
  choice SURVEY.md §7 recommends at >HBM scale)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...observability import track_program
from ...plans import tracked as plan_tracked
from . import regularizers
from .families import get_family


# ---------------------------------------------------------------------------
# per-block jitted kernels. A consumed block's HBM is released when the
# stream iterator drops its reference, so peak device footprint stays
# ≈ (prefetch + 1) blocks.
# ---------------------------------------------------------------------------

@track_program("glm.stream.block_vg")
@partial(jax.jit, static_argnames=("family", "intercept"))
def _block_val_grad(beta, X, y, mask, family, intercept):
    """(Σ pointwise-NLL, Σ ∂NLL/∂β) over one block's valid rows."""

    def f(b):
        bd = b.astype(X.dtype)
        eta = (X @ bd[:-1] + bd[-1]) if intercept else X @ bd
        return jnp.sum(get_family(family).pointwise(eta, y) * mask)

    return jax.value_and_grad(f)(beta)


@track_program("glm.stream.block_val")
@partial(jax.jit, static_argnames=("family", "intercept"))
def _block_val(beta, X, y, mask, family, intercept):
    """Forward-only Σ pointwise-NLL — line-search/step-halving trials that
    only need the value skip the backward pass entirely."""
    bd = beta.astype(X.dtype)
    eta = (X @ bd[:-1] + bd[-1]) if intercept else X @ bd
    return jnp.sum(get_family(family).pointwise(eta, y) * mask)


@track_program("glm.stream.block_vgh")
@partial(jax.jit, static_argnames=("family", "intercept"))
def _block_val_grad_hess(beta, X, y, mask, family, intercept):
    """One fused pass: (Σ NLL, Σ grad, Σ Xᵀ W X) for Newton."""
    fam = get_family(family)
    bd = beta.astype(X.dtype)
    eta = (X @ bd[:-1] + bd[-1]) if intercept else X @ bd

    def f(b):
        bb = b.astype(X.dtype)
        e = (X @ bb[:-1] + bb[-1]) if intercept else X @ bb
        return jnp.sum(fam.pointwise(e, y) * mask)

    val, grad = jax.value_and_grad(f)(beta)
    w = fam.hess_weight(eta, y) * mask
    Xw = X * w[:, None]
    hess = jnp.einsum("ni,nj->ij", Xw, X, preferred_element_type=jnp.float32)
    if intercept:
        col = jnp.sum(Xw, axis=0)
        hess = jnp.block([
            [hess, col[:, None]],
            [col[None, :], jnp.sum(w)[None, None]],
        ])
    return val, grad, hess


@partial(jax.jit, static_argnames=("reg",))
def _finish_vg(val_sum, grad_sum, beta, n_rows, lam, pmask, l1_ratio, reg):
    """mean NLL + smooth penalty, and its gradient, from block sums."""
    pen, pen_g = jax.value_and_grad(
        lambda b: regularizers.value(reg, b, lam, pmask, l1_ratio)
    )(beta)
    return val_sum / n_rows + pen, grad_sum / n_rows + pen_g


# -- multiclass (one-vs-rest) block kernels ---------------------------------
# One data pass is SHARED across all C classes: the block's one-hot
# targets are built on device from class codes and the per-class math is
# vmapped, so X streams through HBM once per epoch regardless of C
# (VERDICT r3 missing #2 — the reference has one fit path for all label
# sets; dask_ml/linear_model/glm.py::LogisticRegression).

def onehot_targets(y, mask, classes_d):
    """(C, n) one-vs-rest targets; padding rows zeroed. The ONE place
    the target-encoding invariant lives — the in-core fit (glm.py's
    jitted wrapper) and every multiclass block kernel build targets
    here."""
    return (y[None, :] == classes_d[:, None]).astype(jnp.float32) \
        * mask[None, :]


def _codes_onehot(y, mask, n_classes):
    return onehot_targets(y, mask, jnp.arange(n_classes, dtype=y.dtype))


@track_program("glm.stream.block_vg_multi")
@partial(jax.jit, static_argnames=("family", "intercept", "n_classes"))
def _block_val_grad_multi(Beta, X, y, mask, family, intercept, n_classes):
    """(Σ_total NLL over classes+rows, ∂/∂Beta (C, d)) for one block.
    ``y`` holds class CODES 0..C-1."""
    Y = _codes_onehot(y, mask, n_classes)

    def f(B):
        Bd = B.astype(X.dtype)
        eta = (X @ Bd[:, :-1].T + Bd[:, -1]) if intercept else X @ Bd.T
        per_class = jax.vmap(
            lambda e, yc: jnp.sum(get_family(family).pointwise(e, yc) * mask),
            in_axes=(1, 0),
        )(eta, Y)
        return jnp.sum(per_class)

    return jax.value_and_grad(f)(Beta)


@track_program("glm.stream.block_val_multi")
@partial(jax.jit, static_argnames=("family", "intercept", "n_classes"))
def _block_val_multi(Beta, X, y, mask, family, intercept, n_classes):
    Y = _codes_onehot(y, mask, n_classes)
    Bd = Beta.astype(X.dtype)
    eta = (X @ Bd[:, :-1].T + Bd[:, -1]) if intercept else X @ Bd.T
    per_class = jax.vmap(
        lambda e, yc: jnp.sum(get_family(family).pointwise(e, yc) * mask),
        in_axes=(1, 0),
    )(eta, Y)
    return jnp.sum(per_class)


@track_program("glm.stream.block_vgh_multi")
@partial(jax.jit, static_argnames=("family", "intercept", "n_classes"))
def _block_val_grad_hess_multi(Beta, X, y, mask, family, intercept,
                               n_classes):
    """One fused pass: (Σ NLL, grad (C, d), per-class Hessians (C, d, d))."""
    Y = _codes_onehot(y, mask, n_classes)
    val, grad = _block_val_grad_multi.__wrapped__(
        Beta, X, y, mask, family, intercept, n_classes
    )
    fam = get_family(family)

    def one_class(beta_c, y_c):
        bd = beta_c.astype(X.dtype)
        eta = (X @ bd[:-1] + bd[-1]) if intercept else X @ bd
        w = fam.hess_weight(eta, y_c) * mask
        Xw = X * w[:, None]
        h = jnp.einsum("ni,nj->ij", Xw, X,
                       preferred_element_type=jnp.float32)
        if intercept:
            col = jnp.sum(Xw, axis=0)
            h = jnp.block([
                [h, col[:, None]],
                [col[None, :], jnp.sum(w)[None, None]],
            ])
        return h
    hess = jax.vmap(one_class)(Beta, Y)
    return val, grad, hess


def _admm_local_body(X, y, mask, b, u, z, rho, n_rows, local_iter, family,
                     intercept):
    """ADMM block-local Newton steps toward prox target v = z - u.

    Identical math to the in-memory shard-local solve
    (``solvers.py::_admm_run::local_newton``) with the mesh shard replaced
    by the streamed block."""
    fam = get_family(family)
    v = z - u

    def local_newton(_, b):
        bd = b.astype(X.dtype)
        eta = (X @ bd[:-1] + bd[-1]) if intercept else X @ bd
        resid = jax.grad(lambda e: jnp.sum(fam.pointwise(e, y) * mask))(eta)
        if intercept:
            g = jnp.concatenate([X.T @ resid, jnp.sum(resid)[None]]) / n_rows \
                + rho * (b - v)
        else:
            g = X.T @ resid / n_rows + rho * (b - v)
        w = fam.hess_weight(eta, y) * mask
        Xw = X * w[:, None]
        h = jnp.einsum("ni,nj->ij", Xw, X,
                       preferred_element_type=jnp.float32) / n_rows
        if intercept:
            col = jnp.sum(Xw, axis=0) / n_rows
            h = jnp.block([
                [h, col[:, None]],
                [col[None, :], (jnp.sum(w) / n_rows)[None, None]],
            ])
        h = h + rho * jnp.eye(b.shape[0], dtype=b.dtype)
        return b - jnp.linalg.solve(h, g)

    return jax.lax.fori_loop(0, local_iter, local_newton, b)


_block_admm_local = track_program("glm.stream.admm_local")(
    partial(jax.jit, static_argnames=(
        "local_iter", "family", "intercept",
    ))(_admm_local_body)
)


@track_program("glm.stream.admm_local_multi")
@partial(jax.jit, static_argnames=("family", "intercept", "local_iter",
                                   "n_classes"))
def _block_admm_local_multi(X, y, mask, B, U, Z, rho, n_rows, local_iter,
                            family, intercept, n_classes):
    """Per-class block-local ADMM Newton, vmapped: one block read serves
    all C consensus problems. B/U/Z are (C, d); y holds class codes."""
    Y = _codes_onehot(y, mask, n_classes)
    return jax.vmap(
        lambda yc, b, u, z: _admm_local_body(
            X, yc, mask, b, u, z, rho, n_rows, local_iter, family, intercept
        )
    )(Y, B, U, Z)


# ---------------------------------------------------------------------------
# super-block scan kernels (ISSUE 3 tentpole): K stacked blocks consumed
# by ONE jitted lax.scan whose accumulator carry is DONATED — one XLA
# dispatch per K blocks, the accumulator buffers reused in place across
# every dispatch of the pass, and no host round-trip inside the scan.
# Per-step masks derive from the super-block's valid-row counts, so an
# all-padding slot (the ragged final super-block) contributes exactly
# zero to every sum — block-order accumulation is identical to the
# per-block loop's.
# ---------------------------------------------------------------------------

import functools as _ft


def _reducer_blocks(kind, n_classes):
    """(per-block kernel, extra static args) for one objective flavor —
    shared by the single-device scan and the sharded shard_map scan so
    the two flavors can never diverge on the per-block math."""
    if n_classes:
        fn = {"val": _block_val_multi, "vg": _block_val_grad_multi,
              "vgh": _block_val_grad_hess_multi}[kind].__wrapped__
        return fn, (n_classes,)
    fn = {"val": _block_val, "vg": _block_val_grad,
          "vgh": _block_val_grad_hess}[kind].__wrapped__
    return fn, ()


def _sb_reducer_sharded(kind, family, intercept, n_classes, mesh,
                        mxu=None, fused=False, interpret=False):
    """Data-parallel super-block reducer (ISSUE 9): the same K-step
    accumulation as :func:`_sb_reducer`, run under ``shard_map`` over
    the stream mesh's "data" axis. Each device scans ONLY its own row
    slab of every block (masks derive from the per-shard valid-row
    counts — ragged tails pad per shard with zero counts), the carry is
    REPLICATED (in/out spec P()), and the dispatch pays exactly ONE
    ``lax.psum`` over "data": the local K-block delta merges once, then
    adds to the running replicated carry. Donation at the jit level
    keeps the carry advancing in place exactly like the single-device
    flavor.

    ``fused=True`` (ISSUE 12 tentpole) swaps the per-block body for the
    fused Pallas kernel running INSIDE the shard_map: each device's
    kernel sees its OWN (S/D, d) slab (tile selection reasons about the
    per-shard slab height, not the global block), produces local raw
    sums from ONE VMEM pass, and the existing single psum per
    super-block merges them — the per-chip kernel speed of the fused
    flavor composed with the data mesh. The replication checker is
    disabled on the fused trace only (pallas_call has no replication
    rule); the unfused program is byte-identical to the pre-feature
    one."""
    from jax.sharding import PartitionSpec as P

    from ..._compat import shard_map
    from ...parallel.mesh import DATA_AXIS, data_shard_spec as spec_of

    if fused:
        from ...ops.pallas_fused import (fused_glm_multi_stream,
                                         fused_glm_stream)

        if n_classes:
            def block_sums(beta, Xb, yb, c):
                return fused_glm_multi_stream(
                    kind, Xb, c, yb, beta, family, intercept,
                    mxu=mxu, interpret=interpret,
                )
        else:
            def block_sums(beta, Xb, yb, c):
                return fused_glm_stream(
                    kind, Xb, c, yb, beta, family, intercept,
                    mxu=mxu, interpret=interpret,
                )
    else:
        fn, extra = _reducer_blocks(kind, n_classes)

    def body(acc, beta, Xs, ys, counts):
        # LOCAL view: Xs (K, S/D, d) or a K-tuple of (S/D, d) blocks,
        # counts (1, K) — this shard's own valid-row counts
        unrolled = isinstance(Xs, (tuple, list))
        r = jnp.arange(Xs[0].shape[0] if unrolled else Xs.shape[1])
        cts = counts[0]
        local = jax.tree.map(jnp.zeros_like, acc)

        def step(lacc, Xb, yb, c):
            if fused:
                out = block_sums(beta, Xb, yb, c)
            else:
                mask = (r < c).astype(Xb.dtype)
                out = fn(beta, Xb, yb, mask, family, intercept, *extra)
                out = out if isinstance(out, tuple) else (out,)
            return tuple(l + o for l, o in zip(lacc, out))

        if unrolled:
            for j in range(len(Xs)):
                local = step(local, Xs[j], ys[j], cts[j])
        else:
            def scan_step(lacc, inp):
                return step(lacc, *inp), jnp.float32(0.0)

            local, _ = jax.lax.scan(scan_step, local, (Xs, ys, cts))
        # the super-block's ONE collective: local sums -> replicated
        # global sums, folded into the replicated running carry
        local = jax.lax.psum(local, DATA_AXIS)
        return tuple(a + l for a, l in zip(acc, local))

    @partial(jax.jit, donate_argnums=(0,))
    def run(acc, beta, Xs, ys, counts):
        unrolled = isinstance(Xs, (tuple, list))
        if unrolled:
            xs_spec = tuple(spec_of(a, 0) for a in Xs)
            ys_spec = tuple(spec_of(a, 0) for a in ys)
        else:
            xs_spec = spec_of(Xs, 1)
            ys_spec = spec_of(ys, 1)
        f = shard_map(
            body, mesh,
            in_specs=(P(), P(), xs_spec, ys_spec, P(DATA_AXIS, None)),
            out_specs=P(),
            check_vma=False if fused else None,
        )
        return f(acc, beta, Xs, ys, counts)

    from ...parallel.mesh import mesh_str

    suffix = "_multi" if n_classes else ""
    name = (f"pallas.glm_{kind}{suffix}.psum" if fused
            else f"superblock.glm.{kind}{suffix}.psum")
    return plan_tracked(name, run, mesh=mesh_str(mesh))


def _sb_reducer_feature_sharded(kind, family, intercept, n_classes,
                                mesh, model_shards):
    """Feature-sharded super-block reducer (ISSUE 18 tentpole): the 2-D
    ("data", "model") flavor of :func:`_sb_reducer_sharded`. Each device
    scans its OWN (K, S/D, d/M) tile of every block — per-chip HBM for
    the streamed X slabs is flat in d — and the replicated (d,)-sized
    carries/operands (beta in, loss/grad[/Hessian] sums out) are the
    only full-width device arrays, so the interface to ``_sb_pass`` /
    ``_merge`` / the host solvers is unchanged (L-BFGS S/Y memory lives
    in host RAM as before — per-chip HBM never sees it).

    Collective structure: the dispatch keeps exactly ONE ``lax.psum``
    over "data" per super-block (the K-step local sums merge once, as
    in the 1-D flavor) and adds "model" collectives exactly where the
    math contracts over features — a per-block psum for
    ``eta = Σ_m X_m @ w_m`` (the feature-dot), and one per-super-block
    ``all_gather`` reassembling the per-feature gradient (and Hessian
    row-tile) slices. The trivial M == 1 case never reaches here:
    ``_sb_pass`` only selects this flavor when the stream actually
    tiled (``sb_model_shards() > 1``), so the 1-D programs stay
    jaxpr-byte-identical."""
    from jax.sharding import PartitionSpec as P

    from ..._compat import shard_map
    from ...parallel.mesh import DATA_AXIS, MODEL_AXIS

    fam = get_family(family)

    def _x_spec(a, lead):
        # X tiles: rows over "data", features (last axis) over "model"
        return P(*((None,) * lead + (DATA_AXIS,)
                   + (None,) * (a.ndim - lead - 2) + (MODEL_AXIS,)))

    def _y_spec(a, lead):
        return P(*((None,) * lead + (DATA_AXIS,)
                   + (None,) * (a.ndim - lead - 1)))

    def _w_local(bd, dm):
        # this shard's (dm,)/(C, dm) feature slice of the replicated
        # weights (intercept column already stripped by the caller)
        mi = jax.lax.axis_index(MODEL_AXIS)
        if bd.ndim == 1:
            return jax.lax.dynamic_slice(bd, (mi * dm,), (dm,))
        return jax.lax.dynamic_slice(
            bd, (0, mi * dm), (bd.shape[0], dm)
        )

    def _gather_feat(t, axis):
        # per-feature slices -> the full-width array, replicated over
        # "model": scatter this shard's tile into a zero full-width
        # buffer and psum (adding zeros — exact), which the replication
        # checker can statically infer (an all_gather it cannot)
        mi = jax.lax.axis_index(MODEL_AXIS)
        dm = t.shape[axis]
        full = t.shape[:axis] + (dm * model_shards,) + t.shape[axis + 1:]
        start = (0,) * axis + (mi * dm,) + (0,) * (t.ndim - axis - 1)
        z = jax.lax.dynamic_update_slice(
            jnp.zeros(full, t.dtype), t, start
        )
        return jax.lax.psum(z, MODEL_AXIS)

    def block_sums(beta, Xb, yb, mask):
        """Local (val, grad-slice[, hess-tile]) sums for ONE block's
        (S/D, d/M) tile. ``eta`` pays the per-block feature psum; val
        and the intercept pieces come out model-REPLICATED, the
        per-feature pieces model-VARYING (gathered once per
        super-block, after the data psum)."""
        dm = Xb.shape[-1]
        bd = beta.astype(Xb.dtype)
        if n_classes:
            B = bd[:, :-1] if intercept else bd
            B_loc = _w_local(B, dm)
            eta = jax.lax.psum(Xb @ B_loc.T, MODEL_AXIS)  # (S/D, C)
            if intercept:
                eta = eta + bd[:, -1]
            Y = _codes_onehot(yb, mask, n_classes)

            def per_eta(e):
                per_class = jax.vmap(
                    lambda ec, yc: jnp.sum(fam.pointwise(ec, yc) * mask),
                    in_axes=(1, 0),
                )(e, Y)
                return jnp.sum(per_class)

            val, r = jax.value_and_grad(per_eta)(eta)
            g_loc = r.T @ Xb  # (C, d/M) — this shard's grad slice
            if kind == "val":
                return (val,)
            if kind == "vg":
                out = (val, g_loc)
                if intercept:
                    out += (jnp.sum(r, axis=0),)  # (C,), replicated
                return out
            # multiclass vgh: the (C, p, p) Hessian stack needs the
            # full-width rows — gather the block's tile (transient,
            # one block at a time) and reuse the 1-D per-class math;
            # every model shard computes the identical stack, so it
            # rides the data psum replicated
            Xf = _gather_feat(Xb, axis=1)  # (S/D, d)
            W = jax.vmap(lambda e, yc: fam.hess_weight(e, yc) * mask,
                         in_axes=(1, 0))(eta, Y)  # (C, S/D)
            XW = Xf[None, :, :] * W[:, :, None]
            H = jnp.einsum("cni,nj->cij", XW, Xf,
                           preferred_element_type=jnp.float32)
            if intercept:
                col = jnp.sum(XW, axis=1)  # (C, d)
                wsum = jnp.sum(W, axis=1)  # (C,)
                H = jnp.concatenate([
                    jnp.concatenate([H, col[:, :, None]], axis=2),
                    jnp.concatenate(
                        [col[:, None, :], wsum[:, None, None]], axis=2
                    ),
                ], axis=1)
            g_full = _gather_feat(g_loc, axis=1)
            if intercept:
                g_full = jnp.concatenate(
                    [g_full, jnp.sum(r, axis=0)[:, None]], axis=1
                )
            return (val, g_full, H)
        w = bd[:-1] if intercept else bd
        w_loc = _w_local(w, dm)
        eta = jax.lax.psum(Xb @ w_loc, MODEL_AXIS)  # the feature-dot
        if intercept:
            eta = eta + bd[-1]
        val, r = jax.value_and_grad(
            lambda e: jnp.sum(fam.pointwise(e, yb) * mask)
        )(eta)
        if kind == "val":
            return (val,)
        g_loc = Xb.T @ r  # (d/M,) — this shard's grad slice
        if kind == "vg":
            out = (val, g_loc)
            if intercept:
                out += (jnp.sum(r),)
            return out
        # vgh: Hessian row-tile H_m = (X_m W)^T X — (d/M, d); the full
        # rows come from a transient per-block gather (the Hessian is
        # inherently (d, d); the streamed wide-d path is lbfgs/vg)
        wgt = fam.hess_weight(eta, yb) * mask
        Xw = Xb * wgt[:, None]
        Xf = _gather_feat(Xb, axis=1)  # (S/D, d)
        H_loc = jnp.einsum("ni,nj->ij", Xw, Xf,
                           preferred_element_type=jnp.float32)
        out = (val, g_loc, H_loc)
        if intercept:
            out += (jnp.sum(r), jnp.sum(Xw, axis=0), jnp.sum(wgt))
        return out

    def _assemble(parts):
        """Replicated full-width sums from the data-psummed local
        tuple: gather the per-feature slices over "model" (their ONE
        per-super-block collective), rebuild the 1-D reducer's
        (val[, grad[, hess]]) carry layout."""
        if kind == "val" or (n_classes and kind == "vgh"):
            return parts  # already full-width / assembled per block
        if n_classes:  # multiclass vg
            if intercept:
                val, g_loc, g_b = parts
                g = jnp.concatenate(
                    [_gather_feat(g_loc, axis=1), g_b[:, None]], axis=1
                )
            else:
                val, g_loc = parts
                g = _gather_feat(g_loc, axis=1)
            return (val, g)
        if kind == "vg":
            if intercept:
                val, g_loc, g_b = parts
                g = jnp.concatenate([_gather_feat(g_loc, axis=0),
                                     g_b[None]])
            else:
                val, g_loc = parts
                g = _gather_feat(g_loc, axis=0)
            return (val, g)
        # binary vgh: grad slices + Hessian row-tiles -> full (p,) /
        # (p, p), intercept row/col appended exactly like the 1-D
        # kernel's jnp.block assembly
        val, g_loc, H_loc = parts[:3]
        g = _gather_feat(g_loc, axis=0)
        H = _gather_feat(H_loc, axis=0)  # (d, d)
        if intercept:
            g_b, col_loc, wsum = parts[3:]
            g = jnp.concatenate([g, g_b[None]])
            col = _gather_feat(col_loc, axis=0)
            H = jnp.block([
                [H, col[:, None]],
                [col[None, :], wsum[None, None]],
            ])
        return (val, g, H)

    def body(acc, beta, Xs, ys, counts):
        unrolled = isinstance(Xs, (tuple, list))
        r = jnp.arange(Xs[0].shape[0] if unrolled else Xs.shape[1])
        cts = counts[0]
        p = acc[1].shape[-1] if len(acc) > 1 else 0

        def zeros_local():
            # local accumulators mirror block_sums' output layout
            # (per-feature slices stay sliced until after the data
            # psum), not the replicated carry's
            dm = (Xs[0].shape[-1] if unrolled else Xs.shape[-1])

            def z(*s):
                return jnp.zeros(s, jnp.float32)

            if kind == "val":
                return (z(),)
            if n_classes:
                if kind == "vg":
                    out = (z(), z(n_classes, dm))
                    return out + ((z(n_classes),) if intercept else ())
                return (z(), z(n_classes, p), z(n_classes, p, p))
            if kind == "vg":
                out = (z(), z(dm))
                return out + ((z(),) if intercept else ())
            d_full = dm * model_shards
            out = (z(), z(dm), z(dm, d_full))
            return out + ((z(), z(dm), z()) if intercept else ())

        def step(lacc, Xb, yb, c):
            mask = (r < c).astype(Xb.dtype)
            out = block_sums(beta, Xb, yb, mask)
            return tuple(l + o for l, o in zip(lacc, out))

        local = zeros_local()
        if unrolled:
            for j in range(len(Xs)):
                local = step(local, Xs[j], ys[j], cts[j])
        else:
            def scan_step(lacc, inp):
                return step(lacc, *inp), jnp.float32(0.0)

            local, _ = jax.lax.scan(scan_step, local, (Xs, ys, cts))
        # the super-block's ONE data collective, as in the 1-D flavor
        local = jax.lax.psum(local, DATA_AXIS)
        # ... then the per-super-block feature reassembly
        full = _assemble(local)
        return tuple(a + f for a, f in zip(acc, full))

    @partial(jax.jit, donate_argnums=(0,))
    def run(acc, beta, Xs, ys, counts):
        unrolled = isinstance(Xs, (tuple, list))
        if unrolled:
            xs_spec = tuple(_x_spec(a, 0) for a in Xs)
            ys_spec = tuple(_y_spec(a, 0) for a in ys)
        else:
            xs_spec = _x_spec(Xs, 1)
            ys_spec = _y_spec(ys, 1)
        f = shard_map(
            body, mesh,
            in_specs=(P(), P(), xs_spec, ys_spec, P(DATA_AXIS, None)),
            out_specs=P(),
        )
        return f(acc, beta, Xs, ys, counts)

    from ...parallel.mesh import mesh_str

    suffix = "_multi" if n_classes else ""
    return plan_tracked(f"superblock.glm.{kind}{suffix}.model_psum",
                        run, mesh=mesh_str(mesh))


@_ft.lru_cache(maxsize=64)
def _sb_reducer(kind, family, intercept, n_classes, mxu=None,
                fused=False, interpret=False, mesh=None,
                model_shards=1):
    """The donated-carry super-block program for one objective flavor:
    ``kind`` in {"val", "vg", "vgh"} lifts the matching per-block kernel
    into a scan over the (K, S, ...) stacks, accumulating its sum tuple.
    Cached per flavor so every pass reuses ONE jitted callable (a fresh
    jax.jit per pass would retrace).

    ``mesh`` (a >1-shard stream mesh, ISSUE 9) selects the shard_map
    data-parallel flavor — replicated carry, per-shard blocks, one
    psum per super-block; its counts operand is the (D, K) per-shard
    matrix, not the global (K,) vector. With ``mesh=None`` (and the
    other knobs at default) this function is byte-for-byte the
    pre-mesh program.

    ``fused=True`` (see ``StreamedObjective._sb_flavor``'s gate) swaps
    the per-block body for the Pallas ``fused_glm_stream`` /
    ``fused_glm_multi_stream`` kernel: ONE VMEM pass per block for
    loss+grad(+Hessian) where the XLA body reads X two to three times,
    with ``mxu`` running the matmuls at bf16/f32-acc
    (config.dtype="auto" on TPU). ``fused`` composes with ``mesh``
    (ISSUE 12): the fused body then runs inside the shard_map program
    on each device's own slab. With ``fused=False`` and ``mxu`` unset
    this function is byte-for-byte the pre-feature program.

    ``model_shards`` > 1 (ISSUE 18: the stream's X tiles actually
    sharded over a 2-D mesh's "model" axis) selects the
    feature-sharded flavor — per-device (K, S/D, d/M) tiles, the
    feature-contracting psums over "model", program names
    ``superblock.glm.*.model_psum``. Callers leave it at the default
    whenever the stream didn't tile, so the M == 1 cache keys (and the
    1-D jaxprs) are untouched."""
    if mesh is not None and model_shards > 1:
        return _sb_reducer_feature_sharded(
            kind, family, intercept, n_classes, mesh, model_shards
        )
    if mesh is not None:
        return _sb_reducer_sharded(kind, family, intercept, n_classes,
                                   mesh, mxu=mxu, fused=fused,
                                   interpret=interpret)
    if fused:
        from ...ops.pallas_fused import (fused_glm_multi_stream,
                                         fused_glm_stream)

        if n_classes:
            def block_sums(beta, Xb, yb, c):
                return fused_glm_multi_stream(
                    kind, Xb, c, yb, beta, family, intercept,
                    mxu=mxu, interpret=interpret,
                )
        else:
            def block_sums(beta, Xb, yb, c):
                return fused_glm_stream(
                    kind, Xb, c, yb, beta, family, intercept,
                    mxu=mxu, interpret=interpret,
                )

        @partial(jax.jit, donate_argnums=(0,))
        def run_fused(acc, beta, Xs, ys, counts):
            unrolled = isinstance(Xs, (tuple, list))

            def step(acc, Xb, yb, c):
                out = block_sums(beta, Xb, yb, c)
                return tuple(a + o for a, o in zip(acc, out))

            if unrolled:
                for j in range(len(Xs)):
                    acc = step(acc, Xs[j], ys[j], counts[j])
                return acc

            def scan_step(acc, inp):
                return step(acc, *inp), jnp.float32(0.0)

            acc, _ = jax.lax.scan(scan_step, acc, (Xs, ys, counts))
            return acc

        suffix = "_multi" if n_classes else ""
        return plan_tracked(f"pallas.glm_{kind}{suffix}", run_fused)
    fn, extra = _reducer_blocks(kind, n_classes)

    @partial(jax.jit, donate_argnums=(0,))
    def run(acc, beta, Xs, ys, counts):
        unrolled = isinstance(Xs, (tuple, list))
        r = jnp.arange(Xs[0].shape[0] if unrolled else Xs.shape[1])

        def step(acc, Xb, yb, c):
            mask = (r < c).astype(Xb.dtype)
            out = fn(beta, Xb, yb, mask, family, intercept, *extra)
            out = out if isinstance(out, tuple) else (out,)
            return tuple(a + o for a, o in zip(acc, out))

        if unrolled:  # CPU layout: same single program, no slice copies
            for j in range(len(Xs)):
                acc = step(acc, Xs[j], ys[j], counts[j])
            return acc

        def scan_step(acc, inp):
            return step(acc, *inp), jnp.float32(0.0)

        acc, _ = jax.lax.scan(scan_step, acc, (Xs, ys, counts))
        return acc

    suffix = "_multi" if n_classes else ""
    return plan_tracked(f"superblock.glm.{kind}{suffix}", run)


# -- device-resident sparse reducers (ISSUE 13 tentpole) --------------------
# The bucketed-nnz flavor of the super-block scan: blocks arrive as
# fixed-shape COO triples (data/cols/rows, padding entries zero-valued)
# and the objective's matvec/gradient run at nnz-proportional cost via
# take + segment_sum (ops/sparse_kernels.py) — XLA's own cost analysis
# then attributes nnz FLOPs to the `superblock.sparse.*` programs, not
# n*d. The Newton Hessian (intrinsically O(d^2) math) scatters its
# block dense ON DEVICE and reuses the exact dense per-block kernel, so
# sparse-vs-dense Newton parity is float-roundoff only. Masks stay
# row-based (the same prefix-count contract as the dense scan).

def _sparse_reducer_sums(kind, family, intercept, n_classes, n_rows,
                         n_features):
    """Per-block sum tuple ``f(beta, data, cols, rows, yb, c)`` for one
    sparse objective flavor — shared by the single-device scan and the
    shard_map flavor (``n_rows`` is the LOCAL slab height there)."""
    from ...ops.sparse_kernels import (sparse_densify, sparse_eta,
                                       sparse_eta_multi)

    S = int(n_rows)

    if kind == "vgh":
        fn, extra = _reducer_blocks("vgh", n_classes)

        def sums(beta, data, cols, rows, yb, c):
            mask = (jnp.arange(S) < c).astype(jnp.float32)
            Xd = sparse_densify(data, cols, rows, S, int(n_features))
            return fn(beta, Xd, yb, mask, family, intercept, *extra)

        return sums

    if n_classes:
        def data_val(B, data, cols, rows, yb, mask):
            W = B[:, :-1] if intercept else B
            eta = sparse_eta_multi(data, cols, rows, W, S)   # (S, C)
            if intercept:
                eta = eta + B[:, -1][None, :]
            Y = _codes_onehot(yb, mask, n_classes)           # (C, S)
            per_class = jax.vmap(
                lambda e, yc: jnp.sum(
                    get_family(family).pointwise(e, yc) * mask
                ),
                in_axes=(1, 0),
            )(eta, Y)
            return jnp.sum(per_class)
    else:
        def data_val(beta, data, cols, rows, yb, mask):
            w = beta[:-1] if intercept else beta
            eta = sparse_eta(data, cols, rows, w, S)
            if intercept:
                eta = eta + beta[-1]
            return jnp.sum(get_family(family).pointwise(eta, yb) * mask)

    if kind == "val":
        def sums(beta, data, cols, rows, yb, c):
            mask = (jnp.arange(S) < c).astype(jnp.float32)
            return (data_val(beta, data, cols, rows, yb, mask),)

        return sums

    def sums(beta, data, cols, rows, yb, c):     # "vg"
        mask = (jnp.arange(S) < c).astype(jnp.float32)
        return jax.value_and_grad(
            lambda b: data_val(b, data, cols, rows, yb, mask)
        )(beta)

    return sums


@_ft.lru_cache(maxsize=64)
def _sb_reducer_sparse(kind, family, intercept, n_classes, n_rows,
                       n_features, mesh=None):
    """The donated-carry super-block program for one SPARSE objective
    flavor: the scan steps through the (K, cap) COO stacks accumulating
    the same sum tuple as :func:`_sb_reducer` — one dispatch per
    super-block, zero recompiles after pass 1 (the plan pads every
    super-block of a fit to ONE capacity). ``mesh`` selects the
    shard_map data-parallel flavor: each device scans its own (K, cap)
    nnz segment with shard-local row ids against its (K, S/D) slab of
    the dense side arrays, and the dispatch pays exactly ONE psum —
    identical collective shape to the dense flavor."""
    suffix = "_multi" if n_classes else ""
    if mesh is None:
        sums = _sparse_reducer_sums(kind, family, intercept, n_classes,
                                    n_rows, n_features)

        @partial(jax.jit, donate_argnums=(0,))
        def run(acc, beta, data, cols, rows, ys, counts):
            def scan_step(acc, inp):
                db, cb, rb, yb, c = inp
                out = sums(beta, db, cb, rb, yb, c)
                out = out if isinstance(out, tuple) else (out,)
                return tuple(a + o for a, o in zip(acc, out)), \
                    jnp.float32(0.0)

            acc, _ = jax.lax.scan(scan_step, acc,
                                  (data, cols, rows, ys, counts))
            return acc

        return plan_tracked(f"superblock.sparse.glm.{kind}{suffix}",
                            run)

    from jax.sharding import PartitionSpec as P

    from ..._compat import shard_map
    from ...parallel.mesh import DATA_AXIS

    sums = _sparse_reducer_sums(kind, family, intercept, n_classes,
                                n_rows, n_features)

    def body(acc, beta, data, cols, rows, ys, counts):
        # LOCAL view: data/cols/rows (K, cap) — this shard's nnz
        # segments with shard-local row ids; ys (K, S/D); counts (1, K)
        cts = counts[0]
        local = jax.tree.map(jnp.zeros_like, acc)

        def scan_step(lacc, inp):
            db, cb, rb, yb, c = inp
            out = sums(beta, db, cb, rb, yb, c)
            out = out if isinstance(out, tuple) else (out,)
            return tuple(l + o for l, o in zip(lacc, out)), \
                jnp.float32(0.0)

        local, _ = jax.lax.scan(scan_step, local,
                                (data, cols, rows, ys, cts))
        local = jax.lax.psum(local, DATA_AXIS)
        return tuple(a + l for a, l in zip(acc, local))

    @partial(jax.jit, donate_argnums=(0,))
    def run(acc, beta, data, cols, rows, ys, counts):
        f = shard_map(
            body, mesh,
            in_specs=(P(), P(), P(None, DATA_AXIS), P(None, DATA_AXIS),
                      P(None, DATA_AXIS), P(None, DATA_AXIS),
                      P(DATA_AXIS, None)),
            out_specs=P(),
        )
        return f(acc, beta, data, cols, rows, ys, counts)

    return plan_tracked(
        f"superblock.sparse.glm.{kind}{suffix}.psum", run
    )


@_ft.lru_cache(maxsize=32)
def _sb_admm_local(local_iter, family, intercept, n_classes,
                   gspmd=False):
    """Super-block ADMM block-local Newton: the K consensus members of
    one super-block solve their independent local problems in ONE
    vmapped dispatch (their (b, u) state slices ride in stacked; the
    stacked B carry is donated). All-padding slots pass their b through
    unchanged.

    ``gspmd=True`` (ROADMAP 1(c) measurement): the super-block arrays
    arrived BATCH-SHARDED over the stream mesh and this plain jit rides
    implicit GSPMD — XLA partitions each block's XᵀWX / Xᵀresid over
    the row shards and inserts cross-device all-reduces of the (d, d)
    Hessian and gradient per local-Newton iteration. Numerically
    identical; tracked under its own ``...admm_local.gspmd`` program
    name (so the report CLI ranks it separately, and with obs_programs
    on XLA's own bytes-accessed lands beside it) while the caller
    records the per-dispatch reduce-volume estimate on the
    ``gspmd_reduce_bytes`` counter."""

    @partial(jax.jit, donate_argnums=(0,))
    def run(Bk, Uk, Xs, ys, counts, z, rho, n_rows):
        unrolled = isinstance(Xs, (tuple, list))
        r = jnp.arange(Xs[0].shape[0] if unrolled else Xs.shape[1])

        def one(b, u, X, y, c):
            mask = (r < c).astype(X.dtype)
            if n_classes:
                Y = _codes_onehot(y, mask, n_classes)
                nb = jax.vmap(
                    lambda yc, bb, uu, zz: _admm_local_body(
                        X, yc, mask, bb, uu, zz, rho, n_rows,
                        local_iter, family, intercept,
                    )
                )(Y, b, u, z.reshape(n_classes, -1))
            else:
                nb = _admm_local_body(X, y, mask, b, u, z, rho, n_rows,
                                      local_iter, family, intercept)
            return jnp.where(c > 0, nb, b)

        if unrolled:  # CPU layout: same single program, no slice copies
            return jnp.stack([
                one(Bk[j], Uk[j], Xs[j], ys[j], counts[j])
                for j in range(len(Xs))
            ])
        return jax.vmap(one)(Bk, Uk, Xs, ys, counts)

    suffix = "_multi" if n_classes else ""
    tail = ".gspmd" if gspmd else ""
    return plan_tracked(f"superblock.glm.admm_local{suffix}{tail}",
                        run)


# ---------------------------------------------------------------------------
# streamed objective: one call = one pass over the stream
# ---------------------------------------------------------------------------

class StreamedObjective:
    """value_and_grad over a BlockStream; counts data passes.

    ``reduce``: optional cross-PROCESS sum of the per-pass accumulators
    (``parallel.distributed.psum_host``) — under a live multi-host
    runtime each process streams only its local shard, the raw
    loss/gradient/Hessian sums merge once per pass, and every process
    sees the identical GLOBAL objective (``n_rows`` is then the global
    row count). The host solvers run replicated on identical inputs, so
    their iterates never diverge across processes."""

    n_classes = None  # multiclass subclass overrides

    def __init__(self, stream, n_rows, lam, pmask, l1_ratio, family, reg,
                 intercept, logger=None, reduce=None, fit_dtype=None):
        self.stream = stream
        self.n_rows = float(n_rows)
        self.lam = lam
        self.pmask = pmask
        self.l1_ratio = l1_ratio
        self.family = family
        self.reg = reg
        self.intercept = intercept
        self.passes = 0
        self.logger = logger
        self.reduce = reduce
        self.fit_dtype = fit_dtype

    def _smooth_clone(self):
        """Same objective with the penalty stripped (proximal solvers
        evaluate the smooth part only and handle the penalty in the
        prox). Overridden by the multiclass subclass so the clone keeps
        its class structure."""
        return type(self)(
            self.stream, self.n_rows, self.lam * 0.0, self.pmask,
            self.l1_ratio, self.family, "none", self.intercept,
            logger=self.logger, reduce=self.reduce,
            fit_dtype=self.fit_dtype,
        )

    def _sb_flavor(self, kind):
        """(mxu, fused, interpret, reason) for this stream's ``kind``
        reducer: the Pallas fused flavor (ISSUE 8, composed with the
        data mesh by ISSUE 12) when opted in and the PER-SHARD slab
        shape (S/D rows — the rows each kernel instance actually sees
        inside shard_map; the whole block on a 1-shard mesh) fits the
        128-row grid/VMEM budget — with the resolved bf16 matmul policy
        riding along — else the XLA flavor, untouched and f32 (the
        streamed XLA reducers accumulate in f32 carries by
        construction; bf16 streamed GLM compute is a fused-kernel-only
        feature, so off-TPU fits fall back to f32 whatever config.dtype
        says). ``reason`` names why fused was gated off (None when it
        engaged) — recorded as solver_info_["fused_stream_reason"] so
        smoke suites can assert the kernels actually ran instead of
        silently falling back."""
        from ...config import mxu_dtype
        from ...ops.pallas_fused import (glm_multi_stream_tile,
                                         glm_stream_tile,
                                         stream_kernel_mode,
                                         stream_mode_reason,
                                         stream_tile_reason)

        if self.n_classes and kind == "vgh":
            # the per-class (C, d, d) Hessian stack stays XLA: a Pallas
            # body would hold C Hessian accumulators in VMEM at once,
            # and multiclass newton is not a streamed hot path
            return None, False, False, "multiclass-hessian-xla"
        M = int(getattr(self.stream, "sb_model_shards", lambda: 1)())
        if M > 1:
            # feature-sharded tiles (2-D mesh, ISSUE 18) stay XLA: the
            # fused Pallas bodies have no per-feature-slice story (the
            # model-axis psum sits mid-objective)
            return None, False, False, f"feature-sharded(M={M})"
        reason = stream_mode_reason()
        if reason is not None:
            return None, False, False, reason
        _, interp = stream_kernel_mode()
        s = self.stream
        try:
            S = int(s.block_rows)
            d = int(np.prod(s.arrays[0].shape[1:], dtype=np.int64))
        except Exception:
            return None, False, False, "no-stream-shape"
        # the fused body runs on each device's OWN slab: the tile gate
        # must reason about S/D rows, not the global block height
        D = max(int(getattr(s, "sb_data_shards", lambda: 1)()), 1)
        S_local = S // D
        tile = (glm_multi_stream_tile(S_local, d, self.n_classes)
                if self.n_classes
                else glm_stream_tile(S_local, d, kind))
        reason = stream_tile_reason(S_local, tile)
        if reason is not None:
            return None, False, False, reason
        if kind in ("vgh", "val"):
            # Hessian passes stay f32 even when fused — the SAME policy
            # the resident path enforces (glm.py restricts bf16 to the
            # smooth first-order solvers: bf16 Hessians risk
            # conditioning, and the matmul they'd speed up is the one
            # whose error a Newton step amplifies). "val" rides along:
            # its ONLY streamed consumer is newton's step-halving line
            # search, and comparing a bf16 objective against the f32
            # vgh value would spuriously reject steps near convergence
            # (the rounding gap exceeds the true decrease there)
            return None, True, interp, None
        return mxu_dtype(self.fit_dtype), True, interp, None

    def _merge(self, *accs):
        """Local pass sums → global sums (merged f64 on host, identical
        on every process; back to f32 for the device epilogue so x64
        stays untouched). Identity without a reduce."""
        if self.reduce is None:
            return accs if len(accs) > 1 else accs[0]
        out = self.reduce(*(np.asarray(a, np.float64) for a in accs))
        out = out if isinstance(out, tuple) else (out,)
        out = tuple(np.asarray(o, np.float32) for o in out)
        return out if len(out) > 1 else out[0]

    def _sb_pass(self, kind, B, init):
        """One super-block pass of the ``kind`` objective: the tuple of
        accumulated sums, or None when the stream doesn't super-block
        (no support, opt-out, sparse source, or K == 1) — the caller
        then runs its per-block loop. The accumulator tuple is the
        scan's DONATED carry: one dispatch per K blocks, its buffers
        reused in place across the whole pass."""
        s = self.stream
        if not (hasattr(s, "use_superblocks") and s.use_superblocks()):
            return None
        from ...observability import record_superblock_donation

        if bool(getattr(s, "sb_sparse", lambda: False)()):
            return self._sb_pass_sparse(kind, B, init)
        sharded = bool(getattr(s, "sb_sharded", lambda: False)())
        mxu, fused, interp, _ = self._sb_flavor(kind)
        if sharded:
            # data-parallel superblock flavor (ISSUE 9): shard_map over
            # the stream mesh, one psum per super-block — with the
            # fused Pallas body inside it when the flavor gate passes
            # (ISSUE 12). The carry enters COMMITTED-replicated so
            # every dispatch (including the first) hits the same
            # compiled executable and the donated buffers alias in
            # place
            from jax.sharding import NamedSharding, PartitionSpec as P

            # the feature-sharded flavor engages ONLY when the stream's
            # X actually tiled over "model" (sb_model_shards > 1); the
            # kwarg is omitted otherwise so the M == 1 reducer cache
            # keys — and with them the 1-D jaxprs — stay byte-identical
            m_shards = int(getattr(s, "sb_model_shards",
                                   lambda: 1)())
            kw = {"model_shards": m_shards} if m_shards > 1 else {}
            run = _sb_reducer(kind, self.family, self.intercept,
                              self.n_classes or 0, mxu=mxu, fused=fused,
                              interpret=interp, mesh=s.mesh, **kw)
            init = jax.device_put(init, NamedSharding(s.mesh, P()))
        else:
            run = _sb_reducer(kind, self.family, self.intercept,
                              self.n_classes or 0, mxu=mxu, fused=fused,
                              interpret=interp)
        acc = init
        acc_bytes = sum(4 * int(np.prod(a.shape) or 1) for a in acc)
        for sb in s.superblocks():
            counts = sb.shard_counts if sharded else sb.counts
            acc = run(acc, B, sb.arrays[0], sb.arrays[1], counts)
            record_superblock_donation(acc_bytes)
        return acc

    def _sb_pass_sparse(self, kind, B, init):
        """The bucketed-nnz flavor of :meth:`_sb_pass` (ISSUE 13): the
        stream stages sparse slabs, the reducers run take/segment_sum
        math at nnz cost, and the dispatch/donation/psum contracts are
        the dense scan's exactly."""
        from ...observability import record_superblock_donation

        s = self.stream
        plan = s.sparse_plan
        sharded = bool(getattr(s, "sb_sharded", lambda: False)())
        D = s.sb_data_shards() if sharded else 1
        S_local = s.block_rows // D
        if sharded:
            from jax.sharding import NamedSharding, PartitionSpec as P

            run = _sb_reducer_sparse(kind, self.family, self.intercept,
                                     self.n_classes or 0, S_local,
                                     plan.n_features, mesh=s.mesh)
            init = jax.device_put(init, NamedSharding(s.mesh, P()))
        else:
            run = _sb_reducer_sparse(kind, self.family, self.intercept,
                                     self.n_classes or 0, S_local,
                                     plan.n_features)
        acc = init
        acc_bytes = sum(4 * int(np.prod(a.shape) or 1) for a in acc)
        for sb in s.superblocks():
            slab = sb.arrays[0]
            counts = sb.shard_counts if sharded else sb.counts
            acc = run(acc, B, slab.data, slab.cols, slab.rows,
                      sb.arrays[1], counts)
            record_superblock_donation(acc_bytes)
        return acc

    def value_and_grad(self, beta):
        self.passes += 1
        beta = jnp.asarray(beta, jnp.float32)
        out = self._sb_pass("vg", beta, (
            jnp.zeros((), jnp.float32), jnp.zeros_like(beta),
        ))
        if out is not None:
            vs, gs = out
        else:
            vs, gs = None, None
            for blk in self.stream:
                Xb, yb = blk.arrays
                v, g = _block_val_grad(beta, Xb, yb, blk.mask, self.family,
                                       self.intercept)
                vs = v if vs is None else vs + v
                gs = g if gs is None else gs + g
        vs, gs = self._merge(vs, gs)
        val, grad = _finish_vg(vs, gs, beta, self.n_rows, self.lam,
                               self.pmask, self.l1_ratio, self.reg)
        return float(val), np.asarray(grad, np.float64)

    def value(self, beta):
        self.passes += 1
        beta = jnp.asarray(beta, jnp.float32)
        out = self._sb_pass("val", beta, (jnp.zeros((), jnp.float32),))
        if out is not None:
            vs, = out
        else:
            vs = None
            for blk in self.stream:
                Xb, yb = blk.arrays
                v = _block_val(beta, Xb, yb, blk.mask, self.family,
                               self.intercept)
                vs = v if vs is None else vs + v
        vs = self._merge(vs)
        pen = regularizers.value(self.reg, beta, self.lam, self.pmask,
                                 self.l1_ratio)
        return float(vs / self.n_rows + pen)

    def value_and_grad_and_hess(self, beta):
        self.passes += 1
        beta = jnp.asarray(beta, jnp.float32)
        p = beta.shape[0]
        out = self._sb_pass("vgh", beta, (
            jnp.zeros((), jnp.float32), jnp.zeros_like(beta),
            jnp.zeros((p, p), jnp.float32),
        ))
        if out is not None:
            vs, gs, hs = out
        else:
            vs, gs, hs = None, None, None
            for blk in self.stream:
                Xb, yb = blk.arrays
                v, g, h = _block_val_grad_hess(beta, Xb, yb, blk.mask,
                                               self.family, self.intercept)
                vs = v if vs is None else vs + v
                gs = g if gs is None else gs + g
                hs = h if hs is None else hs + h
        vs, gs, hs = self._merge(vs, gs, hs)
        val, grad = _finish_vg(vs, gs, beta, self.n_rows, self.lam,
                               self.pmask, self.l1_ratio, self.reg)
        return (float(val), np.asarray(grad, np.float64),
                np.asarray(hs, np.float64) / self.n_rows)

    def log(self, it, val, gnorm):
        from ...observability.live import publish_progress

        # the streamed solvers hold loss/grad_norm on HOST already (the
        # per-pass reduction fetched them) — publishing live gauges
        # costs dict writes, never a device sync; no-op without a
        # telemetry server
        publish_progress(loss=float(val), grad_norm=float(gnorm),
                         iteration=int(it), pass_count=self.passes)
        if self.logger is not None:
            self.logger.log(step=it, loss=float(val), grad_norm=float(gnorm),
                            passes=self.passes)


class MulticlassStreamedObjective(StreamedObjective):
    """Sum of C one-vs-rest objectives over ONE shared stream pass.

    The host solvers see a FLAT (C*d,) parameter vector — the joint
    objective is separable across classes, so minimizing the sum jointly
    (lbfgs/gd/prox on the concatenated vector) reaches each class's own
    optimum; ``pmask`` arrives pre-tiled to (C*d,). Newton and ADMM read
    ``n_classes`` to keep their per-class (d, d) structure."""

    def __init__(self, *args, n_classes=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.n_classes = n_classes

    def _smooth_clone(self):
        return type(self)(
            self.stream, self.n_rows, self.lam * 0.0, self.pmask,
            self.l1_ratio, self.family, "none", self.intercept,
            logger=self.logger, n_classes=self.n_classes,
            reduce=self.reduce, fit_dtype=self.fit_dtype,
        )

    def _B(self, beta_flat):
        return jnp.asarray(beta_flat, jnp.float32).reshape(
            self.n_classes, -1
        )

    def value_and_grad(self, beta):
        self.passes += 1
        B = self._B(beta)
        out = self._sb_pass("vg", B, (
            jnp.zeros((), jnp.float32), jnp.zeros_like(B),
        ))
        if out is not None:
            vs, gs = out
        else:
            vs, gs = None, None
            for blk in self.stream:
                Xb, yb = blk.arrays
                v, g = _block_val_grad_multi(B, Xb, yb, blk.mask,
                                             self.family, self.intercept,
                                             self.n_classes)
                vs = v if vs is None else vs + v
                gs = g if gs is None else gs + g
        vs, gs = self._merge(vs, gs)
        val, grad = _finish_vg(vs, jnp.asarray(gs).ravel(),
                               jnp.asarray(beta, jnp.float32),
                               self.n_rows, self.lam, self.pmask,
                               self.l1_ratio, self.reg)
        return float(val), np.asarray(grad, np.float64)

    def value(self, beta):
        self.passes += 1
        B = self._B(beta)
        out = self._sb_pass("val", B, (jnp.zeros((), jnp.float32),))
        if out is not None:
            vs, = out
        else:
            vs = None
            for blk in self.stream:
                Xb, yb = blk.arrays
                v = _block_val_multi(B, Xb, yb, blk.mask, self.family,
                                     self.intercept, self.n_classes)
                vs = v if vs is None else vs + v
        vs = self._merge(vs)
        pen = regularizers.value(self.reg, jnp.asarray(beta, jnp.float32),
                                 self.lam, self.pmask, self.l1_ratio)
        return float(vs / self.n_rows + pen)

    def value_and_grad_and_hess(self, beta):
        self.passes += 1
        B = self._B(beta)
        p = B.shape[1]
        out = self._sb_pass("vgh", B, (
            jnp.zeros((), jnp.float32), jnp.zeros_like(B),
            jnp.zeros((self.n_classes, p, p), jnp.float32),
        ))
        if out is not None:
            vs, gs, hs = out
        else:
            vs, gs, hs = None, None, None
            for blk in self.stream:
                Xb, yb = blk.arrays
                v, g, h = _block_val_grad_hess_multi(
                    B, Xb, yb, blk.mask, self.family, self.intercept,
                    self.n_classes,
                )
                vs = v if vs is None else vs + v
                gs = g if gs is None else gs + g
                hs = h if hs is None else hs + h
        vs, gs, hs = self._merge(vs, gs, hs)
        val, grad = _finish_vg(vs, jnp.asarray(gs).ravel(),
                               jnp.asarray(beta, jnp.float32),
                               self.n_rows, self.lam, self.pmask,
                               self.l1_ratio, self.reg)
        return (float(val), np.asarray(grad, np.float64),
                np.asarray(hs, np.float64) / self.n_rows)


def _armijo(obj, beta, val, grad, direction, t0=1.0, c=1e-4, backtrack=0.5,
            max_trials=30):
    """Backtracking line search; each trial is one data pass. Returns
    (t, new_val, new_grad) at the accepted point."""
    dg = float(grad @ direction)
    if dg >= 0:  # numerical non-descent: fall back to steepest descent
        direction = -grad
        dg = -float(grad @ grad)
    t = t0
    for _ in range(max_trials):
        nv, ng = obj.value_and_grad(beta + t * direction)
        if nv <= val + c * t * dg or t <= 1e-20:
            return t, direction, nv, ng
        t *= backtrack
    return t, direction, nv, ng


# ---------------------------------------------------------------------------
# solvers (host optimizer state — a handful of d-vectors — over streamed
# device evaluation)
# ---------------------------------------------------------------------------
#
# Every solver takes an optional ``ckpt`` (reliability/stream_ckpt.py):
# the host optimizer state — the iterate plus whatever the solver needs
# to continue bit-exactly — saves after each outer iteration (each
# iteration = one-plus data passes) and clears on completion, so a
# killed multi-hour streamed GLM fit resumes at iteration granularity
# instead of restarting from scratch. A wrong-fingerprint checkpoint
# restores as None and the fit simply starts fresh.

def _ckpt_restore(ckpt):
    if ckpt is None:
        return None
    st = ckpt.restore()
    if st is not None:
        from ...observability._counters import record_stream_checkpoint

        record_stream_checkpoint(resume=True)
    return st


def lbfgs(obj: StreamedObjective, beta0, max_iter=100, tol=1e-6, memory=10,
          ckpt=None, **_):
    if obj.reg not in regularizers.SMOOTH:
        raise ValueError(
            "streamed lbfgs handles smooth penalties only (l2/none); use "
            "solver='proximal_grad' or 'admm' for l1/elastic_net"
        )
    beta = np.asarray(beta0, np.float64)
    S, Y = [], []
    it0 = n_iter = 0
    st = _ckpt_restore(ckpt)
    if st is not None:
        beta = np.asarray(st["beta"], np.float64)
        val = float(st["val"])
        grad = np.asarray(st["grad"], np.float64)
        if "S" in st:
            S = [np.asarray(r, np.float64) for r in np.asarray(st["S"])]
            Y = [np.asarray(r, np.float64) for r in np.asarray(st["Y"])]
        it0 = n_iter = int(st["it"])
        obj.passes = int(st["passes"])
    else:
        val, grad = obj.value_and_grad(beta)
    for it in range(it0, int(max_iter)):
        gnorm = float(np.linalg.norm(grad))
        obj.log(it, val, gnorm)
        if gnorm <= tol:
            break
        # two-loop recursion on host (d-vector ops; data never touched)
        q = grad.copy()
        alphas = []
        for s, y_ in zip(reversed(S), reversed(Y)):
            rho = 1.0 / float(y_ @ s)
            a = rho * float(s @ q)
            q -= a * y_
            alphas.append((rho, a))
        if Y:
            q *= float(S[-1] @ Y[-1]) / float(Y[-1] @ Y[-1])
        for (rho, a), s, y_ in zip(reversed(alphas), S, Y):
            q += (a - rho * float(y_ @ q)) * s
        t, direction, nv, ng = _armijo(obj, beta, val, grad, -q)
        s = t * direction
        y_ = ng - grad
        if float(s @ y_) > 1e-10 * np.linalg.norm(s) * np.linalg.norm(y_):
            S.append(s)
            Y.append(y_)
            if len(S) > memory:
                S.pop(0)
                Y.pop(0)
        beta = beta + s
        val, grad = nv, ng
        n_iter = it + 1
        if ckpt is not None and ckpt.due(n_iter):
            state = dict(beta=beta, val=np.float64(val), grad=grad,
                         it=n_iter, passes=obj.passes)
            if S:
                state["S"], state["Y"] = np.stack(S), np.stack(Y)
            ckpt.save(**state)
    if ckpt is not None:
        ckpt.clear()
    return beta, {"n_iter": n_iter, "grad_norm": float(np.linalg.norm(grad)),
                  "data_passes": obj.passes}


def gradient_descent(obj: StreamedObjective, beta0, max_iter=100, tol=1e-6,
                     init_step=1.0, ckpt=None, **_):
    if obj.reg not in regularizers.SMOOTH:
        raise ValueError(
            "streamed gradient_descent handles smooth penalties only"
        )
    beta = np.asarray(beta0, np.float64)
    it0 = n_iter = 0
    st = _ckpt_restore(ckpt)
    if st is not None:
        beta = np.asarray(st["beta"], np.float64)
        val = float(st["val"])
        grad = np.asarray(st["grad"], np.float64)
        step = float(st["step"])
        it0 = n_iter = int(st["it"])
        obj.passes = int(st["passes"])
    else:
        val, grad = obj.value_and_grad(beta)
        step = init_step
    for it in range(it0, int(max_iter)):
        gnorm = float(np.linalg.norm(grad))
        obj.log(it, val, gnorm)
        if gnorm <= tol:
            break
        t, direction, nv, ng = _armijo(obj, beta, val, grad, -grad, t0=step)
        beta = beta + t * direction
        val, grad = nv, ng
        step = t * 2.0
        n_iter = it + 1
        if ckpt is not None and ckpt.due(n_iter):
            ckpt.save(beta=beta, val=np.float64(val), grad=grad,
                      step=np.float64(step), it=n_iter, passes=obj.passes)
    if ckpt is not None:
        ckpt.clear()
    return beta, {"n_iter": n_iter, "grad_norm": float(np.linalg.norm(grad)),
                  "data_passes": obj.passes}


def newton(obj: StreamedObjective, beta0, max_iter=50, tol=1e-6, ckpt=None,
           **_):
    if obj.reg not in regularizers.SMOOTH:
        raise ValueError("streamed newton handles smooth penalties only")
    beta = np.asarray(beta0, np.float64)
    d = beta.shape[0]
    pmask = np.asarray(obj.pmask, np.float64)
    ridge = (float(obj.lam) * pmask if obj.reg == "l2"
             else np.zeros(d)) + 1e-8
    it0 = n_iter = 0
    st = _ckpt_restore(ckpt)
    if st is not None:
        # newton recomputes val/grad/hess at the loop top, so the
        # iterate + clocks are the whole state (resume pays one extra
        # pass re-evaluating the saved iterate; the math is identical)
        beta = np.asarray(st["beta"], np.float64)
        it0 = n_iter = int(st["it"])
        obj.passes = int(st["passes"])
    gnorm = np.inf
    for it in range(it0, int(max_iter)):
        val, grad, hess = obj.value_and_grad_and_hess(beta)
        gnorm = float(np.linalg.norm(grad))
        obj.log(it, val, gnorm)
        if gnorm <= tol:
            break
        if obj.n_classes:
            # per-class (d, d) solves against the block-diagonal Hessian
            C = obj.n_classes
            G = grad.reshape(C, -1)
            R = ridge.reshape(C, -1)
            delta = np.concatenate([
                np.linalg.lstsq(hess[c] + np.diag(R[c]), G[c], rcond=None)[0]
                for c in range(C)
            ])
        else:
            delta = np.linalg.lstsq(hess + np.diag(ridge), grad,
                                    rcond=None)[0]
        t = 1.0
        while t > 1e-6:
            if obj.value(beta - t * delta) <= val:
                break
            t *= 0.5
        beta = beta - t * delta
        n_iter = it + 1
        if ckpt is not None and ckpt.due(n_iter):
            ckpt.save(beta=beta, it=n_iter, passes=obj.passes)
    if ckpt is not None:
        ckpt.clear()
    return beta, {"n_iter": n_iter, "grad_norm": gnorm,
                  "data_passes": obj.passes}


def proximal_grad(obj: StreamedObjective, beta0, max_iter=100, tol=1e-7,
                  init_step=1.0, ckpt=None, **_):
    # penalty handled by the prox; the streamed objective evaluates the
    # smooth part only
    smooth = obj._smooth_clone()
    lam = float(np.asarray(obj.lam))
    pmask_j = jnp.asarray(obj.pmask)
    beta = np.asarray(beta0, np.float64)
    it0 = n_iter = 0
    st = _ckpt_restore(ckpt)
    if st is not None:
        beta = np.asarray(st["beta"], np.float64)
        val = float(st["val"])
        grad = np.asarray(st["grad"], np.float64)
        step = float(st["step"])
        it0 = n_iter = int(st["it"])
        smooth.passes = int(st["passes"])
    else:
        val, grad = smooth.value_and_grad(beta)
        step = init_step
    delta = np.inf

    def candidate(t):
        return np.asarray(regularizers.prox(
            obj.reg, jnp.asarray(beta - t * grad), lam, t, pmask_j,
            obj.l1_ratio,
        ), np.float64)

    for it in range(it0, int(max_iter)):
        t = step
        while True:
            z = candidate(t)
            dz = z - beta
            quad = val + float(grad @ dz) + float(dz @ dz) / (2.0 * t)
            # evaluate value AND gradient in the trial pass: the accepted
            # candidate's gradient is reused below, so acceptance costs no
            # extra epoch over the stream
            zv, zg = smooth.value_and_grad(z)
            if zv <= quad or t <= 1e-20:
                break
            t *= 0.5
        delta = float(np.linalg.norm(z - beta)) / max(t, 1e-20)
        beta = z
        val, grad = zv, zg
        smooth.log(it, val, delta)
        step = t * 1.2
        n_iter = it + 1
        if ckpt is not None and ckpt.due(n_iter):
            ckpt.save(beta=beta, val=np.float64(val), grad=grad,
                      step=np.float64(step), it=n_iter,
                      passes=smooth.passes)
        if delta <= tol:
            break
    if ckpt is not None:
        ckpt.clear()
    obj.passes = smooth.passes
    return beta, {"n_iter": n_iter, "opt_residual": float(delta),
                  "data_passes": obj.passes}


def admm(obj: StreamedObjective, beta0, max_iter=250, tol=1e-4, rho=1.0,
         local_iter=8, ckpt=None, **_):
    """Block-consensus ADMM: each streamed block is a consensus member
    (the in-memory version's mesh shard, ``solvers.py::_admm_run``).
    Per-block (b, u) state is (n_blocks, d) on host — tiny next to X."""
    reg = obj.reg
    lam = float(np.asarray(obj.lam))
    if reg == "none":
        reg, lam = "l2", 0.0
    n_blocks = obj.stream.n_blocks
    # consensus spans every process's blocks: the z-update and residuals
    # use GLOBAL block sums/counts so all processes step identically
    reduce = obj.reduce or (lambda *a: a[0] if len(a) == 1 else a)
    glob_blocks = int(reduce(np.asarray(float(n_blocks))))
    d = len(np.asarray(beta0))
    B = np.tile(np.asarray(beta0, np.float32)[None], (n_blocks, 1))
    U = np.zeros((n_blocks, d), np.float32)
    z = jnp.asarray(beta0, jnp.float32)
    pmask_j = jnp.asarray(obj.pmask)
    rho_f = float(rho)
    it0 = n_iter = 0
    st = _ckpt_restore(ckpt)
    if st is not None and np.asarray(st["B"]).shape == B.shape:
        B = np.asarray(st["B"], np.float32)
        U = np.asarray(st["U"], np.float32)
        z = jnp.asarray(np.asarray(st["z"], np.float32))
        rho_f = float(st["rho"])
        it0 = n_iter = int(st["it"])
        obj.passes = int(st["passes"])
    primal = dual = np.inf
    C = obj.n_classes
    s = obj.stream
    # ADMM's block-local Newton is O(d^2) per member whatever the input
    # format — sparse-staged streams keep the per-block densify loop
    # (reason recorded via _fused_stream_info as "admm-local-newton")
    use_sb = (hasattr(s, "use_superblocks") and s.use_superblocks()
              and not bool(getattr(s, "sb_sparse", lambda: False)()))
    for it in range(it0, int(max_iter)):
        obj.passes += 1
        bi = 0
        if use_sb:
            # one dispatch advances the K consensus members of each
            # super-block (GLM local-Newton, vmapped over the stack;
            # stacked-B carry donated)
            from ...observability import (record_gspmd_reduce,
                                          record_superblock_donation)

            sb_sharded = bool(getattr(s, "sb_sharded", lambda: False)())
            runner = _sb_admm_local(int(local_iter), obj.family,
                                    obj.intercept, C or 0,
                                    gspmd=sb_sharded)
            for sb in s.superblocks():
                k = int(sb.counts.shape[0])
                kr = sb.n_blocks
                Bk = np.zeros((k, d), np.float32)
                Uk = np.zeros((k, d), np.float32)
                Bk[:kr] = B[bi:bi + kr]
                Uk[:kr] = U[bi:bi + kr]
                if C:
                    out = runner(
                        jnp.asarray(Bk).reshape(k, C, -1),
                        jnp.asarray(Uk).reshape(k, C, -1),
                        sb.arrays[0], sb.arrays[1], sb.counts, z.ravel(),
                        jnp.float32(rho_f), jnp.float32(obj.n_rows),
                    )
                    B[bi:bi + kr] = np.asarray(out).reshape(k, -1)[:kr]
                else:
                    out = runner(
                        jnp.asarray(Bk), jnp.asarray(Uk), sb.arrays[0],
                        sb.arrays[1], sb.counts, z,
                        jnp.float32(rho_f), jnp.float32(obj.n_rows),
                    )
                    B[bi:bi + kr] = np.asarray(out)[:kr]
                record_superblock_donation(Bk.nbytes)
                if sb_sharded:
                    # implicit-GSPMD reduce volume of this dispatch
                    # (ROADMAP 1(c)): per block slot, class, and
                    # local-Newton iteration, the partitioned XᵀWX +
                    # Xᵀresid pay one cross-device all-reduce of the
                    # (p, p) Hessian and the (p,) gradient; logical
                    # payload = iters * K * C * (p² + p) * 4 bytes,
                    # counted once per crossing (ring traffic
                    # multiplies by ~2(D-1)/D on real links — the
                    # counter records the payload, the topology factor
                    # belongs to the interconnect)
                    p = d // (C or 1)
                    record_gspmd_reduce(
                        int(local_iter) * k * (C or 1) * (p * p + p) * 4
                    )
                bi += kr
        else:
            for blk in obj.stream:
                Xb, yb = blk.arrays
                if C:
                    # one block read serves all C consensus problems
                    B[bi] = np.asarray(_block_admm_local_multi(
                        Xb, yb, blk.mask, jnp.asarray(B[bi]).reshape(C, -1),
                        jnp.asarray(U[bi]).reshape(C, -1), z.reshape(C, -1),
                        jnp.float32(rho_f), jnp.float32(obj.n_rows),
                        local_iter, obj.family, obj.intercept, C,
                    )).ravel()
                else:
                    B[bi] = np.asarray(_block_admm_local(
                        Xb, yb, blk.mask, jnp.asarray(B[bi]),
                        jnp.asarray(U[bi]), z, jnp.float32(rho_f),
                        jnp.float32(obj.n_rows), local_iter, obj.family,
                        obj.intercept,
                    ))
                bi += 1
        bu_sum, = (reduce(np.asarray((B + U).sum(axis=0), np.float64)),)
        bu_mean = jnp.asarray(np.asarray(bu_sum, np.float32) / glob_blocks)
        z_new = regularizers.prox(reg, bu_mean, lam,
                                  1.0 / (rho_f * glob_blocks), pmask_j,
                                  obj.l1_ratio)
        z_h = np.asarray(z_new, np.float32)
        U = U + B - z_h[None, :]
        primal2 = float(reduce(
            np.asarray(((B - z_h[None, :]) ** 2).sum(), np.float64)
        ))
        primal = float(np.sqrt(primal2))
        dual = float(rho_f * np.sqrt(glob_blocks)
                     * np.linalg.norm(z_h - np.asarray(z)))
        z = z_new
        obj.log(it, primal, dual)
        n_iter = it + 1
        if primal <= tol and dual <= tol:
            break
        if primal > 10.0 * dual:
            rho_f *= 2.0
            U /= 2.0
        elif dual > 10.0 * primal:
            rho_f *= 0.5
            U *= 2.0
        if ckpt is not None and ckpt.due(n_iter):
            # saved AFTER the rho adaptation so a resumed iteration
            # continues with exactly the state an uninterrupted run
            # would carry into it
            ckpt.save(B=B, U=U, z=np.asarray(z, np.float32),
                      rho=np.float64(rho_f), it=n_iter,
                      passes=obj.passes)
    if ckpt is not None:
        ckpt.clear()
    return (np.asarray(z, np.float64),
            {"n_iter": n_iter, "primal_residual": primal,
             "dual_residual": dual, "data_passes": obj.passes})


STREAMED_SOLVERS = {
    "admm": admm,
    "lbfgs": lbfgs,
    "newton": newton,
    "gradient_descent": gradient_descent,
    "proximal_grad": proximal_grad,
}


def solve_streamed(solver, stream, n_rows, beta0, family, reg, lam, pmask,
                   l1_ratio=0.5, intercept=True, max_iter=100, tol=1e-6,
                   logger=None, reduce=None, fit_dtype=None, ckpt=None,
                   **kwargs):
    """``reduce`` (``distributed.psum_host``): merge per-pass block sums
    across processes — each process streams its LOCAL shard, ``n_rows``
    is the GLOBAL count, and the fit equals the single-process fit over
    the concatenated data. ``ckpt`` (a reliability.StreamCheckpoint)
    arms iteration-granular save/auto-resume in the solver."""
    if solver not in STREAMED_SOLVERS:
        raise ValueError(
            f"Unknown solver {solver!r}; options: {sorted(STREAMED_SOLVERS)}"
        )
    obj = StreamedObjective(
        stream, n_rows, jnp.asarray(lam, jnp.float32), jnp.asarray(pmask),
        l1_ratio, family, reg, intercept, logger=logger, reduce=reduce,
        fit_dtype=fit_dtype,
    )
    beta, info = STREAMED_SOLVERS[solver](
        obj, beta0, max_iter=max_iter, tol=tol, ckpt=ckpt, **kwargs
    )
    info["streamed"] = True
    info["n_blocks"] = stream.n_blocks
    info.update(_fused_stream_info(obj, stream, solver, fit_dtype))
    from .solvers import check_finite_result

    return check_finite_result(beta, info, solver)


def _fused_stream_info(obj, stream, solver, fit_dtype):
    """The fit-info fields describing the streamed pass flavor: the
    data-parallel width, whether the fused Pallas reducers carried the
    pass, WHY they did not (``fused_stream_reason`` — None when fused
    engaged, else e.g. "off-TPU" / "non-128-mult shard rows" /
    "per-block-path", so tpu_smoke can assert fused actually ran
    instead of silently falling back), and the resolved precision
    policy (streamed XLA flavors are f32-only — an auto policy that
    fell back must be on record). The flavor gate is checked for the
    reducer KIND this solver's passes actually run: newton's vgh tile
    budget (it also holds the (d, d) Hessian accumulator) can refuse a
    width the vg kernel accepts, and admm never uses the reducers at
    all."""
    out = {}
    use_sb = hasattr(stream, "use_superblocks") and stream.use_superblocks()
    out["stream_shards"] = int(
        getattr(stream, "sb_data_shards", lambda: 1)()
    ) if use_sb else 1
    # 2-D mesh audit trail (ISSUE 18): the model-axis width the X tiles
    # actually sharded over (1 on 1-D meshes and wherever tiling was
    # refused), and WHY a 2-D mesh didn't tile (None when it did or
    # when there was no model axis to tile over)
    out["stream_model_shards"] = int(
        getattr(stream, "sb_model_shards", lambda: 1)()
    ) if use_sb else 1
    out["model_tile_reason"] = getattr(stream, "model_tile_reason",
                                       None)
    # the device-resident sparse flavor's audit trail (ISSUE 13),
    # mirroring fused_stream_reason: None iff the bucketed-nnz scan
    # carried the pass, else why it fell back — "stream-sparse-off",
    # the plan's density/spill reason, "per-block-path" (K == 1),
    # "admm-local-newton", or "dense-source" for dense inputs
    sparse_sb = bool(getattr(stream, "sb_sparse", lambda: False)())
    plan = getattr(stream, "sparse_plan", None)
    src_reason = getattr(stream, "sparse_reason", None)
    if sparse_sb and solver != "admm":
        out["sparse_stream"] = True
        out["sparse_stream_reason"] = None
    else:
        out["sparse_stream"] = False
        if sparse_sb and solver == "admm":
            out["sparse_stream_reason"] = "admm-local-newton"
        elif plan is not None:
            out["sparse_stream_reason"] = "per-block-path"
        elif src_reason is not None:
            out["sparse_stream_reason"] = src_reason
        else:
            out["sparse_stream_reason"] = "dense-source"
    info_kind = {"newton": "vgh", "admm": None}.get(solver, "vg")
    if info_kind is None:
        mxu, fused, reason = None, False, "admm-local-newton"
    elif out["sparse_stream"]:
        # the fused Pallas kernels are a dense-slab feature; the sparse
        # scan runs its own XLA programs
        mxu, fused, reason = None, False, "sparse-stream"
    elif not use_sb:
        mxu, fused, reason = None, False, "per-block-path"
    else:
        mxu, fused, _, reason = obj._sb_flavor(info_kind)
    out["fused_stream"] = bool(fused)
    out["fused_stream_reason"] = reason
    from ...config import fit_dtype_info

    if fused and mxu is not None:
        out.update(fit_dtype_info(fit_dtype))
    elif fused:
        # fused but f32 (the vgh/Hessian reducer rejects bf16)
        out.update({"fit_dtype": "float32",
                    "fit_dtype_source": "hessian-f32"})
    else:
        out.update({"fit_dtype": "float32",
                    "fit_dtype_source": "streamed-xla"})
    return out


def solve_streamed_multi(solver, stream, n_rows, B0, family, reg, lam,
                         pmask, l1_ratio=0.5, intercept=True, max_iter=100,
                         tol=1e-6, logger=None, reduce=None,
                         fit_dtype=None, ckpt=None, **kwargs):
    """One-vs-rest streamed fit: ``B0``/result are (C, d); ``pmask`` is
    the per-class (d,) mask, tiled here. Every epoch reads the data
    ONCE for all classes (class-stacked block kernels); the host solvers
    run unchanged on the flattened (C*d,) vector."""
    if solver not in STREAMED_SOLVERS:
        raise ValueError(
            f"Unknown solver {solver!r}; options: {sorted(STREAMED_SOLVERS)}"
        )
    B0 = np.asarray(B0, np.float32)
    C, d = B0.shape
    pmask_t = np.tile(np.asarray(pmask, np.float32), C)
    obj = MulticlassStreamedObjective(
        stream, n_rows, jnp.asarray(lam, jnp.float32),
        jnp.asarray(pmask_t), l1_ratio, family, reg, intercept,
        logger=logger, n_classes=C, reduce=reduce, fit_dtype=fit_dtype,
    )
    beta, info = STREAMED_SOLVERS[solver](
        obj, B0.ravel(), max_iter=max_iter, tol=tol, ckpt=ckpt, **kwargs
    )
    info["streamed"] = True
    info["n_blocks"] = stream.n_blocks
    info["n_classes"] = C
    info.update(_fused_stream_info(obj, stream, solver, fit_dtype))
    from .solvers import check_finite_result

    beta, info = check_finite_result(np.asarray(beta), info, solver)
    return np.asarray(beta).reshape(C, d), info
