"""Fleet-scope observability: one trace per request across processes,
one /metrics page for the whole fleet, one live terminal view.

`examples/13_request_traces.py` traced requests INSIDE one process and
`examples/14_federation.py` routed across processes — this example is
their join (ISSUE 19):

- **trace propagation** — the router mints a request trace and every
  process the request touches CONTINUES the same pid-prefixed id
  (``X-Trace-Context`` over HTTP, thread-local context in-process), so
  the Perfetto export draws one arrow from the router's admit through
  the worker's queue/pack/execute/demux stages;
- ``MetricsFederator``  — rides the federation status poller (the SAME
  ``/status`` scrape that feeds routing — no second fetch), folds every
  process's counters/gauges/histograms into fleet-wide
  ``dask_ml_tpu_fleet_*`` families on the router's ``/metrics``
  (counters sum, gauges get a ``{process=}`` label, latency histograms
  merge bucket-for-bucket) plus a ``/status/fleet`` JSON block with an
  SLO burn-rate and latched alerts;
- ``report --watch``    — ``python -m dask_ml_tpu.observability.report
  --watch http://router:9100`` re-renders the serving/fleet/trace
  tables in place while the run is live (``--once`` for CI).

Everything is host-side and off by default: ``obs_fleet_federate=False``
builds no federator, and the serving jaxprs are byte-identical either
way (asserted in ``tests/test_fleet_observability.py``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dask_ml_tpu import config
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.observability import _requests as rtrace
from dask_ml_tpu.observability import report as report_cli
from dask_ml_tpu.observability.live import TelemetryServer, render_prometheus
from dask_ml_tpu.serving import (
    BucketLadder,
    FederatedFleet,
    FleetServer,
    LocalEndpoint,
)

n = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 20_000))
X, y = make_classification(n_samples=n, n_features=16, n_informative=8,
                           random_state=0)
clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
Xh = X.to_numpy().astype(np.float32)
ladder = BucketLadder(8, 256, 2.0)

# -- a 2-"process" fleet with tracing + federation ON ------------------------
#    (LocalEndpoints are the virtual-process transport — against real
#    remote processes these are "http://host:port" strings and the
#    trace id rides the X-Trace-Context header)
with config.set(obs_trace_sample=1.0, obs_fleet_federate=True):
    f0 = FleetServer(clf, name="fobs", replicas=1, ladder=ladder,
                     batch_window_ms=1.0, timeout_ms=0).warmup().start()
    f1 = FleetServer(clf, name="fobs", replicas=1, ladder=ladder,
                     batch_window_ms=1.0, timeout_ms=0).warmup().start()
    ts = TelemetryServer(port=0).start()
    with FederatedFleet([LocalEndpoint(f0, "p0"), LocalEndpoint(f1, "p1")],
                        name="fobs", ladder=ladder, poll_s=0.2) as fed:
        for i in range(8):
            fed.predict(Xh[i * 16:(i + 1) * 16])

        # -- one request, one trace, two lanes -----------------------------
        recs = rtrace.traces_data()["traces"]
        router = [r for r in recs if r.get("federation") == "fobs"]
        rt = router[0]
        legs = [r for r in recs
                if r["trace_id"] == rt["trace_id"] and r is not rt]
        print(f"trace {rt['trace_id']}: router "
              f"{sorted(rt['stages'])} -> {rt['process']} "
              f"{sorted(legs[0]['stages'])}")
        assert {"admit", "queue_pop", "execute_done",
                "complete"} <= set(legs[0]["stages"])

        # -- the federated exposition --------------------------------------
        fed._poll_once()                 # (the poller does this on its own)
        fleet_lines = [ln for ln in render_prometheus().splitlines()
                       if ln.startswith("dask_ml_tpu_fleet_")
                       and "_bucket" not in ln]
        print("router /metrics fleet families:")
        for ln in fleet_lines[:8]:
            print(f"  {ln}")
        assert any(ln.startswith("dask_ml_tpu_fleet_processes 2")
                   for ln in fleet_lines)

        blk = fed._federator.fleet_block()
        print(f"/status/fleet: {blk['n_scraped']} processes scraped, "
              f"slo burn {blk['slo']['burn_rate']:.2f}x budget, "
              f"{len(blk['slo']['alerts'])} latched alerts")

        # -- the live terminal view (--once: one frame, CI-checkable) ------
        print("--- report --watch --once " + "-" * 34)
        rc = report_cli.main(["--watch", ts.url, "--once"])
        assert rc == 0

    ts.stop()
    f0.stop(drain=False)
    f1.stop(drain=False)

print("fleet observability example done")
