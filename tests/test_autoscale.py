"""SLO-driven autoscaling (dask_ml_tpu/serving/autoscale.py) and the
replay load-test harness (serving/loadtest.py).

The load-bearing assertions: queue pressure above the up-band GROWS the
fleet (new replica warmed off-path, installed under the lock, counted
and gauged), sustained headroom below the down-band RETIRES the
least-loaded replica with a graceful drain and DROPS its gauge series,
bounds/cooldown hold, and the replay harness turns a recorded mix into
a pass/fail SLO verdict (canary flip restored, outcome accounting
exact).
"""

import time

import numpy as np
import pytest

from dask_ml_tpu import config, observability as obs
from dask_ml_tpu.serving import (
    BucketLadder,
    FleetServer,
    ReplicaAutoscaler,
    replay_load_test,
    synthesize_records,
)
from dask_ml_tpu.serving.autoscale import ReplicaAutoscaler as _RA


@pytest.fixture(scope="module")
def two_logregs():
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(
        n_samples=600, n_features=12, n_informative=6, random_state=0
    )
    X2, y2 = make_classification(
        n_samples=600, n_features=12, n_informative=6, random_state=7
    )
    a = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
    b = LogisticRegression(solver="lbfgs", max_iter=30).fit(X2, y2)
    return a, b, X.to_numpy().astype(np.float32)


def _ladder():
    return BucketLadder(8, 64, 2.0)


def _seed_slow(fleet, exec_s=0.5):
    """Fake a warm, SLOW execution window on every replica so the
    predictor returns a confident big number."""
    for r in fleet.replicas:
        r._exec.observe("predict", fleet.ladder.max_rows, exec_s)


def _seed_fast(fleet, exec_s=1e-4):
    for r in fleet.replicas:
        r._exec.observe("predict", fleet.ladder.max_rows, exec_s)


# -- signal ------------------------------------------------------------------

def test_signal_none_on_cold_fleet(two_logregs):
    """A cold fleet (no execution history) neither grows nor shrinks."""
    a, _, _ = two_logregs
    fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder())
    with fleet:
        sc = ReplicaAutoscaler(fleet, min_replicas=1, max_replicas=3,
                               up_ms=10.0, down_ms=1.0, patience=1,
                               cooldown_s=0.0)
        assert sc.signal_ms() is None
        sc.tick()
        assert len(fleet.replicas) == 1
        assert sc.events == []


def test_band_defaults_derive_from_slo(two_logregs):
    a, _, _ = two_logregs
    with config.set(serving_slo_ms=200.0):
        fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder())
        sc = ReplicaAutoscaler(fleet)
        assert sc.up_ms == pytest.approx(160.0)
        assert sc.down_ms == pytest.approx(40.0)
        fleet.stop(drain=False)


# -- scale up ----------------------------------------------------------------

def test_scale_up_on_queue_pressure(two_logregs):
    """Predicted completion above the up-band for `patience` ticks adds
    a replica at the registry's current version — warmed, gauged,
    counted — and the hysteresis counters reset after the action."""
    a, _, Xh = two_logregs
    fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder(),
                        batch_window_ms=1.0, timeout_ms=0)
    with fleet.warmup():
        r0 = fleet.replicas[0]
        r0.pause()
        _seed_slow(fleet, 0.5)            # 500ms per batch
        futs = [fleet.submit(Xh[:32]) for _ in range(4)]   # queue rows
        sc = ReplicaAutoscaler(fleet, min_replicas=1, max_replicas=3,
                               up_ms=100.0, down_ms=1.0, patience=2,
                               cooldown_s=0.0)
        assert sc.signal_ms() > 100.0
        before = obs.counters_snapshot().get("serving_scale_ups", 0)
        sc.tick()
        assert len(fleet.replicas) == 1    # patience not yet met
        sc.tick()
        assert len(fleet.replicas) == 2
        assert sc.events[-1][0] == "up" and sc.events[-1][1] == 2
        after = obs.counters_snapshot().get("serving_scale_ups", 0)
        assert after - before == 1
        assert sc._above == 0
        fresh = fleet.replicas[-1]
        assert fresh.replica_id == 1
        assert fresh.model_version == fleet.version
        assert fresh.healthy
        # the fresh replica actually serves
        r0.resume()
        got = fleet.predict(Xh[:5])
        assert got.shape == (5,)
        for f in futs:
            f.result(30)


def test_scale_up_respects_max_and_cooldown(two_logregs):
    a, _, Xh = two_logregs
    fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder(),
                        batch_window_ms=1.0, timeout_ms=0)
    with fleet.warmup():
        fleet.replicas[0].pause()
        _seed_slow(fleet, 0.5)
        futs = [fleet.submit(Xh[:32]) for _ in range(4)]
        sc = ReplicaAutoscaler(fleet, min_replicas=1, max_replicas=2,
                               up_ms=50.0, down_ms=1.0, patience=1,
                               cooldown_s=60.0)
        sc.tick()
        assert len(fleet.replicas) == 2
        # above the band again, but inside the cooldown AND at max
        _seed_slow(fleet, 0.5)
        sc.tick()
        sc.tick()
        assert len(fleet.replicas) == 2
        for r in fleet.replicas:
            r.resume()
        for f in futs:
            f.result(30)


# -- scale down --------------------------------------------------------------

def test_scale_down_drains_and_drops_gauges(two_logregs):
    """Sustained headroom retires the least-loaded replica: removed
    from routing FIRST, drained gracefully, its serving_replica_* and
    queue gauge series dropped from the live registry."""
    from dask_ml_tpu.observability.live import (
        TelemetryServer,
        gauges_snapshot,
    )

    a, _, Xh = two_logregs
    with TelemetryServer(port=0):
        fleet = FleetServer(a, name="clf", replicas=2, ladder=_ladder(),
                            batch_window_ms=1.0)
        with fleet.warmup():
            _seed_fast(fleet)
            # traffic latches per-replica gauge series
            for _ in range(3):
                fleet.predict(Xh[:8])
            import dask_ml_tpu.serving.metrics as smetrics

            for r in fleet.replicas:
                smetrics.set_queue_gauges(0, 0, replica=r.replica_id)
            have = {(n, dict(ls).get("replica"))
                    for (n, ls) in gauges_snapshot()}
            assert ("serving_replica_healthy", "0") in have
            assert ("serving_queue_depth", "1") in have
            sc = ReplicaAutoscaler(fleet, min_replicas=1,
                                   max_replicas=2, up_ms=1e6,
                                   down_ms=1e5, patience=2,
                                   cooldown_s=0.0)
            before = obs.counters_snapshot().get("serving_scale_downs",
                                                 0)
            sc.tick()
            assert len(fleet.replicas) == 2
            sc.tick()
            assert len(fleet.replicas) == 1
            after = obs.counters_snapshot().get("serving_scale_downs",
                                                0)
            assert after - before == 1
            assert sc.events[-1][0] == "down"
            gone = "1" if fleet.replicas[0].replica_id == 0 else "0"
            have = {(n, dict(ls).get("replica"))
                    for (n, ls) in gauges_snapshot()}
            assert ("serving_replica_healthy", gone) not in have
            assert ("serving_queue_depth", gone) not in have
            # the survivor still serves and keeps its series
            assert fleet.predict(Xh[:4]).shape == (4,)
            sc.tick()   # at min: no further shrink
            assert len(fleet.replicas) == 1


def test_scale_down_never_below_min(two_logregs):
    a, _, _ = two_logregs
    fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder())
    with fleet:
        _seed_fast(fleet)
        sc = ReplicaAutoscaler(fleet, min_replicas=1, max_replicas=3,
                               up_ms=1e6, down_ms=1e5, patience=1,
                               cooldown_s=0.0)
        sc.tick()
        sc.tick()
        assert len(fleet.replicas) == 1
        assert sc.events == []


# -- arming from config ------------------------------------------------------

def test_autoscaler_armed_from_config(two_logregs):
    a, _, _ = two_logregs
    with config.set(serving_autoscale=True,
                    serving_autoscale_interval_s=0.05,
                    serving_slo_ms=100.0):
        fleet = FleetServer(a, name="clf", replicas=1,
                            ladder=_ladder())
        fleet.start()
        try:
            assert fleet._autoscaler is not None
            assert fleet._autoscaler._thread is not None
        finally:
            fleet.stop()
        assert fleet._autoscaler is None
    # default off
    fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder())
    with fleet:
        assert fleet._autoscaler is None


def test_scale_events_visible_in_loop(two_logregs):
    """The armed thread really scales: under faked pressure the loop
    adds a replica within a few intervals."""
    a, _, Xh = two_logregs
    fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder(),
                        batch_window_ms=1.0, timeout_ms=0,
                        autoscale=False)
    with fleet.warmup():
        fleet.replicas[0].pause()
        _seed_slow(fleet, 0.5)
        futs = [fleet.submit(Xh[:32]) for _ in range(4)]
        sc = ReplicaAutoscaler(fleet, min_replicas=1, max_replicas=2,
                               interval_s=0.05, up_ms=50.0,
                               down_ms=1.0, patience=1,
                               cooldown_s=10.0).start()
        try:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline \
                    and len(fleet.replicas) < 2:
                time.sleep(0.05)
            assert len(fleet.replicas) == 2
        finally:
            sc.stop()
        for r in fleet.replicas:
            r.resume()
        for f in futs:
            f.result(30)


# -- replay load test --------------------------------------------------------

def test_synthesize_records_deterministic():
    r1 = synthesize_records(50, methods=("predict", "predict_proba"),
                            rows=(1, 32), rate_rps=100.0, seed=3)
    r2 = synthesize_records(50, methods=("predict", "predict_proba"),
                            rows=(1, 32), rate_rps=100.0, seed=3)
    assert r1 == r2
    assert len(r1) == 50
    assert all(rec["req_capture"] for rec in r1)
    assert all(1 <= rec["n_rows"] <= 32 for rec in r1)
    assert {rec["method"] for rec in r1} \
        == {"predict", "predict_proba"}
    ts = [rec["t_unix"] for rec in r1]
    assert ts == sorted(ts)


def test_replay_load_test_verdict_and_accounting(two_logregs):
    """Every record resolves into exactly one outcome bucket; a healthy
    fleet under a generous SLO passes."""
    a, _, Xh = two_logregs
    fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder(),
                        batch_window_ms=1.0)
    with fleet.warmup():
        recs = synthesize_records(30, rows=(1, 32), rate_rps=500.0,
                                  seed=1)
        rep = replay_load_test(fleet, Xh, records=recs, speed=5.0,
                               slo_ms=30_000.0, quantile=99.0)
    assert rep["requests"] == 30
    assert rep["ok"] + rep["shed"] + rep["timeout"] + rep["error"] \
        == 30
    assert rep["ok"] == rep["admitted"] == 30
    assert rep["passed"] is True
    assert rep["latency_ms"]["p99"] is not None
    assert rep["latency_ms"]["p99"] <= 30_000.0


def test_replay_load_test_slo_miss_fails(two_logregs):
    """An absurd SLO budget fails the verdict (latency quantile above
    it) even with zero errors."""
    a, _, Xh = two_logregs
    fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder(),
                        batch_window_ms=1.0)
    with fleet.warmup():
        recs = synthesize_records(10, rows=(1, 16), rate_rps=500.0)
        rep = replay_load_test(fleet, Xh, records=recs, speed=10.0,
                               slo_ms=1e-4, quantile=99.0)
    assert rep["error"] == 0
    assert rep["passed"] is False


def test_replay_load_test_canary_flip_restores(two_logregs):
    """canary_version= runs the mix against an ARCHIVED version (a
    zero-recompile hot-swap) and flips back after — shadow canary."""
    a, b, Xh = two_logregs
    fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder(),
                        batch_window_ms=1.0)
    with fleet.warmup():
        v2 = fleet.publish(b)
        assert fleet.version == v2
        before = obs.counters_snapshot().get("recompiles", 0)
        recs = synthesize_records(10, rows=(1, 16), rate_rps=500.0)
        rep = replay_load_test(fleet, Xh, records=recs, speed=10.0,
                               slo_ms=30_000.0, canary_version=1)
        after = obs.counters_snapshot().get("recompiles", 0)
        assert rep["canary_version"] == 1
        assert rep["restored_version"] == v2
        assert rep["passed"] is True
        assert fleet.version == v2
        assert fleet.registry.current_version("clf") == v2
        assert after - before == 0
    assert _RA is ReplicaAutoscaler  # both export paths are one class


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_replay_load_test_factory_under_fault_plan(two_logregs):
    """A factory target is CONSTRUCTED inside the armed fault_plan
    scope (workers capture config at construction) and stopped by the
    harness; the chaos run's outcome accounting stays exact."""
    a, _, Xh = two_logregs

    def factory():
        return FleetServer(a, name="clf", replicas=2,
                           ladder=_ladder(), batch_window_ms=1.0,
                           timeout_ms=0,   # deadline-free: requeued
                           supervise=True).warmup().start()

    recs = synthesize_records(20, rows=(1, 16), rate_rps=300.0, seed=5)
    with config.set(serving_supervise_interval_s=0.1):
        rep = replay_load_test(factory, Xh, records=recs, speed=5.0,
                               slo_ms=30_000.0,
                               fault_plan="replica_worker:crash@3")
    assert rep["requests"] == 20
    assert rep["ok"] + rep["shed"] + rep["timeout"] + rep["error"] \
        == 20
    # the supervised fleet absorbs the worker crash: zero lost admits
    assert rep["error"] == 0 and rep["timeout"] == 0
