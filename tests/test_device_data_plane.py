"""Device data plane completeness (VERDICT r2 #4): ShardedArray inputs
must never round-trip the full dataset through host — not in the
wrappers, not in GLM label encoding, not in concurrent GridSearchCV over
sharded input. The spy counts every ShardedArray.to_numpy() pull."""

import numpy as np
import pytest

from dask_ml_tpu.parallel import as_sharded
from dask_ml_tpu.parallel.sharded import ShardedArray


@pytest.fixture()
def spy(monkeypatch):
    calls = []
    orig = ShardedArray.to_numpy

    def spy_fn(self):
        calls.append(self.n_rows)
        return orig(self)

    monkeypatch.setattr(ShardedArray, "to_numpy", spy_fn)
    return calls


@pytest.fixture(scope="module")
def xy_device():
    rng = np.random.RandomState(0)
    X = rng.randn(480, 8).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.randn(480) > 0).astype(np.float32)
    return X, y


def _no_full_pulls(calls, n):
    assert not any(c >= n for c in calls), calls


@pytest.mark.slow
def test_sgd_fit_stays_on_device(xy_device, spy):
    from dask_ml_tpu.models.sgd import SGDClassifier

    X, y = xy_device
    Xs, ys = as_sharded(X), as_sharded(y)
    clf = SGDClassifier(random_state=0, max_iter=5).fit(Xs, ys)
    _no_full_pulls(spy, len(X))
    assert clf.score(X, y) > 0.7


def test_incremental_wrapper_stays_on_device(xy_device, spy):
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.wrappers import Incremental

    X, y = xy_device
    Xs, ys = as_sharded(X), as_sharded(y)
    inc = Incremental(SGDClassifier(random_state=0), random_state=0)
    inc.fit(Xs, ys, classes=[0.0, 1.0])
    _no_full_pulls(spy, len(X))
    # the wrapped device model is fitted and usable
    assert inc.estimator_.coef_.shape == (1, 8)
    # parity with the host-input path
    inc_host = Incremental(SGDClassifier(random_state=0), random_state=0)
    inc_host.fit(X, y, classes=[0.0, 1.0])
    np.testing.assert_allclose(
        inc.estimator_.coef_, inc_host.estimator_.coef_, rtol=1e-4,
        atol=1e-5,
    )


def test_glm_encode_y_stays_on_device(xy_device, spy):
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = xy_device
    Xs, ys = as_sharded(X), as_sharded(y)
    clf = LogisticRegression(solver="lbfgs", max_iter=50).fit(Xs, ys)
    _no_full_pulls(spy, len(X))
    np.testing.assert_array_equal(clf.classes_, [0.0, 1.0])
    assert clf.score(Xs, ys) > 0.7


@pytest.mark.slow
def test_device_classes_integer_labels(xy_device):
    """Integer (and bool) label dtypes must work on the device path, as
    np.unique does on host, and classes_ keeps the label dtype."""
    from dask_ml_tpu.models.sgd import SGDClassifier

    X, y = xy_device
    yi = y.astype(np.int32)
    clf = SGDClassifier(random_state=0, max_iter=3).fit(
        as_sharded(X), as_sharded(yi)
    )
    np.testing.assert_array_equal(clf.classes_, [0, 1])
    assert np.issubdtype(clf.classes_.dtype, np.integer)
    assert set(np.unique(clf.predict(X))) <= {0, 1}


@pytest.mark.slow
def test_device_fit_explicit_classes_kwarg(xy_device):
    """fit(..., classes=[...]) must apply the classes on both data
    planes — labels like {-1, +1} would otherwise train un-encoded."""
    from dask_ml_tpu.models.sgd import SGDClassifier

    X, y = xy_device
    ypm = np.where(y > 0, 1.0, -1.0).astype(np.float32)
    dev = SGDClassifier(random_state=0, max_iter=5).fit(
        as_sharded(X), as_sharded(ypm), classes=[-1.0, 1.0]
    )
    np.testing.assert_array_equal(dev.classes_, [-1.0, 1.0])
    assert set(np.unique(dev.predict(X))) <= {-1.0, 1.0}
    assert dev.score(X, ypm) > 0.7
    host = SGDClassifier(random_state=0, max_iter=5).fit(
        X, ypm, classes=[-1.0, 1.0]
    )
    np.testing.assert_array_equal(host.classes_, [-1.0, 1.0])
    assert host.score(X, ypm) > 0.7


def test_glm_non_binary_dispatches_to_ovr(xy_device):
    # the binary-scan packed check now routes >2 classes to the
    # one-vs-rest path instead of raising (multiclass support)
    from dask_ml_tpu.linear_model import LogisticRegression

    X, _ = xy_device
    y3 = as_sharded(np.arange(len(X), dtype=np.float32) % 3)
    clf = LogisticRegression(solver="lbfgs", max_iter=15).fit(
        as_sharded(X), y3
    )
    assert clf.coef_.shape == (3, X.shape[1])


@pytest.mark.slow
def test_concurrent_gridsearch_sharded_stays_on_device(xy_device, spy):
    """Sharded input + explicit n_jobs: trials run on disjoint submeshes
    with DEVICE-resharded folds (no host_folds materialization)."""
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.model_selection import GridSearchCV

    X, y = xy_device
    Xs, ys = as_sharded(X), as_sharded(y)
    grid = {"C": [0.1, 1.0, 10.0]}
    est = LogisticRegression(solver="lbfgs", max_iter=50)
    conc = GridSearchCV(est, grid, cv=3, n_jobs=2, refit=False)
    conc.fit(Xs, ys)
    _no_full_pulls(spy, len(X))

    seq = GridSearchCV(est, grid, cv=3, scheduler="synchronous",
                       refit=False)
    seq.fit(Xs, ys)
    np.testing.assert_allclose(
        conc.cv_results_["mean_test_score"],
        seq.cv_results_["mean_test_score"], atol=1e-5,
    )
