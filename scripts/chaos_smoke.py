"""Chaos verify gate (ISSUE 11): the failure paths must actually work.

Three gates, each exercising one leg of the reliability plane:

1. **kill-mid-pass resume parity** — a SUBPROCESS streamed SGD fit with
   ``stream_checkpoint_path`` set is SIGKILLed mid-pass (a watcher
   thread in the child kills the process the moment the first pass's
   checkpoint publishes — so the kill often lands during the NEXT
   save, exercising the atomic writer too); rerunning the identical fit
   auto-resumes and must match an uninterrupted control fit to 1e-6,
   with the checkpoint slot cleared on completion.
2. **injected staging IO fault retried** — the same fit under
   ``fault_plan=staging_read:io@3`` + ``stream_io_retries`` completes
   bit-identically, with ``stream_retries_total`` /
   ``faults_injected_total`` > 0 scraped off the child's /metrics.
3. **replica kill under ragged traffic** — a 2-replica fleet with the
   supervisor armed loses one worker to an injected crash mid-traffic:
   the replica must be rebuilt+rewarmed off the serving path and rejoin
   routing, ZERO requests may be lost, and traffic after the rebuild's
   warmup must mint ZERO new XLA compiles.
4. **injected serving fault is trace-visible** (ISSUE 16) — a traced
   server under ``fault_plan=serving_execute:crash@0`` fails the first
   batch typed; every request in that batch must surface on the request
   trace plane tail-sampled with ``fault_injected`` tagged and outcome
   ``error``, while later healthy traffic traces clean.

Prints one JSON line per gate; exit 0 = all gates hold.
Run: ``python scripts/chaos_smoke.py``.
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one fit definition shared by control / killed / resumed / faulted
# children: deterministic data, shuffled passes (the lr-clock identity
# the resume contract must preserve)
CHILD_FIT = r"""
import json, os, sys, threading, time
import numpy as np
from dask_ml_tpu import config
from dask_ml_tpu.models.sgd import SGDClassifier
from dask_ml_tpu.observability import counters_snapshot

ckpt_dir = os.environ.get("CHAOS_CKPT", "")
kill = os.environ.get("CHAOS_KILL") == "1"

rng = np.random.RandomState(7)
X = rng.randn(200_000, 16).astype(np.float32)
y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float32)

if kill:
    def killer():
        # SIGKILL the moment the first pass's checkpoint publishes:
        # no cleanup handlers run — the restart sees exactly what
        # survived the atomic writer
        target = os.path.join(ckpt_dir, "sgd")
        while not os.path.exists(target):
            time.sleep(0.005)
        time.sleep(0.05)
        os.kill(os.getpid(), 9)

    threading.Thread(target=killer, daemon=True).start()

overrides = dict(stream_block_rows=8192)
if ckpt_dir:
    overrides["stream_checkpoint_path"] = ckpt_dir
with config.set(**overrides):
    clf = SGDClassifier(max_iter=10, random_state=0, shuffle=True).fit(X, y)
snap = counters_snapshot()
print("RESULT " + json.dumps({
    "coef": np.ravel(clf.coef_).tolist(),
    "intercept": np.ravel(np.atleast_1d(clf.intercept_)).tolist(),
    "resumes": snap.get("stream_resumes", 0),
    "saves": snap.get("stream_checkpoint_saves", 0),
    "retries": snap.get("stream_retries", 0),
    "ckpt_left": bool(ckpt_dir) and os.path.exists(
        os.path.join(ckpt_dir, "sgd")),
}), flush=True)
time.sleep(float(os.environ.get("CHAOS_LINGER", "0")))
"""

CHILD_FLEET = r"""
import json, threading, time
import numpy as np
from dask_ml_tpu import config
from dask_ml_tpu.models.sgd import SGDClassifier
from dask_ml_tpu.observability import counters_snapshot
from dask_ml_tpu.serving.fleet import FleetServer

rng = np.random.RandomState(3)
X = rng.randn(4000, 12).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)

with config.set(stream_block_rows=0):
    clf = SGDClassifier(max_iter=2, random_state=0).fit(X, y)

out = {"ok": False}
with config.set(serving_min_batch=8, serving_max_batch=64,
                serving_supervise=True,
                serving_supervise_interval_s=0.05,
                obs_drift=False,
                fault_plan="replica_worker:crash@120"):
    fleet = FleetServer(clf, replicas=2, timeout_ms=20000).warmup()
    with fleet:
        # per-thread result slots summed after join (a shared counter
        # += would lose increments under the GIL's preemption points)
        N_CLIENTS, PER = 4, 120
        oks = [0] * N_CLIENTS
        errs = []

        def client(slot):
            crng = np.random.RandomState(slot)
            for i in range(PER):
                n = int(crng.randint(1, 64))
                try:
                    p = fleet.predict(X[:n])
                    assert len(p) == n
                    oks[slot] += 1
                except Exception as exc:
                    errs.append(f"{type(exc).__name__}: {exc}")

        # phase 1: traffic that overlaps the injected worker crash
        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(N_CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        # the supervisor must have rebuilt the dead replica
        deadline = time.time() + 30
        while time.time() < deadline:
            snap = counters_snapshot()
            if snap.get("serving_replica_restarts", 0) >= 1 and \
                    sum(1 for r in fleet.replicas if r.healthy) == 2:
                break
            time.sleep(0.05)
        snap = counters_snapshot()
        out["restarts"] = snap.get("serving_replica_restarts", 0)
        out["healthy"] = sum(1 for r in fleet.replicas if r.healthy)
        out["phase1_ok"] = sum(oks)
        out["phase1_errors"] = errs[:5]
        # phase 2: the rebuilt replica is warmed — steady-state ragged
        # traffic must mint ZERO new XLA compiles from here on
        base_compiles = counters_snapshot().get("recompiles", 0)
        oks2 = [0] * N_CLIENTS
        errs2 = []

        def client2(slot):
            crng = np.random.RandomState(100 + slot)
            for i in range(PER):
                n = int(crng.randint(1, 64))
                try:
                    p = fleet.predict(X[:n])
                    assert len(p) == n
                    oks2[slot] += 1
                except Exception as exc:
                    errs2.append(f"{type(exc).__name__}: {exc}")

        threads = [threading.Thread(target=client2, args=(s,))
                   for s in range(N_CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        out["phase2_ok"] = sum(oks2)
        out["phase2_errors"] = errs2[:5]
        out["phase2_compiles"] = \
            counters_snapshot().get("recompiles", 0) - base_compiles
        out["ok"] = (
            out["restarts"] >= 1 and out["healthy"] == 2
            and not errs and not errs2
            and sum(oks) == N_CLIENTS * PER
            and sum(oks2) == N_CLIENTS * PER
            and out["phase2_compiles"] == 0
        )
print("RESULT " + json.dumps(out), flush=True)
"""


CHILD_FAULT_TRACE = r"""
import json
import numpy as np
from dask_ml_tpu import config, observability as obs
from dask_ml_tpu.models.sgd import SGDClassifier
from dask_ml_tpu.serving import BucketLadder, ModelServer, ServingError

rng = np.random.RandomState(3)
X = rng.randn(4000, 12).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)
with config.set(stream_block_rows=0):
    clf = SGDClassifier(max_iter=2, random_state=0).fit(X, y)

out = {"ok": False}
with config.set(obs_trace_sample=1.0,
                fault_plan="serving_execute:crash@0"):
    with ModelServer(clf, ladder=BucketLadder(8, 64, 2.0)) as srv:
        srv.warmup()
        f = srv.submit(X[:4])
        try:
            f.result(30)
            out["error"] = "faulted batch did not fail"
        except ServingError:
            pass
        # the plan fired once (@0): later traffic is healthy
        for i in range(4):
            srv.submit(X[: 2 + i]).result(30)
d = obs.traces_data()
errors = [t for t in d["traces"] if t["outcome"] == "error"]
clean = [t for t in d["traces"] if t["outcome"] == "ok"]
out["errors"] = len(errors)
out["clean"] = len(clean)
out["fault_tagged"] = sum(1 for t in errors if t.get("fault_injected"))
out["injected_counter"] = obs.counters_snapshot().get(
    "faults_injected_serving_execute", 0)
out.setdefault("ok", False)
out["ok"] = (
    len(errors) >= 1
    and out["fault_tagged"] == len(errors)
    and len(clean) == 4
    and not any(t.get("fault_injected") for t in clean)
    and out["injected_counter"] >= 1
)
print("RESULT " + json.dumps(out), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_child(code, env_extra=None, expect_kill=False, timeout=240):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **(env_extra or {})}
    child = subprocess.Popen(
        [sys.executable, "-c", code], env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        stdout, stderr = child.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        child.kill()
        stdout, stderr = child.communicate()
        raise RuntimeError(
            f"child timed out; stderr: {stderr.decode()[-2000:]}"
        )
    if expect_kill:
        if child.returncode == -signal.SIGKILL:
            return None
        raise RuntimeError(
            f"expected SIGKILL death, got rc={child.returncode}; "
            f"stderr: {stderr.decode()[-2000:]}"
        )
    for line in stdout.decode().splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(
        f"child (rc={child.returncode}) printed no RESULT; stderr: "
        + stderr.decode()[-2000:]
    )


def gate_resume(tmpdir):
    """Gate 1: SIGKILL mid-pass -> auto-resume -> parity 1e-6."""
    control = _run_child(CHILD_FIT)
    ckpt = os.path.join(tmpdir, "ckpt")
    os.makedirs(ckpt, exist_ok=True)
    _run_child(CHILD_FIT, {"CHAOS_CKPT": ckpt, "CHAOS_KILL": "1"},
               expect_kill=True)
    if not os.path.exists(os.path.join(ckpt, "sgd")):
        raise RuntimeError("killed child left no checkpoint behind")
    resumed = _run_child(CHILD_FIT, {"CHAOS_CKPT": ckpt})
    if resumed["resumes"] < 1:
        raise RuntimeError(f"rerun did not resume: {resumed}")
    if resumed["ckpt_left"]:
        raise RuntimeError("completed fit left its checkpoint behind")
    import numpy as np

    err = float(np.abs(
        np.asarray(resumed["coef"]) - np.asarray(control["coef"])
    ).max())
    ierr = float(np.abs(
        np.asarray(resumed["intercept"])
        - np.asarray(control["intercept"])
    ).max())
    if max(err, ierr) > 1e-6:
        raise RuntimeError(
            f"resume parity {max(err, ierr):.3g} > 1e-6"
        )
    return {"gate": "resume", "ok": True, "coef_err": err,
            "resumes": resumed["resumes"], "saves": resumed["saves"]}, \
        control


def gate_io_retry(control):
    """Gate 2: injected staging IOError retried; counters on /metrics;
    result bit-identical to the clean control fit."""
    port = _free_port()
    env = {
        "DASK_ML_TPU_FAULT_PLAN": "staging_read:io@3",
        "DASK_ML_TPU_STREAM_IO_RETRIES": "2",
        "DASK_ML_TPU_OBS_HTTP_PORT": str(port),
        "CHAOS_LINGER": "15",
    }
    child = subprocess.Popen(
        [sys.executable, "-c", CHILD_FIT],
        env={**os.environ, "JAX_PLATFORMS": "cpu", **env}, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        result = None
        metrics = ""
        deadline = time.time() + 240
        while time.time() < deadline:
            line = child.stdout.readline().decode()
            if line.startswith("RESULT "):
                result = json.loads(line[len("RESULT "):])
                break
            if not line and child.poll() is not None:
                raise RuntimeError(
                    "fault child died: "
                    + child.stderr.read().decode()[-2000:]
                )
        if result is None:
            raise RuntimeError("fault child never printed RESULT")
        # scrape the lingering child's /metrics for the counters
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            metrics = r.read().decode()
    finally:
        child.terminate()
        try:
            child.wait(10)
        except Exception:
            child.kill()
    retried = re.search(r"^dask_ml_tpu_stream_retries_total (\d+)",
                        metrics, re.MULTILINE)
    injected = re.search(r"^dask_ml_tpu_faults_injected_total (\d+)",
                         metrics, re.MULTILINE)
    if not retried or int(retried.group(1)) < 1:
        raise RuntimeError("stream_retries_total missing/zero on /metrics")
    if not injected or int(injected.group(1)) < 1:
        raise RuntimeError("faults_injected_total missing/zero on /metrics")
    import numpy as np

    err = float(np.abs(
        np.asarray(result["coef"]) - np.asarray(control["coef"])
    ).max())
    if err > 1e-6:
        raise RuntimeError(f"faulted-fit parity {err:.3g} > 1e-6")
    return {"gate": "io_retry", "ok": True,
            "retries": int(retried.group(1)),
            "injected": int(injected.group(1)), "coef_err": err}


def gate_replica_restart():
    """Gate 3: replica crash under ragged traffic -> supervised rebuild,
    zero lost requests, zero post-rewarm compiles."""
    result = _run_child(CHILD_FLEET, timeout=400)
    if not result.get("ok"):
        raise RuntimeError(f"fleet chaos gate failed: {result}")
    return {"gate": "replica_restart", **result}


def gate_fault_trace():
    """Gate 4: an injected serving_execute fault's batch is tagged
    fault_injected on the request trace plane; healthy traffic after
    the one-shot arm traces clean."""
    result = _run_child(CHILD_FAULT_TRACE, timeout=240)
    if not result.get("ok"):
        raise RuntimeError(f"fault-trace gate failed: {result}")
    return {"gate": "fault_trace", **result}


def main():
    import tempfile

    tmpdir = tempfile.mkdtemp(prefix="chaos_smoke_")
    rc = 0
    try:
        g1, control = gate_resume(tmpdir)
        print(json.dumps(g1))
        print(json.dumps(gate_io_retry(control)))
        print(json.dumps(gate_replica_restart()))
        print(json.dumps(gate_fault_trace()))
    except Exception as exc:
        print(json.dumps({"ok": False,
                          "error": f"{type(exc).__name__}: {exc}"}))
        rc = 1
    finally:
        import shutil

        shutil.rmtree(tmpdir, ignore_errors=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
