"""Device-mesh management — the substrate every estimator runs on.

In the reference (dask-ml), data lives as row-chunked ``dask.array`` blocks
scheduled over workers connected by TCP (``distributed/comm``); here the
equivalent substrate is a ``jax.sharding.Mesh`` over TPU chips, with XLA
collectives over ICI replacing the comm layer entirely (SURVEY.md §5,
"Distributed communication backend").

The default mesh is 1-D over all visible devices with axis name ``"data"``
(pure data-parallel — the reference's row-chunking model, SURVEY.md §2c).
A 2-D ``("data", "model")`` mesh is supported for wide-feature problems
where sharding the feature axis pays (the reference's nearest analog is
dask.array 2-D blockwise matmul).
"""

from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

_state = threading.local()


def device_mesh(shape=None, axis_names=(DATA_AXIS,), devices=None,
                topology_order=None) -> Mesh:
    """Build a mesh over ``devices`` (default: all of ``jax.devices()``).

    ``shape=None`` gives a 1-D mesh over every device. ``shape`` may use -1
    for one axis (inferred), e.g. ``device_mesh((-1, 2), ("data", "model"))``.

    On TPU the device order is TOPOLOGY-AWARE (``mesh_utils``): mesh
    neighbors are ICI neighbors, and on multi-host runs the slow DCN hop
    is the OUTER factor of the data axis — collectives then ride ICI
    rings within a host/slice and cross DCN once, instead of ping-ponging
    over DCN in enumeration order. CPU/GPU keep plain enumeration order.

    ``topology_order`` — None (default): reorder only when ``devices`` is
    omitted (explicit lists keep the caller's order, e.g. disjoint search
    submeshes); True: force reordering even for an explicit full-device
    list (``global_mesh``/``local_mesh`` pass this); False: never.
    """
    explicit = devices is not None
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices, dtype=object)
    n = devices.size
    if shape is None:
        shape = (n,)
    shape = tuple(shape)
    if len(shape) != len(axis_names):
        raise ValueError(f"shape {shape} does not match axis_names {axis_names}")
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        if n % known:
            raise ValueError(f"cannot infer -1 in {shape} from {n} devices")
        shape = tuple(n // known if s == -1 else s for s in shape)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} needs {int(np.prod(shape))} devices, have {n}")
    if topology_order is None:
        topology_order = not explicit
    if topology_order and devices.flat[0].platform == "tpu":
        arranged = _topology_mesh(shape, list(devices.flat))
        if arranged is not None:
            return Mesh(arranged, axis_names)
    return Mesh(devices.reshape(shape), axis_names)


def _topology_mesh(shape, devices):
    """TPU device array in torus-aware order, or None when the topology
    helpers decline (odd shapes, unsupported slice forms) — the caller
    then falls back to enumeration order."""
    try:
        from jax.experimental import mesh_utils

        n_procs = len({d.process_index for d in devices})
        if n_procs > 1 and len(devices) % n_procs == 0:
            if shape[0] % n_procs == 0:
                # DCN outer on the (leading) data axis, ICI inner
                ici = (shape[0] // n_procs,) + tuple(shape[1:])
                dcn = (n_procs,) + (1,) * (len(shape) - 1)
                # granule = process (we factor by process count), not the
                # default slice granule — a multi-host single slice would
                # otherwise mismatch dcn and raise
                return mesh_utils.create_hybrid_device_mesh(
                    ici, dcn, devices=devices, process_is_granule=True
                )
        return mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        return None


def default_mesh() -> Mesh:
    """The ambient mesh: the one set by :func:`use_mesh`, else a cached 1-D
    data mesh over all devices."""
    mesh = getattr(_state, "mesh", None)
    if mesh is not None:
        return mesh
    cached = getattr(_state, "cached_default", None)
    if cached is None or cached.devices.size != len(jax.devices()):
        cached = device_mesh()
        _state.cached_default = cached
    return cached


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Context manager: make ``mesh`` the ambient mesh for estimators that
    don't receive one explicitly."""
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def resolve_mesh(mesh=None) -> Mesh:
    return mesh if mesh is not None else default_mesh()


def data_shard_spec(a, lead: int = 0) -> P:
    """PartitionSpec sharding axis ``lead`` of ``a`` over the "data"
    axis, every other axis replicated — the ONE spec builder the
    sharded superblock scan programs (GLM reducers, SGD scan, KMeans
    assign-stats) use for their block operands, so a future mesh-shape
    change lands in one place."""
    return P(*((None,) * lead + (DATA_AXIS,)
               + (None,) * (a.ndim - lead - 1)))


def stream_data_mesh() -> Mesh:
    """The mesh streamed (out-of-core) fits shard over, resolved from
    ``config.stream_mesh``: 0 = the ambient/default mesh (all local
    devices — data-parallel streaming engages whenever >1 device is
    visible), 1 = a single-device mesh (the sharded superblock flavor
    never engages), N = the first N local devices. Cached per resolved
    device set so every BlockStream of a fit sees the SAME Mesh object
    (scan programs are lru-cached with the mesh in their key)."""
    from ..config import get_config

    n = int(get_config().stream_mesh)
    if n <= 0:
        return default_mesh()
    devices = jax.devices()[: max(min(n, len(jax.devices())), 1)]
    key = (n, len(devices), tuple(d.id for d in devices))
    cached = getattr(_state, "stream_meshes", None)
    if cached is None:
        cached = _state.stream_meshes = {}
    mesh = cached.get(key)
    if mesh is None:
        mesh = cached[key] = device_mesh(devices=devices)
    return mesh


def data_shards(mesh: Mesh) -> int:
    """Number of shards along the data (row) axis."""
    return mesh.shape[DATA_AXIS] if DATA_AXIS in mesh.shape else 1


def row_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """NamedSharding for an array whose leading axis is row-sharded."""
    spec = (DATA_AXIS,) + (None,) * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
