"""Host→device block streaming for larger-than-HBM datasets.

Reference equivalent: dask's chunk scheduling — blocks materialize on
workers as tasks run (SURVEY.md §2b row 1). TPU design (SURVEY.md §7
design stance #1, "the heart of the system"): the working set lives in
host RAM (numpy / np.memmap); fixed-shape blocks are placed onto the mesh
with ``jax.device_put`` AHEAD of compute (device_put is async — issuing
the next transfer before consuming the current block overlaps DMA with
compute, the double-buffer pattern). A consumed block's HBM is released
when its Python reference drops at the next loop iteration, so peak
footprint is ≈ (prefetch + 1) blocks.

Blocks have a fixed padded shape (static shapes for jit); the final
partial block carries its logical row count and a mask.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, data_shards, resolve_mesh


class SparseBlocks:
    """Row-concatenated view over a list of scipy sparse (CSR) blocks —
    the shape a blocked vectorizer naturally produces — WITHOUT the
    ``sp.vstack`` copy. Only supports what streaming needs: ``shape``,
    ``dtype`` and contiguous row-range densification.

    Ref: dask_ml/feature_extraction/text.py produces a dask array of CSR
    chunks; this is its host-side analog feeding BlockStream.
    """

    def __init__(self, blocks):
        blocks = [b.tocsr() if not sp.isspmatrix_csr(b) else b
                  for b in blocks]
        if not blocks:
            raise ValueError("SparseBlocks needs at least one block")
        d = blocks[0].shape[1]
        for b in blocks:
            if b.shape[1] != d:
                raise ValueError("blocks have inconsistent widths")
        self.blocks = blocks
        self.offsets = np.cumsum([0] + [b.shape[0] for b in blocks])
        self.shape = (int(self.offsets[-1]), d)
        self.dtype = blocks[0].dtype
        self.ndim = 2

    def tocsr(self):
        """Materialize as one CSR (O(nnz)) — for host consumers that
        need arbitrary row slicing (e.g. host-estimator block loops)."""
        return sp.vstack(self.blocks).tocsr()

    def slice_dense(self, lo, hi, dtype=np.float32):
        """Densify rows [lo, hi) — touches only the blocks they span."""
        if hi <= lo:
            return np.empty((0, self.shape[1]), dtype)
        i = int(np.searchsorted(self.offsets, lo, side="right") - 1)
        parts = []
        while lo < hi and i < len(self.blocks):
            b_lo, b_hi = self.offsets[i], self.offsets[i + 1]
            take = min(hi, b_hi) - lo
            parts.append(
                _csr_dense(self.blocks[i], lo - b_lo, lo - b_lo + take,
                           dtype)
            )
            lo += take
            i += 1
        return parts[0] if len(parts) == 1 \
            else np.concatenate(parts, axis=0)


def _is_sparse_source(a) -> bool:
    return sp.issparse(a) or isinstance(a, SparseBlocks)


def _n_rows_of(a) -> int:
    # len() raises on scipy sparse ("length is ambiguous")
    return int(a.shape[0]) if _is_sparse_source(a) else len(a)


def _csr_dense(a, lo, hi, dtype):
    """Densify CSR rows [lo, hi) straight into ``dtype`` — casting the
    nnz values first, so the transient is ONE dense block, not a
    float64 block plus its cast copy."""
    blk = a[lo:hi]
    if blk.dtype != dtype:
        blk = blk.astype(dtype)
    return blk.toarray()


def as_row_sliceable(a):
    """Normalize a sparse source to a row-sliceable form (CSR) ONCE —
    call this before a loop of ``_slice_dense`` calls; ``tocsr()`` is
    identity for CSR but O(nnz) for COO/CSC/BSR."""
    return a.tocsr() if sp.issparse(a) and not sp.isspmatrix_csr(a) else a


def _slice_dense(a, lo, hi, dtype):
    """One host block of ``a`` as a dense array — the single densify
    point for sparse sources (O(block) host memory, never the corpus).
    Non-CSR sparse is converted defensively (COO/BSR cannot row-slice);
    loops should pre-normalize with ``as_row_sliceable``."""
    if isinstance(a, SparseBlocks):
        return a.slice_dense(lo, hi, dtype)
    if sp.issparse(a):
        return _csr_dense(a.tocsr(), lo, hi, dtype)
    return np.asarray(a[lo:hi], dtype=dtype)


class Block:
    """One streamed block: device data + logical row count."""

    __slots__ = ("arrays", "n_rows", "mask")

    def __init__(self, arrays, n_rows, mask):
        self.arrays = arrays
        self.n_rows = n_rows
        self.mask = mask


# auto block budget: bytes of ONE block's X on device. Fixed bytes (not a
# fraction of n) so an arbitrarily large memmap still streams in
# HBM-bounded blocks; peak device footprint ≈ (prefetch + 1) blocks.
_AUTO_BLOCK_BYTES = 256 << 20


def auto_block_rows(n_rows: int, row_bytes: int = 4) -> int:
    """Block size from config: ``stream_block_rows`` if set, else an
    HBM byte budget divided by the bytes-per-row of the streamed data."""
    from ..config import get_config

    br = get_config().stream_block_rows
    if br and br > 0:
        return int(br)
    return max(_AUTO_BLOCK_BYTES // max(int(row_bytes), 1), 1)


def grid_partition(n_pad: int, D: int) -> tuple[int, int]:
    """(n_blocks B, rows-per-block S) for ``n_pad`` rows on a D-way data
    axis: at least max(D, 8) blocks — the epoch must yield multiple
    minibatch steps even on a 1-device mesh (a D-only split would
    collapse a single-chip host fit to ONE gradient step per epoch) —
    with S rounded up to a multiple of D so a (B, S, d) block grid's row
    axis shards evenly. The one partition formula behind the fused-epoch
    grid, the Incremental wrapper's block loops, and the SGD host fit —
    device- and host-input fits of the same data train identical
    minibatches."""
    n_pad = max(n_pad, 1)
    target = max(D, 8)
    s = -(-n_pad // target)
    S = max(-(-s // D) * D, 1)
    return -(-n_pad // S), S


def fit_block_rows(X, mesh=None) -> int:
    """Rows per block for an epoch-style fit over host data: the
    ``grid_partition`` size for the resolved mesh, capped by
    ``stream_plan``'s byte budget when X is a source that must stream in
    bounded dense blocks (sparse, memmap, configured block rows) — the
    ONE block-size policy shared by the SGD fit loop and
    ``Incremental._block_size``."""
    n = int(X.shape[0]) if hasattr(X, "shape") else len(X)
    D = max(data_shards(resolve_mesh(mesh)), 1)
    S = max(grid_partition(-(-max(n, 1) // D) * D, D)[1], 1)
    budget = stream_plan(X)
    return S if budget is None else max(min(S, budget), 1)


def stream_plan(X) -> int | None:
    """Rows-per-block when ``X`` should be fitted out-of-core, else None.

    Streams when X is host-resident and either (a) an ``np.memmap`` —
    its backing file may exceed host AND device memory, so it must never
    be materialized whole — or (b) larger than a configured
    ``config.stream_block_rows``. Device-resident inputs (ShardedArray /
    jax.Array) always take the resident path.
    """
    from ..config import get_config

    if _is_sparse_source(X):
        # sparse ALWAYS streams: the device representation is dense, so
        # the only scalable bridge is one densified block at a time
        # (VERDICT r4 missing #2; ref text.py CSR chunks → per-block fit)
        n = X.shape[0]
        if n == 0:
            return None
        row_bytes = 4 * int(np.prod(X.shape[1:], dtype=np.int64) or 1)
        return min(auto_block_rows(n, row_bytes), n)
    if not isinstance(X, np.ndarray) or isinstance(X, np.generic):
        return None
    n = X.shape[0] if X.ndim else 0
    if n == 0:
        return None
    if isinstance(X, np.memmap):
        # blocks stream as float32 regardless of the memmap dtype
        row_bytes = 4 * int(np.prod(X.shape[1:], dtype=np.int64) or 1)
        return min(auto_block_rows(n, row_bytes), n)
    br = get_config().stream_block_rows
    if br and 0 < br < n:
        return br
    return None


class BlockStream:
    """Prefetched epoch iterator over host arrays.

    Parameters
    ----------
    arrays : tuple of host arrays (np.ndarray / np.memmap), equal length.
    block_rows : rows per block (rounded up to a multiple of the mesh's
        data-axis size); None reads ``config.stream_block_rows``, falling
        back to an HBM byte budget divided by the arrays' combined
        bytes-per-row.
    shuffle : shuffle block order each epoch (the reference's
        ``shuffle_blocks``); rows within a block keep locality.
    prefetch : transfers kept in flight ahead of compute (1 = classic
        double buffering); None reads ``config.stream_prefetch``.
    """

    def __init__(self, arrays, block_rows=None, mesh=None, shuffle=False,
                 seed=None, dtype=np.float32, prefetch=None):
        if mesh is None:
            from . import distributed as dist

            if dist.process_count() > 1:
                # live multi-process runtime: blocks are PROCESS-LOCAL
                # data — they shard over this process's devices only
                # (a global-mesh device_put asserts value equality
                # across processes); cross-process merging is the
                # consumer's explicit psum_host of its block sums
                mesh = dist.local_mesh()
        self.mesh = resolve_mesh(mesh)
        # sparse sources normalize to CSR once: COO/BSR don't support
        # row slicing at all and CSC slices rows in O(nnz)
        self.arrays = tuple(
            a.tocsr() if sp.issparse(a) and not sp.isspmatrix_csr(a)
            else a
            for a in arrays
        )
        n = _n_rows_of(self.arrays[0])
        for a in self.arrays:
            if _n_rows_of(a) != n:
                raise ValueError("arrays have inconsistent lengths")
        self.n_rows = n
        # dense bytes-per-row of everything this stream puts on device —
        # sizes the auto block AND caps autotune growth at the same
        # byte budget (growth must not defeat the HBM bound)
        self._row_bytes = sum(
            4 * int(np.prod(a.shape[1:], dtype=np.int64) or 1)
            for a in self.arrays
        )
        if block_rows is None:
            block_rows = min(auto_block_rows(n, self._row_bytes), n)
        if prefetch is None:
            from ..config import get_config

            prefetch = get_config().stream_prefetch
        self.prefetch = max(int(prefetch), 1)
        shards = data_shards(self.mesh)
        self.block_rows = max(
            int(np.ceil(block_rows / shards)) * shards, shards
        )
        self.shuffle = shuffle
        self.rng = np.random.RandomState(seed)
        self.dtype = dtype
        self.n_blocks = int(np.ceil(n / self.block_rows))
        self._shardings = tuple(
            NamedSharding(self.mesh, P(*((DATA_AXIS,) + (None,) * (a.ndim - 1))))
            for a in self.arrays
        )
        self._mask_sharding = NamedSharding(self.mesh, P(DATA_AXIS))

    def _verify_native(self):
        """Which arrays the C++ readahead reader can serve, verified by
        comparing its block 0 against the numpy slice — catches sliced /
        re-offset memmap views whose .offset no longer describes them."""
        from ..io.native import NativeBlockReader, load_block_reader

        oks = []
        for a in self.arrays:
            ok = False
            if (type(a) is np.memmap and a.flags["C_CONTIGUOUS"]
                    and getattr(a, "filename", None) is not None
                    and load_block_reader() is not None):
                try:
                    # the offset/contiguity property is independent of
                    # block size: verify with a SMALL block instead of
                    # double-reading a full (possibly 256 MB) one.
                    # equal_nan: datasets with missing values must not
                    # silently lose the readahead path
                    vb = min(self.block_rows, len(a), 4096)
                    r = NativeBlockReader(a, vb)
                    blk = r.next()
                    ok = blk is not None and np.array_equal(
                        blk, np.asarray(a[: len(blk)]),
                        equal_nan=np.issubdtype(a.dtype, np.floating),
                    )
                    r.close()
                except Exception:
                    ok = False
            oks.append(ok)
        return oks

    def _native_readers(self):
        """Per-array readahead readers for a SEQUENTIAL pass (None where
        inapplicable); the reader thread pread()s blocks ahead of the
        consumer, overlapping disk latency with device transfer/compute
        (native/block_reader.cpp)."""
        if self.shuffle:
            return None
        if getattr(self, "_native_ok", None) is None:
            self._native_ok = self._verify_native()
        if not any(self._native_ok):
            return None
        from ..io.native import NativeBlockReader

        return [
            NativeBlockReader(a, self.block_rows) if ok else None
            for ok, a in zip(self._native_ok, self.arrays)
        ]

    def _block_host(self, b, readers=None):
        lo = b * self.block_rows
        hi = min(lo + self.block_rows, self.n_rows)
        m = hi - lo
        outs = []
        for i, a in enumerate(self.arrays):
            if readers is not None and readers[i] is not None:
                raw = readers[i].next()
                # copy out: the reader's ring buffer is reused, and
                # device_put reads the host buffer asynchronously
                blk = raw.astype(self.dtype, copy=True)
            else:
                blk = _slice_dense(a, lo, hi, self.dtype)
            if m < self.block_rows:  # fixed shape: pad the tail block
                pad = [(0, self.block_rows - m)] + [(0, 0)] * (blk.ndim - 1)
                blk = np.pad(blk, pad)
            outs.append(blk)
        mask = np.zeros(self.block_rows, self.dtype)
        mask[:m] = 1.0
        return outs, m, mask

    def _put(self, host_block):
        outs, m, mask = host_block
        from ..observability import record_transfer

        record_transfer(sum(a.nbytes for a in outs) + mask.nbytes)
        dev = tuple(
            jax.device_put(a, s) for a, s in zip(outs, self._shardings)
        )
        return Block(dev, m, jax.device_put(mask, self._mask_sharding))

    def __iter__(self):
        import time as _time

        order = np.arange(self.n_blocks)
        if self.shuffle:
            self.rng.shuffle(order)
        readers = None
        if not self.shuffle:
            try:
                readers = self._native_readers()
            except Exception:
                readers = None
        # per-pass overlap accounting (SURVEY §7 B0: the double buffer is
        # the heart of the system — measure it, don't assume it):
        #   host_s   — disk/densify/pad time building host blocks
        #   put_s    — host-side device_put issue time
        #   wait_s   — time the CONSUMER would stall: popped block's
        #              transfer not yet complete (overlap shortfall)
        #   consume_s— time the consumer held each block (its compute)
        stats = {"host_s": 0.0, "put_s": 0.0, "wait_s": 0.0,
                 "consume_s": 0.0, "n_blocks": int(self.n_blocks),
                 "block_rows": int(self.block_rows)}
        t_pass = _time.perf_counter()
        # k-deep prefetch: device_put is async, so issuing the next k
        # transfers before consuming the current block overlaps DMA with
        # compute (k=1 is the classic double buffer)
        from collections import deque

        pending = deque()
        from ..observability import NOOP_SPAN, span

        def pop():
            blk = pending.popleft()
            if measure_wait:
                t0 = _time.perf_counter()
                jax.block_until_ready(blk.arrays)
                stats["wait_s"] += _time.perf_counter() - t0
            return blk

        def emit(blk):
            # consume = wall time the generator is SUSPENDED at this
            # yield — exactly the consumer's per-block work
            t_y = _time.perf_counter()
            yield blk
            stats["consume_s"] += _time.perf_counter() - t_y

        # one span per pass: nests under the enclosing fit span and
        # carries the overlap stats + transfer-counter deltas at close
        with span("stream.pass") as sp:
            # the readiness sync serializes the host loop behind each
            # block's transfer, trading a little overlap for the wait_s
            # signal — only pay it when someone consumes the signal: a
            # recording sink (the span resolved one — bound fit logger
            # or configured trace/metrics path, where an unmeasured 0.0
            # would read as "perfectly overlapped") or an autotune pass
            measure_wait = sp is not NOOP_SPAN or getattr(
                self, "_autotune_pass", False
            )
            try:
                for b in order:
                    t0 = _time.perf_counter()
                    hb = self._block_host(b, readers)
                    t1 = _time.perf_counter()
                    stats["host_s"] += t1 - t0
                    pending.append(self._put(hb))
                    stats["put_s"] += _time.perf_counter() - t1
                    if len(pending) > self.prefetch:
                        yield from emit(pop())
                while pending:
                    yield from emit(pop())
            finally:
                stats["pass_s"] = _time.perf_counter() - t_pass
                self.stats = stats
                self._passes = getattr(self, "_passes", 0) + 1
                # the span record IS the per-pass JSONL record (via the
                # thread-bound fit logger or the configured trace sink);
                # `stream_pass` keys it for consumers and the report CLI
                sp.add(stream_pass=self._passes,
                       **{k: (round(v, 6) if isinstance(v, float) else v)
                          for k, v in stats.items()})
                if readers:
                    for r in readers:
                        if r is not None:
                            r.close()

    def _maybe_grow_blocks(self):
        """Epoch-boundary block autotune: when a pass spends more HOST
        time preparing blocks (slice/densify/pad + put issue) than the
        consumer holds them, the per-block fixed costs dominate — double
        the block so fewer, larger transfers amortize them. wait_s is
        deliberately NOT part of the signal: under async dispatch the
        device's compute backlog surfaces as transfer wait, and growing
        blocks doesn't reduce bytes moved — it would misfire on
        compute-bound fits. Only between ``epochs()`` passes (per-block
        solver state like ADMM's never sees a resize), at most twice,
        and only when there are enough blocks that halving their count
        still keeps the mesh busy."""
        st = getattr(self, "stats", None)
        if st is None or self._passes > 2 or self.n_blocks < 16:
            return
        if st["host_s"] + st["put_s"] <= st["consume_s"]:
            return
        shards = data_shards(self.mesh)
        # never grow past the byte budget that bounds device footprint
        # (a block already AT the budget stays there)
        budget_rows = max(_AUTO_BLOCK_BYTES // max(self._row_bytes, 1), 1)
        cap = min(int(np.ceil(self.n_rows / shards)) * shards,
                  max(budget_rows, self.block_rows))
        new_rows = min(self.block_rows * 2, cap)
        if new_rows <= self.block_rows:
            return
        self.block_rows = new_rows
        self.n_blocks = int(np.ceil(self.n_rows / self.block_rows))

    def __len__(self):
        return self.n_blocks

    def epochs(self, n_epochs, autotune=None):
        if autotune is None:
            from ..config import get_config

            autotune = get_config().stream_autotune
        self._autotune_pass = bool(autotune)  # enables wait_s measuring
        try:
            for e in range(n_epochs):
                yield from self
                if autotune and e < n_epochs - 1:
                    self._maybe_grow_blocks()
        finally:
            self._autotune_pass = False


def streamed_map(X, block_rows, fn):
    """Map ``fn(block) -> host array (block_valid_rows, ...)`` over X's
    blocks and concatenate — the one stream→compute→host pattern shared by
    every streamed inference path (GLM decision values, KMeans labels /
    distances, PCA scores). ``fn`` receives the padded device block; its
    output is sliced to the block's logical rows here."""
    outs = []
    for blk in BlockStream((X,), block_rows=block_rows):
        outs.append(np.asarray(fn(blk))[: blk.n_rows])
    return np.concatenate(outs, axis=0)
