"""Perf smoke gate for the super-block streaming hot loop (ISSUE 3 +
the ISSUE 9 data-parallel flavor).

Runs a scaled-down version of bench.py's streamed-SGD section and fails
(exit 1) when the dispatch-collapse contract regresses:

- ``dispatches_per_pass`` must not exceed ceil(n_blocks / superblock_k)
  + 1 — the whole point of super-block execution is one XLA dispatch
  per K blocks, so a pass that dispatches per block again is a
  regression even if it still passes the numeric tests;
- after the first pass has warmed the compile caches, later passes must
  pay ZERO new XLA compiles — a shape wobble (ragged tail leaking into
  the compiled signature, ring buffers changing layout) shows up here
  long before it shows up as a throughput number;
- the SHARDED flavor (8 virtual devices, shard_map + psum scan
  programs) must keep exactly the same dispatch shape: ceil(n_blocks/K)
  dispatches per pass — one per super-block, NOT one per shard — and
  the same zero-compiles-after-pass-1 contract.

Kept small (~64k rows) so verify.sh stays fast; bench.py carries the
full-size throughput numbers.
"""

import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8 virtual devices BEFORE jax initializes so the sharded section has a
# mesh to shard over; the single-device section pins stream_mesh=1,
# which restores the exact pre-mesh staging (including zero-copy).
# force_cpu_platform APPENDS/RAISES the device-count flag inside an
# already-set XLA_FLAGS instead of silently losing it (a setdefault
# would fail the gate on any box that exports XLA_FLAGS for tuning)
from dask_ml_tpu._platform import force_cpu_platform  # noqa: E402

force_cpu_platform(n_devices=8)

import numpy as np  # noqa: E402


def main():
    from dask_ml_tpu import config
    from dask_ml_tpu import observability as obs
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.parallel.streaming import BlockStream

    n, d = 64_000, 32
    rng = np.random.RandomState(0)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)

    failures = []
    # -- single-device section (stream_mesh=1: the pre-mesh hot loop) --
    with config.set(stream_block_rows=n // 32, stream_autotune=False,
                    stream_mesh=1):
        stream = BlockStream((X, y), block_rows=n // 32)
        k = stream.resolve_superblock_k()
        n_blocks = stream.n_blocks
        if k <= 1:
            failures.append(
                f"super-block execution is off (resolved K={k}); the "
                "streamed hot loop is dispatching per block"
            )
        # pass 1: warmup (compiles the scan at the steady-state shapes)
        SGDClassifier(max_iter=1, random_state=0, shuffle=False).fit(X, y)
        obs.counters_reset()
        clf = SGDClassifier(max_iter=2, random_state=0, shuffle=False)
        clf.fit(X, y)
        snap = obs.counters_snapshot()
        st = dict(getattr(clf, "_last_stream_stats", None) or {})

    # fused-kernel dispatch contract (ISSUE 8): enabling the fused
    # streamed kernels must NOT change the dispatch shape of a pass —
    # the Pallas flavor replaces the per-block BODY inside the same
    # scan, never the scan structure (and off-TPU it must be inert).
    with config.set(stream_block_rows=n // 32, stream_autotune=False,
                    stream_mesh=1, pallas_stream=False):
        off = SGDClassifier(max_iter=1, random_state=0, shuffle=False)
        off.fit(X, y)
    off_st = dict(getattr(off, "_last_stream_stats", None) or {})

    budget = math.ceil(n_blocks / max(k, 1)) + 1
    dpp = st.get("dispatches_per_pass")
    if off_st.get("dispatches_per_pass") != dpp:
        failures.append(
            f"fused SGD step changed dispatches_per_pass: "
            f"{dpp} (pallas_stream=on) vs "
            f"{off_st.get('dispatches_per_pass')} (off) — the fused "
            "path must not add dispatches"
        )
    if dpp is None:
        failures.append("no dispatches_per_pass in stream stats — the "
                        "fit did not take the super-block path")
    elif dpp > budget:
        failures.append(
            f"dispatches_per_pass={dpp} exceeds ceil({n_blocks}/{k})+1="
            f"{budget}"
        )
    recompiles = snap.get("recompiles", 0)
    if recompiles > 0:
        failures.append(
            f"{recompiles} new XLA compiles AFTER the first pass — "
            "steady-state streaming must hit only warm compile caches"
        )
    if snap.get("superblock_dispatches", 0) <= 0:
        failures.append("superblock_dispatches counter never moved")

    # -- sharded section (ISSUE 9): 8-way data-parallel streaming ------
    import jax

    sh_dpp = sh_recompiles = sh_shards = None
    if len(jax.devices()) < 8:
        failures.append(
            f"expected 8 virtual devices for the sharded section, got "
            f"{len(jax.devices())} (XLA_FLAGS not honored?)"
        )
    else:
        with config.set(stream_block_rows=n // 32,
                        stream_autotune=False, stream_mesh=0):
            sh_stream = BlockStream((X, y), block_rows=n // 32)
            sh_k = sh_stream.resolve_superblock_k()
            sh_blocks = sh_stream.n_blocks
            SGDClassifier(max_iter=1, random_state=0,
                          shuffle=False).fit(X, y)  # warmup pass
            obs.counters_reset()
            sh = SGDClassifier(max_iter=2, random_state=0,
                               shuffle=False)
            sh.fit(X, y)
            sh_snap = obs.counters_snapshot()
            sh_st = dict(getattr(sh, "_last_stream_stats", None) or {})
        sh_dpp = sh_st.get("dispatches_per_pass")
        sh_shards = sh_st.get("sb_shards")
        sh_recompiles = sh_snap.get("recompiles", 0)
        if sh_shards != 8:
            failures.append(
                f"sharded fit ran at sb_shards={sh_shards}, wanted 8 — "
                "the data-parallel flavor did not engage"
            )
        # ONE dispatch per super-block, never per shard: the sharded
        # budget is EXACT (no +1 slack — a per-shard dispatch leak
        # would multiply dispatches by D, and this is the gate that
        # catches it)
        if sh_dpp != math.ceil(sh_blocks / max(sh_k, 1)):
            failures.append(
                f"sharded dispatches_per_pass={sh_dpp} != "
                f"ceil({sh_blocks}/{sh_k})="
                f"{math.ceil(sh_blocks / max(sh_k, 1))} — one dispatch "
                "per super-block, NOT per shard"
            )
        if sh_recompiles > 0:
            failures.append(
                f"{sh_recompiles} new XLA compiles after pass 1 on the "
                "SHARDED path — sharding must not break the warm-cache "
                "contract"
            )
        if sh_snap.get("shard_slab_puts", 0) <= 0:
            failures.append(
                "shard_slab_puts counter never moved — super-blocks "
                "did not stage per-shard"
            )

    # -- fused x sharded section (ISSUE 12): the Pallas bodies inside
    # the shard_map scan programs (interpret mode on this CPU box) must
    # keep EXACTLY the unfused sharded flavor's dispatch shape and the
    # zero-compiles-after-pass-1 contract — the fusion swaps the
    # per-block BODY, never the scan/psum structure.
    fu_dpp = fu_recompiles = None
    if len(jax.devices()) >= 8:
        nf, df = 16_384, 16
        Xf = rng.randn(nf, df).astype(np.float32)
        yf = (Xf[:, 0] > 0).astype(np.float32)
        # 2048-row blocks -> 256-row per-shard slabs (128-multiple):
        # the fused flavor's tile gate passes at D=8
        def fused_run(interpret):
            with config.set(stream_block_rows=2048,
                            stream_autotune=False, stream_mesh=0,
                            pallas_stream_interpret=interpret):
                SGDClassifier(max_iter=1, random_state=0,
                              shuffle=False).fit(Xf, yf)  # warmup
                obs.counters_reset()
                clf = SGDClassifier(max_iter=2, random_state=0,
                                    shuffle=False)
                clf.fit(Xf, yf)
                return (dict(getattr(clf, "_last_stream_stats", None)
                             or {}),
                        obs.counters_snapshot(),
                        dict(getattr(clf, "solver_info_", None) or {}))
        fu_st, fu_snap, fu_info = fused_run(True)
        base_st, _, _ = fused_run(False)
        fu_dpp = fu_st.get("dispatches_per_pass")
        fu_recompiles = fu_snap.get("recompiles", 0)
        if not fu_info.get("fused_stream"):
            failures.append(
                "fused x sharded section did not engage the Pallas "
                f"bodies (reason={fu_info.get('fused_stream_reason')})"
            )
        if fu_dpp != base_st.get("dispatches_per_pass"):
            failures.append(
                f"fused x sharded changed dispatches_per_pass: "
                f"{fu_dpp} (fused) vs "
                f"{base_st.get('dispatches_per_pass')} (unfused)"
            )
        if fu_recompiles > 0:
            failures.append(
                f"{fu_recompiles} new XLA compiles after pass 1 on the "
                "FUSED sharded path — fusing the bodies must not break "
                "the warm-cache contract"
            )

    # -- sparse section (ISSUE 13): device-resident bucketed-nnz staging
    # must keep the EXACT dispatch shape (one per super-block — the
    # stream plan pads every super-block of a fit to one nnz capacity,
    # so this budget has no +1 slack), pay zero XLA compiles after
    # pass 1 even though pass 2 shuffles, and the nnz-bucket ladder
    # must stay small (<= 4 distinct per-block rungs).
    import scipy.sparse as sp_

    sp_dpp = sp_recompiles = sp_rungs = None
    rng2 = np.random.RandomState(1)
    Xsp = sp_.random(32_000, 64, density=0.05, format="csr",
                     random_state=rng2, dtype=np.float64)
    ssum = np.asarray(Xsp.sum(axis=1)).ravel()
    ysp = (ssum > np.median(ssum)).astype(np.float64)
    with config.set(stream_block_rows=2_000, stream_autotune=False,
                    stream_mesh=1, stream_sparse=True):
        sstream = BlockStream((Xsp, ysp.astype(np.float32)),
                              block_rows=2_000)
        sp_k = sstream.resolve_superblock_k()
        sp_blocks = sstream.n_blocks
        plan = sstream.sparse_plan
        if plan is None:
            failures.append(
                "sparse staging plan did not engage "
                f"(reason={sstream.sparse_reason})"
            )
        else:
            sp_rungs = len(set(plan.block_buckets))
            if sp_rungs > 4:
                failures.append(
                    f"nnz-bucket ladder used {sp_rungs} > 4 distinct "
                    "rungs in one pass"
                )
        SGDClassifier(max_iter=1, random_state=0, shuffle=True).fit(
            Xsp, ysp
        )   # pass 1: warm
        obs.counters_reset()
        spc = SGDClassifier(max_iter=2, random_state=0,
                            shuffle=True).fit(Xsp, ysp)
        sp_snap = obs.counters_snapshot()
        sp_st = dict(getattr(spc, "_last_stream_stats", None) or {})
    sp_dpp = sp_st.get("dispatches_per_pass")
    sp_recompiles = sp_snap.get("recompiles", 0)
    if not (spc.solver_info_ or {}).get("sparse_stream"):
        failures.append(
            "sparse fit did not engage the device-resident path "
            f"(reason={(spc.solver_info_ or {}).get('sparse_stream_reason')})"
        )
    if sp_dpp != math.ceil(sp_blocks / max(sp_k, 1)):
        failures.append(
            f"sparse dispatches_per_pass={sp_dpp} != "
            f"ceil({sp_blocks}/{sp_k})="
            f"{math.ceil(sp_blocks / max(sp_k, 1))} — one dispatch per "
            "super-block with sparse staging"
        )
    if sp_recompiles > 0:
        failures.append(
            f"{sp_recompiles} new XLA compiles after pass 1 on the "
            "SPARSE path — one capacity per fit means shuffled passes "
            "must hit only warm caches"
        )
    if sp_snap.get("sparse_blocks_staged", 0) <= 0:
        failures.append("sparse_blocks_staged counter never moved — "
                        "blocks did not stage as bucketed-nnz slabs")

    # -- search section (ISSUE 14): the adaptive-search cohort rides
    # the streamed superblock plane — every round must be exactly
    # ceil(steps / K) dispatches (one per super-block, the round-1
    # {mid: 1} round exactly one), and after round 1 (which warms the
    # slot RUNG ladder) the whole search — INCLUDING shrinking
    # candidate sets, 8 -> 4 -> 2 -> 1 under decay — must pay zero new
    # XLA compiles: bracket halving reuses compiled scans via padded
    # slot masks, never a recompile per surviving N.
    from dask_ml_tpu.model_selection import IncrementalSearchCV

    ns, ds = 16_384, 16
    Xq = rng.randn(ns, ds).astype(np.float32)
    yq = (Xq[:, 0] > 0).astype(np.float64)
    params_q = {"alpha": list(np.logspace(-4, -1, 8))}
    marks = []

    class _Probe(IncrementalSearchCV):
        def _additional_calls(self, info):
            marks.append(obs.counters_snapshot().get("recompiles", 0))
            return super()._additional_calls(info)

    with config.set(stream_block_rows=2048, stream_autotune=False,
                    stream_mesh=1):
        sq = _Probe(SGDClassifier(learning_rate="constant"), params_q,
                    n_initial_parameters=8, decay_rate=1.0,
                    max_iter=48, fits_per_score=8, random_state=0)
        obs.counters_reset()
        sq.fit(Xq, yq, classes=[0.0, 1.0])
    sm = sq.metadata_["stream"]
    if not sm.get("streamed"):
        failures.append("search section: streamed cohort plane did "
                        f"not engage ({sm})")
    else:
        n_rounds = sm["rounds"]
        k_search = max(2, math.ceil(sm["n_blocks"] / 4))
        expect = 1 + (n_rounds - 1) * math.ceil(8 / k_search)
        if sm["dispatches"] != expect:
            failures.append(
                f"search dispatches={sm['dispatches']} != {expect} "
                f"(1 for round 1 + ceil(8/{k_search}) per later "
                f"round x {n_rounds - 1}) — one dispatch per "
                "super-block per round"
            )
        if n_rounds < 4:
            failures.append(
                f"search ran only {n_rounds} rounds — the shrinking-"
                "bracket contract needs several"
            )
    if len(marks) >= 2 and marks[-1] != marks[0]:
        failures.append(
            f"{marks[-1] - marks[0]} new XLA compiles AFTER round 1 "
            f"across shrinking candidate sets (marks={marks}) — "
            "bracket halving must reuse the compiled scan via the "
            "padded-N slot mask, not recompile at each N"
        )
    # sharded search flavor: the cohort scans run under shard_map on
    # the 8-virtual-device mesh with the same zero-compile contract
    sh_search = None
    if len(jax.devices()) >= 8:
        marks.clear()
        with config.set(stream_block_rows=2048, stream_autotune=False,
                        stream_mesh=0):
            sq8 = _Probe(SGDClassifier(learning_rate="constant"),
                         params_q, n_initial_parameters=8,
                         decay_rate=1.0, max_iter=24, fits_per_score=8,
                         random_state=0)
            obs.counters_reset()
            sq8.fit(Xq, yq, classes=[0.0, 1.0])
        sh_search = sq8.metadata_["stream"]
        if sh_search.get("shards") != 8:
            failures.append(
                f"sharded search ran at shards={sh_search.get('shards')}"
                ", wanted 8 — the cohort psum flavor did not engage"
            )
        if len(marks) >= 2 and marks[-1] != marks[0]:
            failures.append(
                f"{marks[-1] - marks[0]} new XLA compiles after round "
                "1 on the SHARDED search path"
            )

    # -- plans section (ISSUE 15): the CROSS-CLIENT zero-recompile gate.
    # One process warms all three compiled-program machineries through
    # the plan layer — serving's (method, bucket) grid, the stacked
    # C-grid direct solves, and the streamed superblock scan — then
    # runs ragged serving traffic + a second C-grid search + a second
    # streamed fit and asserts ZERO new XLA compiles across ALL of
    # them. Before the plans subsystem each machinery was gated
    # separately; a client whose warmup missed a shape the others
    # relied on could only be caught by its own gate.
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.model_selection import GridSearchCV
    from dask_ml_tpu.serving import BucketLadder, ModelServer

    npl, dpl = 8_192, 16
    Xpl = rng.randn(npl, dpl).astype(np.float32)
    ypl = (Xpl[:, 0] > 0).astype(np.float64)
    grid_c = {"C": [0.1, 1.0, 10.0]}

    def run_search():
        GridSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=5, tol=0.0),
            grid_c, cv=2, refit=False, scheduler="synchronous",
        ).fit(Xpl, ypl)

    pl_recompiles = None
    with config.set(stream_block_rows=1024, stream_autotune=False,
                    stream_mesh=1):
        # max_iter=2: at this scale a pass is ONE superblock dispatch,
        # so the carry-from-previous-output program variant only
        # appears at pass 2 — the warm fit must cover it
        clf_pl = SGDClassifier(max_iter=2, random_state=0,
                               shuffle=False)
        clf_pl.fit(Xpl, ypl)       # warms the streamed scan programs
        run_search()               # warms the stacked C-grid solves
        srv_pl = ModelServer(clf_pl, methods=("predict",),
                             ladder=BucketLadder(8, 128, 2.0),
                             batch_window_ms=1.0, timeout_ms=0)
        srv_pl.warmup()            # warms the serving grid (plan layer)
        obs.counters_reset()
        with srv_pl:
            SGDClassifier(max_iter=2, random_state=0,
                          shuffle=False).fit(Xpl, ypl)
            run_search()
            rngs = np.random.RandomState(7)
            for _ in range(20):
                nreq = rngs.randint(1, 128)
                i = rngs.randint(0, npl - nreq)
                srv_pl.predict(Xpl[i:i + nreq])
            pl_recompiles = obs.counters_snapshot().get("recompiles", 0)
    if pl_recompiles:
        failures.append(
            f"{pl_recompiles} new XLA compiles across the warmed "
            "serving + C-grid search + streamed fit trio — the plan "
            "layer's cross-client zero-recompile contract broke"
        )
    # the plans table must name what warmed: serving rungs + any
    # plan-built program attribution
    from dask_ml_tpu import plans as _plans

    pl_rows = {r["program"]: r for r in _plans.plans_snapshot()}
    srv_row = pl_rows.get("serving.SGDClassifier.predict")
    if not srv_row or srv_row["warmups"] < 1 \
            or "128" not in srv_row["rungs"]:
        failures.append(
            f"plans table missing the warmed serving grid: {srv_row}"
        )
    if "glm.lbfgs_lam_grid" not in pl_rows:
        failures.append(
            "plans table missing the stacked C-grid solve program"
        )

    # -- 2-D mesh section (ISSUE 18): feature-sharded streaming --------
    # mesh_shape="2x4" tiles the streamed X slabs as (rows/2, d/4)
    # per-device blocks; the dispatch-collapse contract must survive
    # unchanged — EXACTLY ceil(n_blocks/K) dispatches per pass (one per
    # super-block, never one per shard or per model tile) and zero XLA
    # compiles after the warming fit. mesh_shape="8x1" must COLLAPSE to
    # the cached 1-D data mesh so the 1-D reducer cache keys — and with
    # them the 1-D jaxprs — stay byte-identical.
    md_dispatches = md_recompiles = md_glm_recompiles = None
    if len(jax.devices()) >= 8:
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.models.pca import PCA
        from dask_ml_tpu.models.solvers.streamed import _sb_reducer
        from dask_ml_tpu.parallel.mesh import (default_mesh,
                                               stream_data_mesh)

        with config.set(stream_mesh=0, mesh_shape="8x1"):
            m81 = stream_data_mesh()
        with config.set(stream_mesh=0, mesh_shape="auto"):
            m1d = stream_data_mesh()
        if not (m81 is m1d and m81 is default_mesh()):
            failures.append(
                "mesh_shape='8x1' did not collapse to the cached 1-D "
                "data mesh object — M=1 must route through the "
                "untouched 1-D programs"
            )
        r81 = _sb_reducer("vg", "logistic", True, 0, mesh=m81)
        r1d = _sb_reducer("vg", "logistic", True, 0, mesh=m1d)
        if r81 is not r1d:
            failures.append(
                "mesh_shape='8x1' minted a DISTINCT vg reducer — the "
                "M=1 cache key (and with it the 1-D jaxpr) must be "
                "byte-identical to the plain data-mesh program"
            )

        n3, d3 = 8_192, 64
        X3 = rng.randn(n3, d3).astype(np.float32)
        with config.set(stream_block_rows=512, stream_autotune=False,
                        stream_mesh=0, mesh_shape="2x4"):
            st3 = BlockStream((X3,), block_rows=512)
            k3 = st3.resolve_superblock_k()
            b3 = st3.n_blocks
            if st3.sb_model_shards() != 4 or st3.sb_data_shards() != 2:
                failures.append(
                    f"2x4 stream staged at "
                    f"{st3.sb_data_shards()}x{st3.sb_model_shards()} "
                    f"(model_tile_reason={st3.model_tile_reason}) — "
                    "the feature tiling did not engage"
                )
            PCA(n_components=8, svd_solver="randomized",
                random_state=0).fit(X3)             # pass 1: warm
            obs.counters_reset()
            PCA(n_components=8, svd_solver="randomized",
                random_state=0).fit(X3)
            md_snap = obs.counters_snapshot()
        md_dispatches = md_snap.get("superblock_dispatches", 0)
        md_recompiles = md_snap.get("recompiles", 0)
        # streamed randomized SVD is a FIXED pass plan: 1 moments pass
        # + (n_iter+1)=3 range passes, each exactly ceil(n_blocks/K)
        # super-block dispatches — the budget is EXACT
        exp3 = 4 * math.ceil(b3 / max(k3, 1))
        if md_dispatches != exp3:
            failures.append(
                f"2-D streamed PCA dispatched {md_dispatches} != "
                f"4*ceil({b3}/{k3})={exp3} — one dispatch per "
                "super-block per pass, NOT per shard/tile"
            )
        if md_recompiles > 0:
            failures.append(
                f"{md_recompiles} new XLA compiles after the warming "
                "fit on the 2-D streamed PCA path"
            )

        n4, d4 = 8_192, 64
        X4 = rng.randn(n4, d4).astype(np.float32)
        y4 = (X4[:, 0] > 0).astype(np.float64)
        with config.set(stream_block_rows=1024, stream_autotune=False,
                        stream_mesh=0, mesh_shape="2x4"):
            st4 = BlockStream((X4, y4.astype(np.float32)),
                              block_rows=1024)
            k4 = st4.resolve_superblock_k()
            b4 = st4.n_blocks
            LogisticRegression(solver="lbfgs", max_iter=5).fit(X4, y4)
            obs.counters_reset()
            LogisticRegression(solver="lbfgs", max_iter=5).fit(X4, y4)
            md_glm_snap = obs.counters_snapshot()
        md_glm_recompiles = md_glm_snap.get("recompiles", 0)
        glm_disp = md_glm_snap.get("superblock_dispatches", 0)
        per_pass = math.ceil(b4 / max(k4, 1))
        if glm_disp <= 0 or glm_disp % per_pass:
            failures.append(
                f"feature-sharded GLM dispatched {glm_disp} — not a "
                f"multiple of ceil({b4}/{k4})={per_pass} per pass"
            )
        if md_glm_recompiles:
            failures.append(
                f"{md_glm_recompiles} new XLA compiles after the "
                "warming fit on the feature-sharded GLM path"
            )
        pl2 = {r["program"] for r in _plans.plans_snapshot()}
        if not any(p.startswith("superblock.glm.")
                   and p.endswith(".model_psum") for p in pl2):
            failures.append(
                "plans table missing the feature-sharded GLM programs "
                "(superblock.glm.*.model_psum)"
            )
        if not any(p.startswith("superblock.pca.") for p in pl2):
            failures.append(
                "plans table missing the streamed PCA programs "
                "(superblock.pca.*)"
            )

    print(f"perf smoke: n_blocks={n_blocks} K={k} "
          f"dispatches_per_pass={dpp} (budget {budget}) "
          f"recompiles_after_pass1={recompiles} | sharded: "
          f"shards={sh_shards} dispatches_per_pass={sh_dpp} "
          f"recompiles_after_pass1={sh_recompiles} | fused-sharded: "
          f"dispatches_per_pass={fu_dpp} "
          f"recompiles_after_pass1={fu_recompiles} | sparse: "
          f"dispatches_per_pass={sp_dpp} "
          f"recompiles_after_pass1={sp_recompiles} "
          f"ladder_rungs={sp_rungs} | search: "
          f"rounds={sm.get('rounds')} dispatches={sm.get('dispatches')} "
          f"shards8={None if sh_search is None else sh_search.get('shards')}"
          f" | plans: cross-client recompiles={pl_recompiles}"
          f" | mesh2d: pca_dispatches={md_dispatches} "
          f"pca_recompiles={md_recompiles} "
          f"glm_recompiles={md_glm_recompiles}")
    if failures:
        for f in failures:
            print(f"PERF SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print("perf smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
