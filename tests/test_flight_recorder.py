"""Flight recorder (ISSUE 4): compiled-program registry (cost/memory
attribution + measured MFU), stall watchdog, Perfetto export, and the
report CLI's --json / programs / double-count fixes."""

import json
import os
import threading
import time

import numpy as np
import pytest

from dask_ml_tpu import config, observability as obs
from dask_ml_tpu.observability.report import (build_report, final_counters,
                                              load_records, report_data,
                                              summarize_spans)


def _read_jsonl(path):
    return [json.loads(line) for line in open(path)]


@pytest.fixture(autouse=True)
def _clean_registry():
    obs.programs_reset()
    obs.counters_reset()
    yield
    obs.programs_reset()


# -- program registry --------------------------------------------------------

def _tracked_matmul(name="test.matmul"):
    import jax

    @obs.track_program(name)
    @jax.jit
    def mm(a, b):
        return a @ b

    return mm


def test_track_program_records_compile_cost_and_calls():
    mm = _tracked_matmul()
    a = np.ones((16, 8), np.float32)
    with config.set(obs_programs=True):
        mm(a, a.T)
        mm(a, a.T)   # warm call: no new compile
    snap = obs.programs_snapshot()
    assert len(snap) == 1
    p = snap[0]
    assert p["program"] == "test.matmul"
    assert p["compiles"] == 1 and p["calls"] == 2
    assert p["compile_s"] > 0
    # XLA's measured cost: 2*16*8*16 FLOPs for the (16,8)x(8,16) matmul
    assert p["flops_per_call"] == pytest.approx(2 * 16 * 8 * 16)
    assert p["flops_total"] == pytest.approx(2 * p["flops_per_call"])
    assert p["hbm_peak_bytes"] and p["hbm_peak_bytes"] > 0
    assert p["exec_s"] > 0


def test_track_program_disabled_is_passthrough_and_records_nothing():
    mm = _tracked_matmul("test.disabled")
    a = np.ones((4, 4), np.float32)
    with config.set(obs_programs=False):
        out = mm(a, a)
    assert np.allclose(np.asarray(out), a @ a)
    assert obs.programs_snapshot() == []


def test_track_program_new_shape_is_new_compile():
    mm = _tracked_matmul("test.shapes")
    with config.set(obs_programs=True):
        mm(np.ones((4, 4), np.float32), np.ones((4, 4), np.float32))
        mm(np.ones((8, 4), np.float32), np.ones((4, 4), np.float32))
    p = obs.programs_snapshot()[0]
    assert p["compiles"] == 2 and p["calls"] == 2


def test_track_program_credits_each_shape_its_own_flops():
    """One program name spans many specializations (the serving bucket
    ladder): each call must be credited ITS shape's FLOPs, not the
    latest-compiled shape's, and a compiling call's wall (trace +
    compile) must not pollute exec_s."""
    mm = _tracked_matmul("test.buckets")
    small = np.ones((8, 4), np.float32)    # 2*8*4*8  = 512 F
    big = np.ones((64, 4), np.float32)     # 2*64*4*64 = 32768 F
    with config.set(obs_programs=True):
        mm(small, small.T)
        mm(big, big.T)      # latest compile is the BIG shape
        mm(small, small.T)  # must still be credited 512, not 32768
    p = obs.programs_snapshot()[0]
    assert p["compiles"] == 2 and p["calls"] == 3
    assert p["flops_total"] == pytest.approx(512 * 2 + 32768)
    assert "_by_shape" not in p  # internals stay out of snapshots


def test_track_program_preserves_raw_body_unwrap():
    """Super-block reducers lift block-kernel BODIES into their scans
    via ``.__wrapped__`` — the tracker must keep that unwrap landing on
    the raw Python function, with the jit still reachable."""
    from dask_ml_tpu.models.solvers.streamed import _block_val_grad

    raw = _block_val_grad.__wrapped__
    assert not hasattr(raw, "__wrapped__")       # the plain function
    assert callable(_block_val_grad.__wrapped_jit__)
    assert hasattr(_block_val_grad, "_cache_size")


def test_program_flops_counter_feeds_span_deltas(tmp_path):
    """A span enclosing tracked-program calls carries the
    ctr_program_flops delta — the raw material of per-span MFU."""
    mm = _tracked_matmul("test.span_flops")
    a = np.ones((16, 8), np.float32)
    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace, obs_programs=True,
                    obs_counters=True):
        mm(a, a.T)  # compile + analyze OUTSIDE the span
        with obs.span("work"):
            mm(a, a.T)
            mm(a, a.T)
    rec = [r for r in _read_jsonl(os.path.join(trace, "trace.jsonl"))
           if r.get("span") == "work"][-1]
    assert rec["ctr_program_flops"] == pytest.approx(2 * 2 * 16 * 8 * 16)


def test_solver_fit_populates_registry():
    from dask_ml_tpu.linear_model import LogisticRegression

    rng = np.random.RandomState(0)
    X = rng.randn(200, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    with config.set(obs_programs=True):
        LogisticRegression(solver="lbfgs", max_iter=5).fit(X, y)
    names = {p["program"] for p in obs.programs_snapshot()}
    assert "glm.lbfgs" in names
    p = [p for p in obs.programs_snapshot()
         if p["program"] == "glm.lbfgs"][0]
    assert p["compiles"] >= 1 and p["flops_per_call"]


# -- peak table ---------------------------------------------------------------

def test_resolve_peak_measured_on_cpu():
    from dask_ml_tpu.observability._peak import mfu_fields, resolve_peak

    peak = resolve_peak(matmul_dim=128, use_cache=False)
    assert peak["flops"] > 0 and peak["source"] == "measured"
    # half the peak's worth of work in 1s -> mfu 0.5 exactly
    f = mfu_fields(peak["flops"] / 2.0, 1.0, 1, peak)
    assert f["mfu"] == pytest.approx(0.5, rel=1e-3)
    assert f["peak"]["source"] == "measured"


def test_bench_peak_table_is_the_shared_one():
    """bench.py's datasheet table now lives in observability/_peak.py;
    the report's MFU and bench's analytic MFU divide by the same peaks."""
    from dask_ml_tpu.observability._peak import DATASHEET_PEAKS

    assert DATASHEET_PEAKS["v5p"] == 459e12
    assert DATASHEET_PEAKS["v4"] == 275e12


# -- watchdog -----------------------------------------------------------------

def test_watchdog_dumps_stalled_span_and_fit_completes(tmp_path):
    """The acceptance fixture: a span sleeping past watchdog_timeout_s
    produces a watchdog record with thread tracebacks + memory gauges
    while the enclosing work completes normally."""
    trace = str(tmp_path / "t")
    stalls = []
    with config.set(trace_dir=trace, watchdog_timeout_s=0.2):
        with obs.watchdog(on_stall=stalls.append, poll_s=0.05):
            with obs.span("stalled.fixture", n_rows=7) as sp:
                time.sleep(0.7)
                sp.add(done=True)
        finished = True
    assert finished and stalls  # the "fit" was never killed
    recs = _read_jsonl(os.path.join(trace, "trace.jsonl"))
    wd = [r for r in recs if r.get("watchdog")]
    assert len(wd) == 1  # reported once, not once per poll
    r = wd[0]
    assert r["span"] == "stalled.fixture"
    assert r["age_s"] >= 0.2 and r["timeout_s"] == 0.2
    # all-thread tracebacks, including the sleeping one; the stalled
    # thread's OWN stack is resolved by ident (same-named threads must
    # not shadow it)
    assert r["stacks"] and any(
        "time.sleep" in "\n".join(st) for st in r["stacks"].values()
    )
    assert "time.sleep" in "\n".join(r["stalled_stack"])
    # the open-span stack names the stalled span
    assert any(s["span"] == "stalled.fixture" for s in r["open_spans"])
    # memory gauges rode along (empty dict -> no dev* keys on CPU; the
    # call itself must not have been skipped: gauge keys are dev<i>_*)
    assert isinstance(obs.device_memory_gauges(), dict)
    # ...and the span itself closed normally afterwards
    closed = [x for x in recs if x.get("span") == "stalled.fixture"
              and "wall_s" in x]
    assert closed and closed[0]["done"] is True


def test_watchdog_catches_sinkless_spans():
    """The wedged-tunnel scenario: NO metrics_path/trace_dir configured
    (bench's timed fits), watchdog armed — a stalled span must still
    reach the on_stall callback. Sinkless tracked spans emit no record
    and, once the watchdog disarms, spans revert to the no-op."""
    stalls = []
    with config.set(trace_dir="", metrics_path="",
                    watchdog_timeout_s=0.15):
        with obs.watchdog(on_stall=stalls.append, poll_s=0.03):
            with obs.span("sinkless.stall") as sp:
                assert sp is not obs.NOOP_SPAN  # tracked for the watchdog
                time.sleep(0.5)
        assert stalls and stalls[0]["span"] == "sinkless.stall"
        # disarmed again: back to the zero-cost no-op
        with obs.span("after") as sp:
            assert sp is obs.NOOP_SPAN
        assert obs.open_spans_snapshot() == []


def test_stream_wait_measure_not_flipped_by_sinkless_watchdog():
    """A watchdog-tracked (sinkless) pass span must NOT switch on the
    per-block readiness syncs — that would perturb the timed runs the
    watchdog observes. wait_s stays unmeasured (0.0) without a sink."""
    from dask_ml_tpu.parallel.streaming import BlockStream

    X = np.random.RandomState(0).rand(512, 4).astype(np.float32)
    with config.set(trace_dir="", metrics_path="",
                    watchdog_timeout_s=30.0):
        with obs.watchdog(poll_s=0.05):
            s = BlockStream((X,), block_rows=128)
            for _ in s:
                pass
    assert s.stats["wait_s"] == 0.0


def test_export_counters_top_level_spans_only():
    """Nested ctr_* deltas are already contained in their parent's —
    the cumulative counter track must not sum both."""
    from dask_ml_tpu.observability.export import to_chrome_trace

    recs = [
        {"span": "pass", "span_id": 2, "parent_id": 1, "t_unix": 10.1,
         "wall_s": 0.1, "thread": "m", "ctr_h2d_bytes": 512},
        {"span": "fit", "span_id": 1, "parent_id": None, "t_unix": 10.2,
         "wall_s": 0.3, "thread": "m", "ctr_h2d_bytes": 512},
    ]
    events = to_chrome_trace(recs)["traceEvents"]
    tracks = [e for e in events if e["ph"] == "C"
              and e["name"] == "h2d_bytes"]
    assert len(tracks) == 1
    assert tracks[0]["args"]["h2d_bytes"] == 512  # not 1024


def test_report_cli_perfetto_rejects_multiple_inputs(tmp_path, capsys):
    from dask_ml_tpu.observability import report

    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for p in (a, b):
        open(p, "w").write("{}\n")
    rc = report.main([a, b, "--perfetto", str(tmp_path / "o.json")])
    assert rc == 2
    assert "exactly one input" in capsys.readouterr().err


def test_watchdog_noop_when_disabled():
    with config.set(watchdog_timeout_s=0.0):
        with obs.watchdog() as wd:
            assert wd is None
        assert not obs.watchdog_active()
    # a DIRECT Watchdog(0).start() must honor the same disable
    # semantics, not arm a poller whose deadline every span exceeds
    wd = obs.Watchdog(0.0).start()
    assert not obs.watchdog_active()
    wd.stop()


def test_report_of_watchdog_only_records_is_not_empty():
    """A killed hung run leaves ONLY watchdog records (its spans never
    closed): the report must render the stalls table without the
    contradictory 'no observability records found' epilogue."""
    recs = [{"watchdog": True, "span": "fit", "thread": "MainThread",
             "age_s": 12.5, "timeout_s": 5.0,
             "stacks": {"MainThread#1": ["frame"]}}]
    out = build_report(recs)
    assert "watchdog stalls" in out
    assert "no observability records found" not in out


def test_watchdog_dump_reaches_bound_logger(tmp_path):
    """A run recording through a thread-bound MetricsLogger only (no
    metrics_path/trace_dir): the watchdog thread cannot see the fitting
    thread's thread-local binding, so the dump falls back to the
    innermost GLOBAL binding — same best-available-guess as the jit
    callback threads."""
    p = str(tmp_path / "m.jsonl")
    with config.set(trace_dir="", metrics_path="",
                    watchdog_timeout_s=0.15):
        with obs.MetricsLogger(p) as lg, obs.active_logger(lg):
            with obs.watchdog(poll_s=0.03):
                with obs.span("bound.stall"):
                    time.sleep(0.5)
    wd = [r for r in _read_jsonl(p) if r.get("watchdog")]
    assert wd and wd[0]["span"] == "bound.stall"


def test_watchdog_callback_never_kills_the_fit(tmp_path):
    def bad_callback(rec):
        raise RuntimeError("observer crash")

    with config.set(trace_dir=str(tmp_path / "t"),
                    watchdog_timeout_s=0.1):
        with obs.watchdog(on_stall=bad_callback, poll_s=0.02):
            with obs.span("s"):
                time.sleep(0.3)


def test_open_spans_snapshot_tracks_nesting(tmp_path):
    with config.set(trace_dir=str(tmp_path / "t")):
        with obs.span("outer"):
            with obs.span("inner"):
                snap = obs.open_spans_snapshot()
                names = [s["span"] for s in snap]
                assert names == ["outer", "inner"]  # oldest first
                assert all(s["thread"] == threading.current_thread().name
                           for s in snap)
        assert obs.open_spans_snapshot() == []


def test_serving_worker_runs_under_watchdog(tmp_path):
    """A wedged batch execution dumps diagnostics from the serving
    worker thread — wire-through test via a slow host estimator."""
    from dask_ml_tpu.serving import ModelServer

    class SlowModel:
        n_features_in_ = 3

        def predict(self, X):
            time.sleep(0.5)
            return np.zeros(len(X))

    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace, watchdog_timeout_s=0.15):
        with ModelServer(SlowModel(), methods=("predict",)) as srv:
            srv.predict(np.ones((4, 3), np.float32))
    recs = _read_jsonl(os.path.join(trace, "trace.jsonl"))
    wd = [r for r in recs if r.get("watchdog")]
    assert wd and wd[0]["span"] == "serving.batch"


# -- perfetto export ----------------------------------------------------------

def _schema_check_chrome_trace(trace):
    assert isinstance(trace, dict)
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    for ev in events:
        assert isinstance(ev["name"], str)
        assert ev["ph"] in ("X", "C", "M", "i")
        assert isinstance(ev["pid"], int)
        if ev["ph"] == "X":
            assert ev["ts"] >= 0 and ev["dur"] >= 0
            assert isinstance(ev["tid"], int)
        elif ev["ph"] == "C":
            assert len(ev["args"]) == 1
    return events


def test_export_span_tree_to_chrome_trace(tmp_path):
    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace, obs_counters=True):
        with obs.span("outer", component="M", n_rows=10):
            obs.record_transfer(1024)
            with obs.span("inner"):
                time.sleep(0.01)
    records = load_records(os.path.join(trace, "trace.jsonl"))
    from dask_ml_tpu.observability.export import to_chrome_trace

    events = _schema_check_chrome_trace(to_chrome_trace(records))
    xs = {e["name"]: e for e in events if e["ph"] == "X"}
    assert "M.outer" in xs and "inner" in xs
    out, inn = xs["M.outer"], xs["inner"]
    # containment: the child nests inside the parent on the timeline
    assert out["ts"] <= inn["ts"]
    assert out["ts"] + out["dur"] >= inn["ts"] + inn["dur"]
    # counter deltas became a counter track
    assert any(e["ph"] == "C" and e["name"] == "h2d_bytes"
               for e in events)


def test_export_counter_and_step_records(tmp_path):
    p = str(tmp_path / "m.jsonl")
    recs = [
        {"time": 0.1, "component": "KMeans", "step": 0, "inertia": 9.0},
        {"time": 0.2, "component": "KMeans", "step": 1, "inertia": 4.0},
        {"time": 0.3, "counters": True, "recompiles": 3,
         "phase": "end"},  # stray string field must not crash
        {"time": 0.4, "span": "fit", "span_id": 1, "parent_id": None,
         "t_unix": 1000.4, "wall_s": 0.3, "sync_s": 0.0,
         "thread": "MainThread"},
    ]
    with open(p, "w") as fh:
        fh.write("\n".join(json.dumps(r) for r in recs) + "\n")
    from dask_ml_tpu.observability.export import write_chrome_trace

    out = str(tmp_path / "trace.json")
    trace = write_chrome_trace(load_records(p), out)
    _schema_check_chrome_trace(trace)
    reloaded = json.load(open(out))  # valid JSON on disk
    names = {e["name"] for e in reloaded["traceEvents"]}
    assert "KMeans.inertia" in names and "recompiles" in names


def test_report_cli_perfetto_flag(tmp_path, capsys):
    from dask_ml_tpu.observability import report

    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace):
        with obs.span("fit", component="X", n_rows=5):
            pass
    out = str(tmp_path / "out.json")
    rc = report.main([os.path.join(trace, "trace.jsonl"),
                      "--perfetto", out])
    assert rc == 0
    captured = capsys.readouterr()
    # status line on stderr: --json's stdout must stay machine-readable
    # when the flags combine
    assert "perfetto" in captured.err and captured.out == ""
    trace_obj = json.load(open(out))
    _schema_check_chrome_trace(trace_obj)


# -- report: --json, hardening, double-count fix ------------------------------

def test_report_json_flag_round_trips(tmp_path, capsys):
    from dask_ml_tpu.observability import report

    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace, obs_counters=True):
        with obs.span("fit", component="M", n_rows=100):
            obs.record_transfer(512)
    rc = report.main([os.path.join(trace, "trace.jsonl"), "--json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert data["spans"][0]["span"] == "M.fit"
    assert data["counters"]["h2d_bytes"] == 512
    assert data["records"] >= 1 and data["path"].endswith("trace.jsonl")


def test_final_counters_drops_non_numeric_fields():
    recs = [{"counters": True, "recompiles": 2, "h2d_bytes": 100,
             "phase": "end", "run": "r1", "ok": True}]
    ctr = final_counters(recs)
    assert ctr == {"recompiles": 2, "h2d_bytes": 100}


def test_summarize_spans_no_double_count_nested_same_group():
    """A nested span of the SAME group (pass inside fit relabeled as
    fit, a retry inside a pass) sits inside its ancestor's wall and
    re-reports rows/flops the ancestor already carries — it must not
    skew the group's wall, samples/s, or program flops; different-group
    nesting keeps its own numbers."""
    recs = [
        {"span": "fit", "span_id": 1, "parent_id": None, "wall_s": 2.0,
         "sync_s": 0.0, "component": "M", "n_rows": 1000,
         "ctr_program_flops": 100.0},
        # same group, nested under 1: wall/rows/flops already contained
        # in the parent's
        {"span": "fit", "span_id": 2, "parent_id": 1, "wall_s": 1.0,
         "sync_s": 0.0, "component": "M", "n_rows": 1000,
         "ctr_program_flops": 60.0},
        # different group, nested: counts its own numbers
        {"span": "pass", "span_id": 3, "parent_id": 1, "wall_s": 0.5,
         "sync_s": 0.0, "component": "M", "n_rows": 400},
    ]
    rows = {key: (n, wall, sps, flops)
            for key, n, wall, sync, sps, flops in summarize_spans(recs)}
    n, wall, sps, flops = rows["M.fit"]
    assert n == 2 and wall == 2.0          # NOT 3.0
    assert sps == pytest.approx(1000 / 2.0)  # NOT 2000/3 or 1000/3
    assert flops == pytest.approx(100.0)   # NOT 160
    assert rows["M.pass"][2] == pytest.approx(400 / 0.5)


def test_report_programs_table_and_span_mfu(tmp_path):
    """Canned run with a programs snapshot + peak: the report renders
    the programs table and a per-span MFU consistent with the recorded
    flops/wall/peak."""
    p = str(tmp_path / "run.jsonl")
    recs = [
        {"span": "fit", "span_id": 1, "parent_id": None, "wall_s": 2.0,
         "sync_s": 0.0, "component": "M", "n_rows": 1000,
         "ctr_program_flops": 4e9},
        {"programs": [
            {"program": "glm.lbfgs", "compiles": 2, "compile_s": 1.5,
             "calls": 10, "exec_s": 2.0, "flops_per_call": 4e8,
             "bytes_per_call": 1e6, "flops_total": 4e9,
             "hbm_peak_bytes": 123 << 20}],
         "peak_flop_per_s_per_chip": 1e10, "peak_source": "measured",
         "device_kind": "cpu", "n_chips": 1},
    ]
    with open(p, "w") as fh:
        fh.write("\n".join(json.dumps(r) for r in recs) + "\n")
    records = load_records(p)
    data = report_data(records)
    # measured MFU: 4e9 flops / 2.0s / 1e10 peak = 0.2
    assert data["spans"][0]["mfu"] == pytest.approx(0.2)
    assert data["peak"]["flop_per_s_per_chip"] == 1e10
    out = build_report(records, path=p)
    assert "programs (XLA cost/memory per compiled entry point)" in out
    assert "glm.lbfgs" in out and "123.0MiB" in out
    assert "0.2000" in out  # both the span and program MFU columns


def test_span_mfu_within_2x_of_analytic(tmp_path):
    """Acceptance: on a recorded run the report's measured per-span MFU
    lands within 2x of the bench-style analytic MFU for the same
    workload (same peak denominator, XLA-counted vs hand-counted
    FLOPs)."""
    import jax

    from dask_ml_tpu.observability._peak import mfu_fields, resolve_peak

    n, d, k = 512, 64, 128

    @obs.track_program("test.mfu_matmul")
    @jax.jit
    def mm(a, b):
        return a @ b

    a = np.random.RandomState(0).randn(n, d).astype(np.float32)
    b = np.random.RandomState(1).randn(d, k).astype(np.float32)
    trace = str(tmp_path / "t")
    reps = 50
    with config.set(trace_dir=trace, obs_programs=True,
                    obs_counters=True):
        jax.block_until_ready(mm(a, b))  # compile outside the span
        with obs.span("workload", n_rows=n) as sp:
            t0 = time.perf_counter()
            for _ in range(reps):
                out = mm(a, b)
            jax.block_until_ready(out)
            elapsed = time.perf_counter() - t0
            sp.sync(out)
        peak = resolve_peak(matmul_dim=256, use_cache=False)
        with obs.MetricsLogger(
                os.path.join(trace, "trace.jsonl")) as lg:
            lg.log(programs=obs.programs_snapshot(),
                   peak_flop_per_s_per_chip=peak["flops"],
                   peak_source=peak["source"],
                   device_kind=peak["device_kind"],
                   n_chips=len(jax.local_devices()))
    analytic = mfu_fields(2.0 * n * d * k * reps, elapsed,
                          len(jax.local_devices()), peak)["mfu"]
    data = report_data(load_records(os.path.join(trace, "trace.jsonl")))
    span_row = [r for r in data["spans"] if r["span"] == "workload"][0]
    assert span_row.get("mfu") is not None
    # measured within 2x of analytic (span wall includes host loop
    # overhead; XLA flops == analytic flops for a plain matmul)
    ratio = span_row["mfu"] / max(analytic, 1e-12)
    assert 0.5 <= ratio <= 2.0, (span_row["mfu"], analytic)


# -- mixed fit + serving recorded run (satellite) -----------------------------

def test_mixed_fit_serving_run_renders_all_tables(tmp_path, capsys):
    """One recorded run containing solver spans, serving.batch spans,
    stream-pass records, counter snapshots AND a programs snapshot
    renders every report table and round-trips through --json and
    --perfetto."""
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.observability import report
    from dask_ml_tpu.parallel import as_sharded
    from dask_ml_tpu.serving import ModelServer

    rng = np.random.RandomState(0)
    X = rng.randn(3000, 8).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace, obs_programs=True,
                    obs_counters=True, stream_block_rows=400):
        # streamed solver fit -> stream-pass records + solver spans
        SGDClassifier(max_iter=2, random_state=0, shuffle=False).fit(X, y)
        clf = LogisticRegression(solver="lbfgs", max_iter=10).fit(
            as_sharded(X), as_sharded(y)
        )
        with ModelServer(clf, methods=("predict",)).warmup() as srv:
            srv.predict(X[:33])
        path = os.path.join(trace, "trace.jsonl")
        with obs.MetricsLogger(path) as lg:
            obs.log_counters(lg)
            obs.log_programs(lg)
    records = load_records(path)
    out = build_report(records, path=path)
    assert "spans (time by component)" in out
    assert "streaming overlap" in out
    assert "programs (XLA cost/memory per compiled entry point)" in out
    assert "counters" in out
    assert "serving.batch" in out
    assert "serving.LogisticRegression.predict" in out
    # --json round-trip
    rc = report.main([path, "--json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert {r["span"] for r in data["spans"]} >= {"serving.batch"}
    assert data["streaming"]["n_passes"] >= 2
    assert any(p["program"].startswith("serving.")
               for p in data["programs"])
    assert data["counters"]["serving_requests"] >= 1
    # --perfetto round-trip
    pf = str(tmp_path / "trace.perfetto.json")
    rc = report.main([path, "--perfetto", pf])
    assert rc == 0
    _schema_check_chrome_trace(json.load(open(pf)))
