"""Live telemetry: watch a fit WHILE it runs.

Everything earlier in the observability stack (spans, counters, the
report CLI) is post-hoc — you read the JSONL after the run. The live
plane is the dask-dashboard analog: set ``config.obs_http_port`` (or
``DASK_ML_TPU_OBS_HTTP_PORT``) and a daemon thread serves

- ``/metrics``  — Prometheus text exposition (counters, fit progress
  gauges, latency histograms) for a scraper,
- ``/status``   — JSON: the open-span stack (what the process is doing
  RIGHT NOW), recent-span report tables, serving windows,
- ``/healthz``  — liveness.

This example runs a streamed SGD fit on one thread and scrapes its own
endpoints from another — the same curl an operator would run against a
wedged production fit::

    curl localhost:<port>/status | python -m json.tool
    curl localhost:<port>/metrics | grep fit_
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import re
import threading
import time
import urllib.request

import numpy as np

from dask_ml_tpu import config
from dask_ml_tpu import observability as obs
from dask_ml_tpu.models.sgd import SGDClassifier

n = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 200_000))
rng = np.random.RandomState(0)
X = rng.randn(n, 16).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)

# port=0 binds an ephemeral port; production would set
# config.obs_http_port so every fit/serving entry arms it automatically
server = obs.TelemetryServer(port=0).start()
print(f"telemetry at {server.url}  (endpoints: /metrics /status /healthz)")


def fit():
    with config.set(stream_block_rows=8192):
        SGDClassifier(max_iter=10, random_state=0).fit(X, y)


t = threading.Thread(target=fit)
t.start()

while t.is_alive():
    time.sleep(0.2)
    with urllib.request.urlopen(server.url + "/status", timeout=5) as r:
        status = json.loads(r.read())
    with urllib.request.urlopen(server.url + "/metrics", timeout=5) as r:
        metrics = r.read().decode()
    gauges = dict(re.findall(r"^dask_ml_tpu_(fit_\w+) ([\d.e+-]+)$",
                             metrics, re.MULTILINE))
    open_spans = " > ".join(s["span"] for s in status["open_spans"])
    print(f"open: [{open_spans or 'idle'}]  "
          f"pass {gauges.get('fit_pass', '?')}/"
          f"{gauges.get('fit_passes_total', '?')}  "
          f"rows/s {float(gauges.get('fit_rows_per_sec', 0)):,.0f}  "
          f"eta {float(gauges.get('fit_eta_seconds', 0)):.2f}s")
t.join()

with urllib.request.urlopen(server.url + "/metrics", timeout=5) as r:
    metrics = r.read().decode()
print("\nfinal /metrics (fit + histogram lines):")
for line in metrics.splitlines():
    if "fit_" in line and not line.startswith("#"):
        print(" ", line)

with urllib.request.urlopen(server.url + "/status", timeout=5) as r:
    status = json.loads(r.read())
spans = [s["span"] for s in status["report"]["spans"]]
print(f"\n/status report covers spans: {spans}")
server.stop()
print("done.")
