"""Headline benchmark: LogisticRegression.fit throughput on device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Metric: samples/sec/chip processed by the device-resident L-BFGS fit
(counting one full data pass per outer iteration — line-search passes are
not counted, so this undercounts true throughput). vs_baseline is the ratio
against scikit-learn's lbfgs LogisticRegression measured the same way on a
subsample on this host's CPU — the reference's per-block compute engine
(SURVEY.md §6: no published in-repo numbers; BASELINE.json configs[0]).

Data is generated ON DEVICE (jax.random) and stays there: the benchmark
measures the compute path, not the host→device tunnel.

Hardening contract (VERDICT r1 weak #2): this script must NEVER exit
without printing a parseable JSON line. Backend init is probed in a
killable subprocess (the axon plugin can hang rather than raise), falls
back to CPU, a watchdog thread bounds total runtime, and any exception
still emits {"value": null, "error": ...}.
The backend and design-matrix dtype are recorded so a bf16 TPU number is
attributable (ADVICE r1 #3).
"""

import json
import os
import subprocess
import sys
import threading
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# persistent compile cache: repeat driver runs skip the ~40s XLA compile
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)

import numpy as np

# TPU backend init via the axon tunnel can HANG (not raise) for minutes.
# Probe it in a killable subprocess; if it doesn't come up, force CPU in
# this process BEFORE jax is imported so a number is always emitted.
_PROBE_TIMEOUT = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "150"))
# Self-watchdog: emit the JSON error line ourselves rather than letting an
# external timeout kill us output-less.
_TOTAL_TIMEOUT = float(os.environ.get("BENCH_TOTAL_TIMEOUT", "1500"))
# A probe can succeed and the NEXT init still wedge (observed r5: the
# tunnel answered once, then hung every process for 30+ min). The child's
# init gets its own, much shorter deadline so the CPU fallback starts
# early instead of burning the whole total budget.
_INIT_TIMEOUT = float(os.environ.get("BENCH_INIT_TIMEOUT", "420"))
_init_done = threading.Event()


def _probe_tpu() -> bool:
    """True iff the default (TPU) backend initializes within the probe
    timeout in a throwaway subprocess."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return False
    code = (
        "import jax; d = jax.devices(); "
        "import sys; sys.exit(0 if len(d) else 1)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=_PROBE_TIMEOUT,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return r.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def _init_backend():
    """Initialize a JAX backend: probe TPU with a hang-proof subprocess,
    fall back to CPU. Returns (jax, backend_name). Never hangs.

    The healthy-TPU path pays backend init twice (probe subprocess + this
    process) — accepted: init is seconds, and compiles are shared via the
    persistent compilation cache.
    """
    if os.environ.get("BENCH_SKIP_PROBE") != "1" and not _probe_tpu():
        from dask_ml_tpu._platform import force_cpu_platform

        force_cpu_platform()
    import jax

    jax.devices()
    _init_done.set()
    return jax, jax.default_backend()


# the peak-FLOPs table moved to dask_ml_tpu/observability/_peak.py so
# the report CLI's MEASURED per-span MFU and these analytic MFU numbers
# divide by the same denominator; bench's hand-written model_flops
# formulas are now the cross-check against the program registry's
# XLA-measured cost_analysis FLOPs, not the only source. Imported lazily:
# dask_ml_tpu imports jax, which must not happen before the CPU-forcing
# logic in _init_backend.


def _resolve_peak():
    """Per-chip peak matmul FLOP/s: datasheet when the device_kind is
    known, else MEASURED with a large square matmul (the only honest
    option on CPU fallback — VERDICT r3 #2 wants MFU 'vs CPU peak on
    fallback'). Delegates to observability/_peak.py, which derives the
    backend itself."""
    from dask_ml_tpu.observability._peak import resolve_peak

    return resolve_peak()


def _mfu_fields(model_flops, elapsed, n_chips, peak):
    """Achieved model FLOP/s and MFU vs per-chip peak (analytic
    model_flops; see observability/_peak.py)."""
    from dask_ml_tpu.observability._peak import mfu_fields

    return mfu_fields(model_flops, elapsed, n_chips, peak)


def _print_stall(rec):
    """Watchdog stall dump -> stderr (the JSON stdout line must stay
    clean): the stalled span plus its thread's stack — the diagnostics
    the wedged-tunnel rounds never had."""
    lines = [f"bench watchdog: span {rec.get('span')!r} open "
             f"{rec.get('age_s')}s on thread {rec.get('thread')!r}"]
    lines.extend(rec.get("stalled_stack", [])[-8:])
    sys.stderr.write("\n".join(lines) + "\n")


def run():
    jax, backend = _init_backend()
    import jax.numpy as jnp

    import dask_ml_tpu  # noqa: F401

    # span-level stall watchdog (observability/_watchdog.py): any span
    # (fit, stream pass, serving batch) open past the deadline dumps
    # all-thread tracebacks + device memory gauges to stderr while the
    # bench keeps running — the in-flight diagnostics the deadline
    # watchdogs above (which only bound TOTAL time) cannot give. Daemon
    # thread; dies with the child.
    from dask_ml_tpu.observability import Watchdog
    from dask_ml_tpu.observability.live import ensure_telemetry

    Watchdog(
        float(os.environ.get("BENCH_WATCHDOG_TIMEOUT", "120")),
        on_stall=_print_stall,
    ).start()
    # live exporter (DASK_ML_TPU_OBS_HTTP_PORT): during a wedged round
    # an operator can curl /status for the open-span stack instead of
    # waiting on the watchdog's one-shot dump; no-op when the env knob
    # is unset, so the timed fits below keep their profile
    ensure_telemetry()
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import as_sharded

    n_chips = len(jax.devices())
    on_tpu = backend == "tpu"
    n_rows = 4_000_000 if on_tpu else 200_000
    n_feat = 256 if on_tpu else 64

    key = jax.random.PRNGKey(0)
    kb, kx, ky = jax.random.split(key, 3)
    beta_true = jax.random.normal(kb, (n_feat,)) / np.sqrt(n_feat)

    @jax.jit
    def gen():
        X = jax.random.normal(kx, (n_rows, n_feat), jnp.float32)
        p = jax.nn.sigmoid(X @ beta_true)
        y = (jax.random.uniform(ky, (n_rows,)) < p).astype(jnp.float32)
        return X, y

    X, y = jax.block_until_ready(gen())
    Xs, ys = as_sharded(X), as_sharded(y)

    max_iter = 50
    from dask_ml_tpu import config

    # bf16 design matrix on TPU: higher MXU throughput, measured identical
    # converged coef error/score vs f32 on this problem (solver state and
    # accumulation stay f32). dtype is recorded in the JSON so the ratio
    # is attributable.
    dtype = "bfloat16" if on_tpu else "float32"
    with config.set(dtype=dtype):
        # warm the compile cache AT FULL SHAPE (XLA programs are
        # shape-specialized) with a 1-iteration fit
        LogisticRegression(solver="lbfgs", max_iter=1, tol=0.0).fit(Xs, ys)

        t0 = time.perf_counter()
        clf = LogisticRegression(solver="lbfgs", max_iter=max_iter, tol=0.0)
        clf.fit(Xs, ys)
        elapsed = time.perf_counter() - t0
    iters = clf.n_iter_ or max_iter

    # traceability run (BASELINE.md measurement protocol): a SEPARATE
    # short fit writes per-iteration JSONL. The timed fit above runs
    # WITHOUT logging — the log=True trace carries a per-iteration host
    # callback that would pollute the headline number.
    metrics_file = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_metrics.jsonl"
    )
    open(metrics_file, "w").close()  # fresh file per bench run
    # program tracking ON for the traceability fit only: the recorded
    # JSONL carries per-program compile/FLOP/HBM attribution (and the
    # fit span a ctr_program_flops delta -> measured MFU in the report
    # CLI) as a cross-check of the analytic logreg_flops below. The
    # TIMED fit above ran without it — the registry's analysis pass
    # costs one extra AOT compile per program.
    from dask_ml_tpu.observability import (MetricsLogger, log_programs,
                                           programs_reset)

    programs_reset()
    with config.set(dtype=dtype, metrics_path=metrics_file,
                    obs_programs=True):
        LogisticRegression(solver="lbfgs", max_iter=10, tol=0.0).fit(Xs, ys)
        # tiny STREAMED fits under program tracking so the report CLI's
        # programs table ranks the streamed super-block kernels — the
        # XLA flavors (superblock.*) here on CPU, the fused Pallas
        # flavors (pallas.sgd_step / pallas.glm_vgh /
        # pallas.kmeans_stream) on real TPU — against the resident
        # programs (ISSUE 8: MFU-ranked kernel attribution)
        try:
            from dask_ml_tpu.cluster import KMeans as _KM
            from dask_ml_tpu.models.sgd import SGDClassifier as _SGD

            _rs = np.random.RandomState(3)
            _Xs = _rs.randn(16_384, 32).astype(np.float32)
            _ys = (_Xs[:, 0] > 0).astype(np.float32)
            with config.set(stream_block_rows=2048):   # 128-multiple:
                # the fused streamed kernels' grid requirement
                _SGD(max_iter=1, random_state=0,
                     shuffle=False).fit(_Xs, _ys)
                LogisticRegression(solver="lbfgs", max_iter=3).fit(
                    _Xs, _ys
                )
                _KM(n_clusters=4, random_state=0, max_iter=2,
                    init="random").fit(_Xs)
        except Exception:
            pass  # attribution extras never break the bench
        with MetricsLogger(metrics_file) as _lg:
            log_programs(_lg)
    value = n_rows * iters / elapsed / n_chips
    peak = _resolve_peak()
    # lbfgs data pass: eta = X@beta (2nd) + grad = X.T@resid (2nd) per
    # counted iteration; line-search passes uncounted (consistent with
    # the samples metric, so mfu undercounts like it does)
    logreg_flops = 4.0 * n_rows * n_feat * iters

    # sklearn reference on a host subsample of the same data
    from sklearn.linear_model import LogisticRegression as SkLR

    sub = min(n_rows, 100_000)
    Xh = np.asarray(X[:sub])
    yh = np.asarray(y[:sub])
    sk = SkLR(solver="lbfgs", max_iter=max_iter, tol=0.0)
    t0 = time.perf_counter()
    sk.fit(Xh, yh)
    sk_elapsed = time.perf_counter() - t0
    sk_iters = int(np.max(sk.n_iter_)) or max_iter
    sk_value = sub * sk_iters / sk_elapsed

    result = {
        "metric": "logreg_fit_samples_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "samples/s/chip",
        "vs_baseline": round(value / sk_value, 3),
        "backend": backend,
        "dtype": dtype,
        "n_chips": n_chips,
        "n_rows": n_rows,
        "n_features": n_feat,
        "iters": int(iters),
        # the baseline side of the ratio, spelled out: sklearn lbfgs on a
        # host subsample of the SAME data, normalized per sample per
        # counted iteration — so the ratio compares per-sample throughput,
        # not absolute wall clock at mismatched sizes
        "baseline": {
            "what": "sklearn LogisticRegression(lbfgs) on this host's CPU",
            "n_rows": int(sub),
            "iters": int(sk_iters),
            "samples_per_sec": round(sk_value, 1),
        },
        "metrics_file": metrics_file,
        **_mfu_fields(logreg_flops, elapsed, n_chips, peak),
    }
    # secondary BASELINE configs (VERDICT r2 #6) — each guarded so a
    # failure degrades to an error entry instead of killing the headline.
    # The headline + each completed extra land in _partial as they finish
    # so the watchdog can emit real numbers even on a deadline overrun.
    _partial["result"] = result
    extras = _partial["extras"]

    def _try(fn, *args):
        try:
            out = fn(*args)
            # a section may return several metric entries (fleet does)
            extras.extend(out if isinstance(out, list) else [out])
        except Exception as exc:  # record and continue; Ctrl-C still exits
            extras.append({"metric": fn.__name__, "value": None,
                           "error": f"{type(exc).__name__}: {exc}"})

    # the extras run under an EXPLICIT f32 default: their recorded
    # metrics are labeled dtype="float32", and the config.dtype="auto"
    # policy (bf16 on TPU since ISSUE 8) must not silently change what
    # a recorded series measures. Sections that time bf16 on purpose
    # (kmeans_bf16 / logreg_bf16 / the streamed bf16 flavor) set
    # dtype="bfloat16" internally, which nests OVER this pin.
    with config.set(dtype="float32"):
        _try(_bench_logreg_f32, jax, on_tpu, n_chips, Xs, ys)
        # free the headline design matrix BEFORE the kmeans/rsvd
        # configs — holding its HBM alongside their working sets OOMs
        # a 16G chip
        del Xs, ys, X, y
        _try(_bench_kmeans, jax, on_tpu, n_chips, peak)
        _try(_bench_kmeans_bf16, jax, on_tpu, n_chips, peak)
        _try(_bench_logreg_bf16, jax, on_tpu, n_chips, peak)
        _try(_bench_rsvd, jax, on_tpu, n_chips, peak)
        _try(_bench_incremental_sgd, jax, on_tpu, n_chips, peak)
        _try(_bench_streamed_sgd, jax, on_tpu, n_chips, peak)
        _try(_bench_sharded_streaming, jax, on_tpu, n_chips)
        _try(_bench_fused_sharded_stream, jax, on_tpu, n_chips)
        _try(_bench_sparse_stream, jax, on_tpu, n_chips)
        _try(_bench_feature_sharded, jax, on_tpu, n_chips)
        _try(_bench_hyperband, jax, on_tpu, n_chips)
        _try(_bench_c_grid_search, jax, on_tpu, n_chips)
        _try(_bench_serving, jax, on_tpu, n_chips)
        _try(_bench_int8_serving, jax, on_tpu, n_chips)
        _try(_bench_fleet, jax, on_tpu, n_chips)
        _try(_bench_drift, jax, on_tpu, n_chips)
        _try(_bench_plan_warm_start, jax, on_tpu, n_chips)
        _try(_bench_request_trace, jax, on_tpu, n_chips)
        _try(_bench_federation, jax, on_tpu, n_chips)
        _try(_bench_fleet_observability, jax, on_tpu, n_chips)
        _try(_bench_incident_plane, jax, on_tpu, n_chips)
    result["extra_metrics"] = extras
    # every successful metric also APPENDS to BENCH_floors.jsonl (run
    # marker + one kind="bench_metric" record each; the file is never
    # truncated, unlike the per-run BENCH_metrics.jsonl trace):
    # scripts/bench_sentinel.py seeds budget floors for metrics no
    # recorded round carries yet from the runs BEFORE the newest one —
    # so the *_bf16 / *_int8 flavors recorded in the session that added
    # them gate the very first round that lands them
    try:
        floors_file = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_floors.jsonl",
        )
        with open(floors_file, "a") as fh:
            fh.write(json.dumps(
                {"kind": "bench_run_start", "t": time.time(),
                 "backend": backend}
            ) + "\n")
            for entry in [result] + extras:
                if entry.get("metric") and entry.get("value") is not None:
                    fh.write(json.dumps({
                        "kind": "bench_metric",
                        "metric": entry["metric"],
                        "value": entry["value"],
                        "unit": entry.get("unit", ""),
                        "backend": entry.get("backend"),
                    }) + "\n")
    except Exception:
        pass
    return result


def _bench_c_grid_search(jax, on_tpu, n_chips):
    """GridSearchCV over a pure-C logreg grid: the stacked-lam fast path
    (all candidates in one compiled solve per fold) vs the general
    per-candidate path (same fits, forced by an extra constant grid
    key). Reports both so the speedup is on record per backend."""
    import time

    import jax.numpy as jnp

    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.model_selection import GridSearchCV
    from dask_ml_tpu.parallel import as_sharded

    n = 1_000_000 if on_tpu else 100_000
    d = 64
    key = jax.random.PRNGKey(5)

    @jax.jit
    def gen():
        kx, ky = jax.random.split(key)
        X = jax.random.normal(kx, (n, d), jnp.float32)
        y = (X[:, 0] + 0.5 * jax.random.normal(ky, (n,)) > 0).astype(
            jnp.float32
        )
        return X, y

    X, y = jax.block_until_ready(gen())
    Xs, ys = as_sharded(X), as_sharded(y)
    Cs = [10.0 ** e for e in range(-4, 4)]

    def run(params):
        s = GridSearchCV(
            LogisticRegression(solver="lbfgs", max_iter=20, tol=0.0),
            params, cv=2, refit=False, scheduler="synchronous",
        )
        s.fit(Xs, ys)
        return s

    run({"C": Cs})  # compile warmup
    t0 = time.perf_counter()
    fast = run({"C": Cs})
    t_fast = time.perf_counter() - t0
    # fail BEFORE paying for the general-path runs, and with a real
    # raise (assert vanishes under -O): a silent fallback would label
    # general-path timing as the fast path
    if getattr(fast, "_c_grid_vmapped_", None) != len(Cs):
        raise RuntimeError(
            "C-grid fast path not taken: "
            f"{getattr(fast, '_c_grid_fallback_', 'ineligible')}"
        )
    general = {"C": Cs, "intercept_scaling": [1.0]}
    run(general)
    t0 = time.perf_counter()
    run(general)
    t_general = time.perf_counter() - t0
    return {
        "metric": "c_grid_search_seconds",
        "value": round(t_fast, 3),
        "unit": "s",
        "backend": jax.default_backend(),
        "dtype": "float32",
        "n_rows": n,
        "n_features": d,
        "n_candidates": len(Cs),
        "cv": 2,
        "general_path_seconds": round(t_general, 3),
        "speedup_vs_general": round(t_general / t_fast, 3),
    }


def _bench_logreg_f32(jax, on_tpu, n_chips, Xs, ys):
    """f32 point for the SAME headline fit so the bf16 contribution is
    attributable (ADVICE r1 #3). Skipped-on-CPU is impossible: on CPU the
    headline IS f32, so this just re-measures at fewer iterations."""
    import time

    from dask_ml_tpu import config
    from dask_ml_tpu.linear_model import LogisticRegression

    max_iter = 20
    with config.set(dtype="float32"):
        LogisticRegression(solver="lbfgs", max_iter=1, tol=0.0).fit(Xs, ys)
        t0 = time.perf_counter()
        clf = LogisticRegression(solver="lbfgs", max_iter=max_iter,
                                 tol=0.0).fit(Xs, ys)
        elapsed = time.perf_counter() - t0
    iters = clf.n_iter_ or max_iter
    return {
        "metric": "logreg_fit_samples_per_sec_per_chip_f32",
        "value": round(Xs.n_rows * iters / elapsed / n_chips, 1),
        "unit": "samples/s/chip",
        "backend": jax.default_backend(),
        "dtype": "float32",
        "n_rows": Xs.n_rows,
        "iters": int(iters),
    }


def _bench_kmeans(jax, on_tpu, n_chips, peak):
    """BASELINE configs[1]: KMeans (k=64) Lloyd iterations/sec. d=128
    keeps the lane dimension at the TPU tile width (d=64 would pad 2x in
    HBM)."""
    import time

    import jax.numpy as jnp

    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.parallel import as_sharded

    n = 8_000_000 if on_tpu else 100_000
    d, k, iters = 128, 64, 10
    key = jax.random.PRNGKey(1)

    @jax.jit
    def gen():
        return jax.random.normal(key, (n, d), jnp.float32)

    X = as_sharded(jax.block_until_ready(gen()))
    init = np.asarray(X.data[:k])
    km = KMeans(n_clusters=k, init=init, max_iter=2, tol=0.0)
    km.fit(X)  # compile warmup at full shape
    t0 = time.perf_counter()
    km = KMeans(n_clusters=k, init=init, max_iter=iters, tol=0.0)
    km.fit(X)
    elapsed = time.perf_counter() - t0
    return {
        "metric": "kmeans_lloyd_iterations_per_sec",
        "value": round(km.n_iter_ / elapsed, 3),
        "unit": "iterations/s",
        "backend": jax.default_backend(),
        "dtype": "float32",
        "n_rows": n,
        "n_features": d,
        "k": k,
        "samples_per_sec_per_chip": round(n * km.n_iter_ / elapsed / n_chips, 1),
        # distance matmul only (2ndk per Lloyd iteration) — a lower bound
        # that excludes the assignment reduce and center accumulation
        **_mfu_fields(2.0 * n * d * k * km.n_iter_, elapsed, n_chips, peak),
    }


def _bench_kmeans_bf16(jax, on_tpu, n_chips, peak):
    """KMeans with config.dtype='bfloat16': the Lloyd distance matmul at
    bf16/f32-accumulation (VERDICT r4 missing #5 — the bf16 policy now
    reaches past the GLMs). On CPU bf16 is emulated and SLOWER — the
    line exists so both dtypes are always on record; TPU is where the
    2x MXU rate shows."""
    import time

    import jax.numpy as jnp

    from dask_ml_tpu import config
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.parallel import as_sharded

    n = 8_000_000 if on_tpu else 100_000
    d, k, iters = 128, 64, 10
    key = jax.random.PRNGKey(1)

    @jax.jit
    def gen():
        return jax.random.normal(key, (n, d), jnp.float32)

    X = as_sharded(jax.block_until_ready(gen()))
    init = np.asarray(X.data[:k])

    def timed(dtype):
        # BOTH dtypes on the XLA path (use_pallas=False): the headline
        # f32 line may use the Pallas kernel on TPU, so this pair — not
        # that line — isolates the dtype effect from the kernel choice
        with config.set(dtype=dtype):
            KMeans(n_clusters=k, init=init, max_iter=2, tol=0.0,
                   use_pallas=False).fit(X)
            km = KMeans(n_clusters=k, init=init, max_iter=iters,
                        tol=0.0, use_pallas=False)
            t0 = time.perf_counter()
            km.fit(X)
            return km.n_iter_, time.perf_counter() - t0

    it_f32, el_f32 = timed("float32")
    it_b16, el_b16 = timed("bfloat16")
    return {
        "metric": "kmeans_lloyd_iterations_per_sec_bf16",
        "value": round(it_b16 / el_b16, 3),
        "unit": "iterations/s",
        "backend": jax.default_backend(),
        "dtype": "bfloat16",
        "n_rows": n,
        "n_features": d,
        "k": k,
        "f32_xla_iterations_per_sec": round(it_f32 / el_f32, 3),
        **_mfu_fields(2.0 * n * d * k * it_b16, el_b16, n_chips, peak),
    }


def _bench_logreg_bf16(jax, on_tpu, n_chips, peak):
    """LogisticRegression with config.dtype='bfloat16' at the headline
    shape of the CURRENT backend (4M x 256 on TPU, 200k x 64 on CPU) —
    on TPU the headline is already bf16 so this re-measures it at fewer
    iterations; on CPU it records the bf16-emulation counterpoint so
    f32 and bf16 lines both exist on every backend."""
    import time

    from dask_ml_tpu import config, datasets
    from dask_ml_tpu.linear_model import LogisticRegression

    n = 4_000_000 if on_tpu else 200_000
    n_feat = 256 if on_tpu else 64
    X, y = datasets.make_classification(
        n_samples=n, n_features=n_feat, random_state=0
    )
    max_iter = 20
    with config.set(dtype="bfloat16"):
        LogisticRegression(solver="lbfgs", max_iter=1, tol=0.0).fit(X, y)
        t0 = time.perf_counter()
        clf = LogisticRegression(solver="lbfgs", max_iter=max_iter,
                                 tol=0.0).fit(X, y)
        elapsed = time.perf_counter() - t0
    iters = clf.n_iter_ or max_iter
    return {
        "metric": "logreg_fit_samples_per_sec_per_chip_bf16",
        "value": round(n * iters / elapsed / n_chips, 1),
        "unit": "samples/s/chip",
        "backend": jax.default_backend(),
        "dtype": "bfloat16",
        "n_rows": n,
        "iters": int(iters),
    }


def _bench_rsvd(jax, on_tpu, n_chips, peak):
    """BASELINE configs[2]: tall-skinny randomized SVD completes."""
    import time

    import jax.numpy as jnp

    from dask_ml_tpu.decomposition import TruncatedSVD
    from dask_ml_tpu.parallel import as_sharded

    n = 1_000_000 if on_tpu else 100_000
    d = 512 if on_tpu else 128
    k = 32
    key = jax.random.PRNGKey(2)

    @jax.jit
    def gen():
        return jax.random.normal(key, (n, d), jnp.float32)

    X = as_sharded(jax.block_until_ready(gen()))
    q_iters = 4  # explicit so the flop model below matches what runs
    # cold run pays the (one-time, cached) XLA compile; the metric is the
    # warm completion — what a second call or a bigger same-shape matrix
    # experiences
    TruncatedSVD(n_components=k, algorithm="randomized", n_iter=q_iters,
                 random_state=0).fit(X)
    svd = TruncatedSVD(n_components=k, algorithm="randomized",
                       n_iter=q_iters, random_state=0)
    t0 = time.perf_counter()
    svd.fit(X)
    elapsed = time.perf_counter() - t0
    assert np.isfinite(svd.singular_values_).all()
    # Halko data passes: X@Omega + q power iters (X.T@Q, X@Qz each) +
    # Q.T@X, all (n, d)x(d, l) with l = k + 10 oversamples = 2ndl(2q+2)
    l = k + 10
    rsvd_flops = 2.0 * n * d * l * (2 * q_iters + 2)
    return {
        "metric": "randomized_svd_seconds",
        "value": round(elapsed, 3),
        "unit": "s",
        "backend": jax.default_backend(),
        "dtype": "float32",
        "n_rows": n,
        "n_features": d,
        "n_components": k,
        **_mfu_fields(rsvd_flops, elapsed, n_chips, peak),
    }


def _bench_incremental_sgd(jax, on_tpu, n_chips, peak):
    """BASELINE configs[3]: Incremental(SGDClassifier) streaming
    partial_fit over TPU-resident blocks — one full epoch, blocks gathered
    on device (take_rows), model state device-resident throughout."""
    import time

    import jax.numpy as jnp

    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.parallel import as_sharded
    from dask_ml_tpu.wrappers import Incremental

    n = 2_000_000 if on_tpu else 400_000
    d = 128
    key = jax.random.PRNGKey(3)

    @jax.jit
    def gen():
        kx, ky = jax.random.split(key)
        X = jax.random.normal(kx, (n, d), jnp.float32)
        y = (X[:, 0] + 0.3 * jax.random.normal(ky, (n,)) > 0).astype(
            jnp.float32
        )
        return X, y

    X, y = jax.block_until_ready(gen())
    Xs, ys = as_sharded(X), as_sharded(y)
    inc = Incremental(SGDClassifier(max_iter=1, random_state=0),
                      shuffle_blocks=False)
    # two warmups: the first compiles at the fresh-zeros weight
    # sharding, the second at the steady-state replicated one
    inc.fit(Xs, ys)
    inc.fit(Xs, ys)
    t0 = time.perf_counter()
    inc.fit(Xs, ys)
    elapsed = time.perf_counter() - t0
    return {
        "metric": "incremental_sgd_samples_per_sec_per_chip",
        "value": round(n / elapsed / n_chips, 1),
        "unit": "samples/s/chip",
        "backend": jax.default_backend(),
        "dtype": "float32",
        "n_rows": n,
        "n_features": d,
        # one epoch: forward (2nd) + backward (2nd) over every sample
        **_mfu_fields(4.0 * n * d, elapsed, n_chips, peak),
    }


def _bench_streamed_sgd(jax, on_tpu, n_chips, peak):
    """Out-of-core SGD over a memmap through the instrumented
    BlockStream (VERDICT r4 weak #2): reports measured overlap — how
    much of each pass moved data (host slice + put + transfer wait) vs
    computed — and the block autotune's growth across epochs."""
    import os
    import tempfile
    import time

    import numpy as np

    from dask_ml_tpu import config
    from dask_ml_tpu.models.sgd import SGDClassifier

    n = 2_000_000 if on_tpu else 400_000
    d = 128
    epochs = 3
    # block height: n/32 as before on CPU; on TPU rounded DOWN to a
    # 128-multiple so the fused Pallas streamed kernels' grid
    # (ops/pallas_fused.stream_tile) engages instead of falling back
    block_rows = max(n // 32, 1)
    if on_tpu:
        block_rows = max(block_rows // 128 * 128, 128)
    rng = np.random.RandomState(7)
    path = os.path.join(tempfile.mkdtemp(), "bench_sgd_X.f32")
    X = np.memmap(path, dtype=np.float32, mode="w+", shape=(n, d))
    w = rng.randn(d).astype(np.float32)
    y = np.empty(n, np.float32)
    for lo in range(0, n, 200_000):
        hi = min(lo + 200_000, n)
        X[lo:hi] = rng.randn(hi - lo, d)
        y[lo:hi] = (X[lo:hi] @ w > 0)
    X.flush()
    Xr = np.memmap(path, dtype=np.float32, mode="r", shape=(n, d))
    # fix the block size so warmup compiles at EXACTLY the timed shape
    # (autotune stays off: a resize would recompile inside the timed
    # region and make the partition load-dependent)
    from dask_ml_tpu.utils.observability import (MetricsLogger,
                                                 active_logger)

    with config.set(stream_block_rows=block_rows,
                    stream_autotune=False):
        warm = SGDClassifier(max_iter=1, random_state=0, shuffle=False)
        warm.fit(Xr, y)  # one full epoch at the timed block shape
        clf = SGDClassifier(max_iter=epochs, random_state=0,
                            shuffle=False)
        # a bound logger turns on the readiness sync so wait_s (the
        # transfer-stall component of "moving") is actually measured,
        # and streams per-pass JSONL next to the memmap
        with MetricsLogger(path + ".stream.jsonl") as lg, \
                active_logger(lg):
            t0 = time.perf_counter()
            clf.fit(Xr, y)
            elapsed = time.perf_counter() - t0
    st = dict(getattr(clf, "_last_stream_stats", None) or {})
    if st.get("superblock_k"):
        # super-block passes stage + device_put on a background worker
        # (overlapped with the scan); the consumer's data-movement cost
        # is its measured STALL, not the worker's busy time
        moving = st.get("wait_s", 0)
    else:
        moving = st.get("host_s", 0) + st.get("put_s", 0) \
            + st.get("wait_s", 0)
    # the per-block path for the on-record super-block speedup ratio
    # (same data, same partition, one dispatch per block instead of
    # one per K)
    with config.set(stream_block_rows=block_rows,
                    stream_autotune=False, stream_superblock=False):
        pb_warm = SGDClassifier(max_iter=1, random_state=0, shuffle=False)
        pb_warm.fit(Xr, y)
        pb = SGDClassifier(max_iter=epochs, random_state=0, shuffle=False)
        t0 = time.perf_counter()
        pb.fit(Xr, y)
        pb_elapsed = time.perf_counter() - t0
    # bf16 streamed flavor (ISSUE 8): the same hot loop with the fit
    # compute dtype forced to bf16 — on TPU this is what the "auto"
    # policy serves by default (fused kernels at bf16 MXU rate); on CPU
    # it documents the software-bf16 penalty the auto policy's f32
    # fallback avoids. Recorded per backend, so the sentinel floor is
    # backend-matched.
    with config.set(stream_block_rows=block_rows, stream_autotune=False,
                    dtype="bfloat16"):
        b16_warm = SGDClassifier(max_iter=1, random_state=0,
                                 shuffle=False)
        b16_warm.fit(Xr, y)
        b16 = SGDClassifier(max_iter=epochs, random_state=0,
                            shuffle=False)
        t0 = time.perf_counter()
        b16.fit(Xr, y)
        b16_elapsed = time.perf_counter() - t0
    # demonstrate the opt-in autotune separately (not in the timed run):
    # 2 epochs, report where the block size and K land
    with config.set(stream_block_rows=block_rows,
                    stream_autotune=True):
        at = SGDClassifier(max_iter=2, random_state=0, shuffle=False)
        at.fit(Xr, y)
    at_st = dict(getattr(at, "_last_stream_stats", None) or {})
    os.unlink(path)
    bf16_metric = {
        "metric": "streamed_sgd_samples_per_sec_per_chip_bf16",
        "value": round(n * epochs / b16_elapsed / n_chips, 1),
        "unit": "samples/s/chip",
        "backend": jax.default_backend(),
        "dtype": "bfloat16",
        "fit_dtype": getattr(b16, "fit_dtype_", None),
        "n_rows": n,
        "n_features": d,
        "epochs": epochs,
        "ratio_vs_f32": round(elapsed / b16_elapsed, 3),
    }
    return [{
        "metric": "streamed_sgd_samples_per_sec_per_chip",
        "value": round(n * epochs / elapsed / n_chips, 1),
        "unit": "samples/s/chip",
        "backend": jax.default_backend(),
        "dtype": "float32",
        "n_rows": n,
        "n_features": d,
        "epochs": epochs,
        "overlap": {
            "block_rows": st.get("block_rows"),
            "n_blocks": st.get("n_blocks"),
            "last_pass_s": st.get("pass_s"),
            "moving_s": round(moving, 4),
            "compute_s": round(st.get("consume_s", 0.0), 4),
            "moving_frac": round(
                moving / max(st.get("pass_s", 0.0), 1e-9), 4
            ),
            # opt-in autotune's landing point after 2 epochs (untimed)
            "autotuned_block_rows": at_st.get("block_rows"),
            "autotuned_n_blocks": at_st.get("n_blocks"),
            "autotuned_superblock_k": at_st.get("superblock_k"),
        },
        "superblock": {
            # the fused hot loop's dispatch accounting (ISSUE 3): one
            # scan per K blocks, donated weight carry
            "superblock_k": st.get("superblock_k"),
            "dispatches_per_pass": st.get("dispatches_per_pass"),
            "per_block_samples_per_sec_per_chip": round(
                n * epochs / pb_elapsed / n_chips, 1
            ),
            "speedup_vs_per_block": round(pb_elapsed / elapsed, 3),
        },
        **_mfu_fields(4.0 * n * d * epochs, elapsed, n_chips, peak),
    }, bf16_metric]


def _bench_sharded_streaming(jax, on_tpu, n_chips):
    """Data-parallel superblock streaming (ISSUE 9): the streamed-SGD
    hot loop at data-axis widths {1, 8}. On CPU each width runs in its
    own grandchild process (`BENCH_SHARDED_CHILD`) so the virtual
    device count can differ per measurement; on TPU both widths run
    in-process over the real chips via config.stream_mesh. Records
    samples/s/chip per width plus the sharded flavor's AGGREGATE
    rows/s — on shared-silicon virtual devices the per-chip number
    documents plumbing overhead, on a real slice it is the scaling
    headline tpu_smoke round-9 verifies."""
    import subprocess
    import time

    def run_width(n_devices):
        if on_tpu:
            from dask_ml_tpu import config as _cfg
            from dask_ml_tpu.models.sgd import SGDClassifier

            import numpy as _np

            n, d, epochs = 400_000, 64, 2
            rng = _np.random.RandomState(9)
            X = rng.randn(n, d).astype(_np.float32)
            y = (X[:, 0] > 0).astype(_np.float32)
            sm = 1 if n_devices == 1 else 0
            with _cfg.set(stream_block_rows=n // 16,
                          stream_autotune=False, stream_mesh=sm):
                SGDClassifier(max_iter=1, random_state=0,
                              shuffle=False).fit(X, y)
                clf = SGDClassifier(max_iter=epochs, random_state=0,
                                    shuffle=False)
                t0 = time.perf_counter()
                clf.fit(X, y)
                elapsed = time.perf_counter() - t0
            st = dict(getattr(clf, "_last_stream_stats", None) or {})
            return {"n_devices": int(st.get("sb_shards", 1)),
                    "rows_per_sec": n * epochs / elapsed,
                    "n_rows": n, "epochs": epochs}
        # no XLA_FLAGS override: the grandchild's force_cpu_platform
        # APPENDS/RAISES the device-count flag inside whatever ambient
        # tuning flags exist — replacing the variable here would run
        # the sharded measurements under a different XLA configuration
        # than every other bench flavor
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            BENCH_SHARDED_CHILD=str(n_devices),
        )
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            timeout=180, capture_output=True, text=True,
        )
        out = _last_json_line(r.stdout)
        if out is None or out.get("error"):
            raise RuntimeError(
                f"sharded child (n_devices={n_devices}) failed: "
                f"{(out or {}).get('error')} "
                f"{(r.stderr or '')[-500:]}"
            )
        return out

    res = {nd: run_width(nd) for nd in (1, 8)}
    # metric names carry the ACTUAL data-parallel width, not the
    # requested one: on CPU the virtual-device forcing makes them equal
    # ({1, 8} per the recorded series), but a TPU attach runs stream_
    # mesh=0 at whatever the slice has — recording a 4-chip (or 1-chip)
    # run under a "dp8" name would seed sentinel floors for a series it
    # never measured
    entries = []
    seen = set()
    for nd in (1, 8):
        r = res[nd]
        chips = max(int(r["n_devices"]), 1)
        if chips in seen:
            continue  # 1-chip attach: the "sharded" run IS the dp1 run
        seen.add(chips)
        entries.append({
            "metric": f"streamed_sgd_sharded_dp{chips}"
                      f"_samples_per_sec_per_chip",
            "value": round(r["rows_per_sec"] / chips, 1),
            "unit": "samples/s/chip",
            "backend": jax.default_backend(),
            "n_devices": chips,
            "n_rows": r["n_rows"],
            "epochs": r["epochs"],
        })
    width = max(int(res[8]["n_devices"]), 1)
    if width > 1:
        entries.append({
            "metric": f"streamed_sgd_sharded_dp{width}_rows_per_sec",
            "value": round(res[8]["rows_per_sec"], 1),
            "unit": "rows/s",
            "backend": jax.default_backend(),
            "n_devices": width,
            # the honest shared-silicon caveat: virtual CPU devices
            # split the same cores, so aggregate ~flat is expected
            # off-TPU
            "vs_dp1_ratio": round(
                res[8]["rows_per_sec"]
                / max(res[1]["rows_per_sec"], 1e-9), 3,
            ),
        })
    return entries


def _sharded_child_main():
    """Grandchild body for `_bench_sharded_streaming` /
    `_bench_fused_sharded_stream` on CPU: one streamed-SGD fit at the
    ambient (forced) virtual device count — with ``BENCH_SHARDED_FUSED``
    set, the fused Pallas bodies run inside the shard_map programs
    through the interpreter at 128-multiple per-shard slabs — one JSON
    line out."""
    out = {"error": None}
    try:
        from dask_ml_tpu._platform import force_cpu_platform

        n_devices = int(os.environ["BENCH_SHARDED_CHILD"])
        fused = bool(os.environ.get("BENCH_SHARDED_FUSED"))
        force_cpu_platform(n_devices=n_devices)
        import numpy as np

        from dask_ml_tpu import config as _cfg
        from dask_ml_tpu.models.sgd import SGDClassifier

        n, d, epochs = 200_000, 32, 2
        if fused:
            # interpreter-speed kernels: a smaller honest measurement,
            # at a block height whose per-shard slab is a 128-multiple
            # (the fused tile gate)
            n, block_rows = 65_536, 2048
        else:
            block_rows = n // 16
        rng = np.random.RandomState(9)
        X = rng.randn(n, d).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        sm = 1 if n_devices == 1 else 0
        with _cfg.set(stream_block_rows=block_rows,
                      stream_autotune=False, stream_mesh=sm,
                      pallas_stream_interpret=fused):
            SGDClassifier(max_iter=1, random_state=0,
                          shuffle=False).fit(X, y)  # warm compiles
            clf = SGDClassifier(max_iter=epochs, random_state=0,
                                shuffle=False)
            t0 = time.perf_counter()
            clf.fit(X, y)
            elapsed = time.perf_counter() - t0
        st = dict(getattr(clf, "_last_stream_stats", None) or {})
        want = n_devices
        if int(st.get("sb_shards", 1)) != want:
            raise RuntimeError(
                f"sharded child ran at sb_shards={st.get('sb_shards')}"
                f", wanted {want}"
            )
        info = dict(getattr(clf, "solver_info_", None) or {})
        if fused and not info.get("fused_stream"):
            raise RuntimeError(
                "fused child fell back to the XLA bodies "
                f"(reason={info.get('fused_stream_reason')})"
            )
        out.update(
            metric="streamed_sgd_sharded_child",
            n_devices=int(st.get("sb_shards", 1)),
            rows_per_sec=n * epochs / elapsed,
            n_rows=n, epochs=epochs,
            dispatches_per_pass=st.get("dispatches_per_pass"),
            fused=fused,
        )
    except Exception as exc:  # one JSON line no matter what
        out["error"] = f"{type(exc).__name__}: {exc}"
        out["metric"] = "streamed_sgd_sharded_child"
    print(json.dumps(out), flush=True)


def _mesh2d_measure(shape):
    """One feature-sharded measurement (ISSUE 18), shared by the TPU
    in-process path and the CPU grandchild: assert the 1-D stage
    REFUSES the wide-d fit under the simulated per-device byte budget
    (typed StreamBudgetExceeded), then time the same fit — and a
    streamed randomized PCA — on the 2-D ``shape`` mesh, where the X
    slabs stage as (rows/D, d/M) per-device tiles under the SAME
    budget."""
    import time

    import numpy as np

    from dask_ml_tpu import config as _cfg
    from dask_ml_tpu import observability as obs
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.models.pca import PCA
    from dask_ml_tpu.parallel.streaming import (BlockStream,
                                                StreamBudgetExceeded)

    n, d, block_rows = 65_536, 512, 2048
    # single-device staging needs K x 2048 x 512 x 4 = ~33.5MB; the 2x4
    # tiles need ~4.3MB — the budget sits between, so the SAME fit is a
    # typed refusal on 1-D and a measurement on the hybrid mesh
    budget = 8_000_000
    rng = np.random.RandomState(18)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)

    refused = False
    try:
        with _cfg.set(stream_block_rows=block_rows,
                      stream_autotune=False, stream_mesh=1,
                      stream_device_byte_budget=budget):
            LogisticRegression(solver="lbfgs", max_iter=2).fit(X, y)
    except StreamBudgetExceeded:
        refused = True
    if not refused:
        raise RuntimeError(
            "1-D stage did not refuse the wide-d fit under "
            f"stream_device_byte_budget={budget}"
        )

    with _cfg.set(stream_block_rows=block_rows, stream_autotune=False,
                  stream_mesh=0, mesh_shape=shape,
                  stream_device_byte_budget=budget):
        st = BlockStream((X, y.astype(np.float32)),
                         block_rows=block_rows)
        D, M = st.sb_data_shards(), st.sb_model_shards()
        if M <= 1:
            raise RuntimeError(
                "model axis did not engage "
                f"(reason={st.model_tile_reason})"
            )
        LogisticRegression(solver="lbfgs", max_iter=2).fit(X, y)  # warm
        obs.counters_reset()
        t0 = time.perf_counter()
        LogisticRegression(solver="lbfgs", max_iter=8).fit(X, y)
        glm_s = time.perf_counter() - t0
        # rows actually streamed through the superblock plane (lbfgs
        # pass count is line-search dependent; the counter is exact)
        glm_rows = obs.counters_snapshot().get(
            "superblock_blocks", 0) * block_rows
        if glm_rows <= 0:
            raise RuntimeError("feature-sharded GLM fit did not stream")

        PCA(n_components=8, svd_solver="randomized",
            random_state=0).fit(X)                      # warm compiles
        t0 = time.perf_counter()
        PCA(n_components=8, svd_solver="randomized",
            random_state=0).fit(X)
        pca_s = time.perf_counter() - t0
    return {
        "mesh": f"{D}x{M}", "n_rows": n, "d": d,
        "glm_rows_per_sec": glm_rows / glm_s,
        # the streamed rSVD pass plan is FIXED: 1 moments + 3 range
        "pca_rows_per_sec": 4 * n / pca_s,
    }


def _mesh2d_child_main():
    """Grandchild body for `_bench_feature_sharded` on CPU: the whole
    measurement at a forced 8-virtual-device pool (mesh 2x4). One JSON
    line out."""
    out = {"error": None, "metric": "feature_sharded_child"}
    try:
        from dask_ml_tpu._platform import force_cpu_platform

        force_cpu_platform(
            n_devices=int(os.environ["BENCH_MESH2D_CHILD"])
        )
        out.update(_mesh2d_measure("2x4"))
    except Exception as exc:  # one JSON line no matter what
        out["error"] = f"{type(exc).__name__}: {exc}"
    print(json.dumps(out), flush=True)


def _bench_feature_sharded(jax, on_tpu, n_chips):
    """Feature-sharded streaming (ISSUE 18): a (rows, d) GLM fit the
    1-D path REFUSES under the simulated per-device byte budget
    (typed StreamBudgetExceeded) completes — and is timed — on the 2-D
    hybrid mesh, plus the streamed randomized PCA at the same width.
    On CPU the measurement runs in a grandchild so the 8-virtual-device
    pool can't leak into other sections; on TPU it runs in-process over
    the real chips with an inferred "-1x2" model axis."""
    if on_tpu:
        if n_chips < 2 or n_chips % 2:
            raise RuntimeError(
                f"needs an even multi-chip attach for a model axis, "
                f"have {n_chips}"
            )
        res = _mesh2d_measure("-1x2")
    else:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   BENCH_MESH2D_CHILD="8")
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            timeout=300, capture_output=True, text=True,
        )
        res = _last_json_line(r.stdout)
        if res is None or res.get("error"):
            raise RuntimeError(
                f"mesh2d child failed: {(res or {}).get('error')} "
                f"{(r.stderr or '')[-500:]}"
            )
    backend = jax.default_backend()
    common = {"backend": backend, "mesh": res["mesh"],
              "n_rows": res["n_rows"], "d": res["d"],
              "refused_1d": True}
    return [
        dict(common, metric="glm_feature_sharded_rows_per_sec",
             value=round(res["glm_rows_per_sec"], 1), unit="rows/s"),
        dict(common, metric="pca_streamed_rows_per_sec",
             value=round(res["pca_rows_per_sec"], 1), unit="rows/s"),
    ]


def _plan_warm_child_main():
    """Grandchild body for `_bench_plan_warm_start`: ONE process's
    fit+serve startup — a streamed SGD fit plus a full serving-grid
    warmup — through ``config.compile_cache_dir`` (the plan layer arms
    it on every ProgramPlan build). One JSON line out; the parent runs
    it twice against one cache dir to measure cold vs warm."""
    out = {"error": None, "metric": "plan_warm_start_child"}
    try:
        cache = os.environ["BENCH_PLAN_WARM_CHILD"]
        import numpy as np

        from dask_ml_tpu import config as _cfg
        from dask_ml_tpu.models.sgd import SGDClassifier
        from dask_ml_tpu.serving import BucketLadder, ModelServer

        rng = np.random.RandomState(11)
        # small data on purpose: startup is the COMPILE bill (streamed
        # scan + the serving grid), not the training compute — that is
        # what the persistent cache amortizes
        n, d = 16_384, 32
        X = rng.randn(n, d).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        with _cfg.set(compile_cache_dir=cache, stream_block_rows=2048,
                      stream_autotune=False, stream_mesh=1):
            t0 = time.perf_counter()
            clf = SGDClassifier(max_iter=2, random_state=0,
                                shuffle=False)
            clf.fit(X, y)
            ModelServer(clf, methods=("predict",),
                        ladder=BucketLadder(8, 256, 2.0)).warmup()
            out["startup_s"] = time.perf_counter() - t0
    except Exception as exc:  # one JSON line no matter what
        out["error"] = f"{type(exc).__name__}: {exc}"
    print(json.dumps(out), flush=True)


def _bench_plan_warm_start(jax, on_tpu, n_chips):
    """Plan warm-start section (ISSUE 15 satellite): cold-process vs
    warm-process fit+serve startup through ``compile_cache_dir``. Two
    identical grandchildren share one fresh cache directory: the first
    (cold) pays every XLA compile and seeds the persistent cache, the
    second (warm) replays them from disk. Records the warm startup
    seconds and the cold/warm speedup ratio (>= 1 when the cache
    works)."""
    import tempfile

    cache = tempfile.mkdtemp(prefix="bench_plan_warm_")
    env = dict(os.environ, BENCH_PLAN_WARM_CHILD=cache)
    env.pop("BENCH_CHILD", None)
    # the ambient env cache (set at bench import for the DRIVER's
    # compiles) would make "cold" warm — the child must see only the
    # fresh per-section directory, via config.compile_cache_dir.
    # Set "" rather than pop: the child re-imports bench.py, whose
    # import-time setdefault would silently restore the shared
    # .jax_cache for a missing var (an empty value is kept and
    # disables jax's env-armed cache)
    env["JAX_COMPILATION_CACHE_DIR"] = ""

    def one():
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, timeout=300, capture_output=True, text=True,
        )
        obj = _last_json_line(r.stdout)
        if not obj or obj.get("error") or obj.get("startup_s") is None:
            raise RuntimeError(
                "plan-warm child failed: "
                f"{obj.get('error') if obj else 'no JSON line'} "
                f"{(r.stderr or '')[-500:]}"
            )
        return float(obj["startup_s"])

    cold = one()
    warm = one()
    backend = jax.default_backend()
    common = {"unit": None, "backend": backend, "dtype": "float32",
              "n_chips": n_chips}
    return [
        {**common, "metric": "plan_warm_start_seconds",
         "value": round(warm, 3), "unit": "s",
         "cold_start_seconds": round(cold, 3),
         "baseline": {
             "what": "identical child process against an empty "
                     "compile cache (cold start)",
             "seconds": round(cold, 3),
         }},
        {**common, "metric": "plan_warm_start_ratio",
         "value": round(cold / max(warm, 1e-9), 3), "unit": "ratio"},
    ]


def _bench_fused_sharded_stream(jax, on_tpu, n_chips):
    """Fused x sharded streamed SGD (ISSUE 12) + the grad-accum flavor.

    On TPU the fused Pallas bodies run COMPILED inside the shard_map
    scan programs over the real chips; on CPU they run through the
    Pallas INTERPRETER in an 8-virtual-device grandchild — recorded
    honestly (backend "cpu", pallas_mode "interpret"), the same way the
    dp8 series documents virtual-device plumbing rather than real
    scaling. The grad-accum metric times the A=2 flavor in-process:
    its per-update host merge is the price of the cross-host-capable
    optimizer, and the recorded ratio vs the sequential flavor keeps
    that price visible."""
    import subprocess
    import time

    entries = []
    if on_tpu:
        from dask_ml_tpu import config as _cfg
        from dask_ml_tpu.models.sgd import SGDClassifier as _SGD

        import numpy as _np

        n, d, epochs = 400_000, 64, 2
        rng = _np.random.RandomState(12)
        X = rng.randn(n, d).astype(_np.float32)
        y = (X[:, 0] > 0).astype(_np.float32)
        with _cfg.set(stream_block_rows=2048, stream_autotune=False,
                      stream_mesh=0):
            _SGD(max_iter=1, random_state=0, shuffle=False).fit(X, y)
            clf = _SGD(max_iter=epochs, random_state=0, shuffle=False)
            t0 = time.perf_counter()
            clf.fit(X, y)
            elapsed = time.perf_counter() - t0
        st = dict(getattr(clf, "_last_stream_stats", None) or {})
        info = dict(getattr(clf, "solver_info_", None) or {})
        if not info.get("fused_stream"):
            # same contract as the CPU child: never record an unfused
            # run under the fused metric name (it would seed a
            # sentinel floor for a series that never ran — e.g. a
            # slice width whose per-shard slabs miss the 128-multiple)
            raise RuntimeError(
                "fused sharded fit fell back to the XLA bodies "
                f"(reason={info.get('fused_stream_reason')})"
            )
        chips = max(int(st.get("sb_shards", 1)), 1)
        entries.append({
            "metric": f"streamed_sgd_sharded_fused_dp{chips}"
                      f"_samples_per_sec_per_chip",
            "value": round(n * epochs / elapsed / chips, 1),
            "unit": "samples/s/chip",
            "backend": jax.default_backend(),
            "pallas_mode": "compiled",
            "fused_stream": True,
            "n_devices": chips, "n_rows": n, "epochs": epochs,
        })
    else:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   BENCH_SHARDED_CHILD="8", BENCH_SHARDED_FUSED="1")
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            timeout=600, capture_output=True, text=True,
        )
        out = _last_json_line(r.stdout)
        if out is None or out.get("error"):
            raise RuntimeError(
                f"fused sharded child failed: "
                f"{(out or {}).get('error')} {(r.stderr or '')[-500:]}"
            )
        chips = max(int(out["n_devices"]), 1)
        entries.append({
            "metric": f"streamed_sgd_sharded_fused_dp{chips}"
                      f"_samples_per_sec_per_chip",
            "value": round(out["rows_per_sec"] / chips, 1),
            "unit": "samples/s/chip",
            "backend": jax.default_backend(),
            # honest recording: this box runs the kernels through the
            # Pallas interpreter on shared-silicon virtual devices —
            # the number gates plumbing regressions, not chip speed
            "pallas_mode": "interpret",
            "n_devices": chips,
            "n_rows": out["n_rows"], "epochs": out["epochs"],
        })

    # grad-accum flavor (in-process; the sequential comparison uses the
    # same data/partition)
    from dask_ml_tpu import config as _cfg
    from dask_ml_tpu.models.sgd import SGDClassifier as _SGD

    import numpy as _np

    n, d, epochs, A = 200_000, 32, 2, 2
    rng = _np.random.RandomState(13)
    X = rng.randn(n, d).astype(_np.float32)
    y = (X[:, 0] > 0).astype(_np.float32)
    base = dict(stream_block_rows=n // 16, stream_autotune=False)

    def timed(**kw):
        with _cfg.set(**base, **kw):
            _SGD(max_iter=1, random_state=0, shuffle=False).fit(X, y)
            clf = _SGD(max_iter=epochs, random_state=0, shuffle=False)
            t0 = time.perf_counter()
            clf.fit(X, y)
            return clf, time.perf_counter() - t0

    seq, t_seq = timed()
    ga, t_ga = timed(stream_grad_accum=A)
    entries.append({
        "metric": f"streamed_sgd_grad_accum_a{A}_samples_per_sec_per_chip",
        "value": round(n * epochs / t_ga / n_chips, 1),
        "unit": "samples/s/chip",
        "backend": jax.default_backend(),
        "grad_accum": A,
        "n_rows": n, "epochs": epochs,
        # the documented price of the cross-host-capable flavor: one
        # host merge + separate apply dispatch per update
        "ratio_vs_sequential": round(t_seq / t_ga, 3),
    })
    return entries


def _bench_sparse_stream(jax, on_tpu, n_chips):
    """Device-resident sparse streaming (ISSUE 13) at the hashed-text
    shape: streamed SGD and GLM over a density ~1%, d=2**14 CSR corpus
    — the bucketed-nnz scan (config.stream_sparse) vs the per-block
    densify baseline (today's default) on the SAME data and block
    partition. The acceptance bar is >= 2x rows/s for at least one of
    SGD/GLM on CPU; nnz/s is the honest cost axis (the sparse path's
    work is nnz-proportional, the baseline's is n*d)."""
    import time

    import numpy as np
    import scipy.sparse as sp

    from dask_ml_tpu import config
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.models.sgd import SGDClassifier

    n = 120_000 if on_tpu else 60_000
    d = 2 ** 14
    npr = max(d // 100, 1)               # density ~1%
    epochs = 2
    block_rows = 1024
    rng = np.random.RandomState(11)
    # fixed-nnz-per-row CSR built directly — sp.random at this n*d is
    # pathological; duplicate column hits sum on both paths identically
    indices = rng.randint(0, d, size=n * npr).astype(np.int32)
    data = rng.rand(n * npr).astype(np.float32)
    indptr = np.arange(0, n * npr + 1, npr, dtype=np.int64)
    X = sp.csr_matrix((data, indices, indptr), shape=(n, d))
    w = rng.randn(d).astype(np.float32)
    eta = X @ w
    y = (eta > np.median(eta)).astype(np.float64)
    nnz = int(X.nnz)

    def timed_sgd(sparse_on):
        with config.set(stream_block_rows=block_rows,
                        stream_autotune=False, stream_mesh=1,
                        stream_sparse=sparse_on):
            warm = SGDClassifier(max_iter=1, random_state=0,
                                 shuffle=False)
            warm.fit(X, y)
            clf = SGDClassifier(max_iter=epochs, random_state=0,
                                shuffle=False)
            t0 = time.perf_counter()
            clf.fit(X, y)
            return time.perf_counter() - t0, clf

    def timed_glm(sparse_on):
        with config.set(stream_block_rows=block_rows,
                        stream_autotune=False, stream_mesh=1,
                        stream_sparse=sparse_on):
            warm = LogisticRegression(solver="gradient_descent",
                                      max_iter=1)
            warm.fit(X, y)
            clf = LogisticRegression(solver="gradient_descent",
                                     max_iter=3)
            t0 = time.perf_counter()
            clf.fit(X, y)
            return time.perf_counter() - t0, clf

    sp_s, sp_clf = timed_sgd(True)
    if not (sp_clf.solver_info_ or {}).get("sparse_stream"):
        raise RuntimeError(
            "sparse SGD bench fell back to densify (reason="
            f"{(sp_clf.solver_info_ or {}).get('sparse_stream_reason')})"
            " — a densify run must never seed a sparse-named floor"
        )
    dn_s, _ = timed_sgd(False)
    g_sp_s, g_clf = timed_glm(True)
    if not (g_clf.solver_info_ or {}).get("sparse_stream"):
        raise RuntimeError(
            "sparse GLM bench fell back to densify (reason="
            f"{(g_clf.solver_info_ or {}).get('sparse_stream_reason')})"
        )
    g_dn_s, g_ref = timed_glm(False)
    # each run normalizes by its OWN pass count: line-search trials
    # branch on float values, so the two flavors may take different
    # numbers of data passes for the same max_iter — the speedup is a
    # per-pass (rows/s vs rows/s) comparison, never raw wall clock of
    # unequal work
    g_passes = max(int((g_clf.solver_info_ or {}).get("data_passes", 1)),
                   1)
    g_dn_passes = max(
        int((g_ref.solver_info_ or {}).get("data_passes", 1)), 1
    )
    g_sp_rps = n * g_passes / g_sp_s
    g_dn_rps = n * g_dn_passes / g_dn_s
    backend = jax.default_backend()
    return [
        {
            "metric": "streamed_sparse_sgd_rows_per_sec",
            "value": round(n * epochs / sp_s, 1),
            "unit": "rows/s",
            "backend": backend,
            "dtype": "float32",
            "n_rows": n, "n_features": d, "density": npr / d,
            "epochs": epochs, "block_rows": block_rows,
            "nnz_per_sec": round(nnz * epochs / sp_s, 1),
            "densify_rows_per_sec": round(n * epochs / dn_s, 1),
            "speedup_vs_densify": round(dn_s / sp_s, 3),
            "criterion": ">=2x vs per-block densify",
        },
        {
            "metric": "streamed_sparse_glm_rows_per_sec",
            "value": round(g_sp_rps, 1),
            "unit": "rows/s",
            "backend": backend,
            "dtype": "float32",
            "n_rows": n, "n_features": d, "density": npr / d,
            "data_passes": g_passes, "block_rows": block_rows,
            "densify_data_passes": g_dn_passes,
            "nnz_per_sec": round(nnz * g_passes / g_sp_s, 1),
            "densify_rows_per_sec": round(g_dn_rps, 1),
            "speedup_vs_densify": round(g_sp_rps / g_dn_rps, 3),
        },
    ]


def _bench_int8_serving(jax, on_tpu, n_chips):
    """Int8 weight-quantized serving flavor (ISSUE 8): warm f32 and
    int8 compiled predict entry points for the same fitted logreg, run
    interleaved best-of passes over a ladder-bucket batch, report int8
    rows/s + the ratio vs f32 + prediction agreement (the >=99.5%
    criterion the parity suite enforces)."""
    import time

    import numpy as np

    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.wrappers import compiled_batch_fn

    n, d = (400_000 if on_tpu else 100_000), 64
    rng = np.random.RandomState(9)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d).astype(np.float32)
    y = (X @ w + 0.5 * rng.randn(n) > 0).astype(np.float32)
    clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(
        X[:50_000], y[:50_000]
    )
    f32 = compiled_batch_fn(clf, "predict")
    q8 = compiled_batch_fn(clf, "predict", quantize="int8")
    batch = X[:4096]
    import jax as _jax

    _jax.block_until_ready(f32._fn(f32._state[0], batch))   # warm
    _jax.block_until_ready(q8._fn(q8._state[0], batch))
    reps = 30

    def best_of(fn):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn(batch)
            np.asarray(out)
            best = min(best, time.perf_counter() - t0)
        return len(batch) * reps / best

    r32 = best_of(f32)
    r8 = best_of(q8)
    agree = float(np.mean(f32(X[:100_000]) == q8(X[:100_000])))
    return {
        "metric": "serving_predict_int8_rows_per_sec_per_chip",
        "value": round(r8 / n_chips, 1),
        "unit": "rows/s/chip",
        "backend": jax.default_backend(),
        "dtype": "int8xbf16",
        "n_features": d,
        "batch_rows": int(len(batch)),
        "f32_rows_per_sec_per_chip": round(r32 / n_chips, 1),
        "ratio_vs_f32": round(r8 / r32, 3),
        "prediction_agreement": round(agree, 5),
    }


def _bench_hyperband(jax, on_tpu, n_chips):
    """BASELINE configs[4]: HyperbandSearchCV wall clock. Since ISSUE
    14 the search cohort rides the streamed superblock plane (one
    BlockStream pass per adaptive round, slot-rung scans); the section
    times BOTH planes over the SAME host data and block partition —
    ``hyperband_seconds`` records the default (streamed) path,
    ``hyperband_device_plane_seconds`` the ``search_stream=False``
    device-resident cohort machinery it replaced, and the ratio is the
    honest A/B on identical bracket schedules (scores asserted equal).
    On this repo's 2-core CPU box the ratio is recorded as measured
    (~1.4-1.7x steady state — the streamed plane removes the device
    plane's per-round as_sharded+stack copies but shares its XLA step
    kernels); the >=2x regime is real TPU, where the fused cohort
    kernels engage and the removed copies are genuine HBM DMA —
    asserted by tpu_smoke round-13, like every other on-chip claim.
    ``hyperband_rows_per_sec`` + ``n_candidates`` land in the metrics
    so bench_sentinel can seed floors for the search plane."""
    import time

    from dask_ml_tpu import config
    from dask_ml_tpu.model_selection import HyperbandSearchCV
    from dask_ml_tpu.models.sgd import SGDClassifier

    n = 400_000
    d = 128
    rng = np.random.RandomState(4)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] + 0.5 * rng.randn(n) > 0).astype(np.float32)
    params = {"alpha": [1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 1e-2],
              "eta0": [0.01, 0.03, 0.05, 0.1, 0.3, 0.5]}

    def run_search(streamed):
        with config.set(search_stream=streamed):
            search = HyperbandSearchCV(
                SGDClassifier(tol=1e-3, random_state=0), params,
                max_iter=27, aggressiveness=3, random_state=0,
            )
            search.fit(X, y, classes=[0.0, 1.0])
        return search

    def timed(streamed):
        run_search(streamed)  # compile warmup: the metric is warm
        t0 = time.perf_counter()
        search = run_search(streamed)
        return search, time.perf_counter() - t0

    search, elapsed = timed(True)
    dev_search, dev_elapsed = timed(False)
    assert search.best_params_ == dev_search.best_params_ and \
        abs(search.best_score_ - dev_search.best_score_) <= 1e-6, (
        "streamed vs device-plane Hyperband diverged — the ratio "
        "below would compare different searches"
    )
    n_trials = len(search.cv_results_["params"])
    total_pf = int(np.sum(search.cv_results_["partial_fit_calls"]))
    meta = search.metadata_["stream"]
    # a fallback run must never seed streamed-named floors (same rule
    # as the sparse section): fail the section loudly instead
    assert meta.get("streamed"), (
        "hyperband bench did not engage the streamed cohort plane "
        f"(metadata: {meta}) — refusing to record streamed metrics "
        "from a device-plane run"
    )
    # rows the bracket actually touched: every partial_fit call trains
    # one block of the shared stream partition
    rows_touched = total_pf * meta["block_rows"]
    backend = jax.default_backend()
    head = {
        "metric": "hyperband_seconds",
        "value": round(elapsed, 3),
        "unit": "s",
        "backend": backend,
        "dtype": "float32",
        "n_rows": n,
        "n_features": d,
        "n_trials": n_trials,
        "n_candidates": n_trials,
        "partial_fit_calls": total_pf,
        "best_score": round(float(search.best_score_), 4),
        "stream_plane": {k: meta[k] for k in
                         ("n_blocks", "block_rows", "n_slots",
                          "dispatches", "shards", "sparse", "fused")},
        "device_plane_seconds": round(dev_elapsed, 3),
        "vs_device_plane": round(dev_elapsed / elapsed, 3),
    }
    rate = {
        "metric": "hyperband_rows_per_sec",
        "value": round(rows_touched / elapsed, 1),
        "unit": "rows/s",
        "backend": backend,
        "dtype": "float32",
        "n_candidates": n_trials,
        "rows_touched": int(rows_touched),
    }
    dev = {
        "metric": "hyperband_device_plane_seconds",
        "value": round(dev_elapsed, 3),
        "unit": "s",
        "backend": backend,
        "dtype": "float32",
        "n_candidates": n_trials,
    }
    return [head, rate, dev]


def _bench_serving(jax, on_tpu, n_chips):
    """Serving section: batched ModelServer throughput + p50/p99 latency
    over concurrent ragged requests vs the naive one-request-at-a-time
    predict loop on the SAME fitted model (which pays a fresh XLA
    compile per novel request shape plus a host->device hop per call —
    exactly what the bucket-ladder micro-batcher amortizes away)."""
    import threading as _threading
    import time

    import jax.numpy as jnp

    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.parallel import as_sharded
    from dask_ml_tpu.serving import BucketLadder, ModelServer

    n = 200_000 if on_tpu else 20_000
    d = 128 if on_tpu else 32
    key = jax.random.PRNGKey(7)

    @jax.jit
    def gen():
        kx, ky = jax.random.split(key)
        X = jax.random.normal(kx, (n, d), jnp.float32)
        y = (X[:, 0] + 0.3 * jax.random.normal(ky, (n,)) > 0).astype(
            jnp.float32
        )
        return X, y

    X, y = jax.block_until_ready(gen())
    clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(
        as_sharded(X), as_sharded(y)
    )
    Xh = np.asarray(X)

    # ragged request mix: sizes drawn log-uniform in [1, 256]
    rng = np.random.RandomState(11)
    n_requests = 400
    sizes = np.maximum(np.exp(
        rng.uniform(0, np.log(256), size=n_requests)
    ).astype(int), 1)
    offs = [int(rng.randint(0, n - s)) for s in sizes]
    requests = [Xh[i:i + int(s)] for s, i in zip(sizes, offs)]
    total_rows = int(sizes.sum())

    # naive loop: per-request direct predict (compiles per novel padded
    # shape; measured over the SAME mix). One untimed pass would hide
    # the compile cost the serving path exists to remove, so the naive
    # number includes it — that asymmetry is the product claim, and the
    # steady-state comparison is still dominated by per-call dispatch.
    t0 = time.perf_counter()
    for r in requests:
        clf.predict(r)
    naive_s = time.perf_counter() - t0

    srv = ModelServer(
        clf, methods=("predict",), ladder=BucketLadder(8, 512, 2.0),
        batch_window_ms=1.0, timeout_ms=0,
    ).warmup()
    n_clients = 8
    shares = [requests[c::n_clients] for c in range(n_clients)]
    with srv:
        t0 = time.perf_counter()

        def client(c):
            for r in shares[c]:
                srv.predict(r)

        threads = [_threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        served_s = time.perf_counter() - t0
        stats = srv.stats()
    lat = stats["latency_s"]
    return {
        "metric": "serving_throughput_rows_per_sec",
        "value": round(total_rows / served_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(naive_s / served_s, 3),
        "backend": jax.default_backend(),
        "dtype": "float32",
        "n_requests": n_requests,
        "total_rows": total_rows,
        "n_clients": n_clients,
        "batches": stats["batches"],
        "latency_p50_ms": round(lat["p50"] * 1e3, 3),
        "latency_p99_ms": round(lat["p99"] * 1e3, 3),
        "baseline": {
            "what": "naive per-request clf.predict loop, same request "
                    "mix (pays per-shape compiles + per-call dispatch)",
            "seconds": round(naive_s, 3),
            "rows_per_sec": round(total_rows / naive_s, 1),
        },
        "served_seconds": round(served_s, 3),
    }


def _bench_drift(jax, on_tpu, n_chips):
    """Drift-overhead section (ISSUE 7): the quality plane must be
    near-free. Two numbers:

    - sketch fold throughput — rows/s through ``FeatureSketch.fold``
      at serving width (the per-batch host cost the serving worker
      pays);
    - serving overhead — the SAME warmed closed-loop ragged mix served
      with ``obs_drift`` on vs off; criterion: the ratio stays >= 0.97
      (<= 3% throughput regression with sketches + shadow sampling on).
    """
    import threading as _threading
    import time

    from dask_ml_tpu.observability import FeatureSketch, drift
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.serving import BucketLadder, ModelServer

    d = 32
    n = 20_000
    X, y = make_classification(n_samples=n, n_features=d,
                               n_informative=d // 4, random_state=0)
    clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    Xh = X.to_numpy().astype(np.float32)

    # -- sketch fold cost per 10k rows ------------------------------------
    sk = FeatureSketch(d)
    block = Xh[:10_000]
    sk.fold(block)                        # warm allocation
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        sk.fold(block)
    fold_s = (time.perf_counter() - t0) / reps
    fold_rows_per_sec = block.shape[0] / fold_s

    # -- serving throughput: sketches on vs off ---------------------------
    rng = np.random.RandomState(11)
    n_requests = 400
    sizes = np.maximum(np.exp(
        rng.uniform(0, np.log(256), size=n_requests)
    ).astype(int), 1)
    offs = [int(rng.randint(0, n - s)) for s in sizes]
    requests = [Xh[i:i + int(s)] for s, i in zip(sizes, offs)]
    total_rows = int(sizes.sum())
    n_clients = 8
    shares = [requests[c::n_clients] for c in range(n_clients)]

    def drive(srv):
        def client(c):
            for r in shares[c]:
                srv.predict(r)

        threads = [_threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    def build(obs_drift_on):
        from dask_ml_tpu import config

        # monitor cadence off: the overhead under test is the fold on
        # the serving path, not a background compute tick landing
        # mid-pass and adding variance
        with config.set(obs_drift=obs_drift_on,
                        obs_drift_interval_s=0.0):
            return ModelServer(
                clf, methods=("predict",),
                ladder=BucketLadder(8, 512, 2.0),
                batch_window_ms=1.0, timeout_ms=0,
            ).warmup()

    # INTERLEAVED passes over two live servers: shared-box load drifts
    # on the same timescale as a pass, so back-to-back blocks of
    # off-then-on confound the machine with the knob — alternating
    # passes and taking each mode's best cancels it
    srv_off, srv_on = build(False), build(True)
    t_offs, t_ons = [], []
    with srv_off, srv_on:
        drive(srv_off)                     # warm passes
        drive(srv_on)
        for _ in range(4):
            t_offs.append(drive(srv_off))
            t_ons.append(drive(srv_on))
    off_s, on_s = min(t_offs), min(t_ons)
    drift.reset()                          # bench must not leak sketches
    ratio = off_s / on_s                   # >= 1.0 means no overhead
    entries = [
        {
            "metric": "drift_sketch_fold_rows_per_sec",
            "value": round(fold_rows_per_sec, 1),
            "unit": "rows/s",
            "backend": jax.default_backend(),
            "dtype": "float32",
            "n_features": d,
            "fold_seconds_per_10k_rows": round(fold_s, 6),
        },
        {
            "metric": "drift_serving_overhead_ratio",
            "value": round(ratio, 4),
            "unit": "ratio",
            "backend": jax.default_backend(),
            "dtype": "float32",
            "criterion": ">= 0.97 (sketches cost <= 3% throughput)",
            "criterion_met": bool(ratio >= 0.97),
            "n_requests": n_requests,
            "total_rows": total_rows,
            "rows_per_sec_off": round(total_rows / off_s, 1),
            "rows_per_sec_on": round(total_rows / on_s, 1),
        },
    ]
    from dask_ml_tpu.observability import MetricsLogger

    metrics_file = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_metrics.jsonl"
    )
    with MetricsLogger(metrics_file) as _lg:
        for e in entries:
            _lg.log(kind="bench_drift", **e)
    return entries


def _bench_request_trace(jax, on_tpu, n_chips):
    """Request-trace overhead section (ISSUE 16): the trace plane's
    cost, measured. The SAME warmed closed-loop ragged mix served with
    ``obs_trace_sample=0`` (the default — no trace object ever
    allocated, the zero-overhead contract the jaxpr-identity test
    pins) vs ``1.0`` (every request stage-stamped, tail-sampled,
    histogram-folded). Tracing is host-side Python (~20us per request
    after the cadence fix in ``_slow_threshold``); against ms-scale
    accelerator steps that amortizes below 3% (criterion >= 0.97 on
    TPU), but this CPU bench's sub-ms batches are an adversarial
    denominator — there the criterion is >= 0.70 and the floor
    sentinel guards the recorded ratio against regression."""
    import threading as _threading
    import time

    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.observability import traces_reset
    from dask_ml_tpu.serving import BucketLadder, ModelServer

    d = 32
    n = 20_000
    X, y = make_classification(n_samples=n, n_features=d,
                               n_informative=d // 4, random_state=0)
    clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    Xh = X.to_numpy().astype(np.float32)

    rng = np.random.RandomState(13)
    n_requests = 400
    sizes = np.maximum(np.exp(
        rng.uniform(0, np.log(256), size=n_requests)
    ).astype(int), 1)
    offs = [int(rng.randint(0, n - s)) for s in sizes]
    requests = [Xh[i:i + int(s)] for s, i in zip(sizes, offs)]
    total_rows = int(sizes.sum())
    n_clients = 8
    shares = [requests[c::n_clients] for c in range(n_clients)]

    def drive(srv):
        def client(c):
            for r in shares[c]:
                srv.predict(r)

        threads = [_threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    def build(sample):
        from dask_ml_tpu import config

        # a small keep bound: the steady-state cost under test is the
        # stamps + sampler decision + histogram folds, not an unbounded
        # retention deque
        with config.set(obs_trace_sample=sample, obs_trace_keep=64,
                        obs_drift=False):
            return ModelServer(
                clf, methods=("predict",),
                ladder=BucketLadder(8, 512, 2.0),
                batch_window_ms=1.0, timeout_ms=0,
            ).warmup()

    # interleaved passes, each mode's best — same confound control as
    # the drift section (shared-box load drifts on pass timescales)
    srv_off, srv_on = build(0.0), build(1.0)
    t_offs, t_ons = [], []
    with srv_off, srv_on:
        drive(srv_off)                     # warm passes
        drive(srv_on)
        for _ in range(4):
            t_offs.append(drive(srv_off))
            t_ons.append(drive(srv_on))
    off_s, on_s = min(t_offs), min(t_ons)
    traces_reset()                         # bench must not leak sampler state
    ratio = off_s / on_s                   # >= 1.0 means no overhead
    thresh = 0.97 if on_tpu else 0.70
    entry = {
        "metric": "request_trace_overhead_ratio",
        "value": round(ratio, 4),
        "unit": "ratio",
        "backend": jax.default_backend(),
        "dtype": "float32",
        "criterion": f">= {thresh} (host-side tracing vs this backend's "
                     "step time; <= 3% on accelerator-scale steps)",
        "criterion_met": bool(ratio >= thresh),
        "n_requests": n_requests,
        "total_rows": total_rows,
        "rows_per_sec_untraced": round(total_rows / off_s, 1),
        "rows_per_sec_traced": round(total_rows / on_s, 1),
    }
    from dask_ml_tpu.observability import MetricsLogger

    metrics_file = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_metrics.jsonl"
    )
    with MetricsLogger(metrics_file) as _lg:
        _lg.log(kind="bench_trace", **entry)
    return entry


def _bench_fleet(jax, on_tpu, n_chips):
    """Fleet section (ISSUE 6): 2-replica FleetServer vs a single
    ModelServer over the SAME ragged closed-loop mix, plus
    hot-swap-under-load — client-side p99 while 3 zero-recompile swaps
    land vs a swap-free steady-state pass on the same fleet.

    Replica throughput scaling is a DEVICE-parallelism story: with >1
    real device each replica's params and programs are committed to its
    own chip and XLA runs them concurrently (the >= 1.6x regime). On a
    shared-silicon CPU host both servers ride the same cores, so the
    honest ratio is ~1x — recorded as measured, per backend, exactly
    like the sentinel's backend-matched floors expect. The swap claim
    is backend-independent: p99 must NOT collapse while versions flip,
    because the swap mints zero compiles."""
    import threading as _threading
    import time

    from dask_ml_tpu import observability as obs
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.serving import BucketLadder, FleetServer, ModelServer

    n = 100_000 if on_tpu else 20_000
    d = 128 if on_tpu else 32
    X, y = make_classification(n_samples=n, n_features=d,
                               n_informative=max(d // 4, 2),
                               random_state=0)
    X2, y2 = make_classification(n_samples=n, n_features=d,
                                 n_informative=max(d // 4, 2),
                                 random_state=7)
    a = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    b = LogisticRegression(solver="lbfgs", max_iter=20).fit(X2, y2)
    Xh = X.to_numpy().astype(np.float32)

    rng = np.random.RandomState(11)
    n_requests = 400
    sizes = np.maximum(np.exp(
        rng.uniform(0, np.log(256), size=n_requests)
    ).astype(int), 1)
    offs = [int(rng.randint(0, n - s)) for s in sizes]
    requests = [Xh[i:i + int(s)] for s, i in zip(sizes, offs)]
    total_rows = int(sizes.sum())
    n_clients = 8
    shares = [list(range(c, n_requests, n_clients))
              for c in range(n_clients)]
    ladder = BucketLadder(8, 512, 2.0)

    def drive(server):
        """One closed-loop pass; returns (seconds, per-request secs)."""
        lats = np.zeros(n_requests)

        def client(c):
            for i in shares[c]:
                t1 = time.perf_counter()
                server.predict(requests[i])
                lats[i] = time.perf_counter() - t1

        threads = [_threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, lats

    srv = ModelServer(a, methods=("predict",), ladder=ladder,
                      batch_window_ms=1.0, timeout_ms=0).warmup()
    with srv:
        drive(srv)                       # warm pass
        single_s, _ = drive(srv)

    fleet = FleetServer(a, name="bench", replicas=2, ladder=ladder,
                        batch_window_ms=1.0, timeout_ms=0).warmup()
    with fleet:
        drive(fleet)                     # warm pass
        fleet_s, steady_lats = drive(fleet)
        # hot-swap pass: same traffic while 3 publishes roll through
        before = obs.counters_snapshot().get("recompiles", 0)
        stop_swaps = _threading.Event()
        swaps = []

        def swapper():
            for est in (b, a, b):
                if stop_swaps.wait(0.05):
                    return
                swaps.append(fleet.publish(est))

        sw = _threading.Thread(target=swapper)
        sw.start()
        swap_s, swap_lats = drive(fleet)
        stop_swaps.set()
        sw.join()
        recompiles = obs.counters_snapshot().get("recompiles", 0) - before
        stats = fleet.stats()

    steady_p99 = float(np.percentile(steady_lats, 99))
    swap_p99 = float(np.percentile(swap_lats, 99))
    entries = _fleet_entries(jax, n_chips, n_requests, total_rows,
                             n_clients, single_s, fleet_s, swap_s,
                             steady_p99, swap_p99, swaps, recompiles,
                             stats)
    # the fleet numbers join the per-run record the headline fit opened
    from dask_ml_tpu.observability import MetricsLogger

    metrics_file = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_metrics.jsonl"
    )
    with MetricsLogger(metrics_file) as _lg:
        for e in entries:
            _lg.log(kind="bench_fleet", **e)
    return entries


def _fleet_entries(jax, n_chips, n_requests, total_rows, n_clients,
                   single_s, fleet_s, swap_s, steady_p99, swap_p99,
                   swaps, recompiles, stats):
    common = {
        "unit": "",
        "backend": jax.default_backend(),
        "dtype": "float32",
        "n_chips": n_chips,
        "replicas": 2,
        "n_requests": n_requests,
        "total_rows": total_rows,
        "n_clients": n_clients,
    }
    return [
        {
            **common,
            "metric": "fleet_2replica_throughput_rows_per_sec",
            "value": round(total_rows / fleet_s, 1),
            "unit": "rows/s",
            # replicas-vs-single on the same mix: ~1x on shared-silicon
            # CPU (see docstring), the >= 1.6x claim is per-device
            "vs_baseline": round(single_s / fleet_s, 3),
            "baseline": {
                "what": "single warmed ModelServer, same ragged mix",
                "seconds": round(single_s, 3),
                "rows_per_sec": round(total_rows / single_s, 1),
            },
            "fleet_seconds": round(fleet_s, 3),
        },
        {
            **common,
            "metric": "fleet_hot_swap_p99_seconds",
            "value": round(swap_p99, 4),
            "unit": "s",
            # the product claim: p99 under 3 rolling hot-swaps vs the
            # swap-free pass on the same fleet — flat, because the swap
            # compiles nothing
            "vs_baseline": round(swap_p99 / max(steady_p99, 1e-9), 3),
            "baseline": {
                "what": "steady-state p99 on the same 2-replica fleet, "
                        "no swaps",
                "p99_s": round(steady_p99, 4),
            },
            "swaps": len(swaps),
            "recompiles_during_swaps": int(recompiles),
            "swap_pass_seconds": round(swap_s, 3),
            "final_version": stats["version"],
        },
    ]


def _bench_federation(jax, on_tpu, n_chips):
    """Federation section (ISSUE 17): the same ragged closed-loop mix
    served through a :class:`FederatedFleet` router over two fleet
    processes (LocalEndpoints — the virtual-process transport, so the
    number measures ROUTING, not urllib), then a failover pass where
    one process dies mid-run: every admitted request must still
    resolve (``fleet_failover_lost_requests`` is recorded but, being
    0 by contract, never seeds a sentinel floor — the federation smoke
    gates it), plus the plans-warm autoscale spin-up latency
    (``ReplicaAutoscaler.scale_up`` returns it) against the same
    process's COLD first warmup."""
    import threading as _threading
    import time

    from dask_ml_tpu import observability as obs
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.serving import (
        BucketLadder,
        FederatedFleet,
        FleetServer,
        LocalEndpoint,
        ReplicaAutoscaler,
        ServingError,
    )

    n = 100_000 if on_tpu else 20_000
    d = 128 if on_tpu else 32
    X, y = make_classification(n_samples=n, n_features=d,
                               n_informative=max(d // 4, 2),
                               random_state=0)
    a = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    Xh = X.to_numpy().astype(np.float32)

    rng = np.random.RandomState(17)
    n_requests = 400
    sizes = np.maximum(np.exp(
        rng.uniform(0, np.log(256), size=n_requests)
    ).astype(int), 1)
    offs = [int(rng.randint(0, n - s)) for s in sizes]
    requests = [Xh[i:i + int(s)] for s, i in zip(sizes, offs)]
    total_rows = int(sizes.sum())
    n_clients = 8
    shares = [list(range(c, n_requests, n_clients))
              for c in range(n_clients)]
    ladder = BucketLadder(8, 512, 2.0)

    def drive(server):
        """One closed-loop pass; returns (seconds, lost-count)."""
        lost = [0] * n_clients

        def client(c):
            for i in shares[c]:
                try:
                    server.predict(requests[i])
                except ServingError:
                    lost[c] += 1

        threads = [_threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, sum(lost)

    t0 = time.perf_counter()
    f0 = FleetServer(a, name="fed0", replicas=1, ladder=ladder,
                     batch_window_ms=1.0, timeout_ms=0).warmup()
    cold_warmup_s = time.perf_counter() - t0
    f1 = FleetServer(a, name="fed1", replicas=1, ladder=ladder,
                     batch_window_ms=1.0, timeout_ms=0).warmup()
    f0.start()
    f1.start()
    fed = FederatedFleet(
        [LocalEndpoint(f0, "p0"), LocalEndpoint(f1, "p1")],
        name="fed0", ladder=ladder, poll_s=0.1,
    ).start()
    try:
        drive(fed)                       # warm pass
        fed_s, _ = drive(fed)
        # failover pass: the ranked-first process dies mid-run; the
        # whole-request re-issue must lose nothing
        c0 = obs.counters_snapshot()
        victim = {"p0": f0, "p1": f1}[
            fed._ranked("predict", 64)[0].endpoint.process_id]
        killer = _threading.Timer(max(fed_s / 2, 0.05),
                                  lambda: victim.stop(drain=False))
        killer.start()
        failover_s, n_lost = drive(fed)
        killer.cancel()
        reroutes = obs.counters_snapshot() \
            .get("serving_process_reroutes", 0) \
            - c0.get("serving_process_reroutes", 0)
    finally:
        fed.stop()
        for f in (f0, f1):
            try:
                f.stop(drain=False)
            except Exception:
                pass

    # plans-warm spin-up: the same process has already compiled the
    # ladder, so scale_up's off-path warmup replays cached programs —
    # min over a few cycles (ms-scale timing, keep the floor stable)
    f2 = FleetServer(a, name="fed-scale", replicas=1, ladder=ladder,
                     batch_window_ms=1.0, timeout_ms=0).warmup().start()
    try:
        scaler = ReplicaAutoscaler(f2, min_replicas=1, max_replicas=4,
                                   interval_s=3600.0, patience=1,
                                   cooldown_s=0.0)
        spinups = []
        for _ in range(3):
            spinups.append(scaler.scale_up())
        warm_spinup_s = min(spinups)
    finally:
        f2.stop(drain=False)

    common = {
        "backend": jax.default_backend(),
        "dtype": "float32",
        "n_chips": n_chips,
        "processes": 2,
        "n_requests": n_requests,
        "total_rows": total_rows,
        "n_clients": n_clients,
    }
    entries = [
        {
            **common,
            "metric": "fleet_federated_rows_per_sec",
            "value": round(total_rows / fed_s, 1),
            "unit": "rows/s",
            "federated_seconds": round(fed_s, 3),
        },
        {
            **common,
            "metric": "fleet_failover_lost_requests",
            "value": int(n_lost),
            "unit": "requests",
            "criterion": "== 0 (whole-request re-issue on ProcessDown)",
            "criterion_met": n_lost == 0,
            "process_reroutes": int(reroutes),
            "failover_pass_seconds": round(failover_s, 3),
        },
        {
            **common,
            "metric": "autoscale_spinup_seconds",
            "value": round(warm_spinup_s, 4),
            "unit": "s",
            # plan-warm vs cold: the scale-up replays this process's
            # already-minted programs; the cold number is the same
            # ladder's first-ever warmup
            "vs_baseline": round(warm_spinup_s
                                 / max(cold_warmup_s, 1e-9), 4),
            "baseline": {
                "what": "cold 1-replica fleet warmup, same ladder",
                "seconds": round(cold_warmup_s, 3),
            },
            "spinups_s": [round(s, 4) for s in spinups],
        },
    ]
    from dask_ml_tpu.observability import MetricsLogger

    metrics_file = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_metrics.jsonl"
    )
    with MetricsLogger(metrics_file) as _lg:
        for e in entries:
            _lg.log(kind="bench_federation", **e)
    return entries


def _bench_fleet_observability(jax, on_tpu, n_chips):
    """Fleet observability section (ISSUE 19): what the fleet-scope
    planes cost, measured.

    - ``federated_scrape_seconds`` — one router poll tick with the
      metrics federator riding it: both processes' /status docs
      fetched (the SAME scrape routing uses — no second read), every
      counter/gauge/histogram folded into the fleet registry. This is
      the periodic off-path cost of ``obs_fleet_federate=True``.
    - ``federated_tracing_overhead_ratio`` — the same warmed
      closed-loop ragged mix through the ROUTER with the whole fleet
      plane on (trace propagation + per-leg continuation + federation)
      vs the all-defaults router. Host-side Python against this CPU
      backend's sub-ms steps is an adversarial denominator (same
      framing as ``request_trace_overhead_ratio``) — criterion >= 0.97
      on TPU, >= 0.60 here, floor-sentinel guarded."""
    import threading as _threading
    import time

    from dask_ml_tpu import config
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.observability import traces_reset
    from dask_ml_tpu.serving import (
        BucketLadder,
        FederatedFleet,
        FleetServer,
        LocalEndpoint,
    )

    d = 32
    n = 20_000
    X, y = make_classification(n_samples=n, n_features=d,
                               n_informative=d // 4, random_state=0)
    clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    Xh = X.to_numpy().astype(np.float32)

    rng = np.random.RandomState(23)
    n_requests = 400
    sizes = np.maximum(np.exp(
        rng.uniform(0, np.log(256), size=n_requests)
    ).astype(int), 1)
    offs = [int(rng.randint(0, n - s)) for s in sizes]
    requests = [Xh[i:i + int(s)] for s, i in zip(sizes, offs)]
    total_rows = int(sizes.sum())
    n_clients = 8
    shares = [list(range(c, n_requests, n_clients))
              for c in range(n_clients)]
    ladder = BucketLadder(8, 512, 2.0)

    def drive(fed):
        def client(c):
            for i in shares[c]:
                fed.predict(requests[i])

        threads = [_threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    def build(on):
        # federation + tracing captured at construction (the trace
        # gate and worker config are construction-time state)
        overrides = {"obs_drift": False}
        if on:
            overrides.update(obs_trace_sample=1.0, obs_trace_keep=64,
                             obs_fleet_federate=True)
        with config.set(**overrides):
            f0 = FleetServer(clf, name=f"fobs{int(on)}", replicas=1,
                             ladder=ladder, batch_window_ms=1.0,
                             timeout_ms=0).warmup().start()
            f1 = FleetServer(clf, name=f"fobs{int(on)}", replicas=1,
                             ladder=ladder, batch_window_ms=1.0,
                             timeout_ms=0).warmup().start()
            fed = FederatedFleet(
                [LocalEndpoint(f0, "p0"), LocalEndpoint(f1, "p1")],
                name=f"fobs{int(on)}", ladder=ladder, poll_s=3600.0,
            ).start()
        return fed, (f0, f1)

    fed_off, fleets_off = build(False)
    fed_on, fleets_on = build(True)
    try:
        # the scrape tick, isolated: min over repeats (µs-ms scale)
        scrapes = []
        for _ in range(20):
            t0 = time.perf_counter()
            fed_on._poll_once()
            scrapes.append(time.perf_counter() - t0)
        scrape_s = min(scrapes)

        # interleaved passes, each mode's best (shared-box confound
        # control, same as the request-trace section)
        drive(fed_off)                   # warm passes
        drive(fed_on)
        t_offs, t_ons = [], []
        for _ in range(4):
            t_offs.append(drive(fed_off))
            t_ons.append(drive(fed_on))
        off_s, on_s = min(t_offs), min(t_ons)
    finally:
        for fed, fleets in ((fed_off, fleets_off), (fed_on, fleets_on)):
            fed.stop()
            for f in fleets:
                try:
                    f.stop(drain=False)
                except Exception:
                    pass
    traces_reset()                       # no sampler state leaks
    ratio = off_s / on_s                 # >= 1.0 means no overhead
    thresh = 0.97 if on_tpu else 0.60
    common = {
        "backend": jax.default_backend(),
        "dtype": "float32",
        "processes": 2,
        "n_requests": n_requests,
        "total_rows": total_rows,
    }
    entries = [
        {
            **common,
            "metric": "federated_scrape_seconds",
            "value": round(scrape_s, 6),
            "unit": "s",
            "criterion": "off-path: one poll tick scrapes + merges "
                         "both processes' full telemetry",
            "scrapes_s": [round(s, 6) for s in scrapes[:5]],
        },
        {
            **common,
            "metric": "federated_tracing_overhead_ratio",
            "value": round(ratio, 4),
            "unit": "ratio",
            "criterion": f">= {thresh} (router + 2-leg trace "
                         "continuation + federation vs all-defaults "
                         "router; <= 3% on accelerator-scale steps)",
            "criterion_met": bool(ratio >= thresh),
            "rows_per_sec_plain": round(total_rows / off_s, 1),
            "rows_per_sec_observed": round(total_rows / on_s, 1),
        },
    ]
    from dask_ml_tpu.observability import MetricsLogger

    metrics_file = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_metrics.jsonl"
    )
    with MetricsLogger(metrics_file) as _lg:
        for e in entries:
            _lg.log(kind="bench_fleet_observability", **e)
    return entries


def _bench_incident_plane(jax, on_tpu, n_chips):
    """Incident plane section (ISSUE 20): what the alert engine costs,
    measured.

    - ``alert_tick_seconds`` — one full evaluation pass of a
      representative armed rule set (3 user rules + the 5 built-ins)
      over a populated counter/gauge registry: the engine's entire
      periodic cost (host dicts only — nothing else runs between
      ticks).
    - ``alerting_overhead_ratio`` — the same warmed closed-loop ragged
      mix through ONE ModelServer with the engine armed and ticking at
      a 20x-production cadence (0.25s vs the 5s default) vs disarmed —
      same server object, identical jaxprs, so the ratio isolates the
      ticker + registry contention. Criterion >= 0.97 on TPU, >= 0.60
      on this host-bound CPU backend, floor-sentinel guarded."""
    import threading as _threading
    import time

    from dask_ml_tpu import config
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression
    from dask_ml_tpu.observability import alerts
    from dask_ml_tpu.observability.live import gauge_set
    from dask_ml_tpu.serving import BucketLadder, ModelServer

    d = 32
    n = 20_000
    X, y = make_classification(n_samples=n, n_features=d,
                               n_informative=d // 4, random_state=0)
    clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    Xh = X.to_numpy().astype(np.float32)

    # -- the tick, isolated: a detached engine (no thread) driven by
    # hand over a registry populated the way a serving process's is
    for i in range(16):
        gauge_set(f"bench_plane_gauge_{i}", float(i))
    rules = alerts.parse_rules(
        "serving_slo_violations:rate>5/60s,"
        "bench_plane_gauge_3:gauge>1e9,"
        "serving_requests:counter>=1000000000"
    )
    rules.extend(alerts._builtin_rules())
    eng = alerts.AlertEngine(rules, 3600.0)
    ticks = []
    for _ in range(200):
        t0 = time.perf_counter()
        eng.tick()
        ticks.append(time.perf_counter() - t0)
    tick_s = min(ticks)

    rng = np.random.RandomState(29)
    n_requests = 400
    sizes = np.maximum(np.exp(
        rng.uniform(0, np.log(256), size=n_requests)
    ).astype(int), 1)
    offs = [int(rng.randint(0, n - s)) for s in sizes]
    requests = [Xh[i:i + int(s)] for s, i in zip(sizes, offs)]
    total_rows = int(sizes.sum())
    n_clients = 8
    shares = [list(range(c, n_requests, n_clients))
              for c in range(n_clients)]

    def drive(srv):
        def client(c):
            for i in shares[c]:
                srv.submit(requests[i]).result(60)

        threads = [_threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    # ONE server serves both modes (the plane is pure host-side — the
    # serving jaxprs are byte-identical either way, asserted in
    # tests/test_incident_plane.py); the singleton engine arms/disarms
    # around each ON pass, interleaved best-of as everywhere else
    with config.set(obs_drift=False):
        srv = ModelServer(clf, ladder=BucketLadder(8, 512, 2.0),
                          batch_window_ms=1.0, timeout_ms=0)
        srv.warmup()
        try:
            with srv:
                drive(srv)               # warm pass
                t_offs, t_ons = [], []
                for _ in range(4):
                    t_offs.append(drive(srv))
                    with config.set(
                        obs_alert_rules="serving_slo_violations:"
                                        "rate>1000000/60s",
                        obs_alert_interval_s=0.25,
                    ):
                        assert alerts.ensure_engine() is not None
                        t_ons.append(drive(srv))
                        alerts.stop_engine()
                off_s, on_s = min(t_offs), min(t_ons)
        finally:
            alerts.reset()
    ratio = off_s / on_s                 # >= 1.0 means no overhead
    thresh = 0.97 if on_tpu else 0.60
    common = {
        "backend": jax.default_backend(),
        "dtype": "float32",
        "n_requests": n_requests,
        "total_rows": total_rows,
    }
    entries = [
        {
            **common,
            "metric": "alert_tick_seconds",
            "value": round(tick_s, 6),
            "unit": "s",
            "n_rules": len(rules),
            "criterion": "off-path: one evaluation pass over the live "
                         "registry (3 user rules + 5 built-ins), host "
                         "dicts only",
        },
        {
            **common,
            "metric": "alerting_overhead_ratio",
            "value": round(ratio, 4),
            "unit": "ratio",
            "criterion": f">= {thresh} (same warmed server, engine "
                         "armed @0.25s tick vs disarmed; <= 3% on "
                         "accelerator-scale steps)",
            "criterion_met": bool(ratio >= thresh),
            "rows_per_sec_plain": round(total_rows / off_s, 1),
            "rows_per_sec_alerting": round(total_rows / on_s, 1),
        },
    ]
    from dask_ml_tpu.observability import MetricsLogger

    metrics_file = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_metrics.jsonl"
    )
    with MetricsLogger(metrics_file) as _lg:
        for e in entries:
            _lg.log(kind="bench_incident_plane", **e)
    return entries


_emit_lock = threading.Lock()
_emitted = False
# progressive results for the watchdog: headline result + extras list
_partial = {"result": None, "extras": []}


def _emit(result) -> None:
    """Print the one JSON line exactly once, even if the watchdog and the
    main thread race at the deadline."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        _emitted = True
        print(json.dumps(result), flush=True)


def _error_result(msg):
    return {
        "metric": "logreg_fit_samples_per_sec_per_chip",
        "value": None,
        "unit": "samples/s/chip",
        "vs_baseline": None,
        "error": msg,
    }


def _deadline_result(msg):
    """Best result available at a deadline: the completed headline (plus
    whatever extras finished), marked truncated — else the error line."""
    if _partial["result"] is not None:
        out = dict(_partial["result"])
        out["extra_metrics"] = list(_partial["extras"])
        out["truncated"] = msg
        return out
    return _error_result(msg)


def _start_watchdog():
    """Daemon threads that emit a JSON line and hard-exit if the bench
    overruns its deadlines. Threads (not SIGALRM) because a hang inside
    native XLA code never returns to the bytecode loop, so a Python
    signal handler would never run.

    Two deadlines: BENCH_INIT_TIMEOUT bounds backend init alone (a wedged
    tunnel hangs there; exiting early lets the parent orchestrator fall
    back to CPU with most of the budget intact), BENCH_TOTAL_TIMEOUT
    bounds the whole run and emits any completed numbers."""

    def watch_init():
        time.sleep(_INIT_TIMEOUT)
        if not _init_done.is_set():
            _emit(_error_result(
                f"watchdog: backend init exceeded "
                f"BENCH_INIT_TIMEOUT={_INIT_TIMEOUT}s (wedged tunnel)"
            ))
            os._exit(4)

    def watch_total():
        time.sleep(_TOTAL_TIMEOUT)
        _emit(_deadline_result(
            f"watchdog: exceeded BENCH_TOTAL_TIMEOUT={_TOTAL_TIMEOUT}s"
        ))
        os._exit(3)

    threading.Thread(target=watch_init, daemon=True).start()
    threading.Thread(target=watch_total, daemon=True).start()


def _child_main():
    _start_watchdog()
    try:
        result = run()
    except BaseException as exc:  # emit a JSON line NO MATTER WHAT
        result = _deadline_result(f"{type(exc).__name__}: {exc}")
        traceback.print_exc(file=sys.stderr)
    _emit(result)


def _last_json_line(text):
    """Last stdout line that parses as a metric JSON object, else None."""
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and "metric" in obj:
                return obj
    return None


def _run_child(env, timeout):
    """Run this script as a killable child; return its metric JSON (from
    a clean exit OR a timeout kill — the child streams partial results)
    or None."""
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, timeout=timeout, capture_output=True, text=True,
        )
        out, err = r.stdout, r.stderr
    except subprocess.TimeoutExpired as exc:
        out = exc.stdout or ""
        err = exc.stderr or ""
        if isinstance(out, bytes):
            out = out.decode("utf-8", "replace")
        if isinstance(err, bytes):
            err = err.decode("utf-8", "replace")
    if err:
        sys.stderr.write(err[-4000:])
    return _last_json_line(out)


# host-CPU budget reserved for the fallback child when the TPU attempt
# burns its slice of the budget first
_CPU_RESERVE = float(os.environ.get("BENCH_CPU_RESERVE", "600"))


def main():
    """Orchestrator: probe TPU; if alive, attempt the full bench in a
    killable child (a wedged axon tunnel hangs mid-process, beyond any
    in-process recovery); if the child produces nothing usable, rerun on
    CPU so the driver ALWAYS records a real measurement. Child mode
    (BENCH_CHILD=1) is the benchmark itself.

    One shared deadline: probe + TPU child + CPU fallback all fit inside
    BENCH_TOTAL_TIMEOUT (children get the REMAINING budget via their env,
    their internal watchdogs firing first so partial numbers still
    surface), and a parent watchdog emits the error line at the deadline
    if everything else failed — the 'never exit without a JSON line'
    contract holds at the advertised bound."""
    if os.environ.get("BENCH_PLAN_WARM_CHILD"):
        _plan_warm_child_main()
        return
    if os.environ.get("BENCH_SHARDED_CHILD"):
        _sharded_child_main()
        return
    if os.environ.get("BENCH_MESH2D_CHILD"):
        _mesh2d_child_main()
        return
    if os.environ.get("BENCH_CHILD") == "1":
        _child_main()
        return
    t_end = time.monotonic() + _TOTAL_TIMEOUT

    # the children's budget floors (240s TPU, 120s CPU, ≤probe to start)
    # can exceed a small configured total; the parent deadline honors
    # whichever is larger so a still-running fallback child is never
    # killed with its result imminent
    parent_deadline = max(_TOTAL_TIMEOUT,
                          _PROBE_TIMEOUT + 240.0 + 120.0) + 90

    def parent_watch():
        time.sleep(parent_deadline)
        _emit(_error_result(
            f"orchestrator: exceeded BENCH_TOTAL_TIMEOUT={_TOTAL_TIMEOUT}s"
        ))
        os._exit(5)

    threading.Thread(target=parent_watch, daemon=True).start()
    env = dict(os.environ, BENCH_CHILD="1")
    if _probe_tpu():
        remaining = t_end - time.monotonic()
        tpu_budget = max(remaining - min(_CPU_RESERVE, remaining * 0.45),
                         240.0)
        env_tpu = dict(
            env, BENCH_SKIP_PROBE="1",
            BENCH_TOTAL_TIMEOUT=str(int(tpu_budget - 30)),
            # floor at the probe timeout (an init as slow as one the
            # probe just accepted must not be killed as "wedged"), but
            # never past the child's own total deadline — a huge probe
            # timeout must not disable the early-fallback init watchdog
            BENCH_INIT_TIMEOUT=str(int(min(
                max(min(_INIT_TIMEOUT, tpu_budget / 3), _PROBE_TIMEOUT),
                tpu_budget - 30,
            ))),
        )
        result = _run_child(env_tpu, tpu_budget)
        if result is not None and result.get("value") is not None:
            _emit(result)
            return
        sys.stderr.write("bench: TPU child produced no usable number; "
                         "falling back to CPU\n")
    cpu_budget = max(t_end - time.monotonic(), 120.0)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_TOTAL_TIMEOUT"] = str(int(max(cpu_budget - 30, 90)))
    result = _run_child(env, cpu_budget)
    _emit(result if result is not None
          else _error_result("CPU fallback child produced no JSON line"))


if __name__ == "__main__":
    main()
