"""JSONL metrics core: loggers, the ambient jit-step sink, and the
host-callback capability probe.

Reference: dask's diagnostics/dashboard (SURVEY.md §5 tracing row —
``dask/diagnostics``, bokeh task stream). TPU equivalent: per-step JSONL
metric lines (loss, inertia, samples/s/chip) a controller can tail, and
thin wrappers over ``jax.profiler`` for TensorBoard/Perfetto traces.
"""

from __future__ import annotations

import contextlib
import json
import sys
import threading
import time

import jax


class MetricsLogger:
    """Append one JSON object per step to a file (or stdout)."""

    def __init__(self, path=None, extra=None):
        self.path = path
        self.extra = extra or {}
        self._fh = None
        self.t0 = time.time()
        # log() is called from trial worker threads and jit callback
        # threads; one lock keeps the lazy open and each JSONL line atomic
        self._lock = threading.Lock()

    def _handle(self):
        if self.path is None:
            return sys.stdout
        if self._fh is None:
            self._fh = open(self.path, "a")
        return self._fh

    def log(self, step=None, **metrics):
        # t_unix anchors the record on the wall clock so `report --merge`
        # can place counters/programs-only files (no span records) on the
        # shared timeline; a record's own t_unix (spans) wins via update()
        now = time.time()
        rec = {"time": round(now - self.t0, 6),
               "t_unix": round(now, 6), **self.extra}
        if step is not None:
            rec["step"] = step
        rec.update(metrics)
        line = json.dumps(rec) + "\n"
        with self._lock:
            h = self._handle()
            h.write(line)
            h.flush()

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# The jit-step sink registry is module-GLOBAL, not thread-local:
# jax.debug.callback runs on the runtime's callback threads, which never
# see the fitting thread's locals. Each fit registers its own logger and
# removes exactly ITS entry on exit (not a save/restore of a single slot,
# which a non-LIFO exit under concurrent fits would corrupt). Concurrent
# fits share the sink: records all land in the (one) configured metrics
# file, only the per-fit `extra` fields of overlapping fits may mix.
_active_loggers = []
_active_lock = threading.Lock()

# per-thread view of the same bindings: span sink resolution must only
# see the logger THIS thread bound — the global stack serves the jit
# callback threads, where "innermost" is the best available guess, but
# a concurrent fit on another thread must not have its span records
# routed through (and stamped with the extras of) this thread's logger
_thread_bindings = threading.local()


def thread_bound_logger():
    """The innermost logger bound via :func:`active_logger` ON THIS
    THREAD (None when this thread bound nothing)."""
    st = getattr(_thread_bindings, "stack", None)
    return st[-1] if st else None


@contextlib.contextmanager
def active_logger(logger):
    """Bind ``logger`` as an ambient jit-step sink: ``emit_jit_step``
    callbacks fired from inside compiled loops (lax.while_loop bodies)
    write to it. Device-side programs can't hold a Python handle, so the
    binding is ambient, scoped to the fit call. On exit, pending callback
    effects are flushed (``jax.effects_barrier``) before unbinding so tail
    iterations are never dropped."""
    if logger is None:
        yield None
        return
    st = getattr(_thread_bindings, "stack", None)
    if st is None:
        st = _thread_bindings.stack = []
    with _active_lock:
        _active_loggers.append(logger)
    st.append(logger)
    try:
        yield logger
    finally:
        try:
            jax.effects_barrier()  # drain in-flight debug callbacks first
        finally:
            # unbind even when the barrier raises (a failing callback):
            # a leaked entry would route every later fit's records — and
            # every later span on this thread — to a dead logger
            st.remove(logger)  # OUR entry (non-LIFO exits possible)
            with _active_lock:
                _active_loggers.remove(logger)


def _jit_step_cb(step, metrics_names, *values):
    with _active_lock:
        lg = _active_loggers[-1] if _active_loggers else None
    if lg is not None:
        lg.log(step=int(step),
               **{n: float(v) for n, v in zip(metrics_names, values)})
    # resident fits' in-jit step metrics (loss, grad_norm, ...) double
    # as live progress gauges; publish_progress is a no-op bool check
    # unless a telemetry server is running, and the values are already
    # host floats here (the callback runtime synced them) — no new sync
    try:
        from .live import publish_progress

        publish_progress(step=int(step),
                         **{n: float(v)
                            for n, v in zip(metrics_names, values)})
    except Exception:
        pass


def emit_jit_step(step, **metrics):
    """Call INSIDE a jitted loop body to emit one JSONL record per
    iteration via ``jax.debug.callback`` (callers gate on a static flag so
    the no-logging trace carries zero callback overhead)."""
    names = tuple(sorted(metrics))
    jax.debug.callback(
        _jit_step_cb, step, names, *(metrics[n] for n in names)
    )


_callbacks_supported = None


def jit_callbacks_supported() -> bool:
    """Whether the active backend can run host callbacks from compiled
    code. Some TPU runtimes (axon PJRT) cannot — per-step jit logging
    must then degrade to one summary record per fit instead of crashing
    the solve. Probed once with a tiny program; tests that swap backends
    (or assert on probe behavior) reset it with
    :func:`reset_jit_callbacks_probe`."""
    global _callbacks_supported
    if _callbacks_supported is None:
        try:
            def probe(x):
                jax.debug.callback(lambda v: None, x)
                return x + 1

            jax.block_until_ready(jax.jit(probe)(0))
            jax.effects_barrier()
            _callbacks_supported = True
        except Exception:
            _callbacks_supported = False
    return _callbacks_supported


def reset_jit_callbacks_probe():
    """Drop the cached capability probe so the next
    :func:`jit_callbacks_supported` call re-runs it (tests swap backends
    and monkeypatch the probe; a process-lifetime cache would leak the
    first answer across them)."""
    global _callbacks_supported
    _callbacks_supported = None


@contextlib.contextmanager
def fit_logger(component, **extra):
    """Per-fit MetricsLogger bound to ``config.metrics_path``; yields None
    (a no-op for callers that guard on it) when the knob is unset. This is
    how estimators/solvers wire per-step JSONL without every call site
    touching config (BASELINE.md measurement protocol)."""
    from ..config import get_config
    from .live import ensure_telemetry

    # every fit passes through here: the one hook that arms the live
    # telemetry server for resident fits (config.obs_http_port; a single
    # config read when the knob is at its 0 default)
    ensure_telemetry()
    path = get_config().metrics_path
    if not path:
        yield None
        return
    logger = MetricsLogger(path, extra={"component": component, **extra})
    try:
        yield logger
    finally:
        logger.close()


def timed(fn, *args, **kwargs):
    """(result, seconds) with a block_until_ready barrier — the honest way
    to time an async-dispatch jax program."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    out = jax.block_until_ready(out)
    return out, time.perf_counter() - t0


@contextlib.contextmanager
def profile_trace(log_dir):
    """jax.profiler trace context (view in TensorBoard / Perfetto)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_profiler_server(port=9999):
    """Live-capture profiler endpoint (SURVEY.md §5:
    jax.profiler.start_server)."""
    return jax.profiler.start_server(port)
