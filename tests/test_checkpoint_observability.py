"""Checkpoint/resume + observability + config subsystems (SURVEY.md §5:
built beyond the reference — dask-ml restarts searches from scratch)."""

import json
import os

import numpy as np
import pytest


def test_pytree_roundtrip(tmp_path):
    import jax.numpy as jnp

    from dask_ml_tpu.utils import checkpoint as ckpt

    tree = {
        "beta": jnp.arange(6, dtype=jnp.float32),
        "it": jnp.asarray(3),
        "nested": {"m": jnp.ones((2, 2))},
    }
    path = os.path.join(tmp_path, "state")
    ckpt.save_pytree(path, tree)
    got = ckpt.restore_pytree(path, like=tree)
    np.testing.assert_allclose(np.asarray(got["beta"]), np.arange(6))
    assert int(got["it"]) == 3
    np.testing.assert_allclose(np.asarray(got["nested"]["m"]), 1.0)


def test_host_roundtrip(tmp_path):
    from sklearn.linear_model import SGDClassifier

    from dask_ml_tpu.utils import checkpoint as ckpt

    rng = np.random.RandomState(0)
    X = rng.randn(50, 4)
    y = (X[:, 0] > 0).astype(int)
    est = SGDClassifier(random_state=0).fit(X, y)
    p = os.path.join(tmp_path, "est.pkl")
    ckpt.save_host(p, est)
    got = ckpt.restore_host(p)
    np.testing.assert_array_equal(got.predict(X), est.predict(X))


def test_search_checkpoint_roundtrip(tmp_path):
    from dask_ml_tpu.utils.checkpoint import SearchCheckpoint

    sc = SearchCheckpoint(os.path.join(tmp_path, "search"))
    assert sc.load() is None
    history = [{"model_id": 0, "score": 0.5}]
    meta = {0: {"partial_fit_calls": 3}}
    sc.save_round(2, history, meta, models={0: "modelblob"})
    state = sc.load()
    assert state["round"] == 2
    assert state["history"] == history
    assert state["meta"] == meta
    assert state["models"][0] == "modelblob"


def test_metrics_logger_jsonl(tmp_path):
    from dask_ml_tpu.utils.observability import MetricsLogger

    p = os.path.join(tmp_path, "metrics.jsonl")
    with MetricsLogger(p, extra={"run": "t1"}) as log:
        log.log(step=0, loss=1.5)
        log.log(step=1, loss=0.7, samples_per_sec=123.0)
    lines = [json.loads(l) for l in open(p)]
    assert len(lines) == 2
    assert lines[0]["run"] == "t1" and lines[0]["step"] == 0
    assert lines[1]["samples_per_sec"] == 123.0
    assert all("time" in rec for rec in lines)


def test_timed():
    from dask_ml_tpu.utils.observability import timed

    out, secs = timed(lambda a, b: a + b, 2, b=3)
    assert out == 5 and secs >= 0.0


def test_config_set_overrides_and_env():
    from dask_ml_tpu import config

    base = config.get_config()
    assert base.dtype in ("float32", "bfloat16")
    with config.set(stream_block_rows=4096, dtype="bfloat16"):
        cfg = config.get_config()
        assert cfg.stream_block_rows == 4096
        assert cfg.dtype == "bfloat16"
        with config.set(dtype="float32"):
            assert config.get_config().dtype == "float32"
            assert config.get_config().stream_block_rows == 4096
    assert config.get_config().stream_block_rows == base.stream_block_rows


def test_config_rejects_unknown_key():
    from dask_ml_tpu import config

    with pytest.raises(TypeError):
        with config.set(not_a_field=1):
            pass
