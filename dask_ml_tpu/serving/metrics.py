"""Serving telemetry: per-batch spans, counters, latency histograms and
SLO accounting.

Everything funnels through ``dask_ml_tpu/observability/`` — the same
JSONL sinks, span tree, counter registry and live-telemetry registry
the fit paths use, so a recorded serving run and a recorded fit
aggregate under one report CLI and one ``/metrics`` page. Per batch the
server emits one ``serving.batch`` span carrying bucket, occupancy, and
padding attributes (plus the counter deltas it caused — recompiles paid
mid-serving show up HERE, on the batch that paid them). Counters
accumulate the run totals:

- ``serving_requests`` / ``serving_rows``   — admitted work
- ``serving_batches`` / ``serving_padded_rows`` — batching efficiency
  (padding waste = padded_rows / (rows + padded_rows))
- ``serving_shed`` / ``serving_timeouts`` / ``serving_errors`` —
  backpressure outcomes
- ``serving_slo_violations`` — requests whose end-to-end latency
  exceeded ``config.serving_slo_ms`` (0 = no SLO)

Latency quantiles come from fixed-boundary log-spaced histograms
(``observability._hist``): O(1) thread-safe ``observe`` from the worker
while any number of scrape/stats readers take consistent snapshots, and
— unlike the retired ring window — nothing is ever forgotten, so a p99
over a million-request day really covers the day. :class:`LatencyWindow`
keeps its name and API (``observe`` / ``percentiles`` / ``count``) as
the server-local view; :func:`observe_request_latency` additionally
feeds the process-wide per-(method, bucket) histogram series the
``/metrics`` exporter renders
(``dask_ml_tpu_serving_latency_seconds_bucket{method=...,bucket=...}``).
"""

from __future__ import annotations

from ..observability import span
from ..observability._counters import (
    record_federation_publish,
    record_process_failover,
    record_process_reroute,
    record_registry_publish,
    record_scale_down,
    record_scale_up,
    record_serving_batch,
    record_serving_drop,
    record_serving_request,
    record_serving_reroute,
    record_serving_slo_violation,
    record_serving_swap,
)
from ..observability._hist import (
    Histogram,
    percentiles_from,
    snapshot_delta,
)
from ..observability.live import gauge_set, histogram, live_publishing

__all__ = ["LatencyWindow", "batch_span", "drop_replica_gauges",
           "drop_process_gauges", "record_batch", "record_request",
           "record_drop", "observe_request_latency", "set_queue_gauges",
           "set_replica_gauges", "set_process_gauges",
           "set_replica_count_gauge", "record_swap", "record_reroute",
           "record_publish", "record_scale_up", "record_scale_down",
           "record_process_reroute", "record_process_failover",
           "record_federation_publish"]

# counter recording lives in observability/_counters.py (the shared
# registry the report CLI and span deltas read); these are the serving
# package's local names for it
record_request = record_serving_request
record_batch = record_serving_batch
record_drop = record_serving_drop
record_swap = record_serving_swap
record_reroute = record_serving_reroute
record_publish = record_registry_publish


def batch_span(method: str, bucket: int, rows: int, n_requests: int,
               queue_depth: int):
    """The per-batch span: one JSONL record per executed micro-batch
    with the occupancy/padding signals a capacity review needs. Cheap
    no-op when no sink is configured (same contract as every other
    span)."""
    return span(
        "serving.batch", method=method, bucket=bucket, rows=rows,
        n_requests=n_requests, queue_depth=queue_depth,
        occupancy=round(rows / bucket, 4),
    )


def observe_request_latency(method: str, bucket: int,
                            seconds: float) -> None:
    """One request's end-to-end latency (enqueue -> demux) into the
    process-wide per-(method, bucket) histogram series, plus the SLO
    violation counter when ``config.serving_slo_ms`` is set. Called by
    the worker per request per batch — one bisect + dict adds, no
    device interaction. The histogram series is gated like the queue
    gauges (same no-exporter-nobody-pays rule): ``LatencyWindow``
    already keeps the run's latency summary, so without a live server
    the registry write is pure dead work; the SLO counter stays
    unconditional — it feeds the report counters table, server or not."""
    if live_publishing():
        histogram(
            "serving_latency_seconds",
            labels=(("method", str(method)),
                    ("bucket", str(int(bucket)))),
        ).observe(seconds)
    from ..config import get_config

    slo_ms = get_config().serving_slo_ms
    if slo_ms and seconds * 1e3 > slo_ms:
        record_serving_slo_violation()


def set_queue_gauges(depth: int, inflight_rows: int,
                     replica=None) -> None:
    """Live queue-depth / inflight gauges (scraped via /metrics). Only
    written while a telemetry server is up — the steady-state serving
    loop must not pay dict writes for an exporter nobody runs. A fleet
    replica labels its series (``replica="0"``...) so per-replica load
    imbalance is visible on one scrape; a standalone server keeps the
    unlabeled family."""
    if not live_publishing():
        return
    labels = () if replica is None else (("replica", str(replica)),)
    gauge_set("serving_queue_depth", depth, labels)
    gauge_set("serving_inflight_rows", inflight_rows, labels)


def drop_replica_gauges(replica) -> None:
    """Remove a dead/unregistered replica's labeled gauge series
    (``serving_replica_version`` / ``serving_replica_healthy`` and its
    ``serving_queue_depth`` / ``serving_inflight_rows`` children) from
    the live registry — the same ``drop_labeled_series`` mechanism
    drift's version eviction uses. Without this a replica marked dead
    kept its stale series latched on /metrics forever (and pinned
    cardinality-cap slots live replicas need)."""
    from ..observability.live import drop_labeled_series

    labels = (("replica", str(replica)),)
    for family in ("serving_replica", "serving_queue_depth",
                   "serving_inflight_rows"):
        drop_labeled_series(family, labels)


def set_replica_count_gauge(fleet, n: int) -> None:
    """The autoscaler's headline gauge: how many replicas ``fleet`` is
    running RIGHT NOW (``dask_ml_tpu_serving_replicas{fleet=...}``) —
    scale-ups/downs move it, the ``serving_scale_ups/downs_total``
    counters say how often."""
    if not live_publishing():
        return
    gauge_set("serving_replicas", int(n), (("fleet", str(fleet)),))


def set_process_gauges(process, healthy=None, replicas=None) -> None:
    """Per-PROCESS federation gauges: the router's live view of each
    fleet process (``serving_process_healthy`` flips to 0 on failover,
    ``serving_process_replicas`` mirrors the remote /status replica
    count)."""
    if not live_publishing():
        return
    labels = (("process", str(process)),)
    if healthy is not None:
        gauge_set("serving_process_healthy", 1 if healthy else 0,
                  labels)
    if replicas is not None:
        gauge_set("serving_process_replicas", int(replicas), labels)


def drop_process_gauges(process) -> None:
    """Remove a dead fleet PROCESS's labeled gauge series from the live
    registry — the federation twin of :func:`drop_replica_gauges`, so
    /metrics never latches phantom processes after a failover."""
    from ..observability.live import drop_labeled_series

    drop_labeled_series("serving_process", (("process", str(process)),))


def set_replica_gauges(replica, version=None, healthy=None) -> None:
    """Per-replica served-model-version + health gauges — the /metrics
    view of a rolling hot-swap (each replica's version gauge flips as
    the swap reaches it) and of failover (healthy drops to 0)."""
    if not live_publishing():
        return
    labels = (("replica", str(replica)),)
    if version is not None:
        gauge_set("serving_replica_version", int(version), labels)
    if healthy is not None:
        gauge_set("serving_replica_healthy", 1 if healthy else 0,
                  labels)


class LatencyWindow:
    """Histogram-backed latency summary (seconds): thread-safe O(1)
    ``observe`` from the serving worker, quantile reads from any thread
    without touching the writer's path. The name is historical — the
    retired implementation was a ring window whose quantile read raced
    a concurrent ``observe`` on the shared buffer AND silently forgot
    everything older than its 4096 slots; the histogram keeps the whole
    run. ``size`` is accepted for API compatibility and ignored."""

    __slots__ = ("_hist",)

    def __init__(self, size=None, bounds=None):
        self._hist = Histogram(bounds)

    @property
    def count(self) -> int:
        return self._hist.count

    def observe(self, seconds: float) -> None:
        self._hist.observe(seconds)

    def percentiles(self, qs=(50, 99)) -> dict:
        return self._hist.percentiles(qs)

    def snapshot(self) -> dict:
        return self._hist.snapshot()

    def percentiles_between(self, prev_snapshot, qs=(50, 99),
                            cur=None) -> dict:
        """Quantiles over the WINDOW since ``prev_snapshot`` (a
        ``snapshot()`` the caller took earlier; None = lifetime). The
        windowed view the fleet's routing/admission and
        ``ModelServer.stats()`` ride — a recent degradation shows up
        immediately instead of being diluted by a long fast history.

        Pass ``cur`` when the caller already snapshotted (and is, say,
        advancing a cursor to that same snapshot): computing the delta
        from a SECOND fresh snapshot would double-count observations
        landing between the two in this window and the next."""
        return percentiles_from(
            snapshot_delta(self.snapshot() if cur is None else cur,
                           prev_snapshot), qs
        )
