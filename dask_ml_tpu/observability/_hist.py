"""Fixed-boundary log-spaced histograms: the live plane's distribution
primitive.

The serving latency story needs quantiles that are (a) thread-safe
against a reader scraping while the worker records, (b) O(1) per
``observe`` with zero allocation, and (c) renderable as Prometheus
histogram series (cumulative ``le`` buckets). A sorted/ring window gives
exact quantiles but couples readers and writers through one buffer and
FORGETS everything older than the window; a fixed-boundary histogram
keeps every observation ever made, costs one bisect + three adds per
record, and the scrape path reads a consistent snapshot under the same
small lock.

Boundaries default to a 1-2-5 ladder over 1e-5 .. 100 seconds (seven
decades: 10µs device dispatches through multi-minute stalled passes).
Quantiles are estimated by linear interpolation inside the winning
bucket and clamped to the observed [min, max] — at the default ladder
the estimate is within a factor of 2.5 of exact everywhere, and much
tighter in practice because latency mass concentrates in few buckets.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left

# 1-2-5 per decade, 1e-5 s .. 1e2 s. A literal (not a comprehension) so
# the Prometheus ``le`` labels are stable, exact decimals run to run.
DEFAULT_BOUNDS = (
    1e-05, 2e-05, 5e-05,
    1e-04, 2e-04, 5e-04,
    0.001, 0.002, 0.005,
    0.01, 0.02, 0.05,
    0.1, 0.2, 0.5,
    1.0, 2.0, 5.0,
    10.0, 20.0, 50.0,
    100.0,
)


class Histogram:
    """Thread-safe fixed-boundary histogram.

    ``observe(v)`` is one bisect over the (immutable) boundary tuple
    plus three adds under the lock — no allocation, no resize, safe from
    any thread. ``counts`` has ``len(bounds) + 1`` slots; the last is
    the +Inf overflow bucket. Bucket semantics match Prometheus:
    bucket ``i`` counts observations ``v <= bounds[i]``.
    """

    __slots__ = ("bounds", "_counts", "_sum", "_count", "_min", "_max",
                 "_lock")

    def __init__(self, bounds=None):
        self.bounds = tuple(float(b) for b in (bounds or DEFAULT_BOUNDS))
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("histogram bounds must be strictly increasing")
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def observe(self, value: float) -> None:
        value = float(value)
        i = bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value

    def snapshot(self) -> dict:
        """Consistent copy: {bounds, counts, sum, count, min, max} —
        ``counts[i]`` is per-bucket (NOT cumulative); the exposition
        layer accumulates."""
        with self._lock:
            return {
                "bounds": self.bounds,
                "counts": list(self._counts),
                "sum": self._sum,
                "count": self._count,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
            }

    def percentiles(self, qs=(50, 99)) -> dict:
        """{'p50': ..., 'p99': ...} estimated by linear interpolation
        inside the winning bucket, clamped to observed [min, max]
        (NaN-valued when empty, matching the old LatencyWindow
        contract)."""
        return percentiles_from(self.snapshot(), qs)

    def merge(self, other) -> "Histogram":
        """Fold another histogram (or a snapshot-shaped dict — e.g. one
        scraped off a remote process's /status) into this one:
        bucket-wise count sums plus the sum/count/min/max fields, the
        ``sketch.merge_profiles`` contract for latency distributions.
        Exact, not approximate, BECAUSE the boundaries are fixed — two
        histograms over the same 1-2-5 ladder merge bucket-for-bucket,
        and the merged quantiles match pooling the raw observations to
        within one bucket width (asserted by the property test in
        tests/test_fleet_observability.py). Mismatched boundaries raise:
        resampling across ladders would silently corrupt quantiles.
        Returns ``self`` for chaining."""
        snap = other.snapshot() if isinstance(other, Histogram) else other
        bounds = tuple(float(b) for b in snap["bounds"])
        if bounds != self.bounds:
            raise ValueError(
                f"cannot merge histograms with different bounds "
                f"({len(bounds)} vs {len(self.bounds)} edges)"
            )
        counts = [int(c) for c in snap["counts"]]
        if len(counts) != len(self._counts):
            raise ValueError("snapshot counts length does not match")
        mn, mx = snap.get("min"), snap.get("max")
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += float(snap["sum"])
            self._count += int(snap["count"])
            if mn is not None and float(mn) < self._min:
                self._min = float(mn)
            if mx is not None and float(mx) > self._max:
                self._max = float(mx)
        return self


def percentiles_from(snap: dict, qs=(50, 99)) -> dict:
    """Quantiles from any snapshot-shaped dict (a :meth:`snapshot` or a
    :func:`snapshot_delta` window): linear interpolation inside the
    winning bucket, clamped to the snapshot's [min, max]; NaN when the
    snapshot is empty."""
    out = {}
    n = snap["count"]
    if n <= 0:
        return {f"p{q}": float("nan") for q in qs}
    counts, bounds = snap["counts"], snap["bounds"]
    for q in qs:
        rank = max(min(math.ceil(q / 100.0 * n), n), 1)
        cum = 0
        value = snap["max"]
        for i, c in enumerate(counts):
            if c <= 0:
                continue
            if cum + c >= rank:
                # bucket 0's floor is the observed min (all its
                # members are <= bounds[0] and the min is among
                # them); the overflow bucket's ceiling is the max
                lo = bounds[i - 1] if i > 0 else snap["min"]
                hi = bounds[i] if i < len(bounds) else snap["max"]
                frac = (rank - cum) / c
                value = lo + frac * (hi - lo)
                break
            cum += c
        out[f"p{q}"] = float(
            min(max(value, snap["min"]), snap["max"])
        )
    return out


def merge_snapshots(snaps) -> dict | None:
    """Pool several snapshot-shaped dicts of ONE histogram family into
    a merged snapshot (the fleet federator's bucket-for-bucket merge as
    a standalone function, mirroring ``sketch.merge_profiles``). The
    first snapshot's bounds win; later snapshots with different bounds
    raise. None when ``snaps`` is empty."""
    h = None
    for snap in snaps:
        if snap is None:
            continue
        if h is None:
            h = Histogram(snap["bounds"])
        h.merge(snap)
    return h.snapshot() if h is not None else None


def snapshot_delta(cur: dict, prev: dict | None) -> dict:
    """The WINDOW between two snapshots of one histogram as another
    snapshot-shaped dict — the delta-quantile primitive behind
    ``ModelServer.stats()``'s windowed latency and the fleet's routing/
    admission predictions (an all-time p99 over a long fast history
    dilutes a fresh degradation; a window sees it immediately).

    ``prev=None`` (or a fresh cursor) returns ``cur`` itself. The
    window's true min/max were not tracked, so they are estimated from
    the populated delta buckets' edges (lifetime min/max bound the
    open-ended first/overflow buckets) — quantile error stays within
    one bucket, same contract as the lifetime estimate."""
    if prev is None or prev.get("count", 0) == 0:
        return cur
    bounds = cur["bounds"]
    counts = [c - p for c, p in zip(cur["counts"], prev["counts"])]
    n = cur["count"] - prev["count"]
    lo = hi = None
    for i, c in enumerate(counts):
        if c > 0:
            if lo is None:
                lo = cur["min"] if i == 0 else bounds[i - 1]
            hi = cur["max"] if i >= len(bounds) else bounds[i]
    return {
        "bounds": bounds,
        "counts": counts,
        "sum": cur["sum"] - prev["sum"],
        "count": n,
        "min": lo,
        "max": hi,
    }
