"""Fused GLM value+grad Pallas kernel (ops/pallas_fused.py): one X pass
per value_and_grad. Interpret-mode parity vs the XLA loss across
families, solvers, and dtypes (the kernel auto-engages compiled on real
TPU; scripts/tpu_smoke.py asserts the same parity there)."""

import numpy as np
import pytest

from dask_ml_tpu import config
from dask_ml_tpu.datasets import (
    make_classification, make_counts, make_regression,
)
from dask_ml_tpu.linear_model import (
    LinearRegression, LogisticRegression, PoissonRegression,
)

PALLAS = {"use_pallas": True, "pallas_interpret": True}


@pytest.mark.parametrize("name,maker,Est", [
    ("logistic", make_classification, LogisticRegression),
    ("normal", make_regression, LinearRegression),
    ("poisson", make_counts, PoissonRegression),
])
def test_fused_glm_matches_xla(name, maker, Est):
    X, y = maker(n_samples=3000, n_features=24, random_state=0)
    base = Est(solver="lbfgs", max_iter=60, tol=1e-8).fit(X, y)
    pal = Est(solver="lbfgs", max_iter=60, tol=1e-8,
              solver_kwargs=PALLAS).fit(X, y)
    np.testing.assert_allclose(pal.coef_, base.coef_, atol=5e-4)
    np.testing.assert_allclose(np.ravel(pal.intercept_),
                               np.ravel(base.intercept_), atol=5e-4)


def test_fused_glm_gradient_descent_and_bf16():
    X, y = make_classification(n_samples=3000, n_features=16,
                               random_state=1)
    base = LogisticRegression(solver="gradient_descent", max_iter=40,
                              tol=1e-8).fit(X, y)
    pal = LogisticRegression(solver="gradient_descent", max_iter=40,
                             tol=1e-8, solver_kwargs=PALLAS).fit(X, y)
    assert np.mean(pal.predict(X) == base.predict(X)) > 0.999
    # bf16 design matrix: kernel matvec at bf16 with f32 accumulation
    with config.set(dtype="bfloat16"):
        b16 = LogisticRegression(solver="lbfgs", max_iter=40,
                                 solver_kwargs=PALLAS).fit(X, y)
    assert b16.score(X, y) > 0.8


def test_fused_glm_kernel_direct():
    """Kernel-level check against the autodiff reference, including the
    padded-tail masking."""
    import jax
    import jax.numpy as jnp

    from dask_ml_tpu.models.solvers.families import get_family
    from dask_ml_tpu.ops.pallas_fused import fused_glm_value_grad

    rng = np.random.RandomState(2)
    n, d = 391, 13   # ragged on purpose: tile padding + masked tail
    X = rng.randn(n, d).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    beta = rng.randn(d).astype(np.float32) * 0.1
    n_valid = 350    # rows past this are padding

    def ref(b):
        eta = X @ b
        m = (np.arange(n) < n_valid).astype(np.float32)
        return jnp.sum(get_family("logistic").pointwise(
            jnp.asarray(eta), jnp.asarray(y)) * m)

    v_ref = float(ref(jnp.asarray(beta)))
    g_ref = np.asarray(jax.grad(lambda b: ref(b))(jnp.asarray(beta)))
    v, g = fused_glm_value_grad(X, n_valid, y, beta, family="logistic",
                                interpret=True)
    np.testing.assert_allclose(float(v), v_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g), g_ref, rtol=1e-4, atol=1e-5)


def test_auto_gate_falls_back_when_kernel_fails(monkeypatch):
    """An auto-selected kernel that fails to compile must not kill the
    fit: the solve retries on the XLA loss with a warning (an EXPLICIT
    use_pallas=True still surfaces the error)."""
    import warnings

    from dask_ml_tpu.models.solvers import solvers as S

    X, y = make_classification(n_samples=500, n_features=8, random_state=0)

    real_chunk = S._lbfgs_chunk
    calls = {"n": 0}

    def flaky(*a, **kw):
        if kw.get("use_pallas"):
            calls["n"] += 1
            raise RuntimeError("Mosaic lowering failed (simulated)")
        return real_chunk(*a, **kw)

    monkeypatch.setattr(S, "_lbfgs_chunk", flaky)
    # force the auto gate open without a TPU: _resolve_pallas(None, ...)
    monkeypatch.setattr(S, "_resolve_pallas",
                        lambda up, mesh, fam, X=None: True if up is None
                        else bool(up))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    assert calls["n"] == 1
    assert any("retrying on the XLA" in str(x.message) for x in w)
    assert clf.score(X, y) > 0.7
    # explicit opt-in: the error propagates
    with pytest.raises(Exception, match="Mosaic"):
        LogisticRegression(solver="lbfgs", max_iter=5,
                           solver_kwargs={"use_pallas": True}).fit(X, y)


@pytest.mark.slow
def test_fused_multiclass_matches_vmapped():
    """The flat multi-target kernel solve (one X pass for ALL classes
    per iteration) converges to the vmapped per-class solution — the
    objective is separable, so the joint optimum is the same."""
    X, y = make_classification(n_samples=3000, n_features=16, n_classes=3,
                               n_informative=9, random_state=1)
    base = LogisticRegression(solver="lbfgs", max_iter=80,
                              tol=1e-8).fit(X, y)
    pal = LogisticRegression(solver="lbfgs", max_iter=80, tol=1e-8,
                             solver_kwargs=PALLAS).fit(X, y)
    assert pal.solver_info_.get("fused_multi") is True
    assert base.solver_info_.get("fused_multi") is None
    np.testing.assert_allclose(pal.coef_, base.coef_, atol=2e-3)
    assert np.mean(pal.predict(X) == base.predict(X)) > 0.999


@pytest.mark.parametrize("Est,maker,pen", [
    (LogisticRegression, make_classification, "l1"),
    (LinearRegression, make_regression, "elastic_net"),
])
def test_fused_proximal_grad_matches_xla(Est, maker, pen):
    """proximal_grad's smooth part through the fused kernel: relative
    coefficient parity with the XLA loss. Support membership can flip
    only for coefficients AT the prox threshold (near-zero on both
    sides) — accumulation-order noise, not divergence."""
    X, y = maker(n_samples=3000, n_features=18, random_state=0)
    kw = dict(solver="proximal_grad", penalty=pen, max_iter=120, tol=1e-9)
    base = Est(**kw).fit(X, y)
    pal = Est(**kw, solver_kwargs=PALLAS).fit(X, y)
    c0 = np.asarray(base.coef_, float)
    c1 = np.asarray(pal.coef_, float)
    scale = max(np.abs(c0).max(), 1e-12)
    assert np.abs(c1 - c0).max() / scale < 5e-3
    flipped = (np.abs(c0) > 1e-6) != (np.abs(c1) > 1e-6)
    assert (np.abs(c0)[flipped] < 1e-3 * scale).all()
    assert (np.abs(c1)[flipped] < 1e-3 * scale).all()


@pytest.mark.parametrize("name,maker,Est", [
    ("logistic", make_classification, LogisticRegression),
    ("normal", make_regression, LinearRegression),
    ("poisson", make_counts, PoissonRegression),
])
def test_fused_newton_matches_xla(name, maker, Est):
    """Newton through the fused value+grad+Hessian kernel (one X pass
    for its whole data touch) matches the XLA path."""
    X, y = maker(n_samples=3000, n_features=20, random_state=0)
    base = Est(solver="newton", max_iter=40, tol=1e-9).fit(X, y)
    pal = Est(solver="newton", max_iter=40, tol=1e-9,
              solver_kwargs=PALLAS).fit(X, y)
    np.testing.assert_allclose(pal.coef_, base.coef_, atol=5e-4)


def test_newton_tile_budget():
    from dask_ml_tpu.ops.pallas_fused import glm_newton_tile

    assert glm_newton_tile(100_000, 128, 4) is not None
    assert glm_newton_tile(100_000, 2000, 4) is None  # (d,d) too big
