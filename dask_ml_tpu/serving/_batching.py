"""Micro-batching plumbing: request records, batch packing, ping-pong
staging buffers, and result demultiplexing.

The hot loop's memory discipline: each (method, bucket) pair owns TWO
preallocated host staging arrays used alternately (ping-pong), so
steady-state serving performs zero host allocations for inputs and —
should a future entry point defer its host pull under async dispatch —
batch k+1's pack can never overwrite a host buffer batch k's transfer
is still reading (see PingPongStaging's honesty note: today's entry
points consume their input synchronously, making the alternation
conservative insurance). Device input buffers are donated on backends
that support donation (TPU/GPU).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

import numpy as np

__all__ = ["Request", "PingPongStaging", "pack_batch", "demux_outputs",
           "release_deadline"]


def release_deadline(oldest_enqueue_t, dequeue_t, window_s, slo_s,
                     predicted_exec_s, margin_frac=0.15):
    """When must the coalescing loop stop waiting and dispatch?

    The fixed rule (no SLO configured, or no latency history yet to
    predict from): ``dequeue_t + window_s`` — the classic min/max batch
    window, measured from the first dequeue so a trickle of stragglers
    cannot hold a batch forever.

    The DEADLINE-AWARE rule (``config.serving_slo_ms`` set and an
    execution-time prediction available): the batch must leave early
    enough that the OLDEST request still makes its SLO —
    ``oldest_enqueue + slo - predicted_exec - margin``. That replaces
    the fixed window in both directions: a slow bucket releases a
    partial batch EARLY (waiting would already miss), while an ample
    budget lets the batcher coalesce LONGER than the fixed window for
    better occupancy. The margin (default 15% of the SLO) absorbs
    prediction error and the demux/host tail the execution histogram
    does not see. Never returns earlier than ``dequeue_t`` — an
    already-doomed oldest request dispatches immediately rather than
    waiting at all."""
    if slo_s <= 0 or predicted_exec_s is None:
        return dequeue_t + window_s
    return max(
        oldest_enqueue_t + slo_s - predicted_exec_s
        - slo_s * margin_frac,
        dequeue_t,
    )


class Request:
    """One admitted inference request: a small (n, d) float32 block plus
    the Future its caller is waiting on."""

    __slots__ = ("X", "n_rows", "method", "future", "t_enqueue",
                 "deadline", "seq", "trace")

    def __init__(self, X, method, timeout_s=0.0, future=None):
        self.X = X
        self.n_rows = int(X.shape[0])
        self.method = method
        self.future = future if future is not None else Future()
        self.seq = 0              # stamped by BoundedQueue at admission
        self.trace = None         # RequestTrace when the plane is on
        self.t_enqueue = time.perf_counter()
        self.deadline = (self.t_enqueue + timeout_s) if timeout_s > 0 \
            else None

    def expired(self, now=None) -> bool:
        if self.deadline is None:
            return False
        return (now if now is not None else time.perf_counter()) \
            > self.deadline


class PingPongStaging:
    """Two alternating host staging arrays per (bucket, width) shape.

    ``get(bucket, d)`` returns the next buffer of shape (bucket, d),
    zero-filled only on first allocation — pack_batch overwrites every
    real row and padding rows beyond the batch are masked out at demux,
    so stale padding contents are harmless (they only ever feed rows the
    caller never sees).

    Honesty note on the alternation: today the compiled entry point
    materializes its output on host before returning (``_host_out`` →
    ``np.asarray``), so batch k is fully consumed before batch k+1
    packs — a single buffer would be correct. The ping-pong is
    conservative insurance for donation + async dispatch (a future demux
    that defers the host pull must never overwrite a host source a
    transfer could still be reading); the cost is one extra small host
    buffer per shape.
    """

    __slots__ = ("_bufs", "_flip")

    def __init__(self):
        self._bufs = {}   # (bucket, d) -> [arr0, arr1]
        self._flip = {}   # (bucket, d) -> 0|1

    def get(self, bucket: int, d: int) -> np.ndarray:
        key = (bucket, d)
        pair = self._bufs.get(key)
        if pair is None:
            pair = [np.zeros((bucket, d), np.float32),
                    np.zeros((bucket, d), np.float32)]
            self._bufs[key] = pair
            self._flip[key] = 0
        i = self._flip[key]
        self._flip[key] = 1 - i
        return pair[i]


def pack_batch(requests, ladder, staging):
    """Coalesce ``requests`` (same method, total rows <= ladder top)
    into one padded staging buffer.

    Returns ``(batch, segments, bucket, rows)`` where ``segments`` is a
    list of (request, start) row offsets for demux and ``rows`` the real
    (unpadded) row count.
    """
    rows = sum(r.n_rows for r in requests)
    d = requests[0].X.shape[1]
    bucket = ladder.bucket_for(rows)
    buf = staging.get(bucket, d)
    segments = []
    at = 0
    for r in requests:
        buf[at:at + r.n_rows] = r.X
        segments.append((r, at))
        at += r.n_rows
    if at < bucket:
        # zero the padding tail: model math on padding rows must stay
        # finite (garbage from a previous, larger batch could overflow
        # an exp/sigmoid into NaNs that some backends propagate slowly)
        buf[at:bucket] = 0.0
    return buf, segments, bucket, rows


def demux_outputs(out, segments):
    """Slice each caller's rows back out of the batched output and
    resolve their futures; padding rows (beyond the last segment) are
    dropped here — this is the mask that keeps them out of every
    caller-visible result."""
    for req, start in segments:
        piece = out[start:start + req.n_rows]
        tr = req.trace
        if tr is not None:
            tr.stamp("demux")
        # copy: the slice views the ping-pong output only until the next
        # batch of this bucket lands; the caller's array must be its own
        if not req.future.set_running_or_notify_cancel():
            if tr is not None:
                tr.finish("cancelled")
            continue  # caller cancelled while we computed
        req.future.set_result(np.array(piece))
        if tr is not None:
            # finalize AFTER set_result: the sampler/histogram folds
            # never sit between the compute and the caller's wakeup
            tr.stamp("complete")
            tr.finish("ok")


def fail_requests(requests, exc, outcome="error"):
    """Resolve every request's future with ``exc`` (batch-level failure
    or shed); futures already cancelled — or already resolved by a
    partial demux before the failure — are skipped, never raised on.
    ``outcome`` labels the traced requests' terminal state ("timeout" /
    "shed" / "error" — finish is idempotent, so a request a partial
    demux already completed keeps its first outcome)."""
    for r in requests:
        try:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(exc)
        except Exception:
            pass  # future already in a terminal state
        if r.trace is not None:
            r.trace.finish(outcome)


class BoundedQueue:
    """The admission-controlled request queue: one lock + condition,
    per-method FIFO lanes, a global request bound, and deadline-aware
    popping. ``put_many`` never blocks — over the bound it returns
    "full" and the server sheds with ServerOverloaded (backpressure
    surfaces to the caller immediately instead of silently growing
    latency). Admission is ATOMIC with shutdown: ``close()`` flips the
    closed flag under the same lock, so any successful put
    happens-before close and is guaranteed to be drained by the
    worker's tail loop — no request can strand in a closed queue."""

    __slots__ = ("_lock", "_cond", "_lanes", "_seq", "max_requests",
                 "depth", "rows", "peak_depth", "closed")

    def __init__(self, max_requests):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._lanes = {}          # method -> deque[Request]
        self._seq = 0             # global admission order stamp
        self.max_requests = int(max_requests)
        self.depth = 0
        self.rows = 0             # queued ROWS (the admission/routing
        #                           load signal — depth counts requests)
        self.peak_depth = 0
        self.closed = False

    def put_many(self, reqs) -> str:
        """Admit ALL of ``reqs`` or none (a chunked oversize request
        must not half-enter: shedding part way would burn capacity on
        orphaned chunks). Returns "ok" / "full" / "closed"."""
        from collections import deque

        with self._lock:
            if self.closed:
                return "closed"
            if self.depth + len(reqs) > self.max_requests:
                return "full"
            for req in reqs:
                req.seq = self._seq
                self._seq += 1
                lane = self._lanes.get(req.method)
                if lane is None:
                    lane = self._lanes[req.method] = deque()
                lane.append(req)
            self.depth += len(reqs)
            self.rows += sum(r.n_rows for r in reqs)
            self.peak_depth = max(self.peak_depth, self.depth)
            self._cond.notify()
            return "ok"

    def put(self, req) -> bool:
        return self.put_many([req]) == "ok"

    def close(self) -> None:
        with self._lock:
            self.closed = True
            self._cond.notify_all()

    def _pop_oldest_locked(self):
        # lanes are FIFO deques; the globally oldest request is one of
        # the lane HEADS (O(#methods) scan, O(1) popleft — no per-pop
        # list surgery on the admission-contended hot path)
        best = None
        for lane in self._lanes.values():
            if lane and (best is None or lane[0].seq < best[0].seq):
                best = lane
        if best is None:
            return None
        self.depth -= 1
        req = best.popleft()
        self.rows -= req.n_rows
        return req

    def pop_first(self, timeout):
        """Oldest request across lanes, blocking up to ``timeout``
        seconds; None on timeout."""
        with self._lock:
            if self.depth == 0:
                self._cond.wait(timeout)
            return self._pop_oldest_locked()

    def drain_method(self, method, max_rows):
        """Non-blockingly pop same-``method`` requests while their rows
        fit under ``max_rows``; stops at the first request that would
        overflow the batch (FIFO order within the lane is preserved) or
        when the lane empties."""
        got = []
        with self._lock:
            lane = self._lanes.get(method)
            budget = max_rows
            while lane:
                if lane[0].n_rows > budget:
                    break
                req = lane.popleft()
                self.depth -= 1
                self.rows -= req.n_rows
                budget -= req.n_rows
                got.append(req)
        return got

    def wait_method(self, method, timeout) -> None:
        """Sleep up to ``timeout`` while THIS method's lane is empty.
        The wait rides the queue's single shared condition, so a
        foreign method's admission still wakes the caller early (one
        cheap spurious wakeup per foreign put) — what this prevents is
        the depth>0 busy-spin a whole-queue wait would cause when only
        other methods' requests are pending; callers re-check their
        lane (via drain_method) after waking."""
        with self._lock:
            if not self._lanes.get(method):
                self._cond.wait(timeout)

    def drain_all(self):
        with self._lock:
            out = []
            while True:
                r = self._pop_oldest_locked()
                if r is None:
                    break
                out.append(r)
            return out

    def wake(self):
        with self._lock:
            self._cond.notify_all()
