"""Device-side roc_auc / F1-family / balanced_accuracy parity vs sklearn
(VERDICT r4 missing #4; ref dask_ml/metrics/scorer.py exposes the sklearn
scorer table dask-aware). The point: adaptive search with these scoring
strings must never fall to the host-adapting interop that gathers whole
test folds."""

import numpy as np
import pytest
import sklearn.metrics as skm

from dask_ml_tpu import metrics
from dask_ml_tpu.metrics.scorer import SCORERS, get_scorer
from dask_ml_tpu.parallel import as_sharded

rng = np.random.RandomState(0)


class TestAucParity:
    def test_auc_basic_and_ties(self):
        y = rng.randint(0, 2, 500).astype(np.float64)
        s = rng.rand(500)
        s[::7] = 0.5  # heavy ties
        np.testing.assert_allclose(
            metrics.roc_auc_score(y, s), skm.roc_auc_score(y, s),
            rtol=1e-6,
        )

    def test_auc_weighted(self):
        y = rng.randint(0, 2, 300).astype(np.float64)
        s = rng.rand(300)
        w = rng.rand(300)
        np.testing.assert_allclose(
            metrics.roc_auc_score(y, s, sample_weight=w),
            skm.roc_auc_score(y, s, sample_weight=w),
            rtol=1e-5,
        )

    def test_auc_sharded_with_padding(self):
        # n=101 pads on the 8-device mesh; padded rows must not score
        y = rng.randint(0, 2, 101).astype(np.float64)
        s = rng.rand(101)
        np.testing.assert_allclose(
            metrics.roc_auc_score(as_sharded(y), as_sharded(s)),
            skm.roc_auc_score(y, s),
            rtol=1e-6,
        )

    def test_auc_nonstandard_labels(self):
        y = np.where(rng.rand(200) > 0.5, 10.0, 20.0)
        s = rng.rand(200)
        np.testing.assert_allclose(
            metrics.roc_auc_score(y, s),
            skm.roc_auc_score(y, s),  # sklearn: pos = larger label
            rtol=1e-6,
        )

    def test_auc_one_class_raises(self):
        with pytest.raises(ValueError, match="one class"):
            metrics.roc_auc_score(np.ones(50), rng.rand(50))

    def test_auc_multiclass_raises(self):
        y = rng.randint(0, 3, 60).astype(np.float64)
        with pytest.raises(ValueError, match="multiclass"):
            metrics.roc_auc_score(y, rng.rand(60))


class TestCurves:
    def test_roc_curve_same_function_as_sklearn(self):
        # our curve KEEPS collinear points; compare as a function by
        # interpolating tpr at sklearn's fpr grid
        y = rng.randint(0, 2, 300).astype(np.float64)
        s = rng.rand(300)
        s[::5] = 0.5
        fpr, tpr, thr = metrics.roc_curve(y, s)
        sk_fpr, sk_tpr, _ = skm.roc_curve(y, s)
        assert fpr[0] == 0.0 and tpr[0] == 0.0
        assert fpr[-1] == 1.0 and tpr[-1] == 1.0
        # every sklearn curve point appears among ours (ours keeps
        # collinear points sklearn drops — same curve as a function)
        ours = {(round(a, 9), round(b, 9)) for a, b in zip(fpr, tpr)}
        missing = [(a, b) for a, b in zip(sk_fpr, sk_tpr)
                   if (round(a, 9), round(b, 9)) not in ours]
        assert not missing, missing[:5]
        # thresholds are EXACT y_score values (sklearn contract)
        assert set(thr[np.isfinite(thr)]) <= set(s)
        # AUC of our curve equals sklearn's roc_auc (manual trapezoid:
        # np.trapezoid is numpy>=2-only, np.trapz deprecated there)
        auc = float(np.sum(np.diff(fpr) * (tpr[1:] + tpr[:-1]) / 2))
        np.testing.assert_allclose(auc, skm.roc_auc_score(y, s),
                                   rtol=1e-6)

    def test_precision_recall_curve_and_ap(self):
        y = rng.randint(0, 2, 400).astype(np.float64)
        s = rng.rand(400)
        prec, rec, thr = metrics.precision_recall_curve(y, s)
        sk_p, sk_r, sk_t = skm.precision_recall_curve(y, s)
        assert prec[-1] == 1.0 and rec[-1] == 0.0
        np.testing.assert_allclose(prec, sk_p, atol=1e-12)
        np.testing.assert_allclose(rec, sk_r, atol=1e-12)
        np.testing.assert_allclose(thr, sk_t, atol=0)
        np.testing.assert_allclose(
            metrics.average_precision_score(y, s),
            skm.average_precision_score(y, s), rtol=1e-9,
        )
        w = rng.rand(400)
        np.testing.assert_allclose(
            metrics.average_precision_score(y, s, sample_weight=w),
            skm.average_precision_score(y, s, sample_weight=w),
            rtol=1e-6,
        )

    def test_no_positive_fold_scores_zero_with_warning(self):
        s = rng.rand(20)
        with pytest.warns(UserWarning, match="No positive"):
            assert metrics.average_precision_score(np.zeros(20), s) == 0.0
        with pytest.warns(UserWarning, match="No positive"):
            ap = metrics.average_precision_score(
                np.zeros(20), s, labels=[0.0, 1.0]
            )
        assert ap == 0.0
        with pytest.warns(UserWarning):
            prec, rec, _ = metrics.precision_recall_curve(np.zeros(20), s)
        assert prec[-1] == 1.0 and rec[0] == 1.0 and prec[0] == 0.0

    def test_curve_metrics_refuse_ambiguous_labels(self):
        # sklearn's pos_label rule: {1,2} is ambiguous for the curve
        # family (roc_auc_score alone label-binarizes max-positive)
        y12 = np.where(rng.rand(60) > 0.5, 1.0, 2.0)
        s = rng.rand(60)
        for fn in (metrics.roc_curve, metrics.precision_recall_curve,
                   metrics.average_precision_score):
            with pytest.raises(ValueError, match="ambiguous"):
                fn(y12, s)
        # explicit labels resolve it — POSITIONALLY ([neg, pos]), so a
        # positive class smaller than the negative is expressible
        prec, rec, _ = metrics.precision_recall_curve(
            y12, s, labels=[1.0, 2.0]
        )
        assert prec[-1] == 1.0 and rec[-1] == 0.0
        np.testing.assert_allclose(
            metrics.average_precision_score(y12, s, labels=[2.0, 1.0]),
            skm.average_precision_score(y12, s, pos_label=1),
            rtol=1e-9,
        )
        # roc_auc_score keeps sklearn's larger-label binarization
        np.testing.assert_allclose(
            metrics.roc_auc_score(y12, s), skm.roc_auc_score(y12, s),
            rtol=1e-9,
        )

    def test_roc_curve_single_class_warns_nan(self):
        s = rng.rand(30)
        with pytest.warns(UserWarning, match="No positive"):
            fpr, tpr, thr = metrics.roc_curve(np.zeros(30), s)
        assert np.isnan(tpr).all() and np.isfinite(fpr[1:]).all()
        with pytest.warns(UserWarning, match="No negative"):
            fpr, tpr, thr = metrics.roc_curve(np.ones(30), s)
        assert np.isnan(fpr).all() and np.isfinite(tpr[1:]).all()
        assert len(thr) == len(fpr)

    def test_ap_scorer_registered_and_device(self, xy_classification):
        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = xy_classification
        clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
        got = get_scorer("average_precision")(
            clf, as_sharded(X), as_sharded(y)
        )
        want = skm.average_precision_score(y, clf.decision_function(X))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_curve_sharded_padding(self):
        y = rng.randint(0, 2, 101).astype(np.float64)
        s = rng.rand(101) + 0.5  # all real scores > 0: padding is 0.0
        np.testing.assert_allclose(
            metrics.average_precision_score(as_sharded(y), as_sharded(s)),
            skm.average_precision_score(y, s), rtol=1e-6,
        )
        # padding rows must not fabricate a 0.0 threshold entry, and
        # thresholds stay strictly decreasing real score values
        _, _, thr = metrics.roc_curve(as_sharded(y), as_sharded(s))
        finite = thr[np.isfinite(thr)]
        assert finite.min() > 0.5, finite.min()
        assert np.all(np.diff(thr) < 0)


class TestPRFParity:
    @pytest.mark.parametrize("average", ["binary", "macro", "micro",
                                         "weighted"])
    def test_f1_binary_and_averages(self, average):
        C = 2 if average == "binary" else 4
        y = rng.randint(0, C, 400).astype(np.float64)
        p = rng.randint(0, C, 400).astype(np.float64)
        for ours, ref in [(metrics.f1_score, skm.f1_score),
                          (metrics.precision_score, skm.precision_score),
                          (metrics.recall_score, skm.recall_score)]:
            np.testing.assert_allclose(
                ours(y, p, average=average),
                ref(y, p, average=average, zero_division=0),
                rtol=1e-6, err_msg=f"{ref.__name__}/{average}",
            )

    def test_weighted_samples(self):
        y = rng.randint(0, 3, 300).astype(np.float64)
        p = rng.randint(0, 3, 300).astype(np.float64)
        w = rng.rand(300)
        np.testing.assert_allclose(
            metrics.f1_score(y, p, average="weighted", sample_weight=w),
            skm.f1_score(y, p, average="weighted", sample_weight=w,
                         zero_division=0),
            rtol=1e-6,
        )

    def test_balanced_accuracy(self):
        y = rng.randint(0, 3, 400).astype(np.float64)
        p = rng.randint(0, 3, 400).astype(np.float64)
        np.testing.assert_allclose(
            metrics.balanced_accuracy_score(y, p),
            skm.balanced_accuracy_score(y, p),
            rtol=1e-6,
        )

    def test_confusion_matrix(self):
        y = rng.randint(0, 4, 300).astype(np.float64)
        p = rng.randint(0, 4, 300).astype(np.float64)
        np.testing.assert_array_equal(
            metrics.confusion_matrix(y, p), skm.confusion_matrix(y, p)
        )

    def test_sharded_padding_excluded(self):
        y = rng.randint(0, 3, 101).astype(np.float64)
        p = rng.randint(0, 3, 101).astype(np.float64)
        np.testing.assert_allclose(
            metrics.f1_score(as_sharded(y), as_sharded(p),
                             average="macro"),
            skm.f1_score(y, p, average="macro", zero_division=0),
            rtol=1e-6,
        )

    def test_binary_multiclass_guard(self):
        y = rng.randint(0, 3, 60).astype(np.float64)
        with pytest.raises(ValueError, match="binary"):
            metrics.f1_score(y, y, average="binary")

    def test_label_union_of_true_and_pred(self):
        # y_pred contains a class y_true never mentions: sklearn scores
        y = np.array([0.0, 1.0, 1.0, 0.0])
        p = np.array([0.0, 2.0, 1.0, 0.0])
        np.testing.assert_allclose(
            metrics.f1_score(y, p, average="macro"),
            skm.f1_score(y, p, average="macro", zero_division=0),
            rtol=1e-6,
        )
        np.testing.assert_array_equal(
            metrics.confusion_matrix(y, p), skm.confusion_matrix(y, p)
        )

    def test_missing_pos_label_raises(self):
        y = np.array([2.0, 3.0, 3.0, 2.0])
        p = np.array([2.0, 3.0, 2.0, 2.0])
        with pytest.raises(ValueError, match="pos_label=1"):
            metrics.f1_score(y, p)
        np.testing.assert_allclose(
            metrics.f1_score(y, p, pos_label=3),
            skm.f1_score(y, p, pos_label=3),
            rtol=1e-6,
        )

    def test_counts_chunked_exact(self, monkeypatch):
        # force multi-chunk accumulation: results must match one-chunk
        from dask_ml_tpu.metrics import classification as C

        y = rng.randint(0, 3, 5000).astype(np.float64)
        p = rng.randint(0, 3, 5000).astype(np.float64)
        want = metrics.f1_score(y, p, average="weighted")
        monkeypatch.setattr(C, "_COUNT_CHUNK", 512)
        got = metrics.f1_score(y, p, average="weighted")
        np.testing.assert_allclose(got, want, rtol=1e-12)
        np.testing.assert_array_equal(
            metrics.confusion_matrix(y, p), skm.confusion_matrix(y, p)
        )


class TestScorerIntegration:
    def test_scorer_table_registered(self):
        for name in ("roc_auc", "f1", "f1_macro", "balanced_accuracy",
                     "precision", "recall_weighted"):
            assert name in SCORERS
            assert get_scorer(name) is SCORERS[name]

    def test_roc_auc_scorer_on_estimator(self, xy_classification):
        from dask_ml_tpu.linear_model import LogisticRegression

        X, y = xy_classification
        clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
        got = get_scorer("roc_auc")(clf, as_sharded(X), as_sharded(y))
        want = skm.roc_auc_score(y, clf.decision_function(X))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    @pytest.mark.slow
    def test_search_scoring_no_host_folds(self, xy_classification):
        """The VERDICT done-bar: adaptive search with scoring='roc_auc'
        never routes folds through the host interop cache."""
        from dask_ml_tpu.metrics import scorer as scorer_mod
        from dask_ml_tpu.model_selection import IncrementalSearchCV
        from dask_ml_tpu.models.sgd import SGDClassifier

        X, y = xy_classification
        scorer_mod.clear_host_fold_cache()
        search = IncrementalSearchCV(
            SGDClassifier(loss="log_loss", random_state=0),
            {"alpha": [1e-4, 1e-3, 1e-2]},
            n_initial_parameters=3, max_iter=3, scoring="roc_auc",
            random_state=0,
        )
        search.fit(as_sharded(X), as_sharded(y), classes=np.unique(y))
        assert len(scorer_mod._HOST_FOLD_CACHE) == 0
        assert np.isfinite(search.best_score_)
        assert 0.5 < search.best_score_ <= 1.0
