"""One execution plane for every compiled program (ISSUE 15).

dask-ml's leverage came from ONE execution engine (the dask task graph)
under every estimator; this package is that layer for the rebuild's
compiled programs. Three machineries used to own their own shape
policy, warmup, cache keying and donation — superblock scan programs
(streaming + search cohorts), serving's compiled batch entry points and
bucket ladders, and the stacked C-grid/OvR direct solves. They now all
construct their compiled specializations through here:

- :mod:`~dask_ml_tpu.plans.ladders` — the shape policies
  (:class:`GeometricLadder` / :class:`NnzLadder` /
  :class:`SlotRungLadder`), with padding/mask construction co-located
  with the rung choice;
- :mod:`~dask_ml_tpu.plans.plan` — :class:`ProgramPlan`, the
  declarative spec whose :meth:`~ProgramPlan.build` is the one path to
  a tracked jitted entry point (cache keying, ``track_program``
  registration, donation wiring, ``config.compile_cache_dir`` arming),
  plus :func:`tracked` for pre-jitted scan builders;
- :mod:`~dask_ml_tpu.plans.warmup` — the process-wide
  :data:`warmups` registry: idempotent, attributable
  (``plan_warmups``/``plan_cache_hits`` counters, the ``plans`` table
  on ``/status`` and in the report CLI) warming for every client.

Any new estimator that declares its programs as plans gets streaming +
serving + sharding + telemetry behavior for free — ``naive_bayes``'s
streamed fit / served predict is the worked example
(``examples/12_plans.py``).

Config knobs: ``plan_cache`` (reuse identical plan builds process-wide)
and ``plan_rewarm`` (force warm executions to re-run).
"""

from .ladders import (GeometricLadder, NnzLadder, ShapeLadder,
                      SlotRungLadder)
from .plan import (ProgramPlan, annotate_programs, note_rung,
                   plans_reset, plans_snapshot, register_attr, tracked)
from .warmup import WarmupRegistry, warmups

__all__ = [
    "ShapeLadder", "GeometricLadder", "NnzLadder", "SlotRungLadder",
    "ProgramPlan", "tracked", "register_attr", "note_rung",
    "annotate_programs", "plans_snapshot", "plans_reset",
    "WarmupRegistry", "warmups",
]
