"""Encoder/label/imputer/naive-bayes tests (ref:
tests/preprocessing/test_data.py etc.; sklearn/pandas as oracles)."""

import numpy as np
import pandas as pd
import pytest
import sklearn.preprocessing as skpre

from dask_ml_tpu import preprocessing as pre
from dask_ml_tpu.impute import SimpleImputer
from dask_ml_tpu.naive_bayes import GaussianNB
from dask_ml_tpu.parallel import ShardedArray


def test_label_encoder_array():
    y = np.array([3.0, 1.0, 2.0, 1.0, 3.0])
    le = pre.LabelEncoder().fit(y)
    ref = skpre.LabelEncoder().fit(y)
    np.testing.assert_array_equal(le.classes_, ref.classes_)
    np.testing.assert_array_equal(le.transform(y), ref.transform(y))
    np.testing.assert_array_equal(le.inverse_transform(le.transform(y)), y)
    with pytest.raises(ValueError, match="unseen"):
        le.transform(np.array([5.0]))


def test_label_encoder_sharded():
    y = np.array([2.0, 0.0, 2.0, 4.0, 0.0, 2.0, 4.0])
    sy = ShardedArray.from_array(y)
    le = pre.LabelEncoder().fit(sy)
    codes = le.transform(sy)
    assert isinstance(codes, ShardedArray)
    np.testing.assert_array_equal(
        codes.to_numpy(), skpre.LabelEncoder().fit_transform(y)
    )


def test_label_encoder_categorical_fast_path():
    s = pd.Series(["a", "b", "a", "c"], dtype="category")
    le = pre.LabelEncoder().fit(s)
    np.testing.assert_array_equal(le.classes_, ["a", "b", "c"])
    np.testing.assert_array_equal(le.transform(s), [0, 1, 0, 2])


def test_one_hot_encoder_array():
    X = np.array([[0.0, 1.0], [1.0, 2.0], [0.0, 1.0]])
    ohe = pre.OneHotEncoder().fit(X)
    ref = skpre.OneHotEncoder(sparse_output=False).fit(X)
    np.testing.assert_allclose(ohe.transform(X), ref.transform(X))
    assert list(ohe.get_feature_names_out()) == list(
        ref.get_feature_names_out()
    )


def test_one_hot_encoder_sharded_device_path():
    X = np.array([[0.0], [1.0], [2.0], [1.0], [0.0]])
    sx = ShardedArray.from_array(X)
    ohe = pre.OneHotEncoder().fit(sx)
    out = ohe.transform(sx)
    assert isinstance(out, ShardedArray)
    ref = skpre.OneHotEncoder(sparse_output=False).fit_transform(X)
    np.testing.assert_allclose(out.to_numpy(), ref)


def test_one_hot_encoder_unknown_raises():
    X = np.array([[0.0], [1.0]])
    ohe = pre.OneHotEncoder().fit(X)
    with pytest.raises(ValueError, match="unknown"):
        ohe.transform(np.array([[2.0]]))
    with pytest.raises(ValueError, match="sparse"):
        pre.OneHotEncoder(sparse_output=True).fit(X)


def test_ordinal_encoder_dataframe():
    df = pd.DataFrame({
        "a": pd.Categorical(["x", "y", "x"]),
        "b": [1.0, 2.0, 3.0],
    })
    oe = pre.OrdinalEncoder().fit(df)
    out = oe.transform(df)
    np.testing.assert_array_equal(out["a"], [0, 1, 0])
    np.testing.assert_array_equal(out["b"], df["b"])


def test_categorizer_and_dummy_encoder():
    df = pd.DataFrame({
        "a": ["x", "y", "x", "z"],
        "b": [1.0, 2.0, 3.0, 4.0],
    })
    cat = pre.Categorizer().fit(df)
    dfc = cat.transform(df)
    assert isinstance(dfc["a"].dtype, pd.CategoricalDtype)
    de = pre.DummyEncoder().fit(dfc)
    out = de.transform(dfc)
    assert set(out.columns) == {"b", "a_x", "a_y", "a_z"}
    back = de.inverse_transform(out)
    np.testing.assert_array_equal(back["a"].astype(str), df["a"])
    with pytest.raises(ValueError, match="categorical"):
        pre.DummyEncoder(columns=["a"]).fit(df)  # not categorized


def test_block_transformer():
    X = np.abs(np.random.RandomState(0).randn(40, 3)) + 1.0
    sx = ShardedArray.from_array(X)
    import jax.numpy as jnp

    bt = pre.BlockTransformer(jnp.log)
    out = bt.fit(sx).transform(sx)
    np.testing.assert_allclose(out.to_numpy(), np.log(X), rtol=1e-5)
    np.testing.assert_allclose(
        pre.BlockTransformer(np.log1p).transform(X), np.log1p(X)
    )


@pytest.mark.parametrize("strategy,fill", [
    ("mean", None), ("median", None), ("most_frequent", None),
    ("constant", 7.0),
])
def test_simple_imputer(strategy, fill):
    from sklearn.impute import SimpleImputer as SkImputer

    X = np.array([
        [1.0, 2.0], [np.nan, 3.0], [7.0, np.nan], [7.0, 6.0], [4.0, 6.0],
    ])
    ours = SimpleImputer(strategy=strategy, fill_value=fill).fit(X)
    ref = SkImputer(strategy=strategy, fill_value=fill).fit(X)
    np.testing.assert_allclose(
        ours.statistics_, ref.statistics_.astype(float), rtol=1e-5
    )
    np.testing.assert_allclose(
        ours.transform(X).to_numpy(), ref.transform(X), rtol=1e-5
    )


def test_simple_imputer_bad_strategy():
    with pytest.raises(ValueError, match="strategy"):
        SimpleImputer(strategy="mode").fit(np.zeros((3, 2)))


def test_gaussian_nb_parity():
    from sklearn.naive_bayes import GaussianNB as SkGNB

    from dask_ml_tpu.datasets import make_classification

    X, y = make_classification(n_samples=400, n_features=6, random_state=0)
    ours = GaussianNB().fit(X, y)
    ref = SkGNB().fit(X.to_numpy(), y.to_numpy())
    np.testing.assert_allclose(ours.theta_, ref.theta_, atol=1e-4)
    np.testing.assert_allclose(ours.var_, ref.var_, rtol=1e-3)
    np.testing.assert_allclose(ours.class_prior_, ref.class_prior_, atol=1e-6)
    np.testing.assert_array_equal(ours.predict(X), ref.predict(X.to_numpy()))
    np.testing.assert_allclose(
        ours.predict_proba(X), ref.predict_proba(X.to_numpy()), atol=1e-4
    )
    assert ours.score(X, y) == pytest.approx(
        ref.score(X.to_numpy(), y.to_numpy()), abs=1e-6
    )


def test_onehot_inverse_transform_roundtrip():
    import sklearn.preprocessing as skp

    from dask_ml_tpu.preprocessing import OneHotEncoder

    rng = np.random.RandomState(0)
    X = rng.randint(0, 4, (60, 2)).astype(np.float32)
    enc = OneHotEncoder().fit(X)
    ref = skp.OneHotEncoder(sparse_output=False).fit(X)
    hot = enc.transform(X)
    back = enc.inverse_transform(hot)
    np.testing.assert_array_equal(back, X)
    np.testing.assert_array_equal(back, ref.inverse_transform(ref.transform(X)))


def test_onehot_inverse_transform_unknown_and_mixed():
    import sklearn.preprocessing as skp

    from dask_ml_tpu.preprocessing import OneHotEncoder

    # all-zero rows (unknowns dropped by handle_unknown='ignore') → None
    enc = OneHotEncoder(handle_unknown="ignore").fit(
        np.array([[1.0], [2.0]])
    )
    hot = enc.transform(np.array([[9.0]]))
    back = enc.inverse_transform(hot)
    ref = skp.OneHotEncoder(sparse_output=False, handle_unknown="ignore") \
        .fit(np.array([[1.0], [2.0]]))
    ref_back = ref.inverse_transform(ref.transform(np.array([[9.0]])))
    assert back[0, 0] is None and ref_back[0, 0] is None

    # mixed category dtypes keep their native types (object output)
    import pandas as pd

    df = pd.DataFrame({"s": ["x", "y", "x"], "n": [1.0, 2.0, 1.0]})
    enc2 = OneHotEncoder().fit(df)
    back2 = enc2.inverse_transform(enc2.transform(df))
    assert back2.dtype == object
    assert back2[0, 0] == "x" and back2[0, 1] == 1.0


@pytest.mark.parametrize("drop", ["first", "if_binary"])
def test_one_hot_encoder_drop(drop):
    X = np.array([[0.0, 1.0], [1.0, 2.0], [0.0, 3.0], [1.0, 1.0]])
    ohe = pre.OneHotEncoder(drop=drop).fit(X)
    ref = skpre.OneHotEncoder(sparse_output=False, drop=drop).fit(X)
    np.testing.assert_allclose(ohe.transform(X), ref.transform(X))
    assert list(ohe.get_feature_names_out()) == list(
        ref.get_feature_names_out()
    )
    # inverse round-trips, including the all-zero (dropped) rows
    np.testing.assert_allclose(
        np.asarray(ohe.inverse_transform(ohe.transform(X)), dtype=float), X
    )


def test_one_hot_encoder_drop_array_and_validation():
    X = np.array([[0.0, 1.0], [1.0, 2.0], [0.0, 3.0]])
    ohe = pre.OneHotEncoder(drop=[1.0, 3.0]).fit(X)
    ref = skpre.OneHotEncoder(sparse_output=False,
                              drop=np.array([1.0, 3.0])).fit(X)
    np.testing.assert_allclose(ohe.transform(X), ref.transform(X))
    with pytest.raises(ValueError, match="not a category"):
        pre.OneHotEncoder(drop=[9.0, 1.0]).fit(X)
    with pytest.raises(ValueError, match="shape"):
        pre.OneHotEncoder(drop=[1.0]).fit(X)


def test_one_hot_encoder_drop_sharded_device_path():
    X = np.array([[0.0, 5.0], [1.0, 6.0], [2.0, 5.0], [1.0, 6.0],
                  [0.0, 5.0]])
    sx = ShardedArray.from_array(X)
    ohe = pre.OneHotEncoder(drop="first").fit(sx)
    out = ohe.transform(sx)
    assert isinstance(out, ShardedArray)
    ref = skpre.OneHotEncoder(sparse_output=False, drop="first")
    np.testing.assert_allclose(out.to_numpy(), ref.fit_transform(X))
    # unknown detection still works with drop (checked pre-drop)
    bad = ShardedArray.from_array(np.array([[7.0, 5.0]]))
    with pytest.raises(ValueError, match="unknown"):
        ohe.transform(bad)
