"""Multi-host distributed runtime.

Reference: the ``distributed`` scheduler/worker/comm stack — TCP frames,
msgpack+pickle serialization, heartbeats (SURVEY.md §2b rows 4-5, §5 comm
row). TPU replacement: intra-slice communication is XLA collectives over
ICI compiled into programs (no serialization layer exists at all);
cross-host control is the JAX distributed runtime over DCN. This module
is the thin bring-up layer: ``initialize()`` wraps
``jax.distributed.initialize`` (no-op single-host), ``global_mesh`` spans
every process's devices, and small host-side control messages ride an
all-gather (``broadcast_host`` / ``barrier``) instead of a socket
protocol.

Single-host sessions exercise the same code paths (process_count == 1),
which is how the test suite covers it; a pod run only changes the
environment variables.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import device_mesh

_initialized = False


def initialize(coordinator_address=None, num_processes=None,
               process_id=None, local_device_ids=None):
    """Bring up the JAX distributed runtime (DCN control plane).

    No-op when single-process and no coordinator is configured — the same
    script runs on a laptop, one TPU VM, or every host of a pod slice.
    """
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes is None and \
            "COORDINATOR_ADDRESS" not in __import__("os").environ:
        _initialized = True  # single-process mode
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """The host that runs search controllers (SURVEY.md §3.5: 'asyncio
    controller on host 0')."""
    return jax.process_index() == 0


def global_mesh(axis_names=("data",), shape=None):
    """Mesh over ALL processes' devices (ICI within a slice, DCN across:
    topology-ordered so the DCN hop is the outer factor of the data
    axis)."""
    return device_mesh(shape=shape, axis_names=axis_names,
                       devices=jax.devices(), topology_order=True)


def local_mesh(axis_names=("data",), shape=None):
    """Mesh over THIS process's devices only. Trials placed here never
    emit cross-host collectives, so different processes can run different
    programs concurrently — the placement unit for distributed
    hyperparameter search (SURVEY.md §3.5: 'trials pinned to
    hosts/mesh-subsets')."""
    return device_mesh(shape=shape, axis_names=axis_names,
                       devices=jax.local_devices(), topology_order=True)


def allgather_object(obj):
    """Gather one small picklable host object per process; every process
    receives the list ``[obj_from_proc_0, ..., obj_from_proc_{P-1}]``.
    Variable-size pickles ride the fixed-size device collective by
    padding to the max length (sizes exchanged first) — the control-plane
    result channel for distributed searches, replacing the reference's
    msgpack/pickle frames over TCP (SURVEY.md §5 comm row)."""
    import pickle

    if process_count() == 1:
        return [obj]
    buf = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = allgather_host(np.array([buf.size], np.int32))[:, 0]
    padded = np.zeros(int(sizes.max()), np.uint8)
    padded[: buf.size] = buf
    stacked = allgather_host(padded)
    return [
        pickle.loads(stacked[i, : sizes[i]].tobytes())
        for i in range(len(sizes))
    ]


def psum_host(*arrays):
    """Sum each small host array across processes; every process gets
    the identical (bit-exact — same gather order everywhere) global sum.
    The cross-process merge plane for streamed fits: per-pass
    loss/gradient/Hessian/moment accumulators are additive, so one
    psum of the local sums turns a per-process stream into a global fit
    (SURVEY.md §1 L2 dd partitions; VERDICT r4 missing #3). No-op
    single-process. Returns one array, or a tuple matching the inputs."""
    if process_count() == 1:
        outs = tuple(np.asarray(a) for a in arrays)
        return outs[0] if len(outs) == 1 else outs
    # ONE packed collective regardless of argument count — hot callers
    # (Lloyd stats, Newton's value/grad/Hessian) psum 3 arrays per data
    # pass, and each allgather pays a full DCN round trip
    arrs = [np.asarray(a, np.float64) for a in arrays]
    flat = (np.concatenate([a.ravel() for a in arrs])
            if arrs else np.zeros(0))
    total = allgather_host(flat).sum(axis=0)
    outs, off = [], 0
    for a in arrs:
        outs.append(total[off:off + a.size].reshape(a.shape))
        off += a.size
    return outs[0] if len(outs) == 1 else tuple(outs)


def allgather_host(value: np.ndarray) -> np.ndarray:
    """Gather a small host array from every process; returns the
    (n_processes, *shape) stack on all of them (shape/dtype must match
    across processes). The score-gather channel of distributed searches —
    replaces the reference's worker→scheduler result messages with one
    device-fabric collective.

    The payload rides the collective as raw bytes: ``jnp.asarray`` would
    silently downcast float64 (x64 disabled by default), and score merges
    must be bit-exact with the single-process run."""
    value = np.ascontiguousarray(value)
    if process_count() == 1:
        return value[None]
    from jax.experimental import multihost_utils

    buf = np.frombuffer(value.tobytes(), np.uint8)
    stacked = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(buf), tiled=False)
    )
    return np.stack([
        np.frombuffer(stacked[i].tobytes(), value.dtype).reshape(value.shape)
        for i in range(stacked.shape[0])
    ])


def array_from_process_local(local, mesh=None, dtype=np.float32):
    """Global row-sharded ShardedArray from PER-PROCESS row blocks.

    Each process contributes its OWN rows (global order = process
    order); unlike ``ShardedArray.from_array`` (SPMD: every process
    holds the full array), only the rows that land on a FOREIGN
    process's shards are exchanged — at most one shard's worth per
    process boundary, zero when counts divide evenly. Wire cost note:
    the exchange rides ``allgather_object`` (a broadcast), so each
    boundary parcel reaches every process — O(P x boundary bytes) over
    DCN, fine for the boundary-slice volumes this produces; a
    per-destination channel would be the upgrade if parcels ever grow.
    The reference's analog is dd's partition-locality (a worker's
    partitions stay put; SURVEY.md §1 L2 dd row); here the multi-host
    ingest for PartitionedFrame.to_sharded(mesh=global_mesh())."""
    import jax

    from .mesh import data_shards, row_sharding
    from .sharded import ShardedArray, _padded_rows

    local = np.ascontiguousarray(np.asarray(local, dtype))
    if mesh is None:
        mesh = global_mesh()
    me = jax.process_index()
    shapes = allgather_object(
        (tuple(local.shape[1:]), str(local.dtype))
    )
    if any(s != shapes[0] for s in shapes):
        raise ValueError(
            "array_from_process_local requires identical feature shape "
            f"and dtype on every process; got {shapes}"
        )
    counts = np.asarray(allgather_object(int(local.shape[0])), np.int64)
    n = int(counts.sum())
    off = int(counts[:me].sum())
    n_pad = _padded_rows(n, data_shards(mesh))
    shape = (n_pad,) + local.shape[1:]
    sharding = row_sharding(mesh, local.ndim)
    # exact global row range per device, then per process
    imap = sharding.devices_indices_map(shape)
    proc_ranges = {}
    for dev, idx in imap.items():
        sl = idx[0]
        rng = (sl.start or 0, n_pad if sl.stop is None else sl.stop)
        proc_ranges.setdefault(dev.process_index, set()).add(rng)
    # ship the slices of MY rows that land on foreign shards
    parcels = {}
    for q, ranges in proc_ranges.items():
        if q == me:
            continue
        for a, b in sorted(ranges):
            lo, hi = max(a, off), min(b, off + local.shape[0])
            if lo < hi:
                parcels.setdefault(q, []).append(
                    (lo, local[lo - off:hi - off])
                )
    received = allgather_object(parcels)
    # assemble my shards: own overlap + foreign parcels; rows >= n stay
    # zero (the trailing padding row_mask hides)
    mine = {}
    for a, b in sorted(proc_ranges.get(me, ())):
        buf = np.zeros((b - a,) + local.shape[1:], dtype=local.dtype)
        lo, hi = max(a, off), min(b, off + local.shape[0])
        if lo < hi:
            buf[lo - a:hi - a] = local[lo - off:hi - off]
        for sender in received:
            for g0, arr in sender.get(me, []):
                l2, h2 = max(a, g0), min(b, g0 + arr.shape[0])
                if l2 < h2:
                    buf[l2 - a:h2 - a] = arr[l2 - g0:h2 - g0]
        mine[(a, b)] = buf

    def cb(idx):
        sl = idx[0]
        a = sl.start or 0
        return mine[(a, n_pad if sl.stop is None else sl.stop)]

    data = jax.make_array_from_callback(shape, sharding, cb)
    return ShardedArray(data, n, mesh)


def barrier(name="barrier"):
    """Cross-host sync point: a tiny psum over every device."""
    x = jnp.ones((jax.device_count(),))
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = global_mesh()
    y = jax.jit(
        lambda v: jnp.sum(v),
        in_shardings=NamedSharding(mesh, P("data")),
        out_shardings=NamedSharding(mesh, P()),
    )(x)
    return float(y)


def broadcast_host(value: np.ndarray, root: int = 0) -> np.ndarray:
    """Broadcast a small host array from the coordinator to all processes
    — replaces the reference's scheduler→worker control messages. Rides
    the device fabric (device_put + replication), not a socket."""
    if process_count() == 1:
        return np.asarray(value)
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.broadcast_one_to_all(
            jnp.asarray(value), is_source=process_index() == root
        )
    )
