"""Replay-driven load testing: recorded traffic in, SLO verdict out.

PR 16 left the substrate: ``obs_req_capture`` writes one JSONL record
per ADMITTED request (method, rows, admit wall clock) and
``observability._requests.replay`` re-issues a record list at the
recorded inter-arrival spacing. This module turns that into a harness
with a pass/fail answer — the load-test generalization of the PR 7
one-batch canary:

- :func:`replay_load_test` drives a recorded (method, rows, rate) mix
  against a live server/fleet/federation, measures per-request
  end-to-end latency and outcome (ok / shed / timeout / error), and
  verdicts the run against ``serving_slo_ms`` at a chosen quantile;
- ``fault_plan=`` runs the mix through the chaos plane: the plan is
  armed around SERVER CONSTRUCTION (worker threads capture their
  creator's config — pass ``target`` as a zero-arg factory so the
  workers are born under the armed plan);
- ``canary_version=`` flips the target's registry to an ARCHIVED
  version for the duration (a zero-recompile hot-swap), replays the
  mix against it, and flips back — a shadow load test answering "would
  the canary hold the SLO under yesterday's real traffic" before any
  user sees it;
- :func:`synthesize_records` builds a deterministic capture-shaped mix
  when no recording exists yet (tests, smokes, benches).
"""

from __future__ import annotations

import time

import numpy as np

from ._server import RequestTimeout, ServingError, SloShed

__all__ = ["replay_load_test", "synthesize_records"]


def synthesize_records(n_requests, methods=("predict",),
                       rows=(1, 64), rate_rps=200.0, seed=0) -> list:
    """A deterministic capture-shaped record list (the
    ``load_capture`` schema: t_unix / method / n_rows) for harness runs
    with no real recording: request sizes draw log-uniformly from
    ``rows=(lo, hi)``, methods round-robin, inter-arrivals are
    exponential at ``rate_rps`` (a Poisson burst, not a metronome)."""
    rng = np.random.default_rng(seed)
    lo, hi = int(rows[0]), int(rows[1])
    t = 0.0
    records = []
    for i in range(int(n_requests)):
        t += float(rng.exponential(1.0 / max(rate_rps, 1e-9)))
        n = int(round(np.exp(rng.uniform(np.log(max(lo, 1)),
                                         np.log(max(hi, 1))))))
        records.append({
            "req_capture": True,
            "t_unix": round(t, 6),
            "method": methods[i % len(methods)],
            "n_rows": max(min(n, hi), lo),
        })
    return records


def _quantile_ms(lats_s, q):
    if not lats_s:
        return None
    return float(np.percentile(np.asarray(lats_s, np.float64),
                               q)) * 1e3


def replay_load_test(target, X, records=None, capture_path=None,
                     speed=1.0, slo_ms=None, quantile=99.0,
                     canary_version=None, fault_plan=None,
                     result_timeout_s=60.0) -> dict:
    """Replay a recorded mix against ``target`` and verdict the SLO.

    Parameters
    ----------
    target : server-like or zero-arg callable
        Anything with ``submit(X, method=...) -> Future`` (ModelServer,
        FleetServer, FederatedFleet). Pass a CALLABLE returning a
        started+warmed server to run it under an armed ``fault_plan`` —
        serving workers capture config at construction, so a plan armed
        after the fact never fires on them; a factory target is
        constructed (and stopped) inside the armed scope.
    X : (n, d) array — the feature pool requests slice rows from
        (wrapping), so the replay exercises the data plane, not zeros.
    records / capture_path
        The mix: an explicit record list (``synthesize_records``) or a
        trace JSONL to ``load_capture`` from. One of the two.
    speed : float — replay speedup (10 = 10x the recorded rate).
    slo_ms : float, default ``config.serving_slo_ms`` — verdict budget.
    quantile : float — the latency quantile the verdict holds against.
    canary_version : int — flip the target's registry to this ARCHIVED
        version for the run, flip back after (shadow canary test).
    fault_plan : str — chaos plan armed around the run (and around
        factory construction).

    Returns the report dict; ``report["passed"]`` is the verdict:
    latency quantile within ``slo_ms`` (when an SLO is set) AND zero
    errored admitted requests (sheds are deliberate backpressure and
    counted, not failed; a TIMED-OUT admitted request fails the run —
    it was lost to the client)."""
    from .. import config
    from ..observability import _requests as rtrace

    if records is None:
        if capture_path is None:
            raise ValueError("need records= or capture_path=")
        records = rtrace.load_capture(capture_path)
    pool = np.asarray(X, np.float32)
    if pool.ndim == 1:
        pool = pool[None, :]
    pool_n = int(pool.shape[0])

    overrides = {}
    if fault_plan is not None:
        overrides["fault_plan"] = fault_plan
    if slo_ms is not None:
        overrides["serving_slo_ms"] = float(slo_ms)
    with config.set(**overrides):
        srv = target() if callable(target) else target
        own_server = callable(target)
        restored_version = None
        try:
            if canary_version is not None:
                cur = srv.registry.current_version(srv.name)
                if int(canary_version) != cur:
                    restored_version = cur
                    srv.rollback(int(canary_version))
            budget_ms = float(config.get_config().serving_slo_ms
                              if slo_ms is None else slo_ms)
            outcomes = {"ok": 0, "shed": 0, "timeout": 0, "error": 0}
            futures = []
            lats_s = []
            cursor = [0]

            def _submit(method, n_rows):
                i = cursor[0]
                cursor[0] = i + n_rows
                idx = np.arange(i, i + n_rows) % pool_n
                t0 = time.perf_counter()
                try:
                    fut = srv.submit(pool[idx], method=method)
                except SloShed:
                    outcomes["shed"] += 1
                    return
                except ServingError:
                    outcomes["error"] += 1
                    return
                futures.append((fut, t0))

            mix = rtrace.replay(records, _submit, speed=speed)
            for fut, t0 in futures:
                try:
                    fut.result(result_timeout_s)
                    lats_s.append(time.perf_counter() - t0)
                    outcomes["ok"] += 1
                except SloShed:
                    # federated submits resolve sheds at the future
                    outcomes["shed"] += 1
                except RequestTimeout:
                    outcomes["timeout"] += 1
                except Exception:
                    outcomes["error"] += 1
        finally:
            if restored_version is not None:
                try:
                    srv.rollback(restored_version)
                except Exception:
                    pass
            if own_server:
                try:
                    srv.stop()
                except Exception:
                    pass

    p_ms = _quantile_ms(lats_s, quantile)
    passed = outcomes["error"] == 0 and outcomes["timeout"] == 0
    if budget_ms > 0 and p_ms is not None:
        passed = passed and p_ms <= budget_ms
    return {
        **mix,
        **outcomes,
        "admitted": len(futures),
        "latency_ms": {
            "p50": _quantile_ms(lats_s, 50.0),
            f"p{quantile:g}": p_ms,
        },
        "slo_ms": budget_ms,
        "quantile": float(quantile),
        "canary_version": canary_version,
        "restored_version": restored_version,
        "passed": bool(passed),
    }
