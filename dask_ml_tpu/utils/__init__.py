"""Ref: dask_ml/utils.py (SURVEY.md §2a Support row)."""
import numpy as np

from .testing import assert_estimator_equal, copy_learned_attributes
from .validation import check_array, check_chunks, check_is_fitted, check_X_y


def handle_zeros_in_scale(scale):
    """Ref: dask_ml/utils.py::handle_zeros_in_scale."""
    return np.where(scale == 0.0, 1.0, scale)


def slice_columns(X, columns):
    """Ref: dask_ml/utils.py::slice_columns."""
    from ..compose._column_transformer import _select

    return _select(X, columns)
