"""GLM solvers: lbfgs, gradient_descent, newton, proximal_grad, admm.

Reference equivalent: ``dask_glm/algorithms.py`` (SURVEY.md §2b row 6,
§3.2). The reference keeps optimizer state on the *client* and pays a full
cluster round-trip per function evaluation (scipy's Fortran L-BFGS-B driving
dask graphs). The TPU design inverts that (SURVEY.md §7 design stance #2):

- Solver state lives ON DEVICE. Each solver is a single jitted program whose
  outer iteration is a ``lax.while_loop``; line searches
  (Armijo backtracking, optax zoom) are inner ``while_loop``s. Host sees one
  scalar diagnostics tuple at the end — zero per-iteration round-trips.
- Data parallelism is implicit: X is row-sharded, so ``X @ beta`` /
  ``X.T @ r`` lower to per-shard matmuls + ICI psum (the reference's
  tree-reduce, without the task graph).
- ADMM runs per-shard local Newton solves inside ``shard_map`` with a psum
  consensus z-update — the reference gathers per-chunk betas to the client
  and broadcasts z back over TCP each outer iteration.

All jitted entry points are module-level with static (family, reg) names so
XLA's compile cache is shared across estimator instances.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ...parallel.mesh import DATA_AXIS
from ...observability import emit_jit_step, track_program
from ...plans import ProgramPlan
from ..solvers import regularizers
from ..solvers.families import get_family
from ...ops.linalg import shard_map


def _smooth_loss(beta, X, y, mask, n_rows, lam, pmask, l1_ratio, family, reg):
    """Mask-weighted mean NLL + smooth penalty. One psum under jit.

    The matvec casts beta to X's dtype with f32 accumulation, so a bf16
    design matrix (config.dtype="bfloat16") runs the MXU at bf16 rate
    while the loss/penalty stay f32."""
    eta = jax.lax.dot_general(
        X, beta.astype(X.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    base = jnp.sum(get_family(family).pointwise(eta, y) * mask) / n_rows
    return base + regularizers.value(reg, beta, lam, pmask, l1_ratio)


def _pallas_loss(X, y, mask, n_rows, lam, pmask, l1_ratio, family, reg,
                 mesh, interpret):
    """Smooth loss whose DATA term's value and gradient both come from
    the fused Pallas kernel (``ops/pallas_fused.fused_glm_value_grad``):
    one X pass per value_and_grad instead of XLA's two (forward matvec +
    gradient matmul) — the GLM fit is HBM-bound, so this halves the
    traffic of every solver iteration. The kernel runs per shard inside
    shard_map with a psum merge; a custom_vjp hands autodiff the
    kernel's gradient, and the penalty/mean scaling stay ordinary XLA on
    the (d,) vector."""
    from ...ops.pallas_fused import fused_glm_value_grad

    def data_vg(beta):
        return _shard_psum_call(
            mesh,
            lambda bs, xs, ys, ms, nv: fused_glm_value_grad(
                xs, nv, ys, bs, family=family, interpret=interpret
            ),
            2, beta, X, y, mask,
        )

    return _custom_vjp_loss(data_vg, n_rows, reg, lam, pmask, l1_ratio)


def _shard_psum_call(mesh, per_shard, n_out, beta, X, y, mask):
    """Run a per-shard GLM kernel under shard_map and psum its
    ``n_out`` partial outputs — the ONE copy of the (specs, prefix
    valid-row count, psum) sharding contract used by every fused
    solver path."""
    def shard(bs, xs, ys, ms):
        nv = jnp.sum(ms.astype(jnp.int32))
        outs = per_shard(bs, xs, ys, ms, nv)
        return tuple(jax.lax.psum(o, DATA_AXIS) for o in outs)

    f = shard_map(
        shard, mesh=mesh,
        in_specs=(P(), P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=tuple(P() for _ in range(n_out)),
    )
    return f(beta, X, y, mask)


def _custom_vjp_loss(data_vg, n_rows, reg, lam, pmask, l1_ratio):
    """Wrap a kernel-backed ``beta -> (value, grad)`` into a scalar loss
    whose autodiff uses the kernel's gradient (custom_vjp), plus the
    penalty/mean scaling in XLA — the ONE copy of this scaffolding,
    shared by the single- and multi-target Pallas paths."""

    @jax.custom_vjp
    def data_sum(beta):
        v, _ = data_vg(beta)
        return v

    def fwd(beta):
        return data_vg(beta)

    def bwd(g, ct):
        return (ct * g,)

    data_sum.defvjp(fwd, bwd)

    def loss(beta):
        return data_sum(beta) / n_rows + regularizers.value(
            reg, beta, lam, pmask, l1_ratio
        )

    return loss


def _select_loss(use_pallas, X, y, mask, n_rows, lam, pmask, l1_ratio,
                 family, reg, mesh, interpret):
    """The ONE place a jitted solver body picks its smooth loss: the
    fused Pallas value+grad (one X pass per evaluation) or the plain
    XLA objective."""
    if use_pallas:
        return _pallas_loss(X, y, mask, n_rows, lam, pmask, l1_ratio,
                            family, reg, mesh, interpret)
    return partial(_smooth_loss, X=X, y=y, mask=mask, n_rows=n_rows,
                   lam=lam, pmask=pmask, l1_ratio=l1_ratio,
                   family=family, reg=reg)


def _resolve_pallas(use_pallas, mesh, family, X=None):
    """Auto gate for the fused GLM kernel: real TPU backend, a plain
    data-parallel mesh (feature-sharded TP layouts keep the GSPMD
    path), known family, and a design narrow enough that a row tile
    fits the kernel's VMEM budget (wide designs keep the XLA loss,
    whose matmuls tile the feature dim freely)."""
    if use_pallas is not None:
        return bool(use_pallas)
    from ...parallel.mesh import MODEL_AXIS
    from ...ops.pallas_fused import glm_tile

    return (
        jax.default_backend() == "tpu"
        and mesh is not None
        and mesh.shape.get(MODEL_AXIS, 1) == 1
        and family in ("logistic", "normal", "poisson")
        and (X is None or glm_tile(
            X.shape[0], X.shape[1], X.dtype.itemsize
        ) is not None)
    )


def _pallas_fallback(make_run, use_pallas, auto, solver):
    """Insurance for the AUTO-gated kernel: if the Pallas-enabled
    program fails to compile/lower (an untested Mosaic shape corner),
    the solve silently retries on the XLA loss instead of killing the
    fit — but only when the kernel was auto-selected; an explicit
    use_pallas=True surfaces the error."""
    run = make_run(use_pallas)
    if not (use_pallas and auto):
        return run

    state = {"run": run, "fell_back": False}

    def guarded(**kw):
        if state["fell_back"]:
            return state["run"](**kw)
        try:
            # materialize INSIDE the try: jitted results dispatch
            # asynchronously, so a post-compile runtime fault would
            # otherwise surface later, outside this guard
            return jax.block_until_ready(state["run"](**kw))
        except Exception as exc:
            import warnings

            warnings.warn(
                f"Pallas-enabled {solver} solve failed "
                f"({type(exc).__name__}: {exc}); retrying on the XLA "
                "loss — if the retry also fails, the original error was "
                "not the kernel's", RuntimeWarning,
            )
            # LATCH the fallback: later chunks (checkpointed solves call
            # run per chunk) must not re-attempt the failing compile
            state["run"] = make_run(False)
            state["fell_back"] = True
            return state["run"](**kw)

    return guarded


def _host_scalars(*vals):
    """Fetch a handful of device result scalars in ONE device→host
    transfer — separate int()/float() pulls each pay a full round trip,
    which dominates small fits on tunneled runtimes."""
    return np.asarray(jnp.stack([
        jnp.asarray(v, jnp.float32) for v in vals
    ]))


def check_finite_result(beta, info, solver):
    """NaN/Inf sanitizer (SURVEY.md §5 race-detection row): a NaN ends a
    ``gnorm > tol`` while_loop as "converged", silently. Every solver
    funnels its result through here; non-finite parameters raise instead
    of becoming a model."""
    beta_h = np.asarray(beta)  # the one beta fetch — callers reuse it
    scalars = [v for v in info.values() if isinstance(v, (int, float))]
    if not np.isfinite(beta_h).all() or not np.all(np.isfinite(scalars)):
        raise FloatingPointError(
            f"solver {solver!r} produced non-finite parameters "
            f"(info={info}): the input contains NaN/Inf or the solve "
            f"diverged — validate the data or reduce the step size / C"
        )
    return beta_h, info


def _check_smooth(reg, solver):
    if reg not in regularizers.SMOOTH:
        raise ValueError(
            f"solver {solver!r} handles smooth penalties only (l2/none), got "
            f"{reg!r}; use solver='proximal_grad' or 'admm' for l1/elastic_net"
        )


# --------------------------------------------------------------------------
# L-BFGS (optax, zoom linesearch) — whole optimization in one XLA program
# --------------------------------------------------------------------------

@track_program("glm.lbfgs")
@partial(jax.jit, static_argnames=("family", "reg", "memory", "log",
                                   "use_pallas", "mesh", "interpret"))
def _lbfgs_chunk(X, y, mask, n_rows, carry, lam, pmask, l1_ratio, stop_it,
                 tol, family, reg, memory=10, log=False, use_pallas=False,
                 mesh=None, interpret=False):
    """Run the L-BFGS while_loop from ``carry`` until ``stop_it`` (or
    convergence). A full solve is one chunk with stop_it = max_iter; the
    checkpointed path runs k-iteration chunks so (beta, optimizer state)
    hits stable storage between programs (SURVEY.md §5 checkpoint row —
    TPU slices fail whole, recovery is checkpoint-restart)."""
    loss = _select_loss(use_pallas, X, y, mask, n_rows, lam, pmask,
                        l1_ratio, family, reg, mesh, interpret)
    return _lbfgs_loop(loss, carry, stop_it, tol, memory, log)


def _lbfgs_loop(loss, carry, stop_it, tol, memory, log, n_blocks=None):
    """The optax L-BFGS while_loop, shared by every loss flavor (XLA,
    Pallas single-target, Pallas multi-target).

    ``n_blocks`` switches on the stacked multi-solve semantics: the flat
    vector is ``n_blocks`` independent row blocks (classes, lam
    candidates, or both) sharing ONE iteration budget — every iteration
    advances every block, and the loop stops only when the MAX per-block
    gradient norm reaches tol ("every block converged"), matching the
    single-target criterion exactly. The carry then grows a
    ``(n_blocks,)`` int32 vector recording, per block, the last
    iteration at which that block's gradient norm still exceeded tol —
    the block's own convergence point INSIDE the joint trajectory.
    (Not identical to a standalone solve's ``n_iter_``: the shared
    L-BFGS curvature state and line search see every block at once, so
    per-block paths differ even though the separable optimum is the
    same.) Callers surface it as the per-candidate ``n_iter``.

    Each block's RETURNED iterate is frozen at its own convergence
    point — its first iterate whose gradient norm passed tol, exactly
    where a standalone solve of that block would have stopped. Blocks
    the budget cut off return the final joint iterate, again matching
    the standalone cap behavior. Without the freeze an early-converged
    candidate kept refining inside the joint program; the drift is
    below tol but was measured flipping razor-edge predictions, so the
    stacked C-grid's scores disagreed with per-candidate fits on tied
    candidates (the PR-1 tie-break parity failure).
    """
    opt = optax.lbfgs(memory_size=memory)
    value_and_grad = optax.value_and_grad_from_state(loss)
    track = n_blocks is not None

    def cond(carry):
        gnorm, it = carry[2], carry[3]
        return (it < stop_it) & (gnorm > tol)

    def body(carry):
        beta, state, _, it = carry[:4]
        value, grad = value_and_grad(beta, state=state)
        if track:
            conv, frozen, cmask = carry[4:]
            # the gradient is evaluated at the CURRENT iterate: a block
            # whose norm just passed tol converged AT this iterate —
            # record it before the update moves on
            norms = jnp.linalg.norm(grad.reshape(n_blocks, -1), axis=1)
            frozen = jnp.where(cmask[:, None], frozen,
                               beta.reshape(n_blocks, -1))
            cmask = cmask | (norms <= tol)
        updates, state = opt.update(
            grad, state, beta, value=value, grad=grad, value_fn=loss
        )
        beta = optax.apply_updates(beta, updates)
        if track:
            gnorm = jnp.max(norms)
            conv = jnp.where(norms > tol, it + 1, conv)
        else:
            gnorm = jnp.linalg.norm(grad)
        if log:  # static: the silent trace has no callback at all
            emit_jit_step(it, loss=value, grad_norm=gnorm)
        if track:
            return beta, state, gnorm, it + 1, conv, frozen, cmask
        return beta, state, gnorm, it + 1

    if track and len(carry) == 4:
        b0 = carry[0]
        carry = (*carry, jnp.zeros(n_blocks, jnp.int32),
                 b0.reshape(n_blocks, -1),
                 jnp.zeros(n_blocks, jnp.bool_))
    out = jax.lax.while_loop(cond, body, carry)
    if track:
        beta, state, gnorm, it, conv, frozen, cmask = out
        merged = jnp.where(cmask[:, None], frozen,
                           beta.reshape(n_blocks, -1)).reshape(beta.shape)
        return merged, state, gnorm, it, conv
    return out


def _per_block_iters(conv, it_total):
    """Per-block iteration counts in the single-target ``n_iter``
    convention: the confirming iteration that first observes a
    below-tol gradient counts too (+1 over the tracker's last above-tol
    iteration), clamped to the joint budget for blocks the cap cut
    off. Guarantees max(per_block) == the joint program's n_iter."""
    c = np.asarray(conv, np.int64) + 1
    return np.minimum(c, int(it_total))


@track_program("glm.lbfgs_multi_pallas")
@partial(jax.jit, static_argnames=("family", "reg", "memory", "log",
                                   "mesh", "interpret", "n_classes"))
def _lbfgs_multi_pallas_chunk(X, codes, mask, n_rows, carry, lam, pmask_t,
                              l1_ratio, stop_it, tol, family, reg, mesh,
                              n_classes, memory=10, log=False,
                              interpret=False):
    """Joint L-BFGS over the FLAT (C*d,) one-vs-rest vector whose data
    term comes from the multi-target Pallas kernel: every iteration
    reads X ONCE for all C classes (the stacked XLA path reads it twice
    — one batched forward matmul + one gradient matmul). The objective is
    separable across classes, so the joint optimum equals the per-class
    optima; ``pmask_t`` arrives tiled to (C*d,)."""
    from ...ops.pallas_fused import fused_glm_multi_value_grad

    d = pmask_t.shape[0] // n_classes

    def data_vg(bflat):
        v, g = _shard_psum_call(
            mesh,
            lambda Bs, xs, cs, ms, nv: fused_glm_multi_value_grad(
                xs, nv, cs, Bs, family=family, interpret=interpret
            ),
            2, bflat.reshape(n_classes, d), X, codes, mask,
        )
        return v, g.reshape(-1)

    loss = _custom_vjp_loss(data_vg, n_rows, reg, lam, pmask_t, l1_ratio)
    return _lbfgs_loop(loss, carry, stop_it, tol, memory, log,
                       n_blocks=n_classes)


def lbfgs(X, y, mask, n_rows, beta0, family, reg, lam, pmask, l1_ratio=0.5,
          max_iter=100, tol=1e-6, memory=10, log=False, checkpoint_path=None,
          checkpoint_every=0, mesh=None, use_pallas=None,
          pallas_interpret=False, **_):
    """When ``checkpoint_path`` + ``checkpoint_every`` are set (via
    ``solver_kwargs``), the solve runs in k-iteration chunks with
    (beta, optimizer state, it) persisted after each — a killed 3-hour
    fit resumes mid-solve instead of from zero (VERDICT r2 #5)."""
    _check_smooth(reg, "lbfgs")
    pallas_auto = use_pallas is None
    use_pallas = _resolve_pallas(use_pallas, mesh, family, X)
    opt = optax.lbfgs(memory_size=memory)
    carry = (beta0, opt.init(beta0), jnp.asarray(jnp.inf, beta0.dtype), 0)
    tol_a = jnp.asarray(tol, beta0.dtype)

    def make_run(with_pallas):
        return partial(
            _lbfgs_chunk, X, y, mask, n_rows, lam=lam, pmask=pmask,
            l1_ratio=l1_ratio, tol=tol_a, family=family, reg=reg,
            memory=memory, log=log, use_pallas=with_pallas,
            mesh=mesh if with_pallas else None, interpret=pallas_interpret,
        )

    run = _pallas_fallback(make_run, use_pallas, pallas_auto, "lbfgs")
    resumed_from = 0
    if not (checkpoint_path and checkpoint_every):
        beta, state, gnorm, it = run(carry=carry,
                                     stop_it=jnp.asarray(max_iter))
        it, gnorm = _host_scalars(it, gnorm)
    else:
        import os

        from ...utils import checkpoint as ckpt

        if os.path.exists(os.path.abspath(checkpoint_path)):
            restored = ckpt.restore_pytree(checkpoint_path, like=carry)
            # host views: restored leaves come back committed to one
            # device; jit must be free to re-place them with X's sharding
            carry = tuple(jax.tree.map(
                lambda a: np.asarray(a), tuple(restored)
            ))
            resumed_from = int(carry[3])
        while True:
            it = int(carry[3])
            gnorm = float(carry[2])
            if it >= max_iter or (it > 0 and gnorm <= tol):
                break
            stop = min(it + int(checkpoint_every), max_iter)
            carry = run(carry=carry, stop_it=jnp.asarray(stop))
            ckpt.save_pytree(checkpoint_path, tuple(carry))
        # completed: CLEAR the checkpoint — a finished solve's state left
        # on disk would be silently "resumed" (returning the stale beta)
        # by the next fit sharing the path. The path identifies ONE fit;
        # only a killed run leaves state behind.
        import shutil

        shutil.rmtree(os.path.abspath(checkpoint_path), ignore_errors=True)
        beta, state, gnorm, it = carry
    info = {"n_iter": int(it), "grad_norm": float(gnorm)}
    if checkpoint_path and checkpoint_every:
        info["resumed_from"] = resumed_from
    return beta, info


# --------------------------------------------------------------------------
# Gradient descent with Armijo backtracking (dask_glm::gradient_descent)
# --------------------------------------------------------------------------

@track_program("glm.gradient_descent")
@partial(jax.jit, static_argnames=("family", "reg", "log", "use_pallas",
                                   "mesh", "interpret"))
def _gd_run(X, y, mask, n_rows, beta0, lam, pmask, l1_ratio, max_iter, tol,
            init_step, family, reg, armijo=1e-4, backtrack=0.5, grow=2.0,
            log=False, use_pallas=False, mesh=None, interpret=False):
    loss = _select_loss(use_pallas, X, y, mask, n_rows, lam, pmask,
                        l1_ratio, family, reg, mesh, interpret)

    def outer_cond(carry):
        beta, step, gnorm, it = carry
        return (it < max_iter) & (gnorm > tol)

    def outer_body(carry):
        beta, step, _, it = carry
        val, grad = jax.value_and_grad(loss)(beta)
        g2 = jnp.sum(grad * grad)

        def ls_cond(t):
            return (loss(beta - t * grad) > val - armijo * t * g2) & (t > 1e-20)

        t = jax.lax.while_loop(ls_cond, lambda t: t * backtrack, step)
        beta = beta - t * grad
        if log:
            emit_jit_step(it, loss=val, grad_norm=jnp.sqrt(g2))
        return beta, t * grow, jnp.sqrt(g2), it + 1

    beta, step, gnorm, it = jax.lax.while_loop(
        outer_cond, outer_body,
        (beta0, jnp.asarray(init_step, beta0.dtype),
         jnp.asarray(jnp.inf, beta0.dtype), 0),
    )
    return beta, it, gnorm


def gradient_descent(X, y, mask, n_rows, beta0, family, reg, lam, pmask,
                     l1_ratio=0.5, max_iter=100, tol=1e-6, init_step=1.0,
                     log=False, mesh=None, use_pallas=None,
                     pallas_interpret=False, **_):
    _check_smooth(reg, "gradient_descent")
    pallas_auto = use_pallas is None
    use_pallas = _resolve_pallas(use_pallas, mesh, family, X)

    def make_run(with_pallas):
        return partial(
            _gd_run, X, y, mask, n_rows, beta0, lam, pmask, l1_ratio,
            jnp.asarray(max_iter), jnp.asarray(tol, beta0.dtype),
            init_step, family, reg, log=log, use_pallas=with_pallas,
            mesh=mesh if with_pallas else None, interpret=pallas_interpret,
        )

    beta, it, gnorm = _pallas_fallback(
        make_run, use_pallas, pallas_auto, "gradient_descent"
    )()
    it, gnorm = _host_scalars(it, gnorm)
    return beta, {"n_iter": int(it), "grad_norm": float(gnorm)}


# --------------------------------------------------------------------------
# Proximal gradient with backtracking (dask_glm::proximal_grad) — handles
# non-smooth penalties via regularizers.prox
# --------------------------------------------------------------------------

@track_program("glm.proximal_grad")
@partial(jax.jit, static_argnames=("family", "reg", "log", "use_pallas",
                                   "mesh", "interpret"))
def _pg_run(X, y, mask, n_rows, beta0, lam, pmask, l1_ratio, max_iter, tol,
            init_step, family, reg, backtrack=0.5, grow=1.2, log=False,
            use_pallas=False, mesh=None, interpret=False):
    # penalty handled by the prox: the selected loss is smooth-only
    smooth = _select_loss(use_pallas, X, y, mask, n_rows, lam * 0.0,
                          pmask, l1_ratio, family, "none", mesh, interpret)

    def outer_cond(carry):
        beta, step, delta, it = carry
        return (it < max_iter) & (delta > tol)

    def outer_body(carry):
        beta, step, _, it = carry
        val, grad = jax.value_and_grad(smooth)(beta)

        def candidate(t):
            return regularizers.prox(reg, beta - t * grad, lam, t, pmask, l1_ratio)

        def ls_cond(t):
            z = candidate(t)
            dz = z - beta
            quad = val + jnp.vdot(grad, dz) + jnp.sum(dz * dz) / (2.0 * t)
            return (smooth(z) > quad) & (t > 1e-20)

        t = jax.lax.while_loop(ls_cond, lambda t: t * backtrack, step)
        z = candidate(t)
        delta = jnp.linalg.norm(z - beta) / jnp.maximum(t, 1e-20)
        if log:
            emit_jit_step(it, loss=val, opt_residual=delta)
        return z, t * grow, delta, it + 1

    beta, step, delta, it = jax.lax.while_loop(
        outer_cond, outer_body,
        (beta0, jnp.asarray(init_step, beta0.dtype),
         jnp.asarray(jnp.inf, beta0.dtype), 0),
    )
    return beta, it, delta


def proximal_grad(X, y, mask, n_rows, beta0, family, reg, lam, pmask,
                  l1_ratio=0.5, max_iter=100, tol=1e-7, init_step=1.0,
                  log=False, mesh=None, use_pallas=None,
                  pallas_interpret=False, **_):
    pallas_auto = use_pallas is None
    use_pallas = _resolve_pallas(use_pallas, mesh, family, X)

    def make_run(with_pallas):
        return partial(
            _pg_run, X, y, mask, n_rows, beta0, lam, pmask, l1_ratio,
            jnp.asarray(max_iter), jnp.asarray(tol, beta0.dtype),
            init_step, family, reg, log=log, use_pallas=with_pallas,
            mesh=mesh if with_pallas else None, interpret=pallas_interpret,
        )

    beta, it, delta = _pallas_fallback(
        make_run, use_pallas, pallas_auto, "proximal_grad"
    )()
    it, delta = _host_scalars(it, delta)
    return beta, {"n_iter": int(it), "opt_residual": float(delta)}


# --------------------------------------------------------------------------
# Newton (dask_glm::newton) with step-halving safeguard, fully on device
# --------------------------------------------------------------------------

@track_program("glm.newton")
@partial(jax.jit, static_argnames=("family", "reg", "log", "use_pallas",
                                   "mesh", "interpret"))
def _newton_run(X, y, mask, n_rows, beta0, lam, pmask, l1_ratio, max_iter, tol,
                family, reg, log=False, use_pallas=False, mesh=None,
                interpret=False):
    fam = get_family(family)
    loss = _select_loss(use_pallas, X, y, mask, n_rows, lam, pmask,
                        l1_ratio, family, reg, mesh, interpret)
    d = beta0.shape[0]
    ridge = (lam * pmask if reg == "l2" else jnp.zeros_like(pmask)) + 1e-8

    if use_pallas:
        from ...ops.pallas_fused import fused_glm_value_grad_hess

        def vgh(beta):
            vs, gs, hs = _shard_psum_call(
                mesh,
                lambda bs, xs, ys, ms, nv: fused_glm_value_grad_hess(
                    xs, nv, ys, bs, family=family, interpret=interpret
                ),
                3, beta, X, y, mask,
            )
            pen, pen_g = jax.value_and_grad(
                lambda b: regularizers.value(reg, b, lam, pmask, l1_ratio)
            )(beta)
            return (vs / n_rows + pen, gs / n_rows + pen_g, hs / n_rows)

    def cond(carry):
        beta, gnorm, it = carry
        return (it < max_iter) & (gnorm > tol)

    def body(carry):
        beta, _, it = carry
        if use_pallas:
            # Newton's whole data touch in one X pass (eta + grad +
            # weighted Hessian statistics come from the fused kernel)
            val, grad, hess = vgh(beta)
            hess = hess + jnp.diag(ridge)
        else:
            val, grad = jax.value_and_grad(loss)(beta)
            eta = X @ beta
            w = fam.hess_weight(eta, y) * mask
            # (d, d) Hessian: per-shard X^T W X + ICI psum, replicated
            # solve
            hess = (X * w[:, None]).T @ X / n_rows + jnp.diag(ridge)
        # lstsq, not solve: stays finite on singular Hessians
        # (underdetermined n < d fits return the min-norm step)
        delta = jnp.linalg.lstsq(hess, grad)[0]

        def ls_cond(t):
            return (loss(beta - t * delta) > val) & (t > 1e-6)

        t = jax.lax.while_loop(ls_cond, lambda t: t * 0.5,
                               jnp.asarray(1.0, beta.dtype))
        beta = beta - t * delta
        if log:
            emit_jit_step(it, loss=val, grad_norm=jnp.linalg.norm(grad))
        return beta, jnp.linalg.norm(grad), it + 1

    beta, gnorm, it = jax.lax.while_loop(
        cond, body, (beta0, jnp.asarray(jnp.inf, beta0.dtype), 0)
    )
    return beta, it, gnorm


def newton(X, y, mask, n_rows, beta0, family, reg, lam, pmask, l1_ratio=0.5,
           max_iter=50, tol=1e-6, log=False, mesh=None, use_pallas=None,
           pallas_interpret=False, **_):
    _check_smooth(reg, "newton")
    pallas_auto = use_pallas is None
    use_pallas = _resolve_pallas(use_pallas, mesh, family, X)
    if use_pallas and pallas_auto:
        # Newton's kernel also carries a (d, d) accumulator — its VMEM
        # budget is tighter than the value+grad kernel's
        from ...ops.pallas_fused import glm_newton_tile

        use_pallas = glm_newton_tile(
            X.shape[0], X.shape[1], X.dtype.itemsize
        ) is not None

    def make_run(with_pallas):
        return partial(
            _newton_run, X, y, mask, n_rows, beta0, lam, pmask, l1_ratio,
            jnp.asarray(max_iter), jnp.asarray(tol, beta0.dtype), family,
            reg, log=log, use_pallas=with_pallas,
            mesh=mesh if with_pallas else None, interpret=pallas_interpret,
        )

    beta, it, gnorm = _pallas_fallback(
        make_run, use_pallas, pallas_auto, "newton"
    )()
    it, gnorm = _host_scalars(it, gnorm)
    return beta, {"n_iter": int(it), "grad_norm": float(gnorm)}


# --------------------------------------------------------------------------
# Consensus ADMM (dask_glm::admm): per-shard local Newton solves inside
# shard_map, psum z-update. One ICI all-reduce per outer iteration where the
# reference pays a gather-to-client + broadcast over TCP.
# --------------------------------------------------------------------------

@track_program("glm.admm")
@partial(jax.jit, static_argnames=("family", "reg", "local_iter", "mesh",
                                   "log"))
def _admm_run(X, y, mask, n_rows, B, U, z, lam, pmask, l1_ratio, rho,
              max_iter, abstol, family, reg, local_iter, mesh, log=False):
    fam = get_family(family)
    n_shards = mesh.shape[DATA_AXIS]

    def shard_iter(Xs, ys, ms, b, u, z, rho):
        b, u = b[0], u[0]
        v = z - u  # local target

        def local_newton(_, b):
            eta = Xs @ b
            resid = (jax.grad(lambda e: jnp.sum(fam.pointwise(e, ys) * ms))(eta))
            g = Xs.T @ resid / n_rows + rho * (b - v)
            w = fam.hess_weight(eta, ys) * ms
            h = (Xs * w[:, None]).T @ Xs / n_rows + rho * jnp.eye(b.shape[0], dtype=b.dtype)
            return b - jnp.linalg.solve(h, g)

        b = jax.lax.fori_loop(0, local_iter, local_newton, b)
        bu_mean = jax.lax.pmean(b + u, DATA_AXIS)
        z_new = regularizers.prox(reg, bu_mean, lam, 1.0 / (rho * n_shards),
                                  pmask, l1_ratio)
        u = u + b - z_new
        primal = jax.lax.psum(jnp.sum((b - z_new) ** 2), DATA_AXIS)
        return b[None], u[None], z_new, primal

    shard_iter_sm = shard_map(
        shard_iter,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS, None), P(DATA_AXIS, None), P(), P()),
        out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None), P(), P()),
    )

    def cond(carry):
        B, U, z, rho, it, primal, dual = carry
        return (it < max_iter) & ((primal > abstol) | (dual > abstol))

    def body(carry):
        B, U, z, rho, it, _, _ = carry
        B, U, z_new, primal2 = shard_iter_sm(X, y, mask, B, U, z, rho)
        dual = rho * jnp.sqrt(jnp.asarray(n_shards, z.dtype)) * jnp.linalg.norm(z_new - z)
        primal = jnp.sqrt(primal2)
        # Boyd §3.4.1 residual balancing; U is the scaled dual, rescale on
        # rho changes
        if log:
            emit_jit_step(it, primal_residual=primal, dual_residual=dual)
        grow = primal > 10.0 * dual
        shrink = dual > 10.0 * primal
        scale = jnp.where(grow, 2.0, jnp.where(shrink, 0.5, 1.0)).astype(z.dtype)
        return B, U / scale, z_new, rho * scale, it + 1, primal, dual

    inf = jnp.asarray(jnp.inf, z.dtype)
    B, U, z, rho, it, primal, dual = jax.lax.while_loop(
        cond, body, (B, U, z, rho, 0, inf, inf)
    )
    return z, it, primal, dual


def admm(X, y, mask, n_rows, beta0, family, reg, lam, pmask, l1_ratio=0.5,
         max_iter=250, tol=1e-4, rho=1.0, local_iter=8, mesh=None, log=False,
         **_):
    if reg == "none":
        reg = "l2"
        lam = jnp.asarray(0.0, beta0.dtype)
    n_shards = mesh.shape[DATA_AXIS]
    d = beta0.shape[0]
    B = jnp.tile(beta0[None], (n_shards, 1))
    U = jnp.zeros((n_shards, d), beta0.dtype)
    z, it, primal, dual = _admm_run(
        X, y, mask, n_rows, B, U, beta0, lam, pmask, l1_ratio,
        jnp.asarray(rho, beta0.dtype), jnp.asarray(max_iter),
        jnp.asarray(tol, beta0.dtype), family, reg, local_iter, mesh,
        log=log,
    )
    it, primal, dual = _host_scalars(it, primal, dual)
    return z, {"n_iter": int(it), "primal_residual": float(primal),
               "dual_residual": float(dual)}


SOLVERS = {
    "admm": admm,
    "lbfgs": lbfgs,
    "newton": newton,
    "gradient_descent": gradient_descent,
    "proximal_grad": proximal_grad,
}


def solve(solver: str, **kwargs):
    if solver not in SOLVERS:
        raise ValueError(f"Unknown solver {solver!r}; options: {sorted(SOLVERS)}")
    beta, info = SOLVERS[solver](**kwargs)
    return check_finite_result(beta, info, solver)


# smooth solvers whose whole solve is one jitted program — these vmap
# cleanly over stacked targets
_VMAP_SOLVERS = ("lbfgs",)


def solve_multi(solver, X, Y, mask, n_rows, B0, family, reg, lam, pmask,
                l1_ratio=0.5, max_iter=100, tol=1e-6, mesh=None, **kwargs):
    """Solve C independent GLMs sharing ONE design matrix (one-vs-rest
    multiclass): ``Y`` is (C, n) targets, ``B0`` (C, d) starts; returns
    ((C, d) betas, info).

    For L-BFGS the C solves run as a SINGLE stacked XLA program — the
    per-class matvecs batch into one (n,d)x(d,C) contraction on the MXU,
    the reference's closest analog being C separate dask-glm solves.
    Other solvers fall back to a per-class loop of their single-target
    programs (correct, C launches).

    Shared-iteration-budget semantics (stacked paths): the C blocks
    advance in lockstep inside one while_loop — every iteration updates
    EVERY class, and the loop runs until the slowest block's gradient
    norm reaches tol (or max_iter). A class that would have converged
    alone in fewer iterations keeps refining (harmless: its gradient is
    already below tol; the objective is separable so blocks cannot
    perturb each other). ``info["n_iter"]`` is therefore the budget the
    PROGRAM ran (the max), while ``info["n_iter_per_class"]`` records
    each block's own convergence point within that joint run — the
    last iteration its gradient norm still exceeded tol."""
    kwargs.pop("log", None)  # per-class step logs would interleave
    use_pallas = kwargs.pop("use_pallas", None)
    pallas_interpret = kwargs.pop("pallas_interpret", False)
    pallas_auto = use_pallas is None
    # leftover kwargs (e.g. checkpoint_path/checkpoint_every) are only
    # honored by the single-target solver functions — fall back to the
    # per-class loop rather than silently dropping them
    plain_kwargs = not {k for k in kwargs if k != "memory"}
    # fused multi-target path: logistic ONLY — the kernel rebuilds
    # one-vs-rest 0/1 targets from class codes, which would destroy
    # real-valued multi-output targets of other families
    if (solver == "lbfgs" and plain_kwargs and family == "logistic"
            and _resolve_pallas(use_pallas, mesh, family, None)):
        from ...ops.pallas_fused import glm_multi_tile

        C, d = B0.shape
        fits_vmem = glm_multi_tile(X.shape[0], d, C,
                                   X.dtype.itemsize) is not None
        if fits_vmem:
            _check_smooth(reg, solver)
            memory = int(kwargs.get("memory", 10))
            # class CODES from the one-hot target stack (padding rows
            # are all-zero -> code 0, masked in-kernel)
            codes = jnp.argmax(Y, axis=0).astype(jnp.float32)
            pmask_t = jnp.tile(jnp.asarray(pmask), C)
            b0 = B0.reshape(-1)
            opt = optax.lbfgs(memory_size=memory)
            carry = (b0, opt.init(b0),
                     jnp.asarray(jnp.inf, b0.dtype), 0)
            try:
                beta, _state, gnorm, it, conv = jax.block_until_ready(
                    _lbfgs_multi_pallas_chunk(
                        X, codes, mask, n_rows, carry, lam, pmask_t,
                        l1_ratio, jnp.asarray(max_iter),
                        jnp.asarray(tol, b0.dtype), family, reg, mesh,
                        C, memory=memory, interpret=pallas_interpret,
                    )
                )
            except Exception as exc:
                if not pallas_auto:
                    raise  # explicit opt-in surfaces the error
                import warnings

                warnings.warn(
                    f"fused multi-target GLM solve failed "
                    f"({type(exc).__name__}: {exc}); retrying on the "
                    "stacked XLA path", RuntimeWarning,
                )
            else:
                it, gnorm = _host_scalars(it, gnorm)
                info = {"n_iter": int(it), "grad_norm": float(gnorm),
                        "n_iter_per_class":
                            _per_block_iters(conv, it).tolist(),
                        "fused_multi": True}
                return check_finite_result(
                    np.asarray(beta).reshape(C, d), info, solver
                )
        elif not pallas_auto:
            raise ValueError(
                f"design too wide for the fused multi-target GLM kernel "
                f"(d={d}, C={C}) — explicit use_pallas=True cannot be "
                "honored; unset it for the stacked XLA path"
            )
    if solver in _VMAP_SOLVERS and plain_kwargs and not (
        use_pallas and solver == "lbfgs"
    ):
        # stacked joint solve over the flat (C*d,) vector — same
        # separable-objective argument as the Pallas multi chunk, with
        # an XLA data term: the C forward matvecs batch into ONE
        # (n,d)x(d,C) matmul. A jax.vmap of the single-target
        # while_loop solver was measured ~5-7x slower PER LANE on
        # XLA:CPU (batched-while_loop lowering) and is gone.
        _check_smooth(reg, solver)
        memory = int(kwargs.pop("memory", 10))
        C, d = B0.shape
        opt = optax.lbfgs(memory_size=memory)
        b0 = jnp.asarray(B0, jnp.float32).reshape(-1)
        carry = (b0, opt.init(b0), jnp.asarray(jnp.inf, b0.dtype), 0)
        beta, _state, gnorm, it, conv = _multi_stacked_chunk(
            X, Y, mask, n_rows, carry, lam, jnp.asarray(pmask),
            l1_ratio, jnp.asarray(max_iter),
            jnp.asarray(tol, jnp.float32), family, reg, C,
            memory=memory,
        )
        it_h, gnorm_h = _host_scalars(it, gnorm)
        info = {"n_iter": int(it_h), "grad_norm": float(gnorm_h),
                "n_iter_per_class": _per_block_iters(conv, it_h).tolist()}
        return check_finite_result(
            np.asarray(beta).reshape(C, d), info, solver
        )
    # per-class loop: forward the pallas knobs — the single-target
    # solvers honor them (an explicit use_pallas request must not be
    # silently dropped here)
    if use_pallas is not None:
        kwargs["use_pallas"] = use_pallas
    if pallas_interpret:
        kwargs["pallas_interpret"] = pallas_interpret
    betas, iters = [], []
    for c in range(Y.shape[0]):
        beta_c, info_c = solve(
            solver, X=X, y=Y[c], mask=mask, n_rows=n_rows, beta0=B0[c],
            family=family, reg=reg, lam=lam, pmask=pmask,
            l1_ratio=l1_ratio, max_iter=max_iter, tol=tol, mesh=mesh,
            **kwargs,
        )
        betas.append(np.asarray(beta_c))
        iters.append(info_c.get("n_iter") or 0)
    return np.stack(betas), {"n_iter": int(max(iters)),
                             "n_iter_per_class": [int(i) for i in iters]}


def _multi_stacked_body(X, Y, mask, n_rows, carry, lam, pmask, l1_ratio,
                        stop_it, tol, family, reg, C, memory=10):
    """Joint L-BFGS over the FLAT (C*d,) multi-target vector with an XLA
    data term: one (n,d)x(d,C) matmul serves every target's forward pass
    and one (d,n)x(n,C) their gradients. ``Y`` is (C, n) targets sharing
    one ``lam``; separable objective, so the joint optimum equals the
    per-target optima."""
    d = X.shape[1]

    def loss(bflat):
        B = bflat.reshape(C, d)
        eta = jax.lax.dot_general(
            X, B.astype(X.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                       # (n, C)
        pw = get_family(family).pointwise(eta, Y.T)
        base = jnp.sum(pw * mask[:, None]) / n_rows
        return base + regularizers.value(
            reg, bflat, lam, jnp.tile(pmask, C), l1_ratio
        )

    # stop when EVERY class block has converged to tol (max per-block
    # norm) — identical criterion to the per-class solves
    return _lbfgs_loop(loss, carry, stop_it, tol, memory, False,
                       n_blocks=C)


def _lam_grid_body(X, y, mask, n_rows, carry, lams, pmask, stop_it, tol,
                   family, reg, k, memory=10):
    """Joint L-BFGS over the FLAT (k*d,) stacked-lam vector: the k
    forward matvecs batch into ONE (n,d)x(d,k) matmul (and the gradient
    into one (d,n)x(n,k)) — real MXU contractions, unlike vmapping the
    single-target while_loop, whose batched-loop lowering measured ~5x
    slower PER LANE on XLA:CPU. The objective is separable across lams,
    so the joint optimum equals the per-lam optima (same argument as the
    multi-target OvR chunk above)."""
    d = X.shape[1]

    def loss(bflat):
        B = bflat.reshape(k, d)
        eta = jax.lax.dot_general(
            X, B.astype(X.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                       # (n, k)
        pw = get_family(family).pointwise(eta, y[:, None])
        base = jnp.sum(pw * mask[:, None]) / n_rows
        if reg == "none":
            return base
        bp = B * pmask[None, :]
        return base + 0.5 * jnp.sum(lams * jnp.sum(bp * bp, axis=1))

    # stop when EVERY candidate's block has converged to tol (max
    # per-block norm) — identical criterion to per-candidate solves
    return _lbfgs_loop(loss, carry, stop_it, tol, memory, False,
                       n_blocks=k)


def _lam_grid_multi_body(X, Y, mask, n_rows, carry, lams, pmask, stop_it,
                         tol, family, reg, k, C, memory=10):
    """C-grid x one-vs-rest: k candidates x C classes as ONE stacked
    (k*C*d,) joint solve. ``Y`` is (C, n) one-hot targets shared by all
    candidates; block j = i*C + c solves class c at lam_i. One
    (n,d)x(d,k*C) matmul per iteration serves the whole search fold."""
    d = X.shape[1]

    def loss(bflat):
        B = bflat.reshape(k * C, d)
        eta = jax.lax.dot_general(
            X, B.astype(X.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                     # (n, k*C)
        targets = jnp.tile(Y.T, (1, k))                       # (n, k*C)
        pw = get_family(family).pointwise(eta, targets)
        base = jnp.sum(pw * mask[:, None]) / n_rows
        if reg == "none":
            return base
        bp = B * pmask[None, :]
        lam_rep = jnp.repeat(lams, C)                         # (k*C,)
        return base + 0.5 * jnp.sum(lam_rep * jnp.sum(bp * bp, axis=1))

    return _lbfgs_loop(loss, carry, stop_it, tol, memory, False,
                       n_blocks=k * C)


# The stacked C-grid / OvR direct-solve programs build through the plan
# layer (ISSUE 15): identical jit flags and bodies (jaxprs byte-
# identical to the decorator-built programs — asserted in
# tests/test_plans.py), with cache keying / track_program registration /
# compile_cache_dir arming owned by plans.ProgramPlan instead of this
# call site. Module-level builds, so XLA's compile cache is shared
# across estimator instances exactly as before.
_multi_stacked_chunk = ProgramPlan(
    name="glm.lbfgs_multi", body=_multi_stacked_body,
    static_argnames=("family", "reg", "C", "memory"),
    group="stacked-solve",
).build()

_lam_grid_chunk = ProgramPlan(
    name="glm.lbfgs_lam_grid", body=_lam_grid_body,
    static_argnames=("family", "reg", "k", "memory"),
    group="stacked-solve",
).build()

_lam_grid_multi_chunk = ProgramPlan(
    name="glm.lbfgs_lam_grid_multi", body=_lam_grid_multi_body,
    static_argnames=("family", "reg", "k", "C", "memory"),
    group="stacked-solve",
).build()


def solve_lam_grid_multi(X, Y, mask, n_rows, lams, pmask, family, reg,
                         max_iter=100, tol=1e-6, memory=10):
    """Multiclass variant of :func:`solve_lam_grid`: returns
    ((k, C, d) betas, info) for k lam values over the shared (C, n)
    one-vs-rest targets."""
    _check_smooth(reg, "lbfgs")
    lams = jnp.asarray(lams, jnp.float32)
    k = int(lams.shape[0])
    C = int(Y.shape[0])
    d = X.shape[1]
    opt = optax.lbfgs(memory_size=memory)
    b0 = jnp.zeros((k * C * d,), jnp.float32)
    carry = (b0, opt.init(b0), jnp.asarray(jnp.inf, b0.dtype), 0)
    beta, _state, gnorm, it, conv = _lam_grid_multi_chunk(
        X, Y, mask, n_rows, carry, lams, jnp.asarray(pmask),
        jnp.asarray(max_iter), jnp.asarray(tol, jnp.float32),
        family, reg, k, C, memory=memory,
    )
    it_h, gnorm_h = _host_scalars(it, gnorm)
    # block j = i*C + c: a candidate's own n_iter is its slowest class
    # (the iteration count a standalone OvR fit of that candidate would
    # have reported)
    conv_kc = _per_block_iters(conv, it_h).reshape(k, C)
    info = {"n_iter": int(it_h), "grad_norm": float(gnorm_h),
            "lam_grid": k, "n_classes": C,
            "n_iter_per_candidate": conv_kc.max(axis=1).tolist(),
            "n_iter_per_block": conv_kc.tolist()}
    return check_finite_result(
        np.asarray(beta).reshape(k, C, d), info, "lbfgs"
    )


def solve_lam_grid(X, y, mask, n_rows, lams, pmask, family, reg,
                   max_iter=100, tol=1e-6, memory=10):
    """k independent GLM solves differing ONLY in the l2 strength, as
    ONE compiled program sharing the design matrix — a whole C grid
    costs one X pass per iteration instead of k (SURVEY.md §3.4 'combos
    batched when homogeneous'; the reference's analog is k separate
    dask-glm solves). Returns ((k, d) betas, info); raises on
    non-finite results (callers fall back to per-candidate fits where
    error_score= applies individually).

    The k candidates share one iteration budget (see
    :func:`solve_multi`): ``info["n_iter"]`` is the joint program's
    iteration count (the slowest candidate's), and
    ``info["n_iter_per_candidate"]`` each candidate's own convergence
    point within the joint trajectory — the last iteration its
    per-block gradient norm still exceeded tol."""
    _check_smooth(reg, "lbfgs")
    lams = jnp.asarray(lams, jnp.float32)
    k = int(lams.shape[0])
    d = X.shape[1]
    opt = optax.lbfgs(memory_size=memory)
    b0 = jnp.zeros((k * d,), jnp.float32)
    carry = (b0, opt.init(b0), jnp.asarray(jnp.inf, b0.dtype), 0)
    beta, _state, gnorm, it, conv = _lam_grid_chunk(
        X, y, mask, n_rows, carry, lams, jnp.asarray(pmask),
        jnp.asarray(max_iter), jnp.asarray(tol, jnp.float32),
        family, reg, k, memory=memory,
    )
    it_h, gnorm_h = _host_scalars(it, gnorm)
    info = {"n_iter": int(it_h), "grad_norm": float(gnorm_h),
            "lam_grid": k,
            "n_iter_per_candidate":
                _per_block_iters(conv, it_h).tolist()}
    return check_finite_result(
        np.asarray(beta).reshape(k, d), info, "lbfgs"
    )
