"""Out-of-core (streamed) fit parity: LogisticRegression / KMeans / PCA
fitted from an np.memmap several× the block size must match the resident
in-memory fit (VERDICT r2 #1 / SURVEY.md §7 B0 'the heart of the
system'), and the stream config knobs must be consumed."""

import numpy as np
import pytest

from dask_ml_tpu import config


def _memmap(tmp_path, arr, name):
    p = str(tmp_path / name)
    mm = np.memmap(p, dtype=np.float32, mode="w+", shape=arr.shape)
    mm[:] = arr
    mm.flush()
    return np.memmap(p, dtype=np.float32, mode="r", shape=arr.shape)


@pytest.fixture(scope="module")
def clf_data():
    rng = np.random.RandomState(0)
    n, d = 4000, 12
    X = rng.randn(n, d).astype(np.float32)
    beta = rng.randn(d) / np.sqrt(d)
    p = 1.0 / (1.0 + np.exp(-(X @ beta + 0.3)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y


@pytest.mark.parametrize("solver,penalty,rtol", [
    ("lbfgs", "l2", 2e-2),
    ("newton", "l2", 2e-2),
    ("gradient_descent", "l2", 5e-2),
    ("proximal_grad", "l1", 5e-2),
    ("admm", "l2", 5e-2),
])
def test_logreg_memmap_matches_resident(tmp_path, clf_data, solver, penalty,
                                        rtol):
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = clf_data
    Xmm = _memmap(tmp_path, X, f"X_{solver}.f32")
    kw = dict(solver=solver, penalty=penalty, C=1.0, max_iter=80, tol=1e-7)

    resident = LogisticRegression(**kw).fit(X.copy(), y)
    with config.set(stream_block_rows=1000):
        streamed = LogisticRegression(**kw).fit(Xmm, y)

    assert streamed.solver_info_["streamed"] is True
    assert streamed.solver_info_["n_blocks"] > 1
    np.testing.assert_allclose(
        streamed.coef_, resident.coef_, rtol=rtol, atol=5e-3
    )
    np.testing.assert_allclose(
        streamed.intercept_, resident.intercept_, rtol=rtol, atol=5e-3
    )
    # predictions agree on the training data
    assert np.mean(streamed.predict(X) == resident.predict(X)) > 0.99


@pytest.mark.slow
def test_linear_regression_memmap(tmp_path):
    from dask_ml_tpu.linear_model import LinearRegression

    rng = np.random.RandomState(1)
    n, d = 3000, 8
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d)
    y = (X @ w + 0.5 + 0.01 * rng.randn(n)).astype(np.float32)
    Xmm = _memmap(tmp_path, X, "Xlin.f32")

    resident = LinearRegression(solver="lbfgs", max_iter=60, tol=1e-7).fit(X, y)
    with config.set(stream_block_rows=800):
        streamed = LinearRegression(solver="lbfgs", max_iter=60, tol=1e-7).fit(Xmm, y)
    assert streamed.solver_info_["streamed"] is True
    np.testing.assert_allclose(streamed.coef_, resident.coef_,
                               rtol=1e-2, atol=1e-3)


def test_config_stream_block_rows_triggers_streaming(clf_data):
    """A plain (non-memmap) ndarray streams when config.stream_block_rows
    is set below n — the knob is consumed, not dead (VERDICT r2 weak #8)."""
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = clf_data
    with config.set(stream_block_rows=1000):
        clf = LogisticRegression(solver="lbfgs", max_iter=50).fit(X, y)
    assert clf.solver_info_["streamed"] is True
    assert clf.solver_info_["n_blocks"] == 4
    # unset: resident path
    clf2 = LogisticRegression(solver="lbfgs", max_iter=50).fit(X, y)
    assert "streamed" not in clf2.solver_info_


def test_stream_prefetch_knob_consumed():
    from dask_ml_tpu.parallel.streaming import BlockStream

    X = np.zeros((64, 2), np.float32)
    with config.set(stream_prefetch=3):
        s = BlockStream((X,), block_rows=8)
    assert s.prefetch == 3
    assert list(b.n_rows for b in s) == [8] * 8


def test_stream_plan_rules():
    from dask_ml_tpu.parallel.streaming import stream_plan

    X = np.zeros((100, 2), np.float32)
    assert stream_plan(X) is None  # small ndarray, no knob: resident
    with config.set(stream_block_rows=10):
        assert stream_plan(X) == 10
    import jax.numpy as jnp

    assert stream_plan(jnp.zeros((100, 2))) is None  # device input


def test_kmeans_memmap_matches_resident(tmp_path):
    from dask_ml_tpu.cluster import KMeans

    rng = np.random.RandomState(2)
    centers_true = rng.randn(4, 6).astype(np.float32) * 4
    X = np.concatenate([
        centers_true[i] + 0.3 * rng.randn(500, 6).astype(np.float32)
        for i in range(4)
    ])
    rng.shuffle(X)
    Xmm = _memmap(tmp_path, X, "Xkm.f32")
    init = centers_true + 0.5  # same explicit init both paths

    resident = KMeans(n_clusters=4, init=init, max_iter=50, tol=1e-6).fit(X)
    with config.set(stream_block_rows=512):
        streamed = KMeans(n_clusters=4, init=init, max_iter=50, tol=1e-6).fit(Xmm)

    np.testing.assert_allclose(
        streamed.cluster_centers_, resident.cluster_centers_,
        rtol=1e-4, atol=1e-4,
    )
    np.testing.assert_allclose(streamed.inertia_, resident.inertia_,
                               rtol=1e-4)
    res_labels = resident.labels_.to_numpy()
    assert np.array_equal(streamed.labels_, res_labels)
    assert streamed.n_iter_ >= 1


@pytest.mark.slow
@pytest.mark.parametrize("init", ["k-means||", "k-means++", "random"])
def test_kmeans_streamed_inits_are_sane(tmp_path, init):
    from dask_ml_tpu.cluster import KMeans

    rng = np.random.RandomState(3)
    centers_true = rng.randn(3, 5).astype(np.float32) * 5
    X = np.concatenate([
        centers_true[i] + 0.2 * rng.randn(400, 5).astype(np.float32)
        for i in range(3)
    ])
    rng.shuffle(X)
    Xmm = _memmap(tmp_path, X, f"Xkm_{init}.f32")
    with config.set(stream_block_rows=400):
        streamed = KMeans(n_clusters=3, init=init, random_state=0,
                          max_iter=100).fit(Xmm)
    resident = KMeans(n_clusters=3, init=init, random_state=0,
                      max_iter=100).fit(X)
    # well-separated blobs: both must land on the (same) global optimum
    np.testing.assert_allclose(streamed.inertia_, resident.inertia_,
                               rtol=0.05)


def test_pca_memmap_matches_resident(tmp_path):
    from dask_ml_tpu.decomposition import PCA

    rng = np.random.RandomState(4)
    n, d = 3000, 10
    scale = np.linspace(5, 0.1, d)
    X = (rng.randn(n, d) * scale + rng.randn(d)).astype(np.float32)
    Xmm = _memmap(tmp_path, X, "Xpca.f32")

    resident = PCA(n_components=4, svd_solver="full").fit(X)
    with config.set(stream_block_rows=700):
        streamed = PCA(n_components=4, svd_solver="full").fit(Xmm)

    np.testing.assert_allclose(streamed.mean_, resident.mean_,
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(
        streamed.explained_variance_, resident.explained_variance_,
        rtol=1e-2,
    )
    np.testing.assert_allclose(
        streamed.singular_values_, resident.singular_values_, rtol=1e-2
    )
    # same V-based sign convention on both paths → direct comparison
    np.testing.assert_allclose(
        streamed.components_, resident.components_, rtol=5e-2, atol=5e-3
    )
    # streamed transform matches resident transform
    t_res = resident.transform(X).to_numpy()
    with config.set(stream_block_rows=700):
        t_str = streamed.transform(Xmm)
    np.testing.assert_allclose(t_str, t_res, rtol=5e-2, atol=5e-3)


@pytest.mark.slow
def test_streamed_inference_paths(tmp_path, clf_data):
    """predict/transform/score also stream for out-of-core inputs — the
    whole pipeline runs without materializing X on device."""
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.decomposition import PCA
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = clf_data
    Xmm = _memmap(tmp_path, X, "Xinfer.f32")
    with config.set(stream_block_rows=1000):
        clf = LogisticRegression(solver="lbfgs", max_iter=50).fit(Xmm, y)
        pred_mm = clf.predict(Xmm)
        proba_mm = clf.predict_proba(Xmm)
    pred_res = clf.predict(X)
    assert isinstance(pred_mm, np.ndarray)
    np.testing.assert_array_equal(pred_mm, pred_res)
    np.testing.assert_allclose(proba_mm[:, 1],
                               clf.predict_proba(X)[:, 1], atol=1e-5)

    with config.set(stream_block_rows=1000):
        km = KMeans(n_clusters=3, init="random", random_state=0,
                    max_iter=20).fit(Xmm)
        labels_mm = km.predict(Xmm)
        dists_mm = km.transform(Xmm)
        score_mm = km.score(Xmm)
    labels_res = km.predict(X).to_numpy()
    np.testing.assert_array_equal(labels_mm, labels_res)
    assert dists_mm.shape == (len(X), 3)
    np.testing.assert_allclose(score_mm, km.score(X), rtol=1e-4)

    with config.set(stream_block_rows=1000):
        scores_mm = PCA(n_components=3).fit_transform(Xmm)
    assert isinstance(scores_mm, np.ndarray)
    assert scores_mm.shape == (len(X), 3)


def test_streamed_metrics_logging(tmp_path, clf_data):
    """config.metrics_path wires per-step JSONL out of the streamed solver
    (VERDICT r2 #3)."""
    import json

    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = clf_data
    path = str(tmp_path / "metrics.jsonl")
    with config.set(metrics_path=path, stream_block_rows=1000):
        LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    records = [json.loads(line) for line in open(path)]
    # per-step solver records; the fit also traces span records
    # (fit + one per stream pass) into the same file
    steps = [r for r in records if "span" not in r]
    assert len(steps) >= 2
    for r in steps:
        assert r["component"] == "LogisticRegression"
        assert "loss" in r and "grad_norm" in r and "step" in r
        assert r["streamed"] is True
    fit_spans = [r for r in records if r.get("span") == "fit"]
    assert len(fit_spans) == 1 and fit_spans[0]["streamed"] is True
