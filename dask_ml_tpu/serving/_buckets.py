"""Shape-bucket ladder for the online serving path.

XLA programs are shape-specialized: a naive server that pads each
micro-batch to its exact row count compiles a fresh program per novel
size — unbounded compile debt under ragged traffic. The ladder fixes a
small geometric set of batch heights (min, min*g, min*g^2, ..., max);
every emitted batch is padded UP to the smallest rung that fits, so
steady-state serving touches at most ``len(ladder)`` compiled programs
per method, all of which ``ModelServer.warmup()`` can compile before the
first request arrives.

Since ISSUE 15 the policy itself lives in the plans subsystem
(:class:`dask_ml_tpu.plans.GeometricLadder` — the same rung math also
feeds the sparse serving nnz grid and the plans warmup registry);
``BucketLadder`` is the serving-configured instance. Geometric (not
linear) spacing is the padding/compile trade: with growth ``g`` the
padded rows waste less than ``(g-1)/g`` of any batch while the rung
count stays logarithmic in ``max/min``.
"""

from __future__ import annotations

from ..plans.ladders import GeometricLadder

__all__ = ["BucketLadder"]


class BucketLadder(GeometricLadder):
    """The geometric sequence of padded batch heights.

    ``bucket_for(n)`` returns the smallest rung >= n; callers chunk
    requests taller than the top rung (``max_rows``) before asking.
    """

    __slots__ = ()

    @classmethod
    def from_config(cls):
        from ..config import get_config

        cfg = get_config()
        return cls(
            min_rows=cfg.serving_min_batch,
            max_rows=cfg.serving_max_batch,
            growth=cfg.serving_bucket_growth,
        )

    def __repr__(self):
        return f"BucketLadder{self.buckets}"
