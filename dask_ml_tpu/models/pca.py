"""PCA / TruncatedSVD / IncrementalPCA via distributed SVD.

Reference: ``dask_ml/decomposition/{pca,truncated_svd,incremental_pca}.py``
(SURVEY.md §2a rows PCA/TruncatedSVD/IncrementalPCA, §3.3 call stack).
The reference lowers to ``da.linalg.svd`` (TSQR task graph) or
``svd_compressed`` (Halko); here those are the single-program TSQR /
randomized SVD kernels in ``ops/linalg.py`` — per-shard QR + ICI
all-gather, psum-reduced matmul passes, small replicated SVD.

Centering: padded rows must stay exactly zero after ``X - mean_``, so the
centered matrix is re-masked before the SVD (zero rows leave R/range
unchanged).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..base import BaseEstimator, TransformerMixin, to_host
from ..ops import linalg
from ..ops.reductions import masked_mean_var
from ..parallel.sharded import ShardedArray
from ..utils.validation import check_array, check_is_fitted


def _resolve_n_components(n_components, n, d):
    if n_components is None:
        return min(n, d)
    if isinstance(n_components, float) and not n_components.is_integer():
        raise ValueError(
            "float n_components means a variance fraction and requires "
            "svd_solver='full'"
        )
    n_components = int(n_components)
    if not 0 < n_components <= min(n, d):
        raise ValueError(
            f"n_components={n_components} must be in (0, {min(n, d)}]"
        )
    return n_components


@partial(jax.jit, static_argnames=("mxu_dtype",))
def _block_pca_moments(X, mask, shift, mxu_dtype=None):
    """Per-block (Σ(x-shift), Σ(x-shift)(x-shift)T), padded rows masked.
    ``shift`` is a rough mean estimate: centering the accumulation keeps
    the f32 block sums ~O(n_b·std²) instead of O(n_b·mean²), avoiding
    catastrophic cancellation in cov = G - n·μμᵀ for data with
    mean ≫ std (the blocks are f64-accumulated on host afterwards).

    ``mxu_dtype=bfloat16`` (config.dtype): the Gram outer product — the
    pass's FLOPs — runs at bf16 with f32 accumulation on CENTERED data
    (small magnitudes, so bf16's ~3 significant digits bound the
    covariance's relative error at ~1e-2; component parity tolerances in
    the tests reflect that). Mean sums stay at input precision."""
    xc = X - shift
    xm = xc * mask[:, None]
    if mxu_dtype is not None and X.dtype != mxu_dtype:
        g = jnp.einsum("ni,nj->ij", xm.astype(mxu_dtype),
                       xc.astype(mxu_dtype),
                       preferred_element_type=jnp.float32)
    else:
        g = jnp.einsum("ni,nj->ij", xm, xc,
                       preferred_element_type=jnp.float32)
    return jnp.tensordot(mask, xc, axes=(0, 0)), g


class PCA(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/decomposition/pca.py::PCA."""

    def __init__(self, n_components=None, copy=True, whiten=False,
                 svd_solver="auto", tol=0.0, iterated_power=0,
                 random_state=None, fit_dtype=None):
        self.n_components = n_components
        self.copy = copy
        self.whiten = whiten
        self.svd_solver = svd_solver
        self.tol = tol
        self.iterated_power = iterated_power
        self.random_state = random_state
        # per-estimator precision override (None = config.dtype policy;
        # "float32" opts the streamed Gram out of the TPU bf16 default,
        # "bfloat16" forces it); resolved choice lands on fit_dtype_
        self.fit_dtype = fit_dtype

    def _solver(self, k, n, d):
        if self.svd_solver == "auto":
            # randomized when asking for a small fraction of a wide matrix
            # (sklearn-style heuristic); exact TSQR otherwise
            return "randomized" if k < 0.8 * min(n, d) and min(n, d) > 200 \
                else "full"
        if self.svd_solver in ("full", "tsqr"):
            return "full"
        if self.svd_solver == "randomized":
            return "randomized"
        raise ValueError(f"Unknown svd_solver {self.svd_solver!r}")

    def fit(self, X, y=None):
        from ..parallel.streaming import stream_plan

        block_rows = stream_plan(X)
        if block_rows is not None:
            return self._fit_streamed(X, block_rows)
        self._fit(X)
        return self

    def _fit_streamed(self, X, block_rows):
        """Out-of-core fit via one streamed moments pass: accumulate
        (Σx, ΣxxᵀX) per block, then eigendecompose the d×d covariance on
        host. For the tall-skinny shapes this estimator targets
        (d ≤ O(10³), BASELINE configs), the Gram route computes the FULL
        spectrum in a single pass — subsuming both the TSQR and
        randomized solvers of the resident path, with one pass where
        Halko needs two. Ref: the reference's ``da.linalg`` reductions
        over host-backed chunks (SURVEY.md §3.3)."""
        from ..parallel import distributed as dist
        from ..parallel.streaming import BlockStream, _slice_dense

        n, d = X.shape
        multi = dist.process_count() > 1
        if multi:
            # multi-host: X is the process-local shard; n/moments merge
            # globally so every process computes the identical global PCA
            n = int(dist.psum_host(np.asarray(float(n))))
        if n < d:
            raise ValueError(
                "PCA requires tall data (n_samples >= n_features); got "
                f"{n} x {d}"
            )
        frac = None
        if (isinstance(self.n_components, float)
                and 0.0 < self.n_components < 1.0):
            frac, k = self.n_components, min(n, d)
        else:
            k = _resolve_n_components(self.n_components, n, d)
        from .streamed_svd import STREAM_GRAM_MAX_D

        if frac is None and self._solver(k, n, d) == "randomized" and (
                self.svd_solver == "randomized"
                or d > STREAM_GRAM_MAX_D):
            # the O(d·k') randomized path (ISSUE 18 layer 3): explicit
            # solver choice, or auto once the d×d Gram stops being the
            # cheap one-pass answer (wide d — the feature-sharded
            # regime on a 2-D mesh)
            return self._fit_streamed_randomized(X, block_rows, k, n, d)
        stream = BlockStream((X,), block_rows=block_rows)
        # shift estimate from a small head slice (exactness not needed —
        # any shift near the mean kills the cancellation, but it must be
        # IDENTICAL on every process: block sums with different shifts
        # cannot merge); _slice_dense handles sparse sources
        head = _slice_dense(X, 0, min(4096, X.shape[0]), np.float64)
        if multi:
            hs, hn = dist.psum_host(head.sum(axis=0),
                                    np.asarray(float(len(head))))
            shift = hs / max(float(hn), 1.0)
        else:
            shift = head.mean(axis=0)
        shift_dev = jnp.asarray(shift, jnp.float32)
        from ..config import fit_dtype_info, mxu_dtype

        mxu = mxu_dtype(getattr(self, "fit_dtype", None))
        # resolved precision on record (auto falls back to f32 off-TPU)
        self.fit_dtype_ = fit_dtype_info(
            getattr(self, "fit_dtype", None)
        )["fit_dtype"]
        s = np.zeros(d, np.float64)
        g = np.zeros((d, d), np.float64)
        for blk in stream:
            bs, bg = _block_pca_moments(blk.arrays[0], blk.mask,
                                        shift_dev, mxu_dtype=mxu)
            s += np.asarray(bs, np.float64)
            g += np.asarray(bg, np.float64)
        if multi:
            s, g = dist.psum_host(s, g)
        mean_c = s / n  # mean of the SHIFTED data
        mean = shift + mean_c
        cov = (g - n * np.outer(mean_c, mean_c)) / (n - 1)
        evals, evecs = np.linalg.eigh(cov)
        order = np.argsort(evals)[::-1]
        ev = np.maximum(evals[order], 0.0)
        vt = evecs[:, order].T
        # deterministic signs, V-based (linalg.svd_flip convention)
        max_abs = np.argmax(np.abs(vt), axis=1)
        signs = np.sign(vt[np.arange(vt.shape[0]), max_abs])
        vt = vt * np.where(signs == 0, 1.0, signs)[:, None]

        total_var = float(ev.sum())
        if frac is not None:
            ratio = np.cumsum(ev / total_var)
            k = int(np.searchsorted(ratio, frac) + 1)
        self.n_components_ = k
        self.components_ = vt[:k]
        self.explained_variance_ = ev[:k]
        self.explained_variance_ratio_ = ev[:k] / total_var
        self.singular_values_ = np.sqrt(ev[:k] * (n - 1))
        self.mean_ = mean
        if k < min(n, d):
            self.noise_variance_ = max(
                (total_var - ev[:k].sum()) / (min(n, d) - k), 0.0
            )
        else:
            self.noise_variance_ = 0.0
        self.n_features_in_ = d
        self.n_samples_ = n
        # per-feature training profile for train-vs-serve drift scoring
        self.training_profile_ = stream.profile_snapshot()
        return self

    def _fit_streamed_randomized(self, X, block_rows, k, n, d):
        """Out-of-core randomized-SVD fit (ISSUE 18 layer 3): the
        range-finder passes stream through the super-block scan with a
        TSQR reduction over "data" (feature-sharded X tiles on a 2-D
        mesh), so device memory is O(d·k') where the Gram route holds
        a d×d covariance. See ``models/streamed_svd.py``."""
        from .streamed_svd import flip_signs_vt, streamed_randomized_svd

        # the streamed rSVD reducers accumulate f32 (the QR chain is
        # precision-bound — no bf16 flavor); on record for /status
        self.fit_dtype_ = "float32"
        key = jax.random.PRNGKey(
            0 if self.random_state is None else int(self.random_state)
        )
        size = min(k + 10, min(n, d))
        out = streamed_randomized_svd(
            X, block_rows, size, max(int(self.iterated_power), 2), key,
            center=True, n_rows_global=n,
        )
        vt = flip_signs_vt(out["vt"])
        s = out["s"]
        ev = s.astype(np.float64) ** 2 / (n - 1)
        total_var = float(out["var1"].sum())
        self.n_components_ = k
        self.components_ = vt[:k]
        self.explained_variance_ = ev[:k]
        self.explained_variance_ratio_ = ev[:k] / total_var
        self.singular_values_ = s[:k].astype(np.float64)
        self.mean_ = out["mean"]
        if k < min(n, d):
            self.noise_variance_ = max(
                (total_var - ev[:k].sum()) / (min(n, d) - k), 0.0
            )
        else:
            self.noise_variance_ = 0.0
        self.n_features_in_ = d
        self.n_samples_ = n
        self.training_profile_ = out["stream"].profile_snapshot()
        return self

    def _fit(self, X):
        X = check_array(X, dtype=np.float32)
        n, d = X.shape
        if n < d:
            raise ValueError(
                "PCA requires tall data (n_samples >= n_features); got "
                f"{n} x {d}"
            )
        frac = None
        if (isinstance(self.n_components, float)
                and 0.0 < self.n_components < 1.0):
            # sklearn's variance-fraction API: needs the full spectrum
            if self._solver(min(n, d), n, d) != "full" and \
                    self.svd_solver not in ("auto", "full", "tsqr"):
                raise ValueError(
                    "n_components as a variance fraction requires "
                    "svd_solver in ('auto', 'full', 'tsqr')"
                )
            frac, k = self.n_components, min(n, d)
        else:
            k = _resolve_n_components(self.n_components, n, d)
        mask = X.row_mask(X.dtype)
        mean, var = masked_mean_var(X.data, mask, n, ddof=1)
        xc = (X.data - mean) * mask[:, None]
        solver = "full" if frac is not None else self._solver(k, n, d)
        if solver == "full":
            u, s, vt = linalg.svd_tall_jit(xc, X.mesh)
        else:
            key = jax.random.PRNGKey(
                0 if self.random_state is None else int(self.random_state)
            )
            u, s, vt = linalg.randomized_svd_jit(
                xc, k, key, X.mesh,
                n_iter=max(int(self.iterated_power), 2),
            )
        u, vt = linalg.svd_flip(u, vt)

        total_var = float(jnp.sum(var))
        ev = to_host(s).astype(np.float64) ** 2 / (n - 1)
        if frac is not None:
            ratio = np.cumsum(ev / total_var)
            k = int(np.searchsorted(ratio, frac) + 1)
        self.n_components_ = k
        self.components_ = to_host(vt)[:k].astype(np.float64)
        self.explained_variance_ = ev[:k]
        self.explained_variance_ratio_ = ev[:k] / total_var
        self.singular_values_ = to_host(s)[:k].astype(np.float64)
        self.mean_ = to_host(mean).astype(np.float64)
        if k < min(n, d):
            self.noise_variance_ = max(
                (total_var - ev[:k].sum()) / (min(n, d) - k), 0.0
            )
        else:
            self.noise_variance_ = 0.0
        self.n_features_in_ = d
        self.n_samples_ = n
        return X, u, s, vt, mask

    def fit_transform(self, X, y=None):
        from ..parallel.streaming import stream_plan

        block_rows = stream_plan(X)
        if block_rows is not None:
            # out-of-core: fit via the streamed moments pass, then the
            # streamed (block-wise) transform — X never materializes
            return self._fit_streamed(X, block_rows).transform(X)
        X, u, s, vt, mask = self._fit(X)
        k = self.n_components_
        scores = u[:, :k] * s[None, :k]
        if self.whiten:
            scores = scores * jnp.sqrt(jnp.asarray(self.n_samples_ - 1,
                                                   scores.dtype)) / s[None, :k]
        return ShardedArray(scores * mask[:, None], X.n_rows, X.mesh)

    def transform(self, X):
        check_is_fitted(self, "components_")
        from ..parallel.streaming import stream_plan, streamed_map

        block_rows = stream_plan(X)
        if block_rows is not None:
            # block-wise host→device→host scores; X never materializes
            comp = jnp.asarray(self.components_, jnp.float32)
            mean = jnp.asarray(self.mean_, jnp.float32)
            scale = (
                jnp.sqrt(jnp.asarray(self.explained_variance_, jnp.float32))
                if self.whiten else None
            )

            def block_scores(blk):
                sc = ((blk.arrays[0] - mean) * blk.mask[:, None]) @ comp.T
                return sc / scale if scale is not None else sc

            return streamed_map(X, block_rows, block_scores)
        X = check_array(X, dtype=np.float32)
        mask = X.row_mask(X.dtype)
        comp = jnp.asarray(self.components_, X.dtype)
        xc = (X.data - jnp.asarray(self.mean_, X.dtype)) * mask[:, None]
        scores = xc @ comp.T
        if self.whiten:
            scores = scores / jnp.sqrt(
                jnp.asarray(self.explained_variance_, X.dtype)
            )
        return ShardedArray(scores, X.n_rows, X.mesh)

    def inverse_transform(self, X):
        check_is_fitted(self, "components_")
        X = check_array(X, dtype=np.float32)
        comp = jnp.asarray(self.components_, X.dtype)
        scores = X.data
        if self.whiten:
            scores = scores * jnp.sqrt(
                jnp.asarray(self.explained_variance_, X.dtype)
            )
        out = scores @ comp + jnp.asarray(self.mean_, X.dtype)
        out = out * X.row_mask(out.dtype)[:, None]
        return ShardedArray(out, X.n_rows, X.mesh)

    # -- probabilistic-PCA scoring (sklearn parity) -----------------------
    def _scoring_components(self):
        """(components, explained_variance) with sklearn's whiten
        adjustment: whitened components_ are unit-scaled, so the model
        covariance needs them rescaled by sqrt(ev)."""
        comp = np.asarray(self.components_, np.float64)
        ev = np.asarray(self.explained_variance_, np.float64)
        if getattr(self, "whiten", False):
            comp = comp * np.sqrt(ev)[:, None]
        return comp, ev

    def get_covariance(self):
        """cov = components_ᵀ diag(ev - σ²) components_ + σ² I (small,
        d×d, host — the data-sized work stays on device in score_samples)."""
        check_is_fitted(self, "components_")
        comp, ev = self._scoring_components()
        sigma2 = float(self.noise_variance_)
        cov = (comp.T * np.maximum(ev - sigma2, 0.0)) @ comp
        cov[np.diag_indices_from(cov)] += max(sigma2, 0.0)
        return cov

    def get_precision(self):
        check_is_fitted(self, "components_")
        d = self.components_.shape[1]
        sigma2 = float(self.noise_variance_)
        if sigma2 <= 0.0:  # incl. roundoff-negative: Woodbury would flip sign
            return np.linalg.pinv(self.get_covariance())
        # Woodbury (sklearn's formula): avoids inverting the full cov
        comp, ev = self._scoring_components()
        scaled = comp * np.sqrt(np.maximum(ev - sigma2, 0.0))[:, None]
        k = comp.shape[0]
        inner = scaled @ scaled.T / sigma2 + np.eye(k)
        precision = (np.eye(d) - scaled.T @ np.linalg.solve(inner, scaled)
                     / sigma2) / sigma2
        return precision

    def score_samples(self, X):
        """Per-sample log-likelihood under the probabilistic PCA model
        (ref: sklearn/dask-ml PCA.score_samples). The d×d precision is
        host math; the (n, d) quadratic form runs sharded on device."""
        check_is_fitted(self, "components_")
        precision = self.get_precision()
        d = np.shape(X)[1]
        sign, logdet = np.linalg.slogdet(precision)
        const = -0.5 * (d * np.log(2.0 * np.pi) - sign * logdet)
        from ..parallel.streaming import stream_plan, streamed_map

        block_rows = stream_plan(X)
        if block_rows is not None:  # out-of-core: block-wise quadratic form
            mean = jnp.asarray(self.mean_, jnp.float32)
            prec = jnp.asarray(precision, jnp.float32)

            def block_ll(blk):
                xc = (blk.arrays[0] - mean) * blk.mask[:, None]
                return -0.5 * jnp.sum((xc @ prec) * xc, axis=1) + const

            return streamed_map(X, block_rows, block_ll)
        X = check_array(X, dtype=np.float32)
        xc = (X.data - jnp.asarray(self.mean_, X.dtype)) \
            * X.row_mask(X.dtype)[:, None]
        quad = jnp.sum(
            (xc @ jnp.asarray(precision, X.dtype)) * xc, axis=1
        )
        return to_host(-0.5 * quad + const)[: X.n_rows]

    def score(self, X, y=None):
        """Mean per-sample log-likelihood (sklearn parity)."""
        return float(np.mean(self.score_samples(X)))


class TruncatedSVD(TransformerMixin, BaseEstimator):
    """Ref: dask_ml/decomposition/truncated_svd.py::TruncatedSVD — same SVD
    backends as PCA, no centering (sparse-friendly semantics)."""

    def __init__(self, n_components=2, algorithm="tsqr", n_iter=5,
                 random_state=None, tol=0.0, compute=True):
        self.n_components = n_components
        self.algorithm = algorithm
        self.n_iter = n_iter
        self.random_state = random_state
        self.tol = tol
        self.compute = compute

    def fit(self, X, y=None):
        from ..parallel.streaming import stream_plan

        block_rows = stream_plan(X)
        if block_rows is not None:
            return self._fit_streamed(X, block_rows)
        self.fit_transform(X)
        return self

    def _fit_streamed(self, X, block_rows):
        """Out-of-core fit via the streamed randomized SVD (ISSUE 18
        layer 3) — NO centering, preserving the estimator's
        sparse-friendly semantics (sparse sources stream densified
        blocks; X never materializes whole)."""
        n, d = int(X.shape[0]), int(X.shape[1])
        k = self.n_components
        if not 0 < k < d:
            raise ValueError(f"n_components={k} must be in (0, {d})")
        if self.algorithm != "randomized":
            raise ValueError(
                "streamed TruncatedSVD requires algorithm='randomized' "
                "(the exact TSQR factorization needs the resident "
                f"matrix); got algorithm={self.algorithm!r}"
            )
        from .streamed_svd import flip_signs_vt, streamed_randomized_svd

        key = jax.random.PRNGKey(
            0 if self.random_state is None else int(self.random_state)
        )
        size = min(k + 10, min(n, d))
        out = streamed_randomized_svd(
            X, block_rows, size, max(int(self.n_iter), 1), key,
            center=False,
        )
        n = out["n"]
        vt = flip_signs_vt(out["vt"])[:k]
        s = out["s"][:k].astype(np.float64)
        # score-column variance WITHOUT a scores pass: the scores are
        # XV, so E[(xv_j)²] = s_j²/n (VᵀXᵀXV = S²) and the score means
        # come from the moments pass's data mean
        sc_mean = out["mean"] @ vt.T
        ev = np.maximum(s ** 2 / n - sc_mean ** 2, 0.0)
        self.components_ = vt
        self.explained_variance_ = ev
        self.explained_variance_ratio_ = ev / float(out["var0"].sum())
        self.singular_values_ = s
        self.n_features_in_ = d
        return self

    def fit_transform(self, X, y=None):
        from ..parallel.streaming import stream_plan

        block_rows = stream_plan(X)
        if block_rows is not None:
            # out-of-core: streamed fit, then the block-wise transform
            # (X never materializes)
            return self._fit_streamed(X, block_rows).transform(X)
        X = check_array(X, dtype=np.float32)
        n, d = X.shape
        k = self.n_components
        if not 0 < k < d:
            raise ValueError(f"n_components={k} must be in (0, {d})")
        mask = X.row_mask(X.dtype)
        data = X.data * mask[:, None]
        if self.algorithm == "tsqr":
            if n < d:
                raise ValueError("tsqr algorithm requires n_samples >= n_features")
            u, s, vt = linalg.svd_tall_jit(data, X.mesh)
        elif self.algorithm == "randomized":
            key = jax.random.PRNGKey(
                0 if self.random_state is None else int(self.random_state)
            )
            u, s, vt = linalg.randomized_svd_jit(
                data, k, key, X.mesh, n_iter=self.n_iter
            )
        else:
            raise ValueError(f"Unknown algorithm {self.algorithm!r}")
        u, vt = linalg.svd_flip(u, vt)
        u, s, vt = u[:, :k], s[:k], vt[:k]
        scores = u * s[None, :]

        # explained variance of the scores (sklearn semantics)
        sc_mean = jnp.sum(scores * mask[:, None], axis=0) / n
        ev = jnp.sum(((scores - sc_mean) ** 2) * mask[:, None], axis=0) / n
        _, full_var = masked_mean_var(X.data, mask, n, ddof=0)
        self.components_ = to_host(vt).astype(np.float64)
        self.explained_variance_ = to_host(ev).astype(np.float64)
        self.explained_variance_ratio_ = self.explained_variance_ / float(
            jnp.sum(full_var)
        )
        self.singular_values_ = to_host(s).astype(np.float64)
        self.n_features_in_ = d
        return ShardedArray(scores, X.n_rows, X.mesh)

    def transform(self, X):
        check_is_fitted(self, "components_")
        from ..parallel.streaming import stream_plan, streamed_map

        block_rows = stream_plan(X)
        if block_rows is not None:  # block-wise scores; X stays host-side
            comp = jnp.asarray(self.components_, jnp.float32)

            def block_scores(blk):
                return (blk.arrays[0] * blk.mask[:, None]) @ comp.T

            return streamed_map(X, block_rows, block_scores)
        X = check_array(X, dtype=np.float32)
        comp = jnp.asarray(self.components_, X.dtype)
        return ShardedArray(X.data @ comp.T, X.n_rows, X.mesh)

    def inverse_transform(self, X):
        check_is_fitted(self, "components_")
        X = check_array(X, dtype=np.float32)
        comp = jnp.asarray(self.components_, X.dtype)
        return ShardedArray(X.data @ comp, X.n_rows, X.mesh)


@jax.jit
def _ipca_update(components, singular, mean, n_seen, xb):
    """One incremental-PCA block update (Ross et al. 2008, as used by
    sklearn's IncrementalPCA): SVD of [S·Vt ; Xb - mean_b ; mean-correction]."""
    m = xb.shape[0]
    col_mean = jnp.mean(xb, axis=0)
    n_total = n_seen + m
    new_mean = (n_seen * mean + m * col_mean) / n_total
    corr = jnp.sqrt(n_seen * m / n_total) * (mean - col_mean)
    stack = jnp.concatenate(
        [singular[:, None] * components, xb - col_mean, corr[None, :]], axis=0
    )
    u, s, vt = jnp.linalg.svd(stack, full_matrices=False)
    return vt, s, new_mean, n_total


@jax.jit
def _block_sums(xb, shift):
    """(Σ(x−s), Σ(x−s)²) of one device block. The shift (≈ the data
    mean, taken from the first block) keeps the f32 sum-of-squares away
    from the E[x²]−E[x]² cancellation that corrupts variance for
    uncentered data; variance is shift-invariant so any s near the mean
    suffices. Cross-block accumulation upcasts to f64 on host."""
    c = xb - shift
    return jnp.sum(c, axis=0), jnp.sum(c * c, axis=0)


class IncrementalPCA(PCA):
    """Ref: dask_ml/decomposition/incremental_pca.py::IncrementalPCA —
    sequential partial_fit over blocks. Here each block update is one jitted
    program; ``fit`` streams the shards of a ShardedArray in order."""

    def __init__(self, n_components=None, whiten=False, copy=True,
                 batch_size=None, svd_solver="auto", iterated_power=0,
                 random_state=None):
        self.n_components = n_components
        self.whiten = whiten
        self.copy = copy
        self.batch_size = batch_size
        self.svd_solver = svd_solver
        self.iterated_power = iterated_power
        self.random_state = random_state

    def _blocks(self, X):
        """Sequential blocks WITHOUT materializing X (VERDICT r4 weak
        #4 — this used to start with ``X.to_numpy()``, an O(n·d) host
        gather of exactly the data the class exists to stream): device
        inputs yield device row slices (no host round-trip at all);
        host inputs (ndarray / memmap / sparse CSR) yield densified
        O(block) slices through the streaming layer's slicer."""
        n, d = int(X.shape[0]), int(X.shape[1])
        bs = self.batch_size or max(n // 10, 5 * d)
        if isinstance(X, ShardedArray):
            n = X.n_rows
            for i in range(0, n, bs):
                yield X.data[i:min(i + bs, n)]
            return
        from ..parallel.streaming import _slice_dense, as_row_sliceable

        X = as_row_sliceable(X)  # once, not per block slice
        for i in range(0, n, bs):
            yield _slice_dense(X, i, min(i + bs, n), np.float32)

    def partial_fit(self, X, y=None, check_input=True):
        self._reject_multihost()
        import scipy.sparse as sp

        if isinstance(X, ShardedArray):
            xb = X.data[: X.n_rows].astype(jnp.float32)
        elif isinstance(X, jax.Array):
            xb = X.astype(jnp.float32)
        elif sp.issparse(X):
            # a CSR block from the Incremental wrapper's sparse loop:
            # densify THIS block only (cast-before-toarray)
            from ..parallel.streaming import _slice_dense

            xb = jnp.asarray(
                _slice_dense(X.tocsr(), 0, X.shape[0], np.float32)
            )
        else:
            xb = jnp.asarray(np.asarray(X, dtype=np.float32))
        d = int(xb.shape[1])
        k = self.n_components or d
        if not hasattr(self, "n_samples_seen_") or self.n_samples_seen_ == 0:
            self._components = jnp.zeros((k, d), jnp.float32)
            self._singular = jnp.zeros((k,), jnp.float32)
            self._mean = jnp.zeros((d,), jnp.float32)
            self.n_samples_seen_ = 0
        vt, s, mean, n_total = _ipca_update(
            self._components, self._singular, self._mean,
            jnp.asarray(self.n_samples_seen_, jnp.float32), jnp.asarray(xb),
        )
        self._components, self._singular, self._mean = vt[:k], s[:k], mean
        self.n_samples_seen_ = int(n_total)
        self._finalize(d, k)
        return self

    def _finalize(self, d, k):
        n = self.n_samples_seen_
        self.components_ = to_host(self._components).astype(np.float64)
        self.singular_values_ = to_host(self._singular).astype(np.float64)
        self.mean_ = to_host(self._mean).astype(np.float64)
        self.explained_variance_ = self.singular_values_ ** 2 / max(n - 1, 1)
        self.n_components_ = k
        self.n_features_in_ = d
        # partial_fit streams never see total variance; fit() refines
        # this from the full-pass variance
        if not hasattr(self, "noise_variance_"):
            self.noise_variance_ = 0.0

    def fit_transform(self, X, y=None):
        # PCA.fit_transform would run the batch SVD path; the incremental
        # algorithm must fit block-wise then transform
        return self.fit(X, y).transform(X)

    @staticmethod
    def _reject_multihost():
        from ..parallel import distributed as dist

        if dist.process_count() > 1:
            # the incremental SVD update is SEQUENTIAL and
            # order-dependent — it cannot psum across shards; PCA's
            # streamed moments fit is the multi-host path
            raise NotImplementedError(
                "IncrementalPCA is single-process; use PCA (streamed "
                "moments psum globally) under a multi-host runtime"
            )

    def fit(self, X, y=None):
        self._reject_multihost()
        if hasattr(self, "n_samples_seen_"):
            del self.n_samples_seen_
        if not hasattr(X, "shape"):  # sklearn-style array-likes (lists)
            X = np.asarray(X, dtype=np.float32)
        if int(X.shape[0]) == 0:
            raise ValueError(
                "Found array with 0 sample(s) while a minimum of 1 is "
                "required by IncrementalPCA"
            )
        # the ratio needs the global per-feature variance; accumulate
        # (n, Σ(x−s), Σ(x−s)²) from the SAME blocks the incremental
        # updates consume — no second full-X placement (the old path ran
        # check_array over all of X, defeating out-of-core fits). The
        # shift (first block's mean) guards the f32 device sums against
        # catastrophic cancellation on uncentered data.
        s1 = s2 = shift = None
        n = 0
        for block in self._blocks(X):
            self.partial_fit(block)
            if isinstance(block, jax.Array):
                if shift is None:
                    shift = jnp.mean(block, axis=0)
                b1, b2 = _block_sums(block, shift)
            else:
                if shift is None:
                    shift = block.mean(axis=0, dtype=np.float64)
                c = block.astype(np.float64) - shift
                b1, b2 = c.sum(axis=0), np.square(c).sum(axis=0)
            b1 = np.asarray(b1, np.float64)
            b2 = np.asarray(b2, np.float64)
            s1 = b1 if s1 is None else s1 + b1
            s2 = b2 if s2 is None else s2 + b2
            n += int(block.shape[0])
        var = (s2 - s1 * s1 / n) / max(n - 1, 1)
        if not np.all(np.isfinite(var)):
            # the variance accumulators see every value, so this is the
            # streamed equivalent of check_array's finiteness gate
            raise ValueError("X contains NaN or infinity")
        total_var = float(np.sum(np.maximum(var, 0.0)))
        self.explained_variance_ratio_ = self.explained_variance_ / total_var
        k, d = self.n_components_, self.n_features_in_
        denom = min(n, d) - k
        self.noise_variance_ = (
            max(total_var - self.explained_variance_.sum(), 0.0) / denom
            if denom > 0 else 0.0
        )
        self.n_samples_ = n
        return self
