"""Headline benchmark: LogisticRegression.fit throughput on device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: samples/sec/chip processed by the device-resident L-BFGS fit
(counting one full data pass per outer iteration — line-search passes are
not counted, so this undercounts true throughput). vs_baseline is the ratio
against scikit-learn's lbfgs LogisticRegression measured the same way on a
subsample on this host's CPU — the reference's per-block compute engine
(SURVEY.md §6: no published in-repo numbers; BASELINE.json configs[0]).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main():
    import jax

    import dask_ml_tpu  # noqa: F401
    from dask_ml_tpu.linear_model import LogisticRegression

    n_chips = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    n_rows = 4_000_000 if on_tpu else 200_000
    n_feat = 256 if on_tpu else 64

    rng = np.random.RandomState(0)
    beta_true = rng.randn(n_feat).astype(np.float32) / np.sqrt(n_feat)
    X = rng.randn(n_rows, n_feat).astype(np.float32)
    logits = X @ beta_true
    y = (rng.uniform(size=n_rows) < 1.0 / (1.0 + np.exp(-logits))).astype(
        np.float32
    )

    max_iter = 50
    # warm the compile cache AT FULL SHAPE (XLA programs are
    # shape-specialized) with a 1-iteration fit
    LogisticRegression(solver="lbfgs", max_iter=1, tol=0.0).fit(X, y)

    t0 = time.perf_counter()
    clf = LogisticRegression(solver="lbfgs", max_iter=max_iter, tol=0.0)
    clf.fit(X, y)
    elapsed = time.perf_counter() - t0
    iters = clf.n_iter_ or max_iter
    value = n_rows * iters / elapsed / n_chips

    # sklearn reference on a subsample of the same data
    from sklearn.linear_model import LogisticRegression as SkLR

    sub = min(n_rows, 100_000)
    sk = SkLR(solver="lbfgs", max_iter=max_iter, tol=0.0)
    t0 = time.perf_counter()
    sk.fit(X[:sub], y[:sub])
    sk_elapsed = time.perf_counter() - t0
    sk_iters = int(np.max(sk.n_iter_)) or max_iter
    sk_value = sub * sk_iters / sk_elapsed

    print(json.dumps({
        "metric": "logreg_fit_samples_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "samples/s/chip",
        "vs_baseline": round(value / sk_value, 3),
    }))


if __name__ == "__main__":
    main()
