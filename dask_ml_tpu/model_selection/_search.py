"""Drop-in CV search: GridSearchCV / RandomizedSearchCV.

Reference: ``dask_ml/model_selection/_search.py`` + ``methods.py``
(SURVEY.md §2a, §3.4 call stack) — the ex-dask-searchcv engine that builds
ONE task graph for the whole search with two key optimizations:

1. ``CVCache``: each fold's train/test arrays extracted once, shared by
   every parameter combination. Here: folds are materialized once via
   ``take_rows`` (device gather) and reused across candidates.
2. Pipeline prefix sharing: identical (step, params, fold) subtrees get
   identical keys and are computed once. Here: an explicit memo dict keyed
   on (fold, prefix estimator-token chain) caches fitted pipeline
   prefixes AND their transformed output — same de-dup, no task graph
   (SURVEY.md §7: "de-dup via explicit controller memo").
3. (beyond the reference) Stacked C-grid fast path: a grid varying only
   the GLM regularization ``C`` — bare, multiclass, or as a Pipeline's
   last step — solves ALL candidates in ONE compiled joint L-BFGS
   program per fold (SURVEY.md §3.4 "combos batched when homogeneous").

Execution: candidates run as a host loop over jitted fits. Device
estimators share XLA compile cache across candidates (same shapes), which
is the jit-level analog of dask's task de-dup.
"""

from __future__ import annotations

import numbers
import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
from sklearn.model_selection import ParameterGrid, ParameterSampler

from ..base import BaseEstimator, clone
from ..metrics.scorer import check_scoring, get_scorer
from ..parallel.mesh import device_mesh, resolve_mesh, use_mesh
from ..parallel.sharded import ShardedArray, take_rows
from ._normalize import estimator_token
from ._split import KFold


def _is_pipeline(est):
    return hasattr(est, "steps") and hasattr(est, "named_steps")


def _is_device_native(est):
    """True if the estimator (or any pipeline step) runs XLA programs on
    the mesh — those candidates must NOT be launched concurrently on
    overlapping device sets: two GSPMD programs whose collectives
    interleave across shared devices can deadlock or abort the runtime.
    Concurrency for them means DISJOINT mesh subsets (SURVEY.md §3.5:
    "trials pinned to hosts/mesh-subsets")."""
    ests = [est]
    if _is_pipeline(est):
        ests += [s for _, s in est.steps]
    return any(type(e).__module__.startswith("dask_ml_tpu") for e in ests)


def _submeshes(mesh, k):
    """Partition a mesh's devices into k disjoint 1-D data meshes covering
    EVERY device: the first (n mod k) submeshes get one extra device, so
    no chip idles when k doesn't divide the device count."""
    devs = mesh.devices.reshape(-1)
    n = devs.size
    k = max(1, min(k, n))
    per, rem = divmod(n, k)
    out, i = [], 0
    for j in range(k):
        size = per + (1 if j < rem else 0)
        out.append(device_mesh(devices=devs[i:i + size]))
        i += size
    return out


def _resolve_scorers(estimator, scoring, refit):
    """({name: scorer}, multimetric). The reference (ex dask-searchcv)
    supports multimetric scoring: a list/dict of scorers producing
    mean_test_<name> columns, with ``refit`` naming the selection metric
    (sklearn contract)."""
    if scoring is None or callable(scoring) or isinstance(scoring, str):
        return {"score": check_scoring(estimator, scoring)}, False
    if isinstance(scoring, (list, tuple, set)):
        scoring = {name: name for name in scoring}
    if not isinstance(scoring, dict) or not scoring:
        raise ValueError(f"cannot interpret scoring={scoring!r}")
    # get_scorer handles BOTH names and callables — callables get the
    # host-adapting wrap so sklearn scorer objects work on sharded folds
    scorers = {name: get_scorer(sc) for name, sc in scoring.items()}
    if refit not in (False, None) and refit not in scorers:
        raise ValueError(
            f"multimetric scoring requires refit to name one of "
            f"{sorted(scorers)} (or refit=False); got {refit!r}"
        )
    return scorers, True


def check_cv(cv=None):
    if cv is None:
        return KFold(n_splits=5)
    if isinstance(cv, numbers.Integral):
        return KFold(n_splits=int(cv))
    if hasattr(cv, "split"):
        return cv
    raise ValueError(f"cannot interpret cv={cv!r}")


def _take(a, idx):
    if isinstance(a, ShardedArray):
        return take_rows(a, idx)
    from ..parallel.streaming import _is_sparse_source, as_row_indexable

    if _is_sparse_source(a):
        # sparse folds stay sparse (CSR row gather, no densify): the
        # C-grid fast path budget-guards its own one-shot densify and
        # the general path's streamed fits consume the CSR directly
        return as_row_indexable(a)[idx]
    return np.asarray(a)[idx]


class _CVCache:
    """Fold extraction (ref methods.py::CVCache). ``cache=True`` (the
    reference's ``cache_cv``) materializes each fold's train/test arrays
    once and shares them across every candidate; ``cache=False`` trades
    compute for memory by re-extracting per use."""

    def __init__(self, X, y, cv, cache=True):
        self._X, self._y = X, y
        self._splits = list(cv.split(X, y))
        self._cache = {} if cache else None
        self.n_folds = len(self._splits)

    def fold(self, fi):
        if self._cache is not None and fi in self._cache:
            return self._cache[fi]
        train_idx, test_idx = self._splits[fi]
        out = (
            _take(self._X, train_idx), _take(self._y, train_idx),
            _take(self._X, test_idx), _take(self._y, test_idx),
        )
        if self._cache is not None:
            self._cache[fi] = out
        return out



class _PrefixMemo:
    """Fitted-pipeline-prefix cache (ref: tokenized graph de-dup).

    Pipelines always execute sequentially (their cached transformed
    outputs live on one mesh), so no locking is needed here."""

    def __init__(self):
        self._memo = {}
        self.hits = 0
        self.misses = 0

    def _get_or_compute(self, key, compute):
        cached = self._memo.get(key)
        if cached is not None:
            self.hits += 1
            return cached
        self.misses += 1
        value = self._memo[key] = compute()
        return value

    def fit_prefix(self, steps, fold_id, X, y):
        """Fit-transform the TRANSFORMER steps, sharing cached fitted
        prefixes + transformed outputs across candidates; returns
        ([(name, fitted_step), ...], Xt, key_so_far)."""
        key = (fold_id,)
        Xt = X
        fitted_steps = []
        for name, step in steps:
            key = key + (estimator_token(step),)
            Xt_in = Xt

            def fit_one(step=step, Xt_in=Xt_in):
                est = clone(step)
                if hasattr(est, "fit_transform"):
                    Xt_new = est.fit_transform(Xt_in, y)
                else:
                    Xt_new = est.fit(Xt_in, y).transform(Xt_in)
                return est, Xt_new

            est, Xt = self._get_or_compute(key, fit_one)
            fitted_steps.append((name, est))
        return fitted_steps, Xt, key

    def fit_pipeline(self, pipe, fold_id, X, y):
        """Fit a pipeline reusing cached fitted prefixes + transformed data."""
        fitted_steps, Xt, key = self.fit_prefix(pipe.steps[:-1], fold_id,
                                                X, y)
        name, step = pipe.steps[-1]
        key = key + (estimator_token(step),)

        def fit_last(step=step, Xt_in=Xt):
            est = clone(step)
            est.fit(Xt_in, y)
            return est

        fitted_steps = fitted_steps + [(name,
                                        self._get_or_compute(key, fit_last))]
        fitted = clone(pipe)
        fitted.steps = fitted_steps
        return fitted


class _BaseSearchCV(BaseEstimator):
    # Deterministic near-tie winner selection: candidates whose mean
    # selection score is within this ABSOLUTE tolerance of the best are
    # considered tied, and the earliest candidate in grid order wins.
    # Rationale: the same grid can execute through different compiled
    # paths (the stacked C-grid program vs per-candidate fits) whose
    # iterates agree only to the solver tolerance — a razor-edge test
    # sample can flip between them, shifting an accuracy-style fold
    # score by 1/n_test. Exact argmax would then hand different paths
    # different winners on genuinely tied candidates; the tolerance
    # absorbs that sub-solver-tol noise so the winner is a function of
    # the problem, not the execution path. cv_results_ (means, ranks)
    # are NOT quantized — only best_index_/best_score_/best_params_,
    # and the selected score is by construction within tie_tol of the
    # true max. Callers needing sklearn's exact-argmax selection set
    # ``search.tie_tol = 0.0`` on the instance.
    tie_tol = 1e-3

    def __init__(self, estimator, scoring=None, cv=None, refit=True,
                 error_score="raise", return_train_score=False,
                 cache_cv=True, scheduler=None, n_jobs=-1):
        self.estimator = estimator
        self.scoring = scoring
        self.cv = cv
        self.refit = refit
        self.error_score = error_score
        self.return_train_score = return_train_score
        self.cache_cv = cache_cv
        self.scheduler = scheduler
        self.n_jobs = n_jobs

    def _candidates(self):
        raise NotImplementedError

    def _resolve_execution(self, n_tasks):
        """Honor the ``scheduler``/``n_jobs`` knobs (reference signature:
        dask scheduler selection). Here: 'threads'/None → a host thread
        pool over jitted fits (threads overlap each candidate's host-side
        Python with the others' device compute; the XLA programs
        themselves already use every chip); 'sync'/'synchronous' → the
        deterministic sequential loop."""
        scheduler = self.scheduler
        if scheduler in (None, "threads", "threading"):
            n_jobs = self.n_jobs
            if n_jobs in (None, -1):
                workers = min(8, n_tasks) or 1
            elif n_jobs < 1:
                raise ValueError(f"n_jobs must be -1 or >=1, got {n_jobs}")
            else:
                workers = min(int(n_jobs), n_tasks) or 1
            return workers
        if scheduler in ("sync", "synchronous", "single-threaded"):
            return 1
        raise ValueError(
            f"scheduler={scheduler!r} not supported; use None, 'threads' "
            f"or 'synchronous'"
        )

    def fit(self, X, y=None, **fit_params):
        from ..metrics.scorer import clear_host_fold_cache

        try:
            return self._fit(X, y, **fit_params)
        finally:
            # fold copies must not outlive the search, even a failed one
            clear_host_fold_cache()

    def _try_C_grid_fast(self, candidates, cache, scorers, scores,
                         train_scores, n_folds, fit_params, memo):
        """True iff every (candidate, fold) score was filled by the
        stacked C-grid solve; False leaves the grids NaN-reset for the
        general path.

        Two eligible shapes: a bare GLM with a pure-``C`` grid, and a
        Pipeline whose LAST step is a GLM with a pure ``<last>__C``
        grid — the transformer prefix fits once per fold (shared via
        ``memo``, exactly as the general pipeline path would) and the
        stacked solve runs on the transformed fold. Scoring uses the
        bare GLM against the transformed folds (equivalent to scoring
        the assembled pipeline on the raw folds, minus k re-transforms
        of the test fold).

        Shared-iteration-budget semantics: the stacked L-BFGS advances
        all k candidates in lockstep until the SLOWEST one converges
        (``solvers.solve_lam_grid``) — an early-converged candidate
        keeps refining inside the joint program, which cannot perturb
        its optimum (the objective is separable across candidates).
        Each fitted clone still reports its own per-candidate
        ``n_iter_`` (the candidate's convergence point within the joint
        trajectory, recorded by the solver as
        ``info["n_iter_per_candidate"]``), so convergence diagnostics
        distinguish fast candidates from the slowest one instead of all
        clones echoing the joint budget."""
        from ..parallel import distributed as _dist

        from ..models.glm import _GLMBase

        est = self.estimator
        pipeline_mode = (_is_pipeline(est) and len(est.steps) >= 2
                         and isinstance(est.steps[-1][1], _GLMBase))
        if pipeline_mode:
            from ..metrics.scorer import _MetricScorer, _default_scorer

            # the pipeline arm scores the bare GLM against TRANSFORMED
            # folds — equivalent only for prediction-only scorers. The
            # registry scorers and the default est.score delegate are
            # prediction-only by construction; a custom callable could
            # read X's raw values, so it keeps the general path.
            if not all(isinstance(sc, _MetricScorer)
                       or sc is _default_scorer
                       for sc in scorers.values()):
                return False
            c_key = f"{est.steps[-1][0]}__C"
            glm = est.steps[-1][1]
        elif isinstance(est, _GLMBase):
            c_key = "C"
            glm = est
        else:
            return False
        if (fit_params or _dist.process_count() > 1 or len(candidates) < 2
                or any(set(p) != {c_key} for p in candidates)):
            return False
        Cs = [p[c_key] for p in candidates]
        if not all(isinstance(c, numbers.Real) and c > 0 for c in Cs):
            return False
        def reset():
            for grid in (scores, train_scores or {}):
                for arr in grid.values():
                    arr[:] = np.nan

        try:
            for fi in range(n_folds):
                Xtr, ytr, Xte, yte = cache.fold(fi)
                if pipeline_mode:
                    prefix, Xtr, _ = memo.fit_prefix(est.steps[:-1], fi,
                                                     Xtr, ytr)
                    for _, t in prefix:
                        Xte = t.transform(Xte)
                models = glm._fit_C_grid(Xtr, ytr, Cs)
                if models is None:
                    # a later fold can be ineligible (e.g. single-class
                    # train split) after earlier folds were scored —
                    # those partial cells must not leak into the
                    # general path's grid
                    reset()
                    return False
                for ci, m in enumerate(models):
                    for name, sc in scorers.items():
                        scores[name][ci, fi] = sc(m, Xte, yte)
                    if train_scores is not None:
                        for name, sc in scorers.items():
                            train_scores[name][ci, fi] = sc(m, Xtr, ytr)
        except Exception as exc:
            import warnings

            # fall back, but LOUDLY: a genuine fast-path defect must be
            # diagnosable, not hidden behind a silent 2x-cost refit
            warnings.warn(
                f"C-grid fast path failed ({type(exc).__name__}: {exc}); "
                "falling back to per-candidate fits", RuntimeWarning,
            )
            self._c_grid_fallback_ = repr(exc)
            reset()
            return False
        self._c_grid_vmapped_ = len(Cs)
        return True

    def _fit(self, X, y=None, **fit_params):
        # per-fit diagnostics must not survive a re-fit that takes a
        # different path (same policy as _memo_stats, which is re-set)
        for attr in ("_c_grid_vmapped_", "_c_grid_fallback_"):
            if hasattr(self, attr):
                delattr(self, attr)
        candidates = list(self._candidates())
        if not candidates:
            raise ValueError("no parameter candidates")
        cv = check_cv(self.cv)
        scorers, multimetric = _resolve_scorers(
            self.estimator, self.scoring, self.refit
        )
        cache = _CVCache(X, y, cv, cache=self.cache_cv)
        memo = _PrefixMemo()
        n_folds = cache.n_folds

        scores = {name: np.full((len(candidates), n_folds), np.nan)
                  for name in scorers}
        train_scores = (
            {name: np.full((len(candidates), n_folds), np.nan)
             for name in scorers}
            if self.return_train_score else None
        )

        def run_task(ci, fi, fold):
            params = candidates[ci]
            Xtr, ytr, Xte, yte = fold
            est = clone(self.estimator).set_params(**params)
            try:
                if _is_pipeline(est):
                    est = memo.fit_pipeline(est, fi, Xtr, ytr)
                else:
                    est.fit(Xtr, ytr, **fit_params)
                for name, sc in scorers.items():
                    scores[name][ci, fi] = sc(est, Xte, yte)
                if self.return_train_score:
                    for name, sc in scorers.items():
                        train_scores[name][ci, fi] = sc(est, Xtr, ytr)
            except Exception:
                if self.error_score == "raise":
                    raise
                for name in scorers:
                    scores[name][ci, fi] = self.error_score

        tasks = [(ci, fi) for ci in range(len(candidates))
                 for fi in range(n_folds)]

        # Homogeneous-GLM fast path (SURVEY.md §3.4 'combos batched
        # when homogeneous'): a grid varying ONLY C over a device GLM
        # solves every candidate in ONE stacked-lam L-BFGS program per
        # fold — one X pass per iteration for the whole grid. Any
        # failure (or ineligible shape) resets the score grid and falls
        # back to the general per-candidate machinery, where
        # error_score= applies.
        if self._try_C_grid_fast(candidates, cache, scorers, scores,
                                 train_scores, n_folds, fit_params, memo):
            tasks = []

        # Multi-process distribution (SURVEY.md §3.5 'trials pinned to
        # hosts', §5 comm row): under a live jax.distributed runtime each
        # process takes a strided share of the (candidate, fold) tasks and
        # fits it on ITS OWN local-device mesh — per-trial programs never
        # emit cross-host collectives, so processes run different trials
        # concurrently. Scores merge through one allgather at the end; the
        # reference's scheduler→worker task placement + result gathering
        # over TCP becomes placement-by-index + a device-fabric collective.
        from ..parallel import distributed as _dist

        n_proc = _dist.process_count()
        my_tasks = tasks
        dist_mesh = None
        if n_proc > 1:
            if isinstance(X, ShardedArray) or isinstance(y, ShardedArray):
                raise ValueError(
                    "multi-process search requires host-resident X/y (each "
                    "process loads its copy and fits a disjoint trial "
                    "subset); a ShardedArray on the global mesh cannot be "
                    "split into per-process trials"
                )
            my_tasks = tasks[_dist.process_index()::n_proc]
            from ..parallel.distributed import local_mesh

            dist_mesh = local_mesh()
            self._dist_stats = (
                len(my_tasks), len(tasks), _dist.process_index(), n_proc
            )

        def _placement():
            import contextlib

            return use_mesh(dist_mesh) if dist_mesh is not None \
                else contextlib.nullcontext()

        def _sync_failures(exc):
            """Exchange failure state so an exception on ONE process fails
            ALL of them fast — peers must not block forever in the merge
            collective waiting for a process that already raised."""
            if n_proc <= 1:
                if exc is not None:
                    raise exc
                return
            from ..parallel.distributed import allgather_object

            errs = allgather_object(None if exc is None else repr(exc))
            if exc is not None:
                raise exc
            bad = [e for e in errs if e is not None]
            if bad:
                raise RuntimeError(
                    f"peer process failed during distributed search: {bad}"
                )

        class _Capture:
            """Placement context that, under multi-process, holds an
            exception instead of raising so the failure is exchanged with
            peers (via _sync_failures) before anyone reaches the merge
            collective."""

            exc = None

            def __enter__(self):
                self._cm = _placement()
                self._cm.__enter__()
                return self

            def __exit__(self, et, ev, tb):
                self._cm.__exit__(et, ev, tb)
                if ev is not None and n_proc > 1:
                    self.exc = ev
                    return True
                return False

        _cap = _Capture()
        with _cap:
            # Pipelines run sequentially: the prefix memo shares fitted
            # transformers AND their transformed (device-resident) outputs
            # across candidates, which must stay on one mesh.
            workers = 1 if _is_pipeline(self.estimator) \
                else self._resolve_execution(len(my_tasks))
            device_native = _is_device_native(self.estimator)
            mesh = X.mesh if isinstance(X, ShardedArray) else resolve_mesh(None)
            if workers > 1 and device_native:
                if mesh.devices.size < 2:
                    workers = 1  # no disjoint subsets to place trials on
                elif isinstance(X, ShardedArray) and self.n_jobs in (None, -1):
                    # X was sharded across the whole mesh, possibly because
                    # it only fits that way — re-placing full folds onto
                    # smaller submeshes could OOM a chip, so trial placement
                    # is opt-in (explicit n_jobs) for sharded inputs
                    workers = 1

            if workers == 1:
                for ci, fi in my_tasks:
                    run_task(ci, fi, cache.fold(fi))
            elif not device_native:
                # host estimators (e.g. raw sklearn): plain thread pool
                with ThreadPoolExecutor(max_workers=workers) as pool:
                    futures = [
                        pool.submit(run_task, ci, fi, cache.fold(fi))
                        for ci, fi in my_tasks
                    ]
                    for f in futures:
                        f.result()  # surface the first error_score='raise'
            else:
                # mesh-subset trial placement (SURVEY.md §3.4/§3.5):
                # partition the mesh into disjoint submeshes, one per
                # worker; each trial checks a submesh out, re-places its
                # (host) fold onto it, and fits entirely within it —
                # concurrent XLA programs never share devices, so their
                # collectives cannot interleave.
                device_folds = isinstance(X, ShardedArray) or \
                    isinstance(y, ShardedArray)
                if device_folds:
                    # Device folds (VERDICT r2 weak #4): reshard each fold
                    # DEVICE-TO-DEVICE onto a submesh BEFORE its trials
                    # launch — reshard programs run on the parent mesh,
                    # and a parent-mesh program in flight while a trial
                    # runs on a sub-mesh can deadlock their collectives on
                    # shared devices. Folds run in WAVES of one fold per
                    # submesh: each wave reshards sequentially, runs its
                    # folds' candidates concurrently, then frees the
                    # copies — peak extra HBM is one fold per submesh, not
                    # cv× the dataset.
                    import jax as _jx

                    from ..parallel.sharded import reshard

                    subs = _submeshes(mesh, min(workers, n_folds))
                    S = len(subs)
                    for w0 in range(0, n_folds, S):
                        wave = list(range(w0, min(w0 + S, n_folds)))
                        wave_folds = {}
                        for j, fi in enumerate(wave):
                            wave_folds[fi] = (subs[j], tuple(
                                reshard(a, subs[j])
                                if isinstance(a, ShardedArray) else a
                                for a in cache.fold(fi)
                            ))
                        # drain parent-mesh programs before trials start
                        _jx.block_until_ready([
                            a.data for _, f in wave_folds.values()
                            for a in f if isinstance(a, ShardedArray)
                        ])

                        def run_fold_group(fi):
                            sub, fold = wave_folds[fi]
                            with use_mesh(sub):
                                for ci, fj in my_tasks:
                                    if fj == fi:
                                        run_task(ci, fj, fold)

                        with ThreadPoolExecutor(
                            max_workers=len(wave)
                        ) as pool:
                            futures = [pool.submit(run_fold_group, fi)
                                       for fi in wave]
                            for f in futures:
                                f.result()
                else:
                    # pure-host folds (X and y both host): extraction is
                    # numpy slicing, safe inside worker threads; each
                    # trial checks a submesh out and the estimator places
                    # its fold onto it — host→device placement is safe
                    # under concurrent launches
                    subs = _submeshes(mesh, workers)
                    workers = len(subs)
                    free = queue.SimpleQueue()
                    for s in subs:
                        free.put(s)

                    def run_on_submesh(ci, fi):
                        sub = free.get()
                        try:
                            with use_mesh(sub):
                                run_task(ci, fi, cache.fold(fi))
                        finally:
                            free.put(sub)

                    with ThreadPoolExecutor(max_workers=workers) as pool:
                        futures = [pool.submit(run_on_submesh, ci, fi)
                                   for ci, fi in my_tasks]
                        for f in futures:
                            f.result()

        _sync_failures(_cap.exc)
        if n_proc > 1:
            # score-gather channel: every process receives every score and
            # assembles identical cv_results_ (each cell was computed by
            # exactly one process; unfilled cells stay NaN on all)
            from ..parallel.distributed import allgather_host

            def merge(local):
                stacked = allgather_host(local)  # (P, C, F)
                filled = ~np.isnan(stacked)
                return np.where(
                    filled.any(axis=0),
                    np.nansum(np.where(filled, stacked, 0.0), axis=0),
                    np.nan,
                )

            scores = {name: merge(a) for name, a in scores.items()}
            if self.return_train_score:
                train_scores = {name: merge(a)
                                for name, a in train_scores.items()}

        results = {"params": candidates}
        means = {}
        for name, arr in scores.items():
            suffix = name if multimetric else "score"
            mean = arr.mean(axis=1)
            means[name] = mean
            order = np.argsort(-mean, kind="stable")
            ranks = np.empty(len(candidates), np.int32)
            ranks[order] = np.arange(1, len(candidates) + 1)
            results[f"mean_test_{suffix}"] = mean
            results[f"std_test_{suffix}"] = arr.std(axis=1)
            results[f"rank_test_{suffix}"] = ranks
            for fi in range(n_folds):
                results[f"split{fi}_test_{suffix}"] = arr[:, fi]
            if self.return_train_score:
                tarr = train_scores[name]
                results[f"mean_train_{suffix}"] = tarr.mean(axis=1)
                results[f"std_train_{suffix}"] = tarr.std(axis=1)
                for fi in range(n_folds):
                    results[f"split{fi}_train_{suffix}"] = tarr[:, fi]
        for key in sorted({k for p in candidates for k in p}):
            results[f"param_{key}"] = np.ma.masked_all(
                len(candidates), dtype=object
            )
            for ci, p in enumerate(candidates):
                if key in p:
                    results[f"param_{key}"][ci] = p[key]
        self.cv_results_ = results
        # selection metric: the single scorer, or the refit-named one
        # (sklearn contract: multimetric + refit=False sets no best_*)
        sel = self.refit if multimetric else "score"
        if sel in means:
            sel_mean = means[sel]
            # near-tie deterministic winner (see class ``tie_tol`` note):
            # earliest candidate within tie_tol of the best — identical
            # across the stacked C-grid and per-candidate execution paths
            # when their scores differ only by sub-solver-tol noise
            best = np.nanmax(sel_mean) if np.isfinite(sel_mean).any() \
                else np.nan
            tied = np.flatnonzero(sel_mean >= best - float(self.tie_tol))
            self.best_index_ = (int(tied[0]) if tied.size
                                else int(np.argmax(sel_mean)))
            self.best_score_ = float(sel_mean[self.best_index_])
            self.best_params_ = candidates[self.best_index_]
        self.n_splits_ = n_folds
        self.scorer_ = scorers if multimetric else scorers["score"]
        self.multimetric_ = multimetric
        self._memo_stats = (memo.hits, memo.misses)

        if self.refit:
            # multi-process: every process refits identically on its local
            # mesh (cv_results_ are identical everywhere, so best_params_
            # agree) — no cross-host program, consistent final state
            with _placement():
                est = clone(self.estimator).set_params(**self.best_params_)
                est.fit(X, y, **fit_params)
            self.best_estimator_ = est
        return self

    # -- delegation to best_estimator_ ------------------------------------
    def _check_refit(self, method):
        if not self.refit:
            raise AttributeError(
                f"{method} is only available when refit=True"
            )

    def predict(self, X):
        self._check_refit("predict")
        return self.best_estimator_.predict(X)

    def predict_proba(self, X):
        self._check_refit("predict_proba")
        return self.best_estimator_.predict_proba(X)

    def transform(self, X):
        self._check_refit("transform")
        return self.best_estimator_.transform(X)

    def decision_function(self, X):
        self._check_refit("decision_function")
        return self.best_estimator_.decision_function(X)

    def score(self, X, y=None):
        if hasattr(self, "scorer_") and self.scoring is not None:
            if getattr(self, "multimetric_", False):
                self._check_refit("score")  # refit names the metric
                return self.scorer_[self.refit](self.best_estimator_, X, y)
            return self.scorer_(self.best_estimator_, X, y)
        self._check_refit("score")
        return self.best_estimator_.score(X, y)

    @property
    def classes_(self):
        return self.best_estimator_.classes_


class GridSearchCV(_BaseSearchCV):
    """Ref: dask_ml/model_selection/_search.py::GridSearchCV."""

    def __init__(self, estimator, param_grid, scoring=None, cv=None,
                 refit=True, error_score="raise", return_train_score=False,
                 cache_cv=True, scheduler=None, n_jobs=-1):
        super().__init__(estimator, scoring=scoring, cv=cv, refit=refit,
                         error_score=error_score,
                         return_train_score=return_train_score,
                         cache_cv=cache_cv, scheduler=scheduler,
                         n_jobs=n_jobs)
        self.param_grid = param_grid

    def _candidates(self):
        return ParameterGrid(self.param_grid)


class RandomizedSearchCV(_BaseSearchCV):
    """Ref: dask_ml/model_selection/_search.py::RandomizedSearchCV."""

    def __init__(self, estimator, param_distributions, n_iter=10,
                 random_state=None, scoring=None, cv=None, refit=True,
                 error_score="raise", return_train_score=False,
                 cache_cv=True, scheduler=None, n_jobs=-1):
        super().__init__(estimator, scoring=scoring, cv=cv, refit=refit,
                         error_score=error_score,
                         return_train_score=return_train_score,
                         cache_cv=cache_cv, scheduler=scheduler,
                         n_jobs=n_jobs)
        self.param_distributions = param_distributions
        self.n_iter = n_iter
        self.random_state = random_state

    def _candidates(self):
        return ParameterSampler(self.param_distributions, self.n_iter,
                                random_state=self.random_state)
