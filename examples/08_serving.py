"""Online serving: wrap a fitted LogisticRegression in a ModelServer
and answer concurrent ragged requests through the micro-batcher.

What the ladder buys: a naive per-request ``predict`` loop pays one XLA
compile per NOVEL request shape (plus a host->device hop per call); the
server coalesces requests into padded batches drawn from a small
geometric ladder of shape buckets, so ``warmup()`` compiles everything
up front and steady-state traffic — any mix of sizes — triggers zero
new compiles (checked below via the observability recompile counter).
Backpressure is typed: a full queue sheds with ``ServerOverloaded``
instead of silently growing latency.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import threading
import time

import numpy as np

from dask_ml_tpu import observability as obs
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.serving import BucketLadder, ModelServer

n = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 50_000))
X, y = make_classification(n_samples=n, n_features=16, n_informative=8,
                           random_state=0)
clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
Xh = X.to_numpy()

ladder = BucketLadder(min_rows=8, max_rows=256, growth=2.0)
server = ModelServer(clf, methods=("predict", "predict_proba"),
                     ladder=ladder, batch_window_ms=1.0, timeout_ms=0)
server.warmup()          # compile the whole (method, bucket) grid now
print(f"ladder: {ladder} -> at most {2 * len(ladder)} compiled programs")

before = obs.counters_snapshot().get("recompiles", 0)
with server:
    def client(seed):
        r = np.random.RandomState(seed)
        for _ in range(40):
            k = int(r.randint(1, 200))
            i = int(r.randint(0, Xh.shape[0] - k))
            req = Xh[i:i + k]
            pred = server.predict(req)          # blocking convenience
            assert pred.shape == (k,)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(s,))
               for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    stats = server.stats()

after = obs.counters_snapshot().get("recompiles", 0)
lat = stats["latency_s"]
print(f"served {stats['requests']} ragged requests in {elapsed:.2f}s "
      f"({stats['batches']} batches, peak queue "
      f"{stats['queue_peak_depth']})")
print(f"latency p50={lat['p50'] * 1e3:.2f}ms p99={lat['p99'] * 1e3:.2f}ms")
print(f"new XLA compiles after warmup: {after - before} (expected 0)")
assert after - before == 0

# parity spot-check: a served answer equals the direct predict
req = Xh[123:180]
with ModelServer(clf, ladder=ladder).warmup() as srv2:
    np.testing.assert_array_equal(
        srv2.predict(req), np.asarray(clf.predict(req))
    )
print("served == direct predict: ok")
