"""Live telemetry plane (ISSUE 5): the histogram registry, Prometheus
/metrics exposition, /status, fit progress gauges published during live
streamed fits, the LatencyWindow rebuild, and ``report --merge``.

The load-bearing assertions: scraping causes ZERO new XLA compiles
(recompile counter before/after), progress gauges actually move while a
streamed fit runs, and every exposition line parses against the
text-format v0.0.4 grammar.
"""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dask_ml_tpu import config, observability as obs
from dask_ml_tpu.observability import live
from dask_ml_tpu.observability._hist import DEFAULT_BOUNDS, Histogram
from dask_ml_tpu.observability._spans import _span_observers


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts from (and leaves behind) a pristine plane: no
    singleton server, no registered observers, empty gauge/histogram
    registry — earlier test FILES may have fed the always-on serving
    histograms, so the pre-test reset matters as much as the post."""
    live.stop_telemetry()
    live.metrics_reset()
    yield
    live.stop_telemetry()
    live.metrics_reset()
    assert _span_observers == []


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


# -- histogram core ----------------------------------------------------------

def test_histogram_counts_sum_and_percentiles():
    h = Histogram()
    assert np.isnan(h.percentiles()["p50"])
    for v in np.linspace(0.001, 0.1, 100):
        h.observe(float(v))
    assert h.count == 100
    assert h.sum == pytest.approx(float(np.sum(np.linspace(0.001, 0.1,
                                                           100))))
    pct = h.percentiles((50, 99))
    # linear interpolation inside the 1-2-5 buckets: estimates land
    # within the winning bucket, clamped to observed range
    assert 0.02 <= pct["p50"] <= 0.06
    assert 0.09 <= pct["p99"] <= 0.1
    snap = h.snapshot()
    assert sum(snap["counts"]) == 100
    assert snap["min"] == pytest.approx(0.001)
    assert snap["max"] == pytest.approx(0.1)


def test_histogram_overflow_bucket_and_bounds_validation():
    h = Histogram(bounds=(0.1, 1.0))
    for v in (0.05, 0.5, 50.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["counts"] == [1, 1, 1]  # <=0.1, <=1.0, +Inf overflow
    assert h.percentiles((99,))["p99"] == pytest.approx(50.0)
    with pytest.raises(ValueError):
        Histogram(bounds=(1.0, 0.5))
    with pytest.raises(ValueError):
        Histogram(bounds=(1.0, 1.0))


def test_histogram_concurrent_observe_loses_nothing():
    h = Histogram()
    n_threads, per = 8, 5000
    errs = []

    def worker(seed):
        try:
            rng = np.random.RandomState(seed)
            for _ in range(per):
                h.observe(float(rng.uniform(1e-4, 1.0)))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    # hammer quantile reads WHILE writers run
    for _ in range(200):
        p = h.percentiles((50, 99))
        if h.count:
            assert p["p50"] <= p["p99"] or np.isnan(p["p50"])
    for t in threads:
        t.join()
    assert not errs
    assert h.count == n_threads * per
    assert sum(h.snapshot()["counts"]) == n_threads * per


# -- LatencyWindow rebuild (satellite: the hammer test) ----------------------

def test_latency_window_hammer_retains_all_observations():
    """The retired ring-window implementation (a) shared one numpy
    buffer between concurrent ``observe`` writers and the quantile
    reader's slice-copy and (b) FORGOT everything older than its 4096
    slots — after 4096 late slow requests its p50 claimed the whole day
    was slow. This hammer fails on that implementation: four threads
    record 4096 fast (1 ms) observations each while a reader thread
    hammers quantiles, then one burst of 4096 slow (100 ms) ones lands;
    a windowed p50 is ~0.1 (only the burst survives), the histogram's
    stays ~0.001 because the 16384 fast observations still exist."""
    from dask_ml_tpu.serving.metrics import LatencyWindow

    win = LatencyWindow(size=4096)
    stop = threading.Event()
    errs = []

    def reader():
        try:
            while not stop.is_set():
                p = win.percentiles((50, 99))
                # NaN = the snapshot was taken before the first observe
                # landed; any later snapshot must be ordered
                if not np.isnan(p["p50"]):
                    assert p["p50"] <= p["p99"] * 1.0000001
        except Exception as e:  # pragma: no cover
            errs.append(e)

    rt = threading.Thread(target=reader)
    rt.start()

    def fast_writer():
        for _ in range(4096):
            win.observe(0.001)

    writers = [threading.Thread(target=fast_writer) for _ in range(4)]
    for t in writers:
        t.start()
    for t in writers:
        t.join()
    for _ in range(4096):           # the late slow burst
        win.observe(0.1)
    stop.set()
    rt.join()
    assert not errs
    assert win.count == 5 * 4096    # nothing lost to racing writers
    # the fast majority still dominates the median: a 4096-slot ring
    # would report p50 == 0.1 here
    assert win.percentiles((50,))["p50"] < 0.01


def test_latency_window_keeps_old_api():
    from dask_ml_tpu.serving.metrics import LatencyWindow

    win = LatencyWindow(size=64)
    assert np.isnan(win.percentiles()["p50"])
    for v in np.linspace(0.001, 0.1, 100):
        win.observe(float(v))
    pct = win.percentiles((50, 99))
    assert 0.0 < pct["p50"] < pct["p99"] <= 0.1
    assert win.count == 100


# -- Prometheus exposition ---------------------------------------------------

_COMMENT_RE = re.compile(
    r"^# (HELP [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram|summary|"
    r"untyped))$"
)
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\\\n]*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\\\n]*\")*\})?"
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$"
)


def _check_exposition_grammar(text):
    """Every line must be a valid v0.0.4 comment or sample; histogram
    series must be cumulative-monotonic and end at the +Inf bucket ==
    _count."""
    assert text.endswith("\n")
    for line in text.rstrip("\n").split("\n"):
        assert _COMMENT_RE.match(line) or _SAMPLE_RE.match(line), line
    # one TYPE line per metric family (a duplicate — e.g. a gauge named
    # after a histogram — makes real scrapers reject the whole page)
    families = re.findall(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) ", text,
                          flags=re.M)
    dupes = {f for f in families if families.count(f) > 1}
    assert not dupes, f"duplicate TYPE declarations: {sorted(dupes)}"
    # per-series histogram invariants
    buckets = {}
    counts = {}
    for line in text.split("\n"):
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)_bucket"
                     r"\{(.*)le=\"([^\"]+)\"\} (\d+)$", line)
        if m:
            key = (m.group(1), m.group(2))
            buckets.setdefault(key, []).append(
                (float("inf") if m.group(3) == "+Inf"
                 else float(m.group(3)), int(m.group(4)))
            )
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)_count"
                     r"(\{.*\})? (\d+)$", line)
        if m:
            counts[(m.group(1), (m.group(2) or "{}")[1:-1])] = \
                int(m.group(3))
    for key, series in buckets.items():
        les = [le for le, _ in series]
        cums = [c for _, c in series]
        assert les == sorted(les), key
        assert les[-1] == float("inf"), key
        assert cums == sorted(cums), key
        name, labels = key
        labels = labels.rstrip(",")
        assert counts[(name, labels)] == cums[-1], key
    return buckets


def test_render_prometheus_grammar_and_kinds():
    obs.counters_reset()
    obs.counter_add("recompiles", 3)
    obs.counter_add("h2d_bytes", 1 << 20)
    live.gauge_set("fit_pass", 4)
    live.gauge_set("serving_queue_depth", 2,
                   labels=(("method", "predict"),))
    h = live.histogram("serving_latency_seconds",
                       labels=(("method", "predict"), ("bucket", "64")))
    for v in (0.001, 0.004, 0.2):
        h.observe(v)
    text = live.render_prometheus()
    buckets = _check_exposition_grammar(text)
    assert "# TYPE dask_ml_tpu_recompiles_total counter" in text
    assert "dask_ml_tpu_recompiles_total 3" in text
    assert "# TYPE dask_ml_tpu_fit_pass gauge" in text
    assert "dask_ml_tpu_fit_pass 4" in text
    assert "# TYPE dask_ml_tpu_serving_latency_seconds histogram" in text
    assert any(k[0] == "dask_ml_tpu_serving_latency_seconds"
               for k in buckets)
    assert 'method="predict"' in text
    obs.counters_reset()


# -- the live server ---------------------------------------------------------

def test_healthz_and_404():
    with obs.TelemetryServer(port=0) as srv:
        status, body = _get(srv.url + "/healthz")
        assert status == 200 and body == "ok\n"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/nope")
        assert ei.value.code == 404


def test_scrape_during_live_streamed_fit_gauges_move_zero_compiles():
    """The acceptance fixture: scrape /metrics from the main thread
    while a streamed SGD fit runs in another. Every scrape parses,
    the fit progress gauges move, a histogram series exists, and the
    scrapes themselves cause zero XLA compiles."""
    from dask_ml_tpu.models.sgd import SGDClassifier

    rng = np.random.RandomState(0)
    X = rng.randn(40_000, 16).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    errs = []
    with obs.TelemetryServer(port=0) as srv:
        def fit():
            try:
                with config.set(stream_block_rows=2048):
                    SGDClassifier(max_iter=6, random_state=0).fit(X, y)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        t = threading.Thread(target=fit)
        t.start()
        seen_pass = []
        while t.is_alive():
            status, text = _get(srv.url + "/metrics")
            assert status == 200
            m = re.search(r"^dask_ml_tpu_fit_pass (\d+)", text,
                          re.MULTILINE)
            if m:
                seen_pass.append(int(m.group(1)))
            time.sleep(0.01)
        t.join()
        assert not errs
        status, text = _get(srv.url + "/metrics")
        _check_exposition_grammar(text)
        seen_pass.append(int(re.search(
            r"^dask_ml_tpu_fit_pass (\d+)", text, re.MULTILINE
        ).group(1)))
        # the gauge moved: the fit ran 6 passes and the final scrape
        # sees the last one; mid-run scrapes only ever saw fewer
        assert seen_pass[-1] == 6
        assert seen_pass == sorted(seen_pass)
        assert re.search(r"^dask_ml_tpu_fit_rows_per_sec \d", text,
                         re.MULTILINE)
        assert re.search(r"^dask_ml_tpu_fit_eta_seconds ", text,
                         re.MULTILINE)
        # >=1 histogram series (pass-seconds) with every pass counted
        m = re.search(r"^dask_ml_tpu_fit_pass_seconds_count (\d+)",
                      text, re.MULTILINE)
        assert m and int(m.group(1)) == 6
        # scraping is pure host-dict reads: no XLA compile, ever
        before = obs.counters_snapshot().get("recompiles", 0)
        for _ in range(5):
            _get(srv.url + "/metrics")
            _get(srv.url + "/status")
            _get(srv.url + "/healthz")
        after = obs.counters_snapshot().get("recompiles", 0)
        assert after == before


def test_status_shows_open_span_stack_and_report_tables():
    with obs.TelemetryServer(port=0) as srv:
        with obs.span("outer", component="Demo"):
            with obs.span("inner"):
                status, body = _get(srv.url + "/status")
        data = json.loads(body)
        names = [s["span"] for s in data["open_spans"]]
        assert names == ["outer", "inner"]   # oldest first
        assert all("age_s" in s and "thread" in s
                   for s in data["open_spans"])
        assert data["pid"] == os.getpid()
        # the closed spans land in the recent ring -> report tables
        status, body = _get(srv.url + "/status")
        data = json.loads(body)
        spans = [r["span"] for r in data["report"]["spans"]]
        assert "Demo.outer" in spans
        assert "counters" in data["report"]


def test_status_serving_window_and_latency_histograms(logreg_fitted):
    from dask_ml_tpu.serving import BucketLadder, ModelServer

    clf, X = logreg_fitted
    with obs.TelemetryServer(port=0) as srv:
        with ModelServer(clf, ladder=BucketLadder(8, 64, 2.0)) as ms:
            for i in range(12):
                ms.predict(X[i * 3:(i + 1) * 3])
            status, body = _get(srv.url + "/status")
            data = json.loads(body)
            assert data["serving"], "live server missing from /status"
            stats = data["serving"][0]
            assert stats["requests"] == 12
            assert "latency_s" in stats
        # per-(method,bucket) histogram series exist and count requests
        status, text = _get(srv.url + "/metrics")
        _check_exposition_grammar(text)
        m = re.findall(
            r'^dask_ml_tpu_serving_latency_seconds_count'
            r'\{method="predict",bucket="(\d+)"\} (\d+)$',
            text, re.MULTILINE,
        )
        assert m and sum(int(c) for _, c in m) == 12
        # queue gauges were published by the worker
        assert re.search(r"^dask_ml_tpu_serving_queue_depth ", text,
                         re.MULTILINE)
        assert re.search(r"^dask_ml_tpu_serving_inflight_rows ", text,
                         re.MULTILINE)


def test_serving_slo_violation_counter(logreg_fitted):
    from dask_ml_tpu.serving import BucketLadder, ModelServer

    clf, X = logreg_fitted
    obs.counters_reset()
    # an SLO of ~0ms: every served request violates it
    with config.set(serving_slo_ms=1e-6):
        with ModelServer(clf, ladder=BucketLadder(8, 64, 2.0)) as ms:
            for i in range(5):
                ms.predict(X[i * 2:(i + 1) * 2])
    assert obs.counters_snapshot().get("serving_slo_violations", 0) == 5
    obs.counters_reset()


def test_watchdog_stall_counter_reaches_metrics_and_report(tmp_path):
    """Satellite: a stall is a COUNTER (live /metrics + report counters
    table), not just a trace record."""
    obs.counters_reset()
    trace = str(tmp_path / "t")
    with config.set(trace_dir=trace, watchdog_timeout_s=0.15):
        with obs.watchdog(poll_s=0.03):
            with obs.span("wedged"):
                time.sleep(0.5)
    snap = obs.counters_snapshot()
    assert snap.get("watchdog_stalls", 0) >= 1
    text = live.render_prometheus()
    assert re.search(r"^dask_ml_tpu_watchdog_stalls_total [1-9]", text,
                     re.MULTILINE)
    # ... and the post-hoc counters table agrees
    from dask_ml_tpu.observability.report import build_report

    out = build_report([{"counters": True, **snap}])
    assert "watchdog_stalls" in out
    # the live /status ring kept the dump (sans stacks)
    with obs.TelemetryServer(port=0) as srv:
        data = json.loads(_get(srv.url + "/status")[1])
        assert any(r.get("span") == "wedged"
                   for r in data["watchdog_stalls"])
        assert all("stacks" not in r for r in data["watchdog_stalls"])
    obs.counters_reset()


def test_ensure_telemetry_config_gated_and_idempotent():
    # port 0 (default): nothing starts
    assert live.ensure_telemetry() is None
    assert live.telemetry_server() is None
    # pick a free port, then let the BlockStream entry arm the server
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    from dask_ml_tpu.parallel.streaming import BlockStream

    X = np.zeros((64, 4), np.float32)
    with config.set(obs_http_port=port):
        for _ in BlockStream((X,), block_rows=32):
            pass
        srv = live.telemetry_server()
        assert srv is not None and srv.port == port
        assert live.ensure_telemetry() is srv   # idempotent
        assert _get(srv.url + "/healthz")[0] == 200
    live.stop_telemetry()
    assert live.telemetry_server() is None


# -- report --merge ----------------------------------------------------------

def _write_jsonl(path, recs):
    with open(path, "w") as fh:
        for r in recs:
            fh.write(json.dumps(r) + "\n")


def test_merge_records_interleaves_by_wall_clock(tmp_path):
    from dask_ml_tpu.observability.report import (load_records,
                                                  merge_records)

    # two processes with different sink origins; ids pid-prefixed like
    # the span layer produces
    base = 1700000000.0
    a = [
        {"time": 0.1, "span": "fit", "span_id": (7 << 24) | 1,
         "parent_id": None, "wall_s": 0.05, "sync_s": 0.0,
         "t_unix": base + 0.1, "component": "A"},
        {"time": 0.2, "component": "A", "step": 0, "loss": 1.0},
        {"time": 0.9, "counters": True, "recompiles": 5,
         "t_unix": base + 0.9},
    ]
    b = [
        {"time": 0.05, "span": "fit", "span_id": (9 << 24) | 1,
         "parent_id": None, "wall_s": 0.01, "sync_s": 0.0,
         "t_unix": base + 0.55, "component": "B"},
        {"time": 0.6, "counters": True, "recompiles": 11,
         "t_unix": base + 1.1},
    ]
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _write_jsonl(pa, a)
    _write_jsonl(pb, b)
    merged = merge_records([load_records(pa), load_records(pb)])
    assert len(merged) == 5
    # wall-clock order: A.fit, A.step (~base+0.2), B.fit (~base+0.55),
    # A counters, B counters
    kinds = [(r.get("component"), bool(r.get("counters")))
             for r in merged]
    assert kinds == [("A", False), ("A", False), ("B", False),
                     (None, True), (None, True)]
    # LAST counters snapshot by wall clock wins (B's, despite file order)
    from dask_ml_tpu.observability.report import final_counters

    assert final_counters(merged)["recompiles"] == 11


def test_merge_clockless_file_lands_after_clocked_records(tmp_path):
    """A legacy aux file with NO t_unix anywhere (counters-only, written
    by a pre-stamping MetricsLogger) must not fall to -inf and sort
    first — its end-of-run counters snapshot would lose "last snapshot
    wins" to any mid-run snapshot in the clocked file."""
    from dask_ml_tpu.observability.report import (final_counters,
                                                  merge_records)

    base = 1700000200.0
    clocked = [
        {"time": 0.1, "span": "fit", "span_id": 1, "parent_id": None,
         "wall_s": 1.0, "sync_s": 0.0, "t_unix": base + 0.1},
        # mid-run snapshot — must NOT become the run's totals
        {"time": 0.5, "counters": True, "recompiles": 2,
         "t_unix": base + 0.5},
    ]
    clockless_aux = [{"time": 0.2, "counters": True, "recompiles": 9}]
    merged = merge_records([clocked, clockless_aux])
    assert merged[-1]["recompiles"] == 9
    assert final_counters(merged)["recompiles"] == 9


def test_report_cli_merge_json_and_perfetto(tmp_path, capsys):
    from dask_ml_tpu.observability import report

    base = 1700000100.0
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _write_jsonl(pa, [
        {"time": 0.1, "span": "fit", "span_id": (3 << 24) | 1,
         "parent_id": None, "depth": 0, "wall_s": 0.2, "sync_s": 0.0,
         "t_unix": base, "component": "A", "n_rows": 100,
         "thread": "MainThread"},
    ])
    _write_jsonl(pb, [
        {"time": 0.1, "span": "fit", "span_id": (4 << 24) | 1,
         "parent_id": None, "depth": 0, "wall_s": 0.1, "sync_s": 0.0,
         "t_unix": base + 1.0, "component": "B", "n_rows": 50,
         "thread": "MainThread"},
    ])
    rc = report.main(["--merge", "--json", pa, pb])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert data["merged_files"] == 2
    spans = {r["span"] for r in data["spans"]}
    assert spans == {"A.fit", "B.fit"}
    # --perfetto accepts multiple inputs ONLY under --merge, and lanes
    # the two processes separately (pid rides the span-id high bits)
    out = str(tmp_path / "trace.json")
    assert report.main([pa, pb, "--perfetto", out]) == 2
    assert report.main(["--merge", pa, pb, "--perfetto", out]) == 0
    trace = json.load(open(out))
    lanes = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M"}
    assert lanes == {"pid3.MainThread", "pid4.MainThread"}
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 2
    # one timeline: B.fit starts ~1s after A.fit on the merged clock
    ts = sorted(e["ts"] for e in xs)
    assert 0.8e6 < ts[1] - ts[0] < 1.4e6


def test_merge_single_file_is_identity(tmp_path, capsys):
    from dask_ml_tpu.observability import report

    p = str(tmp_path / "one.jsonl")
    _write_jsonl(p, [
        {"time": 0.1, "span": "fit", "span_id": 1, "parent_id": None,
         "depth": 0, "wall_s": 1.0, "sync_s": 0.0, "t_unix": 1.7e9,
         "component": "K", "n_rows": 10},
    ])
    assert report.main(["--merge", p]) == 0
    out = capsys.readouterr().out
    assert "K.fit" in out


# -- fixtures ----------------------------------------------------------------

@pytest.fixture(scope="module")
def logreg_fitted():
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(
        n_samples=400, n_features=10, n_informative=5, random_state=0
    )
    clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(X, y)
    return clf, X.to_numpy()
