"""Online serving subsystem (dask_ml_tpu/serving): micro-batching
parity, bucket-ladder compile bounds, backpressure, and drain.

The compile-bound assertions ride the observability recompile counter
(jax.monitoring backend_compile events): warmup pays at most
len(ladder) compiles per method, and a warmed server answers randomized
ragged traffic with ZERO new compiles — the whole point of the shape
ladder.
"""

import threading
import time

import numpy as np
import pytest

from dask_ml_tpu import observability as obs
from dask_ml_tpu.serving import (
    BucketLadder,
    ModelServer,
    RequestTimeout,
    ServerClosed,
    ServerOverloaded,
)
from dask_ml_tpu.serving._batching import BoundedQueue, Request
from dask_ml_tpu.wrappers import compiled_batch_fn


@pytest.fixture(scope="module")
def logreg_fitted():
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(
        n_samples=600, n_features=12, n_informative=6, random_state=0
    )
    clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
    return clf, X.to_numpy()


@pytest.fixture(scope="module")
def logreg_multi_fitted():
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(
        n_samples=600, n_features=10, n_informative=6, n_classes=3,
        random_state=1,
    )
    clf = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
    return clf, X.to_numpy()


def _ladder():
    return BucketLadder(8, 128, 2.0)


# -- bucket ladder -----------------------------------------------------------

def test_ladder_geometry():
    lad = BucketLadder(8, 128, 2.0)
    assert lad.buckets == (8, 16, 32, 64, 128)
    assert lad.bucket_for(1) == 8
    assert lad.bucket_for(8) == 8
    assert lad.bucket_for(9) == 16
    assert lad.bucket_for(128) == 128
    assert lad.padding_for(100) == 28
    with pytest.raises(ValueError):
        lad.bucket_for(129)


def test_ladder_validation():
    with pytest.raises(ValueError):
        BucketLadder(0, 10)
    with pytest.raises(ValueError):
        BucketLadder(16, 8)
    with pytest.raises(ValueError):
        BucketLadder(8, 64, growth=1.0)


def test_ladder_from_config():
    from dask_ml_tpu import config

    with config.set(serving_min_batch=4, serving_max_batch=32,
                    serving_bucket_growth=2.0):
        lad = BucketLadder.from_config()
    assert lad.buckets == (4, 8, 16, 32)


# -- compiled entry points ---------------------------------------------------

def test_compiled_batch_fn_parity_binary(logreg_fitted):
    clf, Xh = logreg_fitted
    for method in ("predict", "predict_proba", "decision_function"):
        fn = compiled_batch_fn(clf, method)
        assert fn.jitted
        got = fn(np.asarray(Xh[:50], np.float32))
        want = getattr(clf, method)(Xh[:50])
        np.testing.assert_allclose(got, np.asarray(want), atol=1e-5)


def test_compiled_batch_fn_parity_multiclass(logreg_multi_fitted):
    clf, Xh = logreg_multi_fitted
    for method in ("predict", "predict_proba", "decision_function"):
        fn = compiled_batch_fn(clf, method)
        got = fn(np.asarray(Xh[:40], np.float32))
        want = getattr(clf, method)(Xh[:40])
        np.testing.assert_allclose(got, np.asarray(want), atol=1e-5)


def test_compiled_batch_fn_kmeans_and_pca():
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.datasets import make_blobs
    from dask_ml_tpu.decomposition import PCA

    X, _ = make_blobs(n_samples=300, n_features=6, centers=4,
                      random_state=0)
    Xh = X.to_numpy()
    km = KMeans(n_clusters=4, random_state=0).fit(X)
    fn = compiled_batch_fn(km, "predict")
    got = fn(np.asarray(Xh[:64], np.float32))
    want = km.predict(Xh[:64]).to_numpy()
    np.testing.assert_array_equal(got, want)
    fnt = compiled_batch_fn(km, "transform")
    np.testing.assert_allclose(
        fnt(np.asarray(Xh[:32], np.float32)),
        km.transform(Xh[:32]).to_numpy(), atol=1e-4,
    )

    pca = PCA(n_components=3, random_state=0).fit(X)
    fnp = compiled_batch_fn(pca, "transform")
    np.testing.assert_allclose(
        fnp(np.asarray(Xh[:32], np.float32)),
        pca.transform(Xh[:32]).to_numpy(), atol=1e-4,
    )


def test_compiled_batch_fn_host_fallback(logreg_fitted):
    from sklearn.linear_model import LogisticRegression as SkLR

    _, Xh = logreg_fitted
    y = (Xh[:, 0] > 0).astype(int)
    sk = SkLR(max_iter=200).fit(Xh, y)
    fn = compiled_batch_fn(sk, "predict")
    assert not fn.jitted
    np.testing.assert_array_equal(
        fn(np.asarray(Xh[:30], np.float32)), sk.predict(Xh[:30])
    )


def test_compiled_batch_fn_unknown_method(logreg_fitted):
    clf, _ = logreg_fitted
    with pytest.raises(AttributeError):
        compiled_batch_fn(clf, "no_such_method")


# -- served-path parity (padding masked out) ---------------------------------

def test_served_parity_vs_direct(logreg_fitted):
    clf, Xh = logreg_fitted
    with ModelServer(clf, methods=("predict", "predict_proba"),
                     ladder=_ladder(), batch_window_ms=1.0) as srv:
        rng = np.random.RandomState(3)
        for _ in range(15):
            n = rng.randint(1, 60)
            i = rng.randint(0, Xh.shape[0] - n)
            req = Xh[i:i + n]
            np.testing.assert_array_equal(
                srv.predict(req), np.asarray(clf.predict(req))
            )
            np.testing.assert_allclose(
                srv.predict_proba(req),
                np.asarray(clf.predict_proba(req)), atol=1e-5,
            )


def test_served_single_row_and_oversize(logreg_fitted):
    clf, Xh = logreg_fitted
    with ModelServer(clf, ladder=_ladder(),
                     batch_window_ms=1.0) as srv:
        # 1-D single-sample request
        got = srv.predict(Xh[7])
        assert got.shape == (1,)
        assert got[0] == np.asarray(clf.predict(Xh[7:8]))[0]
        # taller than the top bucket: chunked + reassembled
        big = Xh[:300]
        np.testing.assert_array_equal(
            srv.predict(big), np.asarray(clf.predict(big))
        )


def test_served_transform_parity():
    from dask_ml_tpu.datasets import make_blobs
    from dask_ml_tpu.decomposition import PCA

    X, _ = make_blobs(n_samples=300, n_features=6, centers=3,
                      random_state=2)
    Xh = X.to_numpy()
    pca = PCA(n_components=2, random_state=0).fit(X)
    with ModelServer(pca, methods=("transform",), ladder=_ladder(),
                     batch_window_ms=1.0) as srv:
        rng = np.random.RandomState(0)
        for _ in range(8):
            n = rng.randint(1, 50)
            i = rng.randint(0, Xh.shape[0] - n)
            req = Xh[i:i + n]
            np.testing.assert_allclose(
                srv.transform(req), pca.transform(req).to_numpy(),
                atol=1e-4,
            )


def test_served_score(logreg_fitted):
    clf, Xh = logreg_fitted
    y = np.asarray(clf.predict(Xh[:100]))
    with ModelServer(clf, ladder=_ladder(), batch_window_ms=1.0) as srv:
        assert srv.score(Xh[:100], y) == 1.0


# -- concurrency + compile bounds --------------------------------------------

def test_concurrent_clients_one_server(logreg_fitted):
    clf, Xh = logreg_fitted
    expected = {}
    rngs = {s: np.random.RandomState(100 + s) for s in range(6)}
    reqs = {}
    for s, rng in rngs.items():
        sizes = [int(rng.randint(1, 90)) for _ in range(20)]
        offs = [int(rng.randint(0, Xh.shape[0] - n)) for n in sizes]
        reqs[s] = [(Xh[i:i + n]) for n, i in zip(sizes, offs)]
        expected[s] = [np.asarray(clf.predict(r)) for r in reqs[s]]
    errs = []

    with ModelServer(clf, ladder=_ladder(), batch_window_ms=2.0,
                     timeout_ms=0) as srv:
        def client(s):
            try:
                for req, want in zip(reqs[s], expected[s]):
                    got = srv.predict(req)
                    if not np.array_equal(got, want):
                        errs.append(f"client {s}: mismatch")
            except Exception as exc:  # noqa: BLE001
                errs.append(f"client {s}: {exc!r}")

        threads = [threading.Thread(target=client, args=(s,))
                   for s in rngs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = srv.stats()
    assert not errs, errs[:3]
    assert stats["requests"] == 120
    # micro-batching actually coalesced: fewer batches than requests
    assert stats["batches"] < 120


def test_warmup_bounds_compiles_and_workload_is_compile_free(
    logreg_fitted,
):
    clf, Xh = logreg_fitted
    srv = ModelServer(clf, methods=("predict", "predict_proba"),
                      ladder=_ladder(), batch_window_ms=2.0,
                      timeout_ms=0)
    before_warm = obs.counters_snapshot().get("recompiles", 0)
    srv.warmup()
    warm_compiles = obs.counters_snapshot().get("recompiles", 0) \
        - before_warm
    # at most one program per (method, rung); the monitoring listener
    # may be unavailable on exotic jax builds — then deltas read 0 and
    # the bound still holds
    assert warm_compiles <= 2 * len(srv.ladder)
    with srv:
        before = obs.counters_snapshot().get("recompiles", 0)
        def client(seed):
            rng = np.random.RandomState(seed)
            for _ in range(25):
                n = rng.randint(1, 100)
                i = rng.randint(0, Xh.shape[0] - n)
                srv.predict(Xh[i:i + n])
                srv.predict_proba(Xh[i:i + n])

        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        after = obs.counters_snapshot().get("recompiles", 0)
    assert after - before == 0, (
        f"warmed server paid {after - before} recompiles on ladder "
        "traffic"
    )


# -- backpressure / timeout / drain ------------------------------------------

def test_overload_sheds_with_typed_error(logreg_fitted):
    clf, Xh = logreg_fitted
    with ModelServer(clf, ladder=_ladder(), max_queue=3,
                     batch_window_ms=1.0, timeout_ms=0) as srv:
        srv.pause()
        futures = [srv.submit(Xh[:4]) for _ in range(3)]
        with pytest.raises(ServerOverloaded):
            srv.submit(Xh[:4])
        snap = obs.counters_snapshot()
        assert snap.get("serving_shed", 0) >= 1
        srv.resume()
        for f in futures:
            assert f.result(timeout=30).shape == (4,)


def test_request_timeout_while_queued(logreg_fitted):
    clf, Xh = logreg_fitted
    with ModelServer(clf, ladder=_ladder(), batch_window_ms=1.0,
                     timeout_ms=50.0) as srv:
        srv.pause()
        fut = srv.submit(Xh[:4])
        time.sleep(0.2)  # let the deadline lapse while queued
        srv.resume()
        with pytest.raises(RequestTimeout):
            fut.result(timeout=30)
        assert obs.counters_snapshot().get("serving_timeouts", 0) >= 1


def test_graceful_drain_completes_queued_requests(logreg_fitted):
    clf, Xh = logreg_fitted
    srv = ModelServer(clf, ladder=_ladder(), batch_window_ms=1.0,
                      timeout_ms=0).start()
    srv.pause()
    futures = [srv.submit(Xh[i:i + 5]) for i in range(0, 50, 5)]
    srv.stop(drain=True)
    for k, f in enumerate(futures):
        got = f.result(timeout=30)
        np.testing.assert_array_equal(
            got, np.asarray(clf.predict(Xh[5 * k:5 * k + 5]))
        )
    with pytest.raises(ServerClosed):
        srv.submit(Xh[:4])


def test_stop_without_drain_sheds(logreg_fitted):
    clf, Xh = logreg_fitted
    srv = ModelServer(clf, ladder=_ladder(), batch_window_ms=1.0,
                      timeout_ms=0).start()
    srv.pause()
    fut = srv.submit(Xh[:4])
    srv.stop(drain=False)
    with pytest.raises(ServerClosed):
        fut.result(timeout=30)


def test_unserved_method_and_bad_width(logreg_fitted):
    clf, Xh = logreg_fitted
    with ModelServer(clf, ladder=_ladder(),
                     batch_window_ms=1.0) as srv:
        with pytest.raises(ValueError):
            srv.submit(Xh[:4], method="transform")
        with pytest.raises(ValueError):
            srv.submit(Xh[:4, :5])
        with pytest.raises(ValueError):
            srv.submit(np.empty((0, Xh.shape[1])))


# -- queue unit behavior -----------------------------------------------------

def test_bounded_queue_fifo_and_bound():
    q = BoundedQueue(2)
    r1 = Request(np.zeros((2, 3), np.float32), "predict")
    r2 = Request(np.zeros((3, 3), np.float32), "predict")
    r3 = Request(np.zeros((1, 3), np.float32), "predict")
    assert q.put(r1) and q.put(r2)
    assert not q.put(r3)          # at bound
    assert q.pop_first(0.0) is r1
    got = q.drain_method("predict", max_rows=10)
    assert got == [r2]
    assert q.depth == 0


def test_bounded_queue_drain_respects_row_budget():
    q = BoundedQueue(10)
    rs = [Request(np.zeros((4, 2), np.float32), "predict")
          for _ in range(4)]
    for r in rs:
        q.put(r)
    first = q.pop_first(0.0)
    assert first is rs[0]
    got = q.drain_method("predict", max_rows=9)  # fits 2 of the 3 left
    assert got == rs[1:3]
    assert q.depth == 1


# -- telemetry ---------------------------------------------------------------

def test_serving_counters_and_spans(logreg_fitted, tmp_path):
    from dask_ml_tpu import config

    clf, Xh = logreg_fitted
    obs.counters_reset()
    trace = tmp_path / "traces"
    with config.set(trace_dir=str(trace)):
        with ModelServer(clf, ladder=_ladder(),
                         batch_window_ms=1.0, timeout_ms=0) as srv:
            for i in range(6):
                srv.predict(Xh[i * 10:(i + 1) * 10 + i])
    snap = obs.counters_snapshot()
    assert snap.get("serving_requests", 0) == 6
    assert snap.get("serving_batches", 0) >= 1
    assert snap.get("serving_rows", 0) > 0
    assert snap.get("serving_padded_rows", 0) >= 0
    import json

    recs = [json.loads(line) for line in
            (trace / "trace.jsonl").read_text().splitlines()]
    batch_spans = [r for r in recs if r.get("span") == "serving.batch"]
    assert batch_spans, "no serving.batch spans recorded"
    for r in batch_spans:
        assert {"bucket", "rows", "occupancy", "n_requests",
                "queue_depth"} <= set(r)


def test_latency_window_percentiles():
    from dask_ml_tpu.serving.metrics import LatencyWindow

    win = LatencyWindow(size=64)
    assert np.isnan(win.percentiles()["p50"])
    for v in np.linspace(0.001, 0.1, 100):
        win.observe(float(v))
    pct = win.percentiles((50, 99))
    assert 0.0 < pct["p50"] < pct["p99"] <= 0.1
    assert win.count == 100


# -- review regressions ------------------------------------------------------

def test_compiled_predict_proba_respects_sgd_loss_guard():
    """sigmoid(hinge margins) is not a probability: the compiled path
    must refuse exactly like the direct method does."""
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.models.sgd import SGDClassifier

    X, y = make_classification(n_samples=300, n_features=6,
                               n_informative=4, random_state=0)
    sgd = SGDClassifier(loss="hinge", max_iter=3, random_state=0)
    sgd.fit(X, y)
    with pytest.raises(AttributeError, match="log_loss"):
        compiled_batch_fn(sgd, "predict_proba")
    with pytest.raises(AttributeError, match="log_loss"):
        ModelServer(sgd, methods=("predict_proba",))
    # log_loss SGD serves probabilities fine
    sgd_log = SGDClassifier(loss="log_loss", max_iter=3, random_state=0)
    sgd_log.fit(X, y)
    fn = compiled_batch_fn(sgd_log, "predict_proba")
    Xh = X.to_numpy()
    np.testing.assert_allclose(
        fn(np.asarray(Xh[:20], np.float32)),
        np.asarray(sgd_log.predict_proba(Xh[:20])), atol=1e-5,
    )


def test_warmup_skips_host_fallback(logreg_fitted):
    """A host (sklearn) estimator has nothing to compile; warmup must
    not demand a feature count it cannot infer."""
    _, Xh = logreg_fitted

    class Opaque:
        def predict(self, X):
            return np.asarray(X)[:, 0]

    srv = ModelServer(Opaque(), ladder=_ladder()).warmup()
    with srv:
        np.testing.assert_allclose(srv.predict(Xh[:9]), Xh[:9, 0])


def test_restart_after_stop(logreg_fitted):
    clf, Xh = logreg_fitted
    srv = ModelServer(clf, ladder=_ladder(), batch_window_ms=1.0)
    with srv:
        srv.predict(Xh[:5])
    with pytest.raises(ServerClosed):
        srv.submit(Xh[:5])
    with srv:  # restart reopens the queue
        np.testing.assert_array_equal(
            srv.predict(Xh[:5]), np.asarray(clf.predict(Xh[:5]))
        )


def test_oversize_admission_is_all_or_nothing(logreg_fitted):
    """A chunked oversize request sheds atomically: either every chunk
    is admitted or none (no orphaned chunks burning capacity)."""
    clf, Xh = logreg_fitted
    with ModelServer(clf, ladder=_ladder(), max_queue=3,
                     batch_window_ms=1.0, timeout_ms=0) as srv:
        srv.pause()
        held = srv.submit(Xh[:4])      # occupies 1 of 3 slots
        # 300 rows over a 128-row top bucket = 3 chunks; 1 + 3 > 3 so
        # the whole request sheds — transiently (room exists when the
        # queue drains), hence ServerOverloaded, not ValueError
        with pytest.raises(ServerOverloaded):
            srv.submit(Xh[:300])
        assert srv._queue.depth == 1   # nothing half-admitted
        srv.resume()
        assert held.result(timeout=30).shape == (4,)


def test_batch_failure_does_not_kill_worker():
    """pack/demux errors must fail the batch's futures and leave the
    worker serving — a dead worker would strand every later request."""
    from dask_ml_tpu.serving import ServingError

    class Opaque:  # no n_features_in_: submit() cannot pre-validate
        def predict(self, X):
            return np.asarray(X)[:, 0]

    with ModelServer(Opaque(), ladder=_ladder(), batch_window_ms=5.0,
                     timeout_ms=0) as srv:
        srv.pause()
        f_ok = srv.submit(np.ones((4, 3), np.float32))
        f_bad = srv.submit(np.ones((4, 5), np.float32))  # ragged width
        srv.resume()
        # the coalesced batch fails to pack: both resolve with the
        # typed error instead of hanging
        with pytest.raises(ServingError):
            f_ok.result(timeout=30)
        with pytest.raises(ServingError):
            f_bad.result(timeout=30)
        # worker survived: a clean request still serves
        np.testing.assert_allclose(
            srv.predict(np.full((3, 3), 2.0, np.float32)), [2.0] * 3
        )


def test_served_regressor_score_constant_target():
    """srv.score must share the package metrics' conventions (constant
    target r2 forced to 0.0, not -inf-ish)."""
    from dask_ml_tpu.datasets import make_regression
    from dask_ml_tpu.linear_model import LinearRegression

    X, y = make_regression(n_samples=300, n_features=6, random_state=0)
    reg = LinearRegression().fit(X, y)
    Xh = X.to_numpy()
    with ModelServer(reg, ladder=_ladder(), batch_window_ms=1.0) as srv:
        assert srv.score(Xh[:50], np.ones(50)) == 0.0
        # and on real targets it matches the estimator's own score
        yh = y.to_numpy()[:50]
        direct = reg.score(Xh[:50], yh)
        assert abs(srv.score(Xh[:50], yh) - direct) < 1e-6


def test_oversize_beyond_queue_capacity_fails_fast(logreg_fitted):
    """A request whose chunk count exceeds max_queue can NEVER be
    admitted — that is a permanent ValueError, not a retryable
    ServerOverloaded."""
    clf, Xh = logreg_fitted
    with ModelServer(clf, ladder=_ladder(), max_queue=2,
                     batch_window_ms=1.0) as srv:
        with pytest.raises(ValueError, match="max_queue"):
            srv.submit(np.repeat(Xh, 2, axis=0)[:3 * 128 + 1])
