"""Dataset generator tests (ref: tests/test_datasets.py in the reference)."""

import numpy as np

from dask_ml_tpu import datasets
from dask_ml_tpu.parallel import ShardedArray


def test_make_classification_shapes():
    X, y = datasets.make_classification(n_samples=103, n_features=7,
                                        random_state=0)
    assert isinstance(X, ShardedArray) and isinstance(y, ShardedArray)
    assert X.shape == (103, 7)
    assert y.shape == (103,)
    assert set(np.unique(y.to_numpy())) == {0.0, 1.0}


def test_make_classification_deterministic():
    X1, _ = datasets.make_classification(n_samples=50, n_features=5,
                                         random_state=7)
    X2, _ = datasets.make_classification(n_samples=50, n_features=5,
                                         random_state=7)
    np.testing.assert_array_equal(X1.to_numpy(), X2.to_numpy())


def test_make_regression():
    X, y = datasets.make_regression(n_samples=64, n_features=6, random_state=1)
    assert X.shape == (64, 6)
    assert np.isfinite(y.to_numpy()).all()


def test_make_blobs_centers_consistent():
    X, y = datasets.make_blobs(n_samples=200, n_features=3, centers=4,
                               random_state=2)
    assert X.shape == (200, 3)
    assert len(np.unique(y.to_numpy())) == 4


def test_make_counts():
    X, y = datasets.make_counts(n_samples=80, n_features=5, random_state=3)
    yv = y.to_numpy()
    assert (yv >= 0).all() and (yv == yv.astype(int)).all()


def test_make_classification_distinct_centers_all_seeds():
    # regression: sampling centers with replacement could give two classes
    # identical centers (~1/32 seeds) -> chance-level data
    for seed in range(40):
        X, y = datasets.make_classification(
            n_samples=200, n_features=8, n_informative=4, random_state=seed
        )
        from sklearn.linear_model import LogisticRegression

        acc = LogisticRegression(max_iter=500).fit(
            X.to_numpy(), y.to_numpy()
        ).score(X.to_numpy(), y.to_numpy())
        assert acc > 0.8, f"seed={seed} acc={acc}"


def test_make_classification_rejects_unknown_kwargs():
    import pytest

    with pytest.raises(TypeError):
        datasets.make_classification(n_samples=10, weights=[0.9, 0.1])


def test_make_classification_df():
    from dask_ml_tpu.datasets import make_classification_df

    df, y = make_classification_df(
        n_samples=200, n_features=6, random_state=0,
        dates=("2020-01-01", "2020-06-01"),
    )
    assert list(df.columns) == ["date"] + [f"feature_{i}" for i in range(6)]
    assert len(df) == 200 and len(y) == 200
    assert df["date"].between("2020-01-01", "2020-06-01").all()
    assert set(np.unique(y)) <= {0, 1}


def test_make_classification_df_predictability_response_rate():
    """Reference semantics: predictability = informative-feature fraction,
    response_rate = positive-class share (ref
    dask_ml/datasets.py::make_classification_df)."""
    from dask_ml_tpu.datasets import make_classification_df

    df, y = make_classification_df(
        n_samples=4000, n_features=10, predictability=0.5,
        response_rate=0.2, random_state=0, flip_y=0.0,
    )
    rate = float((y == 1).mean())
    assert abs(rate - 0.2) < 0.05, rate
    # predictability=0.5 of 10 features -> 5 informative: a linear model
    # must beat chance comfortably
    from sklearn.linear_model import LogisticRegression as SkLR

    acc = SkLR(max_iter=200).fit(df.values, y).score(df.values, y)
    assert acc > 0.75, acc

    import pytest

    with pytest.raises(ValueError):
        make_classification_df(predictability=1.5)
    with pytest.raises(ValueError):
        make_classification_df(response_rate=0.0)
    with pytest.raises(TypeError):
        make_classification_df(bogus_arg=1)


def test_make_classification_wide_informative_is_fast():
    """n_informative=32 means 2**32 hypercube vertices; vertex choice
    must not materialize that population (a ~34 GB allocation that
    looked like a hang). Distinctness and determinism still hold."""
    import time

    from dask_ml_tpu import datasets

    t0 = time.perf_counter()
    X, y = datasets.make_classification(
        n_samples=2000, n_features=64, n_classes=5, n_informative=32,
        random_state=0,
    )
    assert time.perf_counter() - t0 < 30
    assert X.shape == (2000, 64)
    assert len(np.unique(y.to_numpy())) == 5
    X2, y2 = datasets.make_classification(
        n_samples=2000, n_features=64, n_classes=5, n_informative=32,
        random_state=0,
    )
    np.testing.assert_array_equal(X.to_numpy(), X2.to_numpy())
