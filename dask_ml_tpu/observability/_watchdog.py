"""Slow-span watchdog: catch stalls WHILE they happen.

Three consecutive bench rounds once lost their TPU numbers to a wedged
tunnel that hung device init with zero diagnostics. This module is the
flight-recorder answer: an opt-in daemon thread
(``config.watchdog_timeout_s``) that polls the open-span registry
(``_spans.open_spans_snapshot``) and, for any span open past its
deadline, dumps to the trace sink:

- all-thread Python tracebacks (``sys._current_frames`` — a hang inside
  native XLA code still shows WHICH call never returned),
- ``device_memory_gauges()`` (an OOM-adjacent stall is visible as HBM
  pressure),
- the full open-span stack (what the process believed it was doing).

Contract: the watchdog NEVER raises into or kills the observed fit
(same never-raise posture as ``_spans._FileSink``) — it reports each
stalled span once and keeps polling. An optional ``on_stall`` callback
receives each record (bench prints it to stderr; a serving deployment
could page on it).

``bench.py``'s TPU child and ``ModelServer``'s worker both run under
``watchdog()``; with ``watchdog_timeout_s == 0`` (the default) the
context manager is a complete no-op — no thread, nothing armed, nothing
in traced code.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
import traceback

from ._counters import counter_add, counters_enabled, device_memory_gauges
from ._spans import _trace_sink, _track_arm, open_spans_snapshot

# live watchdog threads (for tests / the zero-overhead assertion)
_active_lock = threading.Lock()
_active_watchdogs = 0


def watchdog_active() -> bool:
    with _active_lock:
        return _active_watchdogs > 0


def _thread_stacks() -> dict:
    """Formatted Python stacks of every live thread, keyed by
    ``"<name>#<ident>"`` — the ident keeps same-named threads (every
    ModelServer worker is "dask-ml-tpu-serving") from overwriting each
    other's stacks in the dump."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, 'thread')}#{ident}"
        out[key] = [ln.rstrip("\n")
                    for ln in traceback.format_stack(frame)]
    return out


class Watchdog:
    """One polling thread over the open-span registry."""

    def __init__(self, timeout_s, on_stall=None, poll_s=None, cfg=None):
        self.timeout_s = float(timeout_s)
        self.on_stall = on_stall
        # poll fast enough to catch a stall within ~1/4 deadline, but
        # never busier than 20Hz even for sub-second test deadlines
        self.poll_s = poll_s if poll_s is not None else min(
            max(self.timeout_s / 4.0, 0.05), 1.0
        )
        # the watchdog thread must see the ARMING thread's (thread-local)
        # config — its own would resolve env defaults and likely no sink
        self._cfg = cfg
        self._stop = threading.Event()
        self._thread = None
        self._reported: set[int] = set()

    # -- lifecycle --------------------------------------------------------
    def start(self):
        global _active_watchdogs
        if self.timeout_s <= 0:
            # 0 means DISABLED everywhere (config semantics) — a direct
            # Watchdog(0).start() must not arm a poller whose deadline
            # every open span instantly exceeds
            return self
        if self._thread is not None:
            return self
        if self._cfg is None:
            from ..config import get_config

            self._cfg = get_config()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dask-ml-tpu-watchdog", daemon=True
        )
        with _active_lock:
            _active_watchdogs += 1
        # spans now register in the open-span registry even without a
        # configured sink — a sinkless run's stalls stay catchable
        _track_arm(+1)
        self._thread.start()
        return self

    def stop(self):
        global _active_watchdogs
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(5.0)
        self._thread = None
        with _active_lock:
            _active_watchdogs -= 1
        _track_arm(-1)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    # -- polling loop -----------------------------------------------------
    def _run(self):
        import dataclasses

        from .. import config

        with config.set(**dataclasses.asdict(self._cfg)):
            while not self._stop.wait(self.poll_s):
                try:
                    self._check(time.time())
                except Exception:
                    # the watchdog must never take the process down —
                    # keep polling even if one dump failed
                    pass

    def _check(self, now):
        spans = open_spans_snapshot()
        open_ids = {s["span_id"] for s in spans}
        self._reported &= open_ids  # forget closed spans
        for s in spans:
            age = now - s["t_open_unix"]
            if age <= self.timeout_s or s["span_id"] in self._reported:
                continue
            self._reported.add(s["span_id"])
            self._report(s, age, spans)

    def _report(self, stalled, age, open_spans):
        stacks = _thread_stacks()
        tid = stalled.get("thread_id")
        rec = {
            "watchdog": True,
            "span": stalled["span"],
            "span_id": stalled["span_id"],
            "thread": stalled["thread"],
            "thread_id": tid,
            "age_s": round(age, 3),
            "timeout_s": self.timeout_s,
            "open_spans": [
                {"span": s["span"], "span_id": s["span_id"],
                 "thread": s["thread"],
                 "age_s": round(time.time() - s["t_open_unix"], 3)}
                for s in open_spans
            ],
            "stacks": stacks,
            # the stalled thread's own stack, resolved by ident — the
            # line consumers print without digging through the full dump
            "stalled_stack": stacks.get(
                f"{stalled['thread']}#{tid}", []
            ),
        }
        try:
            rec.update(device_memory_gauges())
        except Exception:
            pass
        if counters_enabled():
            counter_add("watchdog_stalls", 1)
        try:
            # the incident plane: one stall = one builtin:watchdog_stall
            # event (fires the rule + black-box capture when armed;
            # one deque append otherwise)
            from . import alerts as _alerts

            _alerts.note_event("watchdog_stall", value=age, meta={
                "span": stalled["span"], "thread": stalled["thread"],
                "timeout_s": self.timeout_s,
            })
        except Exception:
            pass
        try:
            # feed the live plane's /status stall ring (stacks elided
            # there; the full dump still goes to the trace sink below)
            from .live import note_stall

            note_stall(rec)
        except Exception:
            pass
        sink = None
        try:
            sink = _trace_sink()
            if sink is None:
                # a fit recording through a thread-BOUND logger only
                # (no metrics_path/trace_dir): the watchdog thread
                # cannot see another thread's thread-local binding, so
                # fall back to the innermost GLOBAL binding — the same
                # best-available-guess the jit callback threads use
                from ._metrics import _active_lock, _active_loggers

                with _active_lock:
                    sink = _active_loggers[-1] if _active_loggers \
                        else None
        except Exception:
            sink = None
        if sink is not None:
            try:
                sink.log(**rec)
            except Exception:
                pass  # a full disk must not kill the watchdog either
        if self.on_stall is not None:
            try:
                self.on_stall(rec)
            except Exception:
                pass


@contextlib.contextmanager
def watchdog(timeout_s=None, on_stall=None, poll_s=None):
    """Run the enclosed block under the stall watchdog.

    ``timeout_s=None`` reads ``config.watchdog_timeout_s``; a resolved
    timeout <= 0 makes this a complete no-op (yields None, starts no
    thread) — call sites wire it unconditionally and the config knob
    decides."""
    if timeout_s is None:
        from ..config import get_config

        timeout_s = get_config().watchdog_timeout_s
    if not timeout_s or timeout_s <= 0:
        yield None
        return
    wd = Watchdog(timeout_s, on_stall=on_stall, poll_s=poll_s)
    with wd:
        yield wd
