"""Device-mesh management — the substrate every estimator runs on.

In the reference (dask-ml), data lives as row-chunked ``dask.array`` blocks
scheduled over workers connected by TCP (``distributed/comm``); here the
equivalent substrate is a ``jax.sharding.Mesh`` over TPU chips, with XLA
collectives over ICI replacing the comm layer entirely (SURVEY.md §5,
"Distributed communication backend").

The default mesh is 1-D over all visible devices with axis name ``"data"``
(pure data-parallel — the reference's row-chunking model, SURVEY.md §2c).
A 2-D ``("data", "model")`` mesh is supported for wide-feature problems
where sharding the feature axis pays (the reference's nearest analog is
dask.array 2-D blockwise matmul).
"""

from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

_state = threading.local()


def device_mesh(shape=None, axis_names=(DATA_AXIS,), devices=None) -> Mesh:
    """Build a mesh over ``devices`` (default: all of ``jax.devices()``).

    ``shape=None`` gives a 1-D mesh over every device. ``shape`` may use -1
    for one axis (inferred), e.g. ``device_mesh((-1, 2), ("data", "model"))``.
    """
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices, dtype=object)
    n = devices.size
    if shape is None:
        shape = (n,)
    shape = tuple(shape)
    if len(shape) != len(axis_names):
        raise ValueError(f"shape {shape} does not match axis_names {axis_names}")
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        if n % known:
            raise ValueError(f"cannot infer -1 in {shape} from {n} devices")
        shape = tuple(n // known if s == -1 else s for s in shape)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} needs {int(np.prod(shape))} devices, have {n}")
    return Mesh(devices.reshape(shape), axis_names)


def default_mesh() -> Mesh:
    """The ambient mesh: the one set by :func:`use_mesh`, else a cached 1-D
    data mesh over all devices."""
    mesh = getattr(_state, "mesh", None)
    if mesh is not None:
        return mesh
    cached = getattr(_state, "cached_default", None)
    if cached is None or cached.devices.size != len(jax.devices()):
        cached = device_mesh()
        _state.cached_default = cached
    return cached


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Context manager: make ``mesh`` the ambient mesh for estimators that
    don't receive one explicitly."""
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def resolve_mesh(mesh=None) -> Mesh:
    return mesh if mesh is not None else default_mesh()


def data_shards(mesh: Mesh) -> int:
    """Number of shards along the data (row) axis."""
    return mesh.shape[DATA_AXIS] if DATA_AXIS in mesh.shape else 1


def row_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """NamedSharding for an array whose leading axis is row-sharded."""
    spec = (DATA_AXIS,) + (None,) * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
