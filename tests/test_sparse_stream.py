"""Sparse CSR → streamed-fit bridge (VERDICT r4 missing #2).

Reference behavior being matched: dask-ml streams scipy CSR text blocks
through per-block sklearn estimators end-to-end
(``dask_ml/feature_extraction/text.py``; SURVEY.md §2a Text row, §7
"Sparse" hard part). Here the bridge is ``parallel.streaming``: sparse
sources densify ONE fixed-shape block at a time into the prefetched
device buffer, so the dense corpus never materializes.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import dask_ml_tpu.config as config
from dask_ml_tpu.feature_extraction.text import HashingVectorizer
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.models.sgd import SGDClassifier
from dask_ml_tpu.parallel.streaming import (BlockStream, SparseBlocks,
                                            stream_plan)
from dask_ml_tpu.wrappers import Incremental


def _rand_csr(n, d, density=0.05, seed=0):
    rng = np.random.RandomState(seed)
    return sp.random(n, d, density=density, format="csr",
                     random_state=rng, dtype=np.float64)


@pytest.fixture(scope="module")
def text_corpus():
    rng = np.random.RandomState(7)
    vocab = [f"w{i}" for i in range(300)]
    docs, labels = [], []
    for i in range(400):
        cls = i % 2
        # class-dependent word distribution so the task is learnable
        lo = 0 if cls == 0 else 100
        words = rng.choice(vocab[lo:lo + 200], size=12)
        docs.append(" ".join(words))
        labels.append(cls)
    return docs, np.asarray(labels, np.float64)


class TestSparseBlocks:
    def test_slice_parity_with_vstack(self):
        parts = [_rand_csr(37, 16, seed=s) for s in range(4)]
        stacked = sp.vstack(parts).tocsr()
        sb = SparseBlocks(parts)
        assert sb.shape == stacked.shape
        for lo, hi in [(0, 10), (30, 80), (36, 38), (100, 148), (0, 148)]:
            np.testing.assert_allclose(
                sb.slice_dense(lo, hi),
                stacked[lo:hi].toarray().astype(np.float32),
            )

    def test_width_mismatch_raises(self):
        with pytest.raises(ValueError, match="widths"):
            SparseBlocks([_rand_csr(5, 4), _rand_csr(5, 6)])


class TestBlockStreamSparse:
    def test_blocks_match_dense(self):
        Xs = _rand_csr(101, 8)
        Xd = Xs.toarray()
        got = [
            (np.asarray(b.arrays[0]), b.n_rows, np.asarray(b.mask))
            for b in BlockStream((Xs,), block_rows=32)
        ]
        want = [
            (np.asarray(b.arrays[0]), b.n_rows, np.asarray(b.mask))
            for b in BlockStream((Xd,), block_rows=32)
        ]
        assert len(got) == len(want)
        for (ga, gn, gm), (wa, wn, wm) in zip(got, want):
            assert gn == wn
            np.testing.assert_allclose(ga, wa)
            np.testing.assert_allclose(gm, wm)

    def test_sparse_blocks_source(self):
        parts = [_rand_csr(40, 8, seed=s) for s in range(3)]
        sb = SparseBlocks(parts)
        dense = sp.vstack(parts).toarray()
        out = np.concatenate([
            np.asarray(b.arrays[0])[: b.n_rows]
            for b in BlockStream((sb,), block_rows=32)
        ])
        np.testing.assert_allclose(out, dense.astype(np.float32))

    def test_stream_plan_always_streams_sparse(self):
        assert stream_plan(_rand_csr(50, 4)) is not None
        # dense-row HBM budget: a very wide sparse matrix gets small
        # blocks (built directly — sp.random at this n*m is pathological)
        rng = np.random.RandomState(0)
        n, d, nnz = 10_000, 2 ** 18, 20_000
        wide = sp.csr_matrix(
            (rng.rand(nnz), (rng.randint(0, n, nnz),
                             rng.randint(0, d, nnz))),
            shape=(n, d),
        )
        rows = stream_plan(wide)
        assert rows is not None
        assert rows * 4 * 2 ** 18 <= 260 << 20  # ~one block ≤ budget


class TestSparseEstimators:
    def test_streamed_logreg_matches_dense(self):
        Xs = _rand_csr(300, 12, density=0.3, seed=3)
        s = np.asarray(Xs.sum(axis=1)).ravel()
        y = (s > np.median(s)).astype(np.float64)
        # same streamed solver, same block partition — the ONLY variable
        # is the sparse densify-per-block source
        with config.set(stream_block_rows=64):
            dense = LogisticRegression(solver="lbfgs").fit(Xs.toarray(), y)
            sparse = LogisticRegression(solver="lbfgs").fit(Xs, y)
        assert sparse.solver_info_ is not None
        np.testing.assert_allclose(
            sparse.coef_, dense.coef_, rtol=1e-5, atol=1e-7
        )
        np.testing.assert_allclose(
            sparse.predict_proba(Xs), dense.predict_proba(Xs.toarray()),
            rtol=1e-5, atol=1e-6,
        )

    def test_incremental_sgd_sparse_matches_dense(self):
        Xs = _rand_csr(240, 10, density=0.4, seed=5)
        y = (np.arange(240) % 2).astype(np.float64)
        kw = dict(loss="log_loss", random_state=0, shuffle=False,
                  max_iter=3)
        inc_s = Incremental(SGDClassifier(**kw), shuffle_blocks=False)
        inc_d = Incremental(SGDClassifier(**kw), shuffle_blocks=False)
        inc_s.fit(Xs, y)
        inc_d.fit(Xs.toarray(), y)
        np.testing.assert_allclose(
            inc_s.estimator_.coef_, inc_d.estimator_.coef_,
            rtol=1e-5, atol=1e-6,
        )
        # streamed sparse predict matches the dense path
        np.testing.assert_array_equal(
            inc_s.estimator_.predict(Xs),
            inc_d.estimator_.predict(Xs.toarray()),
        )


class TestSparseFormats:
    def test_coo_and_csc_fit(self):
        Xs = _rand_csr(120, 8, density=0.4, seed=9)
        y = (np.arange(120) % 2).astype(np.float64)
        ref = LogisticRegression(solver="lbfgs").fit(Xs, y)
        for fmt in (Xs.tocoo(), Xs.tocsc()):
            clf = LogisticRegression(solver="lbfgs").fit(fmt, y)
            np.testing.assert_allclose(clf.coef_, ref.coef_, rtol=1e-6)

    def test_sparse_blocks_source_fit(self):
        parts = [_rand_csr(40, 8, density=0.4, seed=s) for s in range(3)]
        sb = SparseBlocks(parts)
        y = (np.arange(120) % 2).astype(np.float64)
        kw = dict(loss="log_loss", random_state=0, shuffle=False,
                  max_iter=2)
        a = SGDClassifier(**kw).fit(sb, y)
        b = SGDClassifier(**kw).fit(sp.vstack(parts).tocsr(), y)
        np.testing.assert_allclose(a.coef_, b.coef_, rtol=1e-6)
        np.testing.assert_array_equal(a.predict(sb), b.predict(sb))
        # Incremental over a SparseBlocks source (host CSR block loop)
        inc = Incremental(SGDClassifier(**kw), shuffle_blocks=False)
        inc.fit(sb, y)
        assert inc.estimator_.coef_.shape == (1, 8)

    def test_pca_sparse_streams(self):
        from dask_ml_tpu.decomposition import PCA

        Xs = _rand_csr(400, 6, density=0.5, seed=2)
        p_s = PCA(n_components=3).fit(Xs)
        p_d = PCA(n_components=3).fit(Xs.toarray())
        np.testing.assert_allclose(
            np.abs(p_s.components_), np.abs(p_d.components_),
            rtol=1e-3, atol=1e-5,
        )

    def test_fingerprint_sparse(self):
        from dask_ml_tpu.utils.validation import data_fingerprint

        Xs = _rand_csr(200, 5, density=0.5, seed=4)
        f1 = data_fingerprint(Xs)
        f2 = data_fingerprint(Xs.copy())
        assert f1 == f2
        Xmod = Xs.copy()
        Xmod[0, 0] = 99.0
        assert data_fingerprint(Xmod) != f1

    def test_parallel_post_fit_fit_sparse_blocks(self):
        from sklearn.feature_extraction.text import TfidfTransformer

        from dask_ml_tpu.wrappers import ParallelPostFit

        parts = [_rand_csr(20, 6, density=0.5, seed=s) for s in range(2)]
        sb = SparseBlocks(parts)
        out = ParallelPostFit(TfidfTransformer()).fit(sb).transform(sb)
        assert sp.issparse(out) and out.shape == (40, 6)

    def test_parallel_post_fit_sparse_output(self):
        from sklearn.feature_extraction.text import TfidfTransformer

        from dask_ml_tpu.wrappers import ParallelPostFit

        Xs = _rand_csr(30, 6, density=0.5, seed=1)
        ppf = ParallelPostFit(TfidfTransformer()).fit(Xs)
        out = ppf.transform(Xs)
        assert sp.issparse(out)
        np.testing.assert_allclose(
            out.toarray(),
            TfidfTransformer().fit(Xs).transform(Xs).toarray(),
        )


class TestTextPipeline:
    def test_hashing_to_incremental_sgd(self, text_corpus):
        docs, y = text_corpus
        hv = HashingVectorizer(n_features=2 ** 12)
        Xs = hv.transform(docs)
        assert sp.issparse(Xs)
        clf = Incremental(
            SGDClassifier(loss="log_loss", random_state=0, max_iter=5),
            shuffle_blocks=False, random_state=0,
        )
        clf.fit(Xs, y)
        acc = (clf.estimator_.predict(Xs) == y).mean()
        assert acc > 0.9

    def test_hashing_to_streamed_logreg(self, text_corpus):
        docs, y = text_corpus
        Xs = HashingVectorizer(n_features=2 ** 12).transform(docs)
        clf = LogisticRegression(solver="lbfgs", max_iter=50).fit(Xs, y)
        assert (clf.predict(Xs) == y).mean() > 0.9

    def test_block_budget_bounds_host_memory(self, text_corpus):
        """The whole point of the bridge: with a block budget set, a wide
        corpus streams in O(block) dense memory. tracemalloc bounds the
        numpy allocations the fit makes — the dense corpus (1600 × 2**16
        × 4 B ≈ 420 MB) must never appear; observed peak is ~3.5 blocks
        (prefetch + the block being built + zero-copy buffers pinned by
        in-flight device_put)."""
        import tracemalloc

        docs, y = text_corpus
        docs, y = docs * 4, np.tile(y, 4)
        Xs = HashingVectorizer(n_features=2 ** 16).transform(docs)
        dense_bytes = Xs.shape[0] * Xs.shape[1] * 4
        block_bytes = 64 * Xs.shape[1] * 4
        with config.set(stream_block_rows=64):
            tracemalloc.start()
            LogisticRegression(solver="gradient_descent", max_iter=3).fit(
                Xs, y
            )
            _, peak = tracemalloc.get_traced_memory()
            tracemalloc.stop()
        # O(block), never O(corpus): ≤ ~6 blocks and ≪ the dense matrix
        assert peak < 6 * block_bytes + (20 << 20), (peak, block_bytes)
        assert peak < dense_bytes / 4, (peak, dense_bytes)
