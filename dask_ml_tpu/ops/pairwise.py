"""Pairwise distance / kernel primitives.

Reference equivalent: ``dask_ml/metrics/pairwise.py``, which maps
sklearn's Cython ``pairwise_distances_argmin_min`` over blocks (SURVEY.md
§3.1). TPU design: one fused XLA expression — the ``x @ y.T`` term rides the
MXU, the norm/argmin epilogue fuses into it, so the "distance + argmin"
pattern the reference pays a Cython call per block for becomes a single
compiled kernel over the whole sharded array.

``y`` (centers / anchor points) is small and replicated; ``x`` may be the
padded row-sharded data — callers mask invalid rows on the results.
"""

from __future__ import annotations

import jax.numpy as jnp


def row_norms_sq(x):
    return jnp.sum(x * x, axis=-1)


def euclidean_distances_sq(x, y, mxu_dtype=None):
    """Squared euclidean distances (n, m) via the MXU-friendly expansion
    ||x||^2 - 2 x.y + ||y||^2, clamped at 0 against cancellation.

    ``mxu_dtype`` (e.g. ``jnp.bfloat16``): run ONLY the cross-term
    matmul — where the FLOPs are — at that dtype with f32 accumulation
    (``preferred_element_type``), twice the MXU rate; the norms and the
    epilogue stay at the input precision. Relative distance error is
    bounded by bf16's input rounding (~4e-3) — argmin assignments are
    robust to it, which is why KMeans exposes this through
    ``config.dtype`` while exact-distance APIs default it off."""
    if mxu_dtype is not None:
        xy = jnp.matmul(x.astype(mxu_dtype), y.astype(mxu_dtype).T,
                        preferred_element_type=jnp.float32)
    else:
        xy = x @ y.T
    d2 = (
        row_norms_sq(x)[:, None]
        - 2.0 * xy
        + row_norms_sq(y)[None, :]
    )
    return jnp.maximum(d2, 0.0)


def euclidean_distances(x, y):
    return jnp.sqrt(euclidean_distances_sq(x, y))


def pairwise_distances_argmin_min(x, y):
    """(labels, min_dists) of nearest row of y for each row of x.

    The KMeans hot kernel (SURVEY.md §3.1 🔥): distances + argmin fuse into
    one program instead of the reference's per-block Cython call.
    """
    d2 = euclidean_distances_sq(x, y)
    labels = jnp.argmin(d2, axis=1)
    return labels, jnp.sqrt(jnp.min(d2, axis=1))


def manhattan_distances(x, y):
    """L1 distances (n, m). No MXU path exists for |x-y| sums; the
    broadcasted form below is fine because y (anchors) is small."""
    return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


def cosine_distances(x, y):
    xn = x / jnp.maximum(jnp.sqrt(row_norms_sq(x))[:, None], 1e-12)
    yn = y / jnp.maximum(jnp.sqrt(row_norms_sq(y))[:, None], 1e-12)
    return jnp.clip(1.0 - xn @ yn.T, 0.0, 2.0)


def linear_kernel(x, y):
    return x @ y.T


def rbf_kernel(x, y, gamma=None):
    if gamma is None:
        gamma = 1.0 / x.shape[-1]
    return jnp.exp(-gamma * euclidean_distances_sq(x, y))


def polynomial_kernel(x, y, degree=3, gamma=None, coef0=1.0):
    if gamma is None:
        gamma = 1.0 / x.shape[-1]
    return (gamma * (x @ y.T) + coef0) ** degree


def sigmoid_kernel(x, y, gamma=None, coef0=1.0):
    if gamma is None:
        gamma = 1.0 / x.shape[-1]
    return jnp.tanh(gamma * (x @ y.T) + coef0)


_PAIRWISE_METRICS = {
    "euclidean": euclidean_distances,
    "l2": euclidean_distances,
    "sqeuclidean": euclidean_distances_sq,
    "manhattan": manhattan_distances,
    "l1": manhattan_distances,
    "cityblock": manhattan_distances,
    "cosine": cosine_distances,
}

PAIRWISE_KERNEL_FUNCTIONS = {
    "linear": linear_kernel,
    "rbf": rbf_kernel,
    "polynomial": polynomial_kernel,
    "sigmoid": sigmoid_kernel,
}


def _unwrap_x(x):
    """Padded row-sharded device array; callers mask padding rows of the
    result (slicing here would force a reshard of the big operand)."""
    return x.data if hasattr(x, "data") and hasattr(x, "n_rows") else x


def _unwrap_y(y):
    """y is the small in-memory operand: slice off padding rows so the
    result has no phantom anchor columns."""
    if hasattr(y, "data") and hasattr(y, "n_rows"):
        return y.data[: y.n_rows]
    return y


def pairwise_distances(x, y, metric="euclidean", **kwargs):
    """Distance matrix (n, m) between ``x`` and in-memory ``y``.

    Ref: ``dask_ml/metrics/pairwise.py::pairwise_distances`` — the reference
    maps sklearn's function over blocks with Y held in memory; here the whole
    matrix is one fused XLA program (the dot term rides the MXU). ``x`` may
    be a plain array or a ShardedArray (unwrapped to its padded device array;
    callers mask padding rows of the result — ``y``'s padding IS sliced off).
    ``metric`` may be a name or a callable ``f(x, y, **kwargs)``.
    """
    x, y = _unwrap_x(x), _unwrap_y(y)
    if callable(metric):
        return metric(x, y, **kwargs)
    try:
        fn = _PAIRWISE_METRICS[metric]
    except KeyError:
        raise ValueError(
            f"unsupported metric {metric!r}; one of "
            f"{sorted(_PAIRWISE_METRICS)} or a callable"
        ) from None
    return fn(x, y, **kwargs)


def pairwise_kernels(x, y, metric="linear", **kwargs):
    """Kernel matrix, mirroring sklearn/dask-ml ``pairwise_kernels``."""
    x, y = _unwrap_x(x), _unwrap_y(y)
    if callable(metric):
        return metric(x, y, **kwargs)
    try:
        fn = PAIRWISE_KERNEL_FUNCTIONS[metric]
    except KeyError:
        raise ValueError(
            f"unsupported kernel {metric!r}; one of "
            f"{sorted(PAIRWISE_KERNEL_FUNCTIONS)} or a callable"
        ) from None
    return fn(x, y, **kwargs)
