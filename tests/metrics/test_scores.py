"""Classification/regression metrics + scorer registry parity with
sklearn (SURVEY.md §2a Metrics row) — on host arrays AND sharded inputs
with padding (the masked-reduction contract)."""

import numpy as np
import pytest
import sklearn.metrics as skm

from dask_ml_tpu.metrics import (
    accuracy_score,
    log_loss,
    mean_absolute_error,
    mean_squared_error,
    r2_score,
)
from dask_ml_tpu.metrics.scorer import SCORERS, check_scoring, get_scorer
from dask_ml_tpu.parallel import as_sharded


@pytest.fixture(scope="module")
def cls_data():
    rng = np.random.RandomState(0)
    y = rng.randint(0, 2, 301).astype(np.float64)  # odd n: real padding
    p = np.clip(rng.uniform(size=301) * 0.8 + y * 0.2, 0.02, 0.98)
    pred = (p > 0.5).astype(np.float64)
    return y, pred, p


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.RandomState(1)
    y = rng.randn(301) * 3 + 1
    pred = y + rng.randn(301) * 0.7
    return y, pred


def test_accuracy_parity(cls_data):
    y, pred, _ = cls_data
    ref = skm.accuracy_score(y, pred)
    assert accuracy_score(y, pred) == pytest.approx(ref, abs=1e-6)
    assert accuracy_score(
        as_sharded(y), as_sharded(pred)
    ) == pytest.approx(ref, abs=1e-6)


def test_log_loss_parity(cls_data):
    y, _, p = cls_data
    proba = np.stack([1 - p, p], axis=1)
    ref = skm.log_loss(y, proba)
    assert log_loss(y, proba) == pytest.approx(ref, rel=1e-5)
    assert log_loss(
        as_sharded(y), as_sharded(proba)
    ) == pytest.approx(ref, rel=1e-5)


@pytest.mark.parametrize("ours,theirs", [
    (mean_squared_error, skm.mean_squared_error),
    (mean_absolute_error, skm.mean_absolute_error),
    (r2_score, skm.r2_score),
])
def test_regression_metric_parity(reg_data, ours, theirs):
    y, pred = reg_data
    ref = theirs(y, pred)
    assert ours(y, pred) == pytest.approx(ref, rel=1e-5)
    assert ours(
        as_sharded(y), as_sharded(pred)
    ) == pytest.approx(ref, rel=1e-5)


def test_scorer_registry(cls_data, reg_data):
    assert set(SCORERS) >= {
        "accuracy", "neg_mean_squared_error", "neg_mean_absolute_error",
        "neg_log_loss", "r2",
    }
    with pytest.raises(ValueError, match="not a valid scoring"):
        get_scorer("nope")

    class Fixed:
        def predict(self, X):
            return np.asarray(X)[:, 0]

        def score(self, X, y):
            return 0.5

    X = np.stack([reg_data[1], reg_data[1]], axis=1)
    y = reg_data[0]
    s = get_scorer("neg_mean_squared_error")(Fixed(), X, y)
    assert s == pytest.approx(-skm.mean_squared_error(y, X[:, 0]), rel=1e-5)
    # check_scoring falls back to est.score; callable passthrough
    assert check_scoring(Fixed(), None)(Fixed(), X, y) == 0.5
    assert check_scoring(Fixed(), lambda e, a, b: 7.0)(Fixed(), X, y) == 7.0

    class NoScore:
        pass

    with pytest.raises(TypeError, match="no score method"):
        check_scoring(NoScore(), None)


def test_greater_is_better_signs(reg_data):
    y, pred = reg_data

    class P:
        def predict(self, X):
            return pred

    assert get_scorer("neg_mean_absolute_error")(P(), None, y) < 0
    assert get_scorer("r2")(P(), None, y) == pytest.approx(
        skm.r2_score(y, pred), rel=1e-5
    )
