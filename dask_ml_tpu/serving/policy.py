"""SLO policy plane: execution-latency prediction, deadline-aware batch
release inputs, and fleet admission decisions.

Two consumers ride the same windowed per-(method, bucket) histograms:

- the micro-batcher's **deadline-aware release** (``_batching.
  release_deadline``): how long may this partial batch keep coalescing
  before the oldest request's SLO budget minus the predicted execution
  time says "dispatch now";
- the fleet's **SLO-aware admission** (:func:`predict_completion_s` /
  :func:`admission_verdict`): given each replica's queued rows and its
  predicted per-batch execution time, would this request complete
  inside ``config.serving_slo_ms``? If no replica can, shed at the door
  (typed ``SloShed``) — backpressure lands BEFORE the queue builds the
  latency collapse, not after requests have already burned their budget
  waiting.

Predictions are WINDOWED quantiles (``observability._hist``
delta-snapshots, rotated every :data:`WINDOW_S` seconds), not lifetime
averages: a model swap or a noisy neighbor changes execution time NOW,
and routing/admission must see the change within a window, undiluted by
hours of healthy history.
"""

from __future__ import annotations

import math
import threading
import time

from ..observability._hist import (
    Histogram,
    percentiles_from,
    snapshot_delta,
)

__all__ = ["ExecStats", "predict_completion_s", "admission_verdict",
           "exec_from_snapshot", "WINDOW_S"]

# windowed-quantile rotation period: predictions read the delta since a
# snapshot at most 2 windows old
WINDOW_S = 10.0
# a window needs this many observations before its quantile outranks
# the lifetime one (tiny windows estimate wildly)
_MIN_WINDOW_N = 8


def _usable(v) -> bool:
    """Is ``v`` a prediction a caller may act on? Degenerate estimates
    — NaN from an empty delta window, 0.0 from a histogram whose only
    mass sits at zero (or a remote snapshot whose sub-microsecond p90
    rounded to 0.0) — must never reach admission: ``predicted <= slo``
    holds trivially at 0.0 and fails unconditionally at NaN, turning a
    not-yet-warm predictor into a confident verdict in either
    direction. Unusable estimates collapse to None, and None ADMITS
    (never shed on ignorance)."""
    return v is not None and math.isfinite(v) and v > 0.0


class ExecStats:
    """Per-(method, bucket) batch EXECUTION seconds (pack -> demux of
    one dispatched micro-batch — not queue wait) with windowed quantile
    prediction.

    ``observe`` is the serving worker's per-batch write: one histogram
    observe. ``predict_s`` answers "how long will the next batch of
    this shape take" from the freshest window with enough mass, falling
    back to the lifetime histogram, then to any sibling bucket's
    estimate (a bucket never executed yet borrows its nearest measured
    neighbor — still better than no admission control at all), then to
    ``None`` (caller keeps the fixed-window rule).
    """

    __slots__ = ("_hists", "_cursors", "_lock")

    def __init__(self):
        self._hists: dict[tuple, Histogram] = {}
        # key -> (snapshot, t_taken): the rotation cursor windows read
        self._cursors: dict[tuple, tuple] = {}
        self._lock = threading.Lock()

    def observe(self, method: str, bucket: int, seconds: float) -> None:
        key = (method, int(bucket))
        h = self._hists.get(key)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(key, Histogram())
        h.observe(seconds)

    def _window(self, key):
        """Delta snapshot since the rotation cursor (rotating it when
        stale); None when the key was never observed."""
        h = self._hists.get(key)
        if h is None:
            return None
        cur = h.snapshot()
        now = time.perf_counter()
        with self._lock:
            prev = self._cursors.get(key)
            if prev is None or now - prev[1] > WINDOW_S:
                self._cursors[key] = (cur, now)
            prev_snap = prev[0] if prev is not None else None
        delta = snapshot_delta(cur, prev_snap)
        return delta if delta["count"] >= _MIN_WINDOW_N else cur

    def _estimate(self, key, q):
        """One key's usable windowed estimate: the window quantile when
        finite and positive, the LIFETIME quantile when the window's is
        degenerate (empty delta -> NaN, all-zero mass -> 0.0), None when
        both are — a conservative, admit-friendly collapse instead of a
        0.0/NaN that admission would treat as certainty."""
        snap = self._window(key)
        if snap is None or snap["count"] <= 0:
            return None
        v = next(iter(percentiles_from(snap, (q,)).values()))
        if _usable(v):
            return v
        h = self._hists.get(key)
        if h is not None and h.count > 0:
            v = next(iter(h.percentiles((q,)).values()))
            if _usable(v):
                return v
        return None

    def predict_s(self, method: str, bucket: int, q: float = 90):
        """Predicted execution seconds for a (method, bucket) batch, or
        None when nothing USABLE was ever measured for the method (an
        empty or not-yet-warm window never yields 0.0/NaN — it yields
        None, and the admission plane admits on None)."""
        key = (method, int(bucket))
        est = self._estimate(key, q)
        if est is not None:
            return est
        # nearest measured sibling bucket of the same method
        best, best_dist = None, math.inf
        for (m, b), h in list(self._hists.items()):
            if m != method or h.count == 0 or (m, b) == key:
                continue
            dist = abs(math.log(max(b, 1)) - math.log(max(bucket, 1)))
            if dist < best_dist:
                best, best_dist = (m, b), dist
        if best is None:
            return None
        return self._estimate(best, q)

    def snapshot(self) -> dict:
        """{"method:bucket": {count, p50, p90}} — the stats()/status
        rendering of the prediction state."""
        out = {}
        for (m, b), h in sorted(self._hists.items()):
            if h.count == 0:
                continue
            pct = h.percentiles((50, 90))
            out[f"{m}:{b}"] = {
                "count": h.count,
                "p50_s": round(pct["p50"], 6),
                "p90_s": round(pct["p90"], 6),
            }
        return out


def predict_completion_s(queue_rows: int, n_rows: int, top_bucket: int,
                         exec_s) -> float | None:
    """Predicted end-to-end seconds for a request of ``n_rows`` joining
    a replica with ``queue_rows`` already queued: the queued work packs
    into ``ceil(rows / top_bucket)`` full batches ahead of (or around)
    this request, each costing one predicted execution. None when no
    USABLE execution estimate exists yet — a missing, non-finite, or
    non-positive ``exec_s`` (an empty or not-yet-warm window) collapses
    to None and admission stays open: never shed on ignorance."""
    if not _usable(exec_s):
        return None
    batches = max(math.ceil((queue_rows + n_rows) / max(top_bucket, 1)),
                  1)
    return batches * exec_s


def admission_verdict(predicted_s, slo_s: float) -> bool:
    """True = admit. Shed only on a CONFIDENT predicted miss: an SLO is
    configured, a FINITE prediction exists, and the predicted
    completion exceeds the full budget (a NaN prediction is ignorance,
    not a miss — it admits)."""
    if slo_s <= 0 or predicted_s is None \
            or not math.isfinite(predicted_s):
        return True
    return predicted_s <= slo_s


def exec_from_snapshot(exec_snap, method: str, bucket: int,
                       q: float = 90):
    """Predicted execution seconds for a (method, bucket) batch out of a
    REMOTE replica's ``stats()["exec_s"]`` snapshot (the
    ``{"method:bucket": {count, p50_s, p90_s}}`` rendering /status
    publishes) — the federation router's cross-process twin of
    :meth:`ExecStats.predict_s`. Nearest measured bucket of the same
    method by log-distance; entries that are thin (count below
    :data:`_MIN_WINDOW_N`) or degenerate (a sub-microsecond quantile
    rounded to 0.0 by the snapshot) are skipped — None (admit) over a
    false confident verdict built from another process's noise."""
    if not exec_snap:
        return None
    field = "p90_s" if q >= 90 else "p50_s"
    best, best_dist = None, math.inf
    for key, entry in exec_snap.items():
        try:
            m, _, b = key.rpartition(":")
            b = int(b)
        except (ValueError, AttributeError):
            continue
        if m != method or not isinstance(entry, dict):
            continue
        if int(entry.get("count", 0)) < _MIN_WINDOW_N:
            continue
        v = entry.get(field, entry.get("p90_s"))
        if not _usable(v):
            continue
        dist = abs(math.log(max(b, 1)) - math.log(max(bucket, 1)))
        if dist < best_dist:
            best, best_dist = float(v), dist
    return best
