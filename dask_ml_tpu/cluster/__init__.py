"""Ref: dask_ml/cluster/__init__.py."""
from ..models.kmeans import KMeans, k_means
from ..models.spectral import SpectralClustering

__all__ = ["KMeans", "SpectralClustering", "k_means"]
