"""Regularizers with proximal operators: L1, L2, ElasticNet.

Reference equivalent: ``dask_glm/regularizers.py`` (SURVEY.md §2b row 6).
Each regularizer exposes ``value`` (penalty term for smooth objectives) and
``prox`` (proximal map for proximal-gradient / ADMM z-updates). ``pmask``
is 1 for penalized coordinates and 0 for the intercept column, which —
unlike dask-glm but like sklearn — is never penalized (sklearn-parity
contract, SURVEY.md §4). ``l1_ratio`` is threaded everywhere so the
functions stay jit-static on regularizer *name* only.
"""

from __future__ import annotations

import jax.numpy as jnp


def _soft_threshold(x, t):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)


def value(reg: str, beta, lam, pmask, l1_ratio=0.5):
    b = beta * pmask
    if reg == "l2":
        return 0.5 * lam * jnp.sum(b * b)
    if reg == "l1":
        return lam * jnp.sum(jnp.abs(b))
    if reg == "elastic_net":
        return lam * (
            l1_ratio * jnp.sum(jnp.abs(b))
            + 0.5 * (1.0 - l1_ratio) * jnp.sum(b * b)
        )
    if reg == "none":
        return jnp.zeros((), dtype=beta.dtype)
    raise ValueError(f"Unknown regularizer {reg!r}")


def prox(reg: str, beta, lam, t, pmask, l1_ratio=0.5):
    """prox_{t * lam * r}(beta), identity on unpenalized coordinates."""
    if reg == "l2":
        out = beta / (1.0 + t * lam)
    elif reg == "l1":
        out = _soft_threshold(beta, t * lam)
    elif reg == "elastic_net":
        out = _soft_threshold(beta, t * lam * l1_ratio) / (
            1.0 + t * lam * (1.0 - l1_ratio)
        )
    elif reg == "none":
        return beta
    else:
        raise ValueError(f"Unknown regularizer {reg!r}")
    return jnp.where(pmask > 0, out, beta)


SMOOTH = {"l2", "none"}
KNOWN = {"l1", "l2", "elastic_net", "none"}
