"""Data splitting: train_test_split, ShuffleSplit, KFold.

Reference: ``dask_ml/model_selection/_split.py`` (SURVEY.md §2a splits
row). ``blockwise=True`` (default, as in the reference) shuffles/splits
WITHIN each shard — no cross-shard data motion; ``blockwise=False`` draws
a global permutation. Either way the split materializes through
``take_rows`` (one XLA gather) rather than the reference's slicing task
graphs.

Splitters yield host-side index arrays (the cheap part — indices are tiny
relative to data); fold extraction gathers on device.
"""

from __future__ import annotations

import numpy as np

from ..parallel.mesh import data_shards
from ..parallel.sharded import ShardedArray, take_rows


def _validate_sizes(n, test_size, train_size):
    if test_size is None and train_size is None:
        test_size = 0.25
    if test_size is None:
        test_size = 1.0 - (
            train_size if isinstance(train_size, float) else train_size / n
        )
    n_test = (
        int(np.ceil(n * test_size)) if isinstance(test_size, float)
        else int(test_size)
    )
    if train_size is None:
        n_train = n - n_test
    else:
        n_train = (
            int(np.floor(n * train_size)) if isinstance(train_size, float)
            else int(train_size)
        )
    if n_test + n_train > n:
        raise ValueError(
            f"train_size + test_size = {n_train + n_test} > n_samples = {n}"
        )
    if n_test < 1 or n_train < 1:
        raise ValueError("resulting train/test sets would be empty")
    return n_train, n_test


def _shard_row_ranges(x: ShardedArray):
    """(start, stop) of logical rows per shard."""
    per = x.padded_shape[0] // data_shards(x.mesh)
    out = []
    for s in range(data_shards(x.mesh)):
        lo = min(s * per, x.n_rows)
        hi = min((s + 1) * per, x.n_rows)
        out.append((lo, hi))
    return out


def _blockwise_split_indices(x, test_size, train_size, rng, shuffle):
    train_parts, test_parts = [], []
    for lo, hi in _shard_row_ranges(x):
        m = hi - lo
        if m == 0:
            continue
        n_train, n_test = _validate_sizes(m, test_size, train_size)
        idx = np.arange(lo, hi)
        if shuffle:
            rng.shuffle(idx)
        test_parts.append(idx[:n_test])
        train_parts.append(idx[n_test:n_test + n_train])
    return np.concatenate(train_parts), np.concatenate(test_parts)


def train_test_split(*arrays, test_size=None, train_size=None,
                     random_state=None, shuffle=True, blockwise=True,
                     **kwargs):
    """Ref: dask_ml/model_selection/_split.py::train_test_split."""
    if not arrays:
        raise ValueError("at least one array required")
    if not shuffle and blockwise:
        blockwise = False  # contiguous split needs no per-block handling
    rng = np.random.RandomState(random_state)
    first = arrays[0]
    from ..parallel.frames import PartitionedFrame

    if isinstance(first, PartitionedFrame):
        return _split_frames(arrays, test_size, train_size, rng, shuffle,
                             blockwise)
    # scipy sparse raises on len() ("length is ambiguous"); a sparse
    # corpus splits by row indexing like everything else — the one
    # row-count rule lives in streaming._n_rows_of
    from ..parallel.streaming import _n_rows_of

    def _rows(a):
        return a.n_rows if isinstance(a, ShardedArray) else _n_rows_of(a)

    n = _rows(first)
    for a in arrays:
        if _rows(a) != n:
            raise ValueError("arrays have inconsistent lengths")

    if blockwise and isinstance(first, ShardedArray):
        train_idx, test_idx = _blockwise_split_indices(
            first, test_size, train_size, rng, shuffle
        )
    else:
        n_train, n_test = _validate_sizes(n, test_size, train_size)
        if shuffle:
            idx = rng.permutation(n)
            test_idx, train_idx = idx[:n_test], idx[n_test:n_test + n_train]
        else:
            # sklearn contract: unshuffled split is train = LEADING rows,
            # test = trailing (the chronological-holdout idiom)
            idx = np.arange(n)
            train_idx = idx[:n_train]
            test_idx = idx[n_train:n_train + n_test]

    out = []
    for a in arrays:
        if isinstance(a, ShardedArray):
            out.extend([take_rows(a, train_idx), take_rows(a, test_idx)])
        else:
            from ..parallel.streaming import (_is_sparse_source,
                                              as_row_indexable)

            a = as_row_indexable(a) if _is_sparse_source(a) \
                else np.asarray(a)
            out.extend([a[train_idx], a[test_idx]])
    return out


def _split_frames(arrays, test_size, train_size, rng, shuffle, blockwise):
    """train_test_split over PartitionedFrames. ``blockwise=True`` (the
    reference's default for dd): each partition splits its own rows — no
    global shuffle crosses partitions. ``blockwise=False``: a global
    permutation over the concatenated frame, re-partitioned afterwards."""
    from ..parallel.frames import PartitionedFrame

    first = arrays[0]
    part_lens = [len(p) for p in first.partitions]
    for a in arrays:
        if not isinstance(a, PartitionedFrame) or \
                [len(p) for p in a.partitions] != part_lens:
            raise ValueError(
                "all arrays must be PartitionedFrames with identical "
                "partition lengths"
            )
    if blockwise:
        train_ix, test_ix = [], []
        for m in part_lens:
            if m == 0:  # empty partitions contribute nothing to either
                train_ix.append(np.arange(0))
                test_ix.append(np.arange(0))
                continue
            n_train, n_test = _validate_sizes(m, test_size, train_size)
            if shuffle:
                idx = rng.permutation(m)
                test_ix.append(idx[:n_test])
                train_ix.append(idx[n_test:n_test + n_train])
            else:  # sklearn contract: train = leading rows
                idx = np.arange(m)
                train_ix.append(idx[:n_train])
                test_ix.append(idx[n_train:n_train + n_test])
        out = []
        for a in arrays:
            out.append(PartitionedFrame([
                p.iloc[ix] for p, ix in zip(a.partitions, train_ix)
            ]))
            out.append(PartitionedFrame([
                p.iloc[ix] for p, ix in zip(a.partitions, test_ix)
            ]))
        return out
    n = sum(part_lens)
    n_train, n_test = _validate_sizes(n, test_size, train_size)
    if shuffle:
        idx = rng.permutation(n)
        test_idx, train_idx = idx[:n_test], idx[n_test:n_test + n_train]
    else:
        # sklearn contract: unshuffled split is train = LEADING rows,
        # test = trailing (the chronological-holdout idiom)
        idx = np.arange(n)
        train_idx, test_idx = idx[:n_train], idx[n_train:n_train + n_test]
    out = []
    for a in arrays:
        host = a.compute()
        out.append(PartitionedFrame.from_pandas(
            host.iloc[train_idx], a.npartitions))
        out.append(PartitionedFrame.from_pandas(
            host.iloc[test_idx], a.npartitions))
    return out


class ShuffleSplit:
    """Ref: dask_ml/model_selection/_split.py::ShuffleSplit."""

    def __init__(self, n_splits=10, test_size=0.1, train_size=None,
                 blockwise=True, random_state=None):
        self.n_splits = n_splits
        self.test_size = test_size
        self.train_size = train_size
        self.blockwise = blockwise
        self.random_state = random_state

    def split(self, X, y=None, groups=None):
        rng = np.random.RandomState(self.random_state)
        from ..parallel.streaming import _n_rows_of

        n = X.n_rows if isinstance(X, ShardedArray) else _n_rows_of(X)
        for _ in range(self.n_splits):
            if self.blockwise and isinstance(X, ShardedArray):
                yield _blockwise_split_indices(
                    X, self.test_size, self.train_size, rng, shuffle=True
                )
            else:
                n_train, n_test = _validate_sizes(
                    n, self.test_size, self.train_size
                )
                idx = rng.permutation(n)
                yield idx[n_test:n_test + n_train], idx[:n_test]

    def get_n_splits(self, X=None, y=None, groups=None):
        return self.n_splits


class KFold:
    """Ref: dask_ml/model_selection/_split.py::KFold."""

    def __init__(self, n_splits=5, shuffle=False, random_state=None):
        self.n_splits = n_splits
        self.shuffle = shuffle
        self.random_state = random_state

    def split(self, X, y=None, groups=None):
        from ..parallel.streaming import _n_rows_of

        n = X.n_rows if isinstance(X, ShardedArray) else _n_rows_of(X)
        if self.n_splits > n:
            raise ValueError(
                f"n_splits={self.n_splits} > n_samples={n}"
            )
        idx = np.arange(n)
        if self.shuffle:
            np.random.RandomState(self.random_state).shuffle(idx)
        sizes = np.full(self.n_splits, n // self.n_splits)
        sizes[: n % self.n_splits] += 1
        stops = np.cumsum(sizes)
        starts = stops - sizes
        for lo, hi in zip(starts, stops):
            test = idx[lo:hi]
            train = np.concatenate([idx[:lo], idx[hi:]])
            yield train, test

    def get_n_splits(self, X=None, y=None, groups=None):
        return self.n_splits
