"""Device-mesh management — the substrate every estimator runs on.

In the reference (dask-ml), data lives as row-chunked ``dask.array`` blocks
scheduled over workers connected by TCP (``distributed/comm``); here the
equivalent substrate is a ``jax.sharding.Mesh`` over TPU chips, with XLA
collectives over ICI replacing the comm layer entirely (SURVEY.md §5,
"Distributed communication backend").

The default mesh is 1-D over all visible devices with axis name ``"data"``
(pure data-parallel — the reference's row-chunking model, SURVEY.md §2c).
A 2-D ``("data", "model")`` mesh is supported for wide-feature problems
where sharding the feature axis pays (the reference's nearest analog is
dask.array 2-D blockwise matmul).
"""

from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

_state = threading.local()


def device_mesh(shape=None, axis_names=(DATA_AXIS,), devices=None,
                topology_order=None) -> Mesh:
    """Build a mesh over ``devices`` (default: all of ``jax.devices()``).

    ``shape=None`` gives a 1-D mesh over every device. ``shape`` may use -1
    for one axis (inferred), e.g. ``device_mesh((-1, 2), ("data", "model"))``.

    On TPU the device order is TOPOLOGY-AWARE (``mesh_utils``): mesh
    neighbors are ICI neighbors, and on multi-host runs the slow DCN hop
    is the OUTER factor of the data axis — collectives then ride ICI
    rings within a host/slice and cross DCN once, instead of ping-ponging
    over DCN in enumeration order. CPU/GPU keep plain enumeration order.

    ``topology_order`` — None (default): reorder only when ``devices`` is
    omitted (explicit lists keep the caller's order, e.g. disjoint search
    submeshes); True: force reordering even for an explicit full-device
    list (``global_mesh``/``local_mesh`` pass this); False: never.
    """
    explicit = devices is not None
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices, dtype=object)
    n = devices.size
    if shape is None:
        shape = (n,)
    shape = tuple(shape)
    if len(shape) != len(axis_names):
        raise ValueError(f"shape {shape} does not match axis_names {axis_names}")
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        if n % known:
            raise ValueError(f"cannot infer -1 in {shape} from {n} devices")
        shape = tuple(n // known if s == -1 else s for s in shape)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} needs {int(np.prod(shape))} devices, have {n}")
    if topology_order is None:
        topology_order = not explicit
    if topology_order and devices.flat[0].platform == "tpu":
        arranged = _topology_mesh(shape, list(devices.flat))
        if arranged is not None:
            return Mesh(arranged, axis_names)
    return Mesh(devices.reshape(shape), axis_names)


def _topology_mesh(shape, devices):
    """TPU device array in torus-aware order, or None when the topology
    helpers decline (odd shapes, unsupported slice forms) — the caller
    then falls back to enumeration order."""
    try:
        from jax.experimental import mesh_utils

        n_procs = len({d.process_index for d in devices})
        if n_procs > 1 and len(devices) % n_procs == 0:
            if shape[0] % n_procs == 0:
                # DCN outer on the (leading) data axis, ICI inner
                ici = (shape[0] // n_procs,) + tuple(shape[1:])
                dcn = (n_procs,) + (1,) * (len(shape) - 1)
                # granule = process (we factor by process count), not the
                # default slice granule — a multi-host single slice would
                # otherwise mismatch dcn and raise
                return mesh_utils.create_hybrid_device_mesh(
                    ici, dcn, devices=devices, process_is_granule=True
                )
        return mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        return None


def default_mesh() -> Mesh:
    """The ambient mesh: the one set by :func:`use_mesh`, else a cached 1-D
    data mesh over all devices."""
    mesh = getattr(_state, "mesh", None)
    if mesh is not None:
        return mesh
    cached = getattr(_state, "cached_default", None)
    if cached is None or cached.devices.size != len(jax.devices()):
        cached = device_mesh()
        _state.cached_default = cached
    return cached


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Context manager: make ``mesh`` the ambient mesh for estimators that
    don't receive one explicitly."""
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def resolve_mesh(mesh=None) -> Mesh:
    return mesh if mesh is not None else default_mesh()


def data_shard_spec(a, lead: int = 0) -> P:
    """PartitionSpec sharding axis ``lead`` of ``a`` over the "data"
    axis, every other axis replicated — the ONE spec builder the
    sharded superblock scan programs (GLM reducers, SGD scan, KMeans
    assign-stats) use for their block operands, so a future mesh-shape
    change lands in one place."""
    return P(*((None,) * lead + (DATA_AXIS,)
               + (None,) * (a.ndim - lead - 1)))


def parse_mesh_shape(s, n_devices: int):
    """Parse a ``config.mesh_shape`` string against ``n_devices``.

    Returns ``None`` for "auto"/""/"1d", else ``(D, M)``. A bare "D"
    normalizes to ``(D, 1)``; M == 1 means the caller must build a plain
    1-D data mesh over D devices (the trivial model axis COLLAPSES so
    the 1-D programs stay jaxpr-byte-identical — asserted in
    perf_smoke). Either factor may be -1 (inferred from ``n_devices``);
    D*M may undershoot ``n_devices`` (the first D*M devices are used)
    but never exceed it."""
    s = str(s or "auto").strip().lower()
    if s in ("auto", "", "1d"):
        return None
    parts = s.split("x")
    if len(parts) not in (1, 2):
        raise ValueError(
            f"mesh_shape {s!r}: expected 'auto', 'D', or 'DxM'"
        )
    try:
        dims = [int(p) for p in parts]
    except ValueError:
        raise ValueError(
            f"mesh_shape {s!r}: expected 'auto', 'D', or 'DxM'"
        ) from None
    if len(parts) == 1:
        dims = dims + [1]
    d, m = dims
    if d == -1 and m == -1:
        raise ValueError(f"mesh_shape {s!r}: only one axis may be -1")
    if d == -1:
        if m < 1 or n_devices % m:
            raise ValueError(
                f"mesh_shape {s!r}: cannot infer data axis from "
                f"{n_devices} devices"
            )
        d = n_devices // m
    elif m == -1:
        if d < 1 or n_devices % d:
            raise ValueError(
                f"mesh_shape {s!r}: cannot infer model axis from "
                f"{n_devices} devices"
            )
        m = n_devices // d
    if d < 1 or m < 1:
        raise ValueError(f"mesh_shape {s!r}: axes must be >= 1 (or -1)")
    if d * m > n_devices:
        raise ValueError(
            f"mesh_shape {s!r} needs {d * m} devices, have {n_devices}"
        )
    return (d, m)


# t5x-style logical-axis rules: named LOGICAL array axes map onto mesh
# axes — batch-like axes shard over "data", feature/embedding axes over
# "model", anything else replicates. The ONE table `to_sharded` /
# `ShardedArray.from_array` and `BlockStream._put_sharded` consult, so
# a future mesh-shape change (or a third axis) lands in one place.
LOGICAL_AXIS_RULES = (
    ("batch", DATA_AXIS),
    ("feature", MODEL_AXIS),
    ("embed", MODEL_AXIS),
)


def logical_axis_spec(logical_axes, mesh: Mesh) -> P:
    """PartitionSpec for an array whose axes carry the LOGICAL names in
    ``logical_axes`` (None entries replicate), resolved through
    :data:`LOGICAL_AXIS_RULES` against ``mesh``: a rule only engages
    when its mesh axis exists on ``mesh`` (so "feature" degrades to
    replicated on a 1-D data mesh and the same call site serves both
    shapes)."""
    rules = dict(LOGICAL_AXIS_RULES)
    names = set(mesh.axis_names)
    spec = []
    for name in logical_axes:
        axis = rules.get(name)
        spec.append(axis if axis in names else None)
    return P(*spec)


def stream_data_mesh() -> Mesh:
    """The mesh streamed (out-of-core) fits shard over, resolved from
    ``config.stream_mesh`` x ``config.mesh_shape``. ``stream_mesh``
    restricts the device POOL: 0 = all local devices, 1 = a single
    device (the sharded superblock flavor never engages), N = the first
    N local devices. ``mesh_shape`` then SHAPES the pool: "auto"/"D"/
    "Dx1" give the 1-D data mesh (today's behavior, byte-identical
    programs), "DxM" a 2-D ("data", "model") mesh over the first D*M
    pool devices. Cached per resolved (knobs, device set) so every
    BlockStream of a fit sees the SAME Mesh object (scan programs are
    lru-cached with the mesh in their key)."""
    from ..config import get_config

    cfg = get_config()
    n = int(cfg.stream_mesh)
    shape_s = str(getattr(cfg, "mesh_shape", "auto"))
    if n <= 0:
        pool = jax.devices()
    else:
        pool = jax.devices()[: max(min(n, len(jax.devices())), 1)]
    dm = parse_mesh_shape(shape_s, len(pool))
    if dm is None:
        if n <= 0:
            return default_mesh()
        devices = pool
    elif dm[1] == 1:
        # trivial model axis: COLLAPSE to the plain 1-D data mesh so the
        # 1-D scan programs stay jaxpr-byte-identical
        devices = pool[: dm[0]]
        if n <= 0 and len(devices) == len(jax.devices()):
            return default_mesh()
        dm = None
    else:
        devices = pool[: dm[0] * dm[1]]
    key = (n, shape_s, len(devices), tuple(d.id for d in devices))
    cached = getattr(_state, "stream_meshes", None)
    if cached is None:
        cached = _state.stream_meshes = {}
    mesh = cached.get(key)
    if mesh is None:
        if dm is None:
            mesh = device_mesh(devices=devices)
        else:
            mesh = device_mesh(dm, (DATA_AXIS, MODEL_AXIS),
                               devices=devices)
        cached[key] = mesh
    return mesh


def data_shards(mesh: Mesh) -> int:
    """Number of shards along the data (row) axis."""
    return mesh.shape[DATA_AXIS] if DATA_AXIS in mesh.shape else 1


def model_shards(mesh: Mesh) -> int:
    """Number of shards along the model (feature) axis; 1 on 1-D meshes."""
    return mesh.shape[MODEL_AXIS] if MODEL_AXIS in mesh.shape else 1


def mesh_str(mesh: Mesh) -> str:
    """Render a mesh as "DxM" — the report CLI / status form (a 1-D
    data mesh over 4 devices renders "4x1")."""
    return f"{data_shards(mesh)}x{model_shards(mesh)}"


def row_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """NamedSharding for an array whose leading axis is row-sharded."""
    spec = (DATA_AXIS,) + (None,) * (ndim - 1)
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
