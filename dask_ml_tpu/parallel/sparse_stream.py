"""Bucketed-nnz sparse block staging (the device-resident sparse path).

The host side of ISSUE 13's tentpole: a sparse source (scipy CSR or the
``SparseBlocks`` view) streams as fixed-shape COO-expanded triples —
``data/cols/rows`` padded to a geometric nnz-bucket ladder — instead of
densifying every block to ``block_rows x d`` on host. The ladder (the
serving ``BucketLadder`` shape policy reused) bounds the number of
compiled specializations a pass can mint; the STACKED scan capacity is
the single top rung any staged block needs, so every super-block of a
fit has the identical ``(K, D * cap)`` shape — one compiled scan
specialization per fit, zero XLA compiles after pass 1 even under
per-pass shuffling.

Sharding: on a D-shard stream mesh each block's rows split into D
contiguous slabs (exactly the dense path's partition); entries land in
their shard's ``cap``-wide segment of the ``(D * cap,)`` staging row
with SHARD-LOCAL row ids, so the shard_map consumers read purely local
nonzeros and keep their one-psum-per-super-block contract. Consumers:
the GLM/SGD/KMeans streamed reducers (PR 13) and, since ISSUE 14, the
adaptive-search cohort scans (``superblock.sparse.sgd_cohort[.psum]``)
— a Hyperband bracket over a hashed-text corpus streams bucketed-nnz
slabs with no densify anywhere in the search.

Fallbacks are decided at PLAN time (one pass over ``indptr``, no data
touched): a corpus — or any single block — denser than
``config.stream_sparse_max_density`` refuses with a recorded reason and
the stream keeps today's per-block densify path.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

__all__ = ["SparseSlab", "SparseStreamPlan", "plan_sparse_stream",
           "sparse_row_nnz", "coo_rows"]

# nnz-bucket ladder policy: rungs grow geometrically from _NNZ_MIN so
# tiny blocks don't mint per-nnz shapes; growth 2.0 bounds padded-nnz
# waste below 50% of any staged block. The policy itself is the plans
# subsystem's NnzLadder (ISSUE 15) — the never-clamp semantics
# documented on _nnz_rung live there now, shared with the serving nnz
# grid's attribution
_NNZ_MIN = 128
_NNZ_GROWTH = 2.0

from ..plans.ladders import NnzLadder as _NnzLadder  # noqa: E402

_NNZ_LADDER = _NnzLadder(min_nnz=_NNZ_MIN, growth=_NNZ_GROWTH)


class SparseSlab:
    """One staged sparse operand: device ``data/cols/rows`` arrays of
    shape ``(K, D * cap)`` (or ``(cap,)`` for a single per-block slab)
    plus the static geometry the jitted consumers key on — ``n_rows``
    (block height S), ``n_features``, ``shards`` (D) and ``cap`` (the
    per-shard nnz capacity). Row ids are LOCAL to their shard's slab."""

    __slots__ = ("data", "cols", "rows", "n_rows", "n_features",
                 "shards", "cap")

    def __init__(self, data, cols, rows, n_rows, n_features, shards,
                 cap):
        self.data = data
        self.cols = cols
        self.rows = rows
        self.n_rows = int(n_rows)
        self.n_features = int(n_features)
        self.shards = int(shards)
        self.cap = int(cap)


def sparse_row_nnz(a) -> np.ndarray:
    """Per-row nonzero counts of a CSR-like source (scipy CSR or
    SparseBlocks) straight off ``indptr`` — no data touched."""
    if sp.isspmatrix_csr(a):
        return np.diff(a.indptr)
    # SparseBlocks: member blocks are CSR by construction
    from .streaming import SparseBlocks

    if isinstance(a, SparseBlocks):
        return np.concatenate([np.diff(b.indptr) for b in a.blocks])
    return np.diff(a.tocsr().indptr)


def coo_rows(a, lo, hi):
    """(data float32, cols int32, rows int32) of rows [lo, hi) of a
    CSR-like source, rows LOCAL (0-based at ``lo``) — pure index
    arithmetic on the CSR arrays, no densify, no scipy row-slice copy
    of anything but the touched nnz range."""
    if sp.isspmatrix_csr(a):
        s0, s1 = int(a.indptr[lo]), int(a.indptr[hi])
        data = np.asarray(a.data[s0:s1], np.float32)
        cols = np.asarray(a.indices[s0:s1], np.int32)
        reps = np.diff(a.indptr[lo:hi + 1])
        rows = np.repeat(np.arange(hi - lo, dtype=np.int32), reps)
        return data, cols, rows
    from .streaming import SparseBlocks

    if isinstance(a, SparseBlocks):
        parts_d, parts_c, parts_r = [], [], []
        i = int(np.searchsorted(a.offsets, lo, side="right") - 1)
        off = 0
        while lo < hi and i < len(a.blocks):
            b_lo, b_hi = int(a.offsets[i]), int(a.offsets[i + 1])
            take = min(hi, b_hi) - lo
            d_, c_, r_ = coo_rows(a.blocks[i], lo - b_lo,
                                  lo - b_lo + take)
            parts_d.append(d_)
            parts_c.append(c_)
            parts_r.append(r_ + off)
            off += take
            lo += take
            i += 1
        if not parts_d:
            z = np.zeros(0, np.float32)
            return z, np.zeros(0, np.int32), np.zeros(0, np.int32)
        return (np.concatenate(parts_d), np.concatenate(parts_c),
                np.concatenate(parts_r))
    return coo_rows(a.tocsr(), lo, hi)


def _nnz_rung(nnz: int, top: int) -> int:
    """Smallest ladder rung >= nnz: geometric from _NNZ_MIN, clipped to
    ``top`` (the max any block needs). Deliberately NOT serving's
    clamped GeometricLadder even though the min/growth policy matches:
    the ladder there CLAMPS its last rung to ``max_rows`` exactly
    (padding waste matters per request), while the staging capacity
    must stay a pure geometric rung — clamping cap to the observed max
    nnz would key the compiled scan shape to the corpus's exact nnz
    instead of its bucket, minting a fresh specialization per corpus.
    Delegates to the plans subsystem's NnzLadder, which encodes exactly
    that never-clamp policy."""
    return _NNZ_LADDER.rung_for(int(nnz), top=int(top))


class SparseStreamPlan:
    """The per-stream sparse staging decision: per-block nnz rungs (the
    deterministic "bucket sequence" of a corpus), the stacked per-shard
    capacity every super-block pads to, and byte accounting for the
    super-block K budget. ``reason`` is None when the device-resident
    path engages, else why it fell back (recorded in solver_info_)."""

    __slots__ = ("n_rows", "n_features", "block_rows", "shards", "cap",
                 "cap1", "block_buckets", "density", "reason",
                 "total_nnz")

    def __init__(self, n_rows, n_features, block_rows, shards, cap,
                 cap1, block_buckets, density, total_nnz, reason=None):
        self.n_rows = n_rows
        self.n_features = n_features
        self.block_rows = block_rows
        self.shards = shards
        self.cap = cap          # per-shard stacked capacity
        self.cap1 = cap1        # single-slab (D=1) capacity
        self.block_buckets = block_buckets  # per-block nnz rung sequence
        self.density = density
        self.total_nnz = total_nnz
        self.reason = reason

    @property
    def engaged(self) -> bool:
        return self.reason is None

    def block_bytes(self) -> int:
        """Device bytes one staged block costs (data f32 + cols i32 +
        rows i32 across the D shard segments) — what the super-block K
        byte budget reasons about in place of the dense S*d*4."""
        return 12 * self.cap * self.shards


def plan_sparse_stream(a, block_rows: int, shards: int,
                       max_density: float) -> SparseStreamPlan:
    """Build the staging plan for sparse source ``a`` at the stream's
    resolved ``block_rows`` / shard count. One pass over ``indptr``."""
    n, d = int(a.shape[0]), int(a.shape[1])
    row_nnz = sparse_row_nnz(a).astype(np.int64)
    total = int(row_nnz.sum())
    density = total / max(n * d, 1)
    n_blocks = max(-(-n // block_rows), 1)
    sd = max(block_rows // max(shards, 1), 1)
    # per-(block, shard) nnz: the capacity the stacked slabs must cover
    pad = n_blocks * block_rows - n
    padded = np.concatenate([row_nnz, np.zeros(pad, np.int64)])
    per_shard = padded.reshape(n_blocks, max(shards, 1), sd).sum(axis=2)
    per_block = per_shard.sum(axis=1)
    top_shard = int(per_shard.max()) if per_shard.size else 0
    top_block = int(per_block.max()) if per_block.size else 0
    buckets = tuple(
        _nnz_rung(int(b), _nnz_rung(top_block, 0)) for b in per_block
    )
    cap = _nnz_rung(top_shard, 0)
    cap1 = _nnz_rung(top_block, 0)
    reason = None
    if density > max_density:
        reason = (f"density {density:.4f} > stream_sparse_max_density "
                  f"{max_density}")
    else:
        # a single over-dense block spills past any useful rung even in
        # a sparse corpus — densify fallback, reason on record
        blk_density = top_block / max(block_rows * d, 1)
        if blk_density > max_density:
            reason = (f"block density {blk_density:.4f} > "
                      f"stream_sparse_max_density {max_density} "
                      "(over-bucket spill)")
    return SparseStreamPlan(n, d, block_rows, max(shards, 1), cap, cap1,
                            buckets, density, total, reason=reason)


def pack_block(a, lo, hi, shards, shard_rows, cap, data_out, cols_out,
               rows_out) -> int:
    """Pack rows [lo, hi) of ``a`` into one staging row — ``*_out`` are
    ``(shards * cap,)`` host views (one slot row of the ring buffer),
    zero-filled here so padding entries carry zero values. Entries land
    in their shard's ``cap``-wide segment with SHARD-LOCAL row ids.
    Returns the block's packed nnz. Raises when a shard's nnz exceeds
    the planned capacity (a source mutated under the stream — the plan
    covered every block at build time)."""
    data_out[:] = 0
    cols_out[:] = 0
    rows_out[:] = 0
    data, cols, rows = coo_rows(a, lo, hi)
    if shards <= 1:
        if len(data) > cap:
            raise ValueError(
                f"sparse block rows [{lo}, {hi}) holds {len(data)} nnz "
                f"> planned capacity {cap}; source changed under the "
                "stream"
            )
        data_out[: len(data)] = data
        cols_out[: len(data)] = cols
        rows_out[: len(data)] = rows
        return len(data)
    # shard s owns local rows [s*shard_rows, (s+1)*shard_rows); entries
    # arrive row-sorted (CSR), so one searchsorted splits them
    bounds = np.searchsorted(
        rows, np.arange(1, shards, dtype=np.int32) * shard_rows
    )
    pieces = np.split(np.arange(len(data)), bounds)
    for s, idx in enumerate(pieces):
        if len(idx) > cap:
            raise ValueError(
                f"sparse block rows [{lo}, {hi}) shard {s} holds "
                f"{len(idx)} nnz > planned capacity {cap}; source "
                "changed under the stream"
            )
        base = s * cap
        data_out[base: base + len(idx)] = data[idx]
        cols_out[base: base + len(idx)] = cols[idx]
        rows_out[base: base + len(idx)] = \
            rows[idx] - s * shard_rows
    return len(data)
