"""Streaming data sketches: the quality-observability primitive.

The systems telemetry plane (spans, counters, /metrics) says where time
and FLOPs went; nothing before this module says anything about the
DATA. A shifted input distribution, a train-serve skew, or a bad
version published under live traffic is invisible until accuracy
collapses offline. These sketches are the cheap, mergeable summaries
that make those failures observable:

- :class:`FeatureSketch` — per-feature moment accumulators (count,
  mean, M2, min, max — Chan's parallel update, so folds and merges
  compose exactly) plus fixed-boundary per-feature histograms. The
  boundaries are a symmetric 1-2-5 ladder over magnitudes 1e-6..1e6
  (the feature-space analog of ``_hist.py``'s latency ladder): FIXED so
  two sketches built anywhere — a training pass this week, a serving
  window next month, another process entirely — subtract and compare
  bucket-for-bucket with no re-binning, which is what the drift scores
  (``drift.py``: PSI/KS over count pairs) require.
- :class:`CategoricalSketch` — space-saving top-k counts for label-like
  values (served ``predict`` outputs): bounded memory under unbounded
  cardinality, counts are upper bounds with the classic space-saving
  error (inherited count of the evicted minimum).

Contracts the call sites rely on:

- **Host-only.** This module never imports jax; a fold is numpy on
  buffers the staging path already holds, so sketching can never add a
  device sync or touch a jaxpr (the zero-overhead test greps for it).
- **Thread-safe.** One lock per sketch; ``fold`` is called from the
  super-block staging worker and the serving worker while the drift
  engine snapshots from its own cadence thread.
- **O(1) memory.** Fixed boundaries, fixed feature count, capped top-k:
  a sketch's footprint is independent of how many rows ever folded.
- **JSON-safe snapshots.** ``to_dict``/``from_dict`` round-trip through
  plain lists/floats, so a training profile rides a fitted estimator
  through ``copy.deepcopy`` into ``ModelRegistry`` snapshots and
  through pickle unchanged.
"""

from __future__ import annotations

import math
import threading

import numpy as np

__all__ = ["FeatureSketch", "CategoricalSketch", "DEFAULT_VALUE_BOUNDS",
           "merge_profiles", "profile_from_dict"]


def _value_bounds():
    """Symmetric 1-2-5 ladder over |v| in 1e-6..1e6 with a zero split:
    negatives mirror positives, so sign-carrying features (standardized
    inputs, margins, residuals) resolve on both sides. 79 edges / 80
    buckets — fine enough that PSI/KS see a fraction-of-a-sigma shift
    on standardized data, small enough that a 256-feature sketch is
    ~160 KB."""
    mags = []
    for e in range(-6, 7):
        for m in (1.0, 2.0, 5.0):
            mags.append(m * 10.0 ** e)
    mags = [m for m in mags if m <= 1e6]
    return tuple(sorted([-m for m in mags] + [0.0] + mags))


DEFAULT_VALUE_BOUNDS = _value_bounds()


class FeatureSketch:
    """Mergeable per-feature streaming summary: moments + fixed-boundary
    histograms over an ``(n_rows, n_features)`` stream.

    ``fold(X)`` is one vectorized pass (searchsorted + bincount + masked
    moment reduction) over a host block; ``merge`` combines two sketches
    exactly (Chan's formula for the moments, count addition for the
    histograms). ``counts[f, i]`` counts values ``v <= bounds[i]`` of
    feature ``f`` (bisect_left semantics, matching ``_hist.Histogram``);
    the last column is the +Inf overflow bucket (non-finite values land
    there and are excluded from the moments).
    """

    __slots__ = ("n_features", "bounds", "_counts", "_n", "_mean",
                 "_m2", "_min", "_max", "_nonfinite", "_rows", "_lock")

    def __init__(self, n_features, bounds=None):
        self.n_features = int(n_features)
        if self.n_features <= 0:
            raise ValueError("FeatureSketch needs n_features >= 1")
        self.bounds = tuple(float(b) for b in
                            (bounds or DEFAULT_VALUE_BOUNDS))
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("sketch bounds must be strictly increasing")
        nb = len(self.bounds) + 1
        self._counts = np.zeros((self.n_features, nb), np.int64)
        self._n = np.zeros(self.n_features, np.int64)
        self._mean = np.zeros(self.n_features, np.float64)
        self._m2 = np.zeros(self.n_features, np.float64)
        self._min = np.full(self.n_features, np.inf)
        self._max = np.full(self.n_features, -np.inf)
        self._nonfinite = 0
        self._rows = 0
        self._lock = threading.Lock()

    @property
    def rows(self) -> int:
        return self._rows

    def fold(self, X) -> int:
        """Accumulate a host block; returns the rows folded. ``X`` is
        (n, d) or (n,) (treated as one feature). Cost is one
        searchsorted + one bincount + a handful of masked column
        reductions — no allocation proportional to history."""
        X = np.asarray(X)
        if X.ndim == 1:
            X = X[:, None]
        if X.ndim != 2 or X.shape[1] != self.n_features:
            raise ValueError(
                f"fold expects (n, {self.n_features}), got {X.shape}"
            )
        n = X.shape[0]
        if n == 0:
            return 0
        X = X.astype(np.float64, copy=False)
        finite = np.isfinite(X)
        all_finite = bool(finite.all())
        nf = finite.sum(axis=0) if not all_finite \
            else np.full(self.n_features, n, np.int64)
        Xz = X if all_finite else np.where(finite, X, 0.0)
        s = Xz.sum(axis=0, dtype=np.float64)
        b_mean = np.divide(s, nf, out=np.zeros_like(s),
                           where=nf > 0)
        dev = Xz - b_mean[None, :]
        if not all_finite:
            dev = np.where(finite, dev, 0.0)
        b_m2 = (dev * dev).sum(axis=0, dtype=np.float64)
        if all_finite:
            b_min, b_max = X.min(axis=0), X.max(axis=0)
        else:
            b_min = np.where(finite, X, np.inf).min(axis=0)
            b_max = np.where(finite, X, -np.inf).max(axis=0)
        # histogram: bisect_left per value, one flat bincount for all
        # features (non-finite sorts past every bound -> overflow)
        nb = self._counts.shape[1]
        idx = np.searchsorted(self.bounds, X)
        idx = np.minimum(idx, nb - 1)
        flat = idx + np.arange(self.n_features)[None, :] * nb
        b_counts = np.bincount(
            flat.ravel(), minlength=self.n_features * nb
        ).reshape(self.n_features, nb)
        with self._lock:
            self._counts += b_counts
            self._merge_moments_locked(nf, b_mean, b_m2, b_min, b_max)
            self._nonfinite += int(n * self.n_features - nf.sum())
            self._rows += n
        return n

    def _merge_moments_locked(self, nf, b_mean, b_m2, b_min, b_max):
        n0 = self._n
        tot = n0 + nf
        safe = np.maximum(tot, 1)
        delta = b_mean - self._mean
        self._mean = self._mean + delta * (nf / safe)
        self._m2 = self._m2 + b_m2 + delta * delta * (n0 * nf / safe)
        self._n = tot
        np.minimum(self._min, b_min, out=self._min)
        np.maximum(self._max, b_max, out=self._max)

    def merge(self, other) -> "FeatureSketch":
        """Fold another sketch (or snapshot dict) into this one — the
        multi-pass / multi-process combiner. Bounds and widths must
        match (fixed boundaries are the whole point)."""
        snap = other.to_dict() if isinstance(other, FeatureSketch) \
            else other
        if tuple(snap["bounds"]) != self.bounds \
                or int(snap["n_features"]) != self.n_features:
            raise ValueError(
                "cannot merge sketches with different bounds/widths"
            )
        with self._lock:
            self._counts += np.asarray(snap["counts"], np.int64)
            self._merge_moments_locked(
                np.asarray(snap["n"], np.int64),
                np.asarray(snap["mean"], np.float64),
                np.asarray(snap["m2"], np.float64),
                np.asarray(snap["min"], np.float64),
                np.asarray(snap["max"], np.float64),
            )
            self._nonfinite += int(snap.get("nonfinite", 0))
            self._rows += int(snap.get("rows", 0))
        return self

    # -- views ------------------------------------------------------------
    def to_dict(self) -> dict:
        """JSON-safe snapshot (consistent under the lock) — what rides
        ``estimator.training_profile_`` and registry versions."""
        with self._lock:
            return {
                "n_features": self.n_features,
                "bounds": list(self.bounds),
                "counts": self._counts.tolist(),
                "n": self._n.tolist(),
                "mean": self._mean.tolist(),
                "m2": self._m2.tolist(),
                "min": [v if math.isfinite(v) else None
                        for v in self._min.tolist()],
                "max": [v if math.isfinite(v) else None
                        for v in self._max.tolist()],
                "nonfinite": int(self._nonfinite),
                "rows": int(self._rows),
            }

    def stats(self) -> dict:
        """Per-feature {mean, std, min, max, n} arrays (host floats)."""
        with self._lock:
            n = self._n.copy()
            var = np.divide(self._m2, np.maximum(n - 1, 1),
                            out=np.zeros_like(self._m2),
                            where=n > 1)
            return {
                "n": n,
                "mean": self._mean.copy(),
                "std": np.sqrt(var),
                "min": self._min.copy(),
                "max": self._max.copy(),
            }

    def counts(self) -> np.ndarray:
        with self._lock:
            return self._counts.copy()

    def quantile(self, q) -> np.ndarray:
        """Per-feature quantile estimate (linear interpolation inside
        the winning bucket, clamped to observed [min, max]) — the same
        contract as ``_hist.percentiles_from``, vectorized over
        features. ``q`` in (0, 1); NaN where a feature saw no rows."""
        with self._lock:
            counts = self._counts.copy()
            n = self._n.copy()
            lo_obs, hi_obs = self._min.copy(), self._max.copy()
        out = np.full(self.n_features, np.nan)
        edges = np.asarray(self.bounds)
        for f in range(self.n_features):
            if n[f] <= 0:
                continue
            rank = min(max(int(math.ceil(q * n[f])), 1), int(n[f]))
            cum = 0
            val = hi_obs[f]
            for i, c in enumerate(counts[f]):
                if c <= 0:
                    continue
                if cum + c >= rank:
                    lo = edges[i - 1] if i > 0 else lo_obs[f]
                    hi = edges[i] if i < len(edges) else hi_obs[f]
                    val = lo + (rank - cum) / c * (hi - lo)
                    break
                cum += c
            out[f] = min(max(val, lo_obs[f]), hi_obs[f])
        return out


def profile_from_dict(snap) -> FeatureSketch:
    """Rebuild a live sketch from a ``to_dict`` snapshot (training
    profiles stored on estimators / registry versions)."""
    sk = FeatureSketch(snap["n_features"], bounds=snap["bounds"])
    sk.merge(snap)
    return sk


def merge_profiles(a, b):
    """Combine two profile snapshots (either may be None) into one
    snapshot dict — multiple ``partial_fit`` passes accumulate one
    training profile."""
    if a is None:
        return b
    if b is None:
        return a
    return profile_from_dict(a).merge(b).to_dict()


class CategoricalSketch:
    """Space-saving top-k counter for label-like streams (served class
    predictions). Bounded at ``k`` tracked values: a new value past
    capacity evicts the current minimum and INHERITS its count (the
    classic overestimate bound — error <= the evicted minimum), so the
    heavy hitters and their approximate frequencies survive unbounded
    cardinality in O(k) memory."""

    __slots__ = ("k", "_counts", "_total", "_lock")

    def __init__(self, k=64):
        self.k = int(k)
        if self.k <= 0:
            raise ValueError("CategoricalSketch needs k >= 1")
        self._counts: dict = {}
        self._total = 0
        self._lock = threading.Lock()

    def fold(self, values) -> int:
        vals, cnts = np.unique(np.asarray(values).ravel(),
                               return_counts=True)
        with self._lock:
            for v, c in zip(vals.tolist(), cnts.tolist()):
                key = str(v)
                if key in self._counts:
                    self._counts[key] += int(c)
                elif len(self._counts) < self.k:
                    self._counts[key] = int(c)
                else:
                    victim = min(self._counts, key=self._counts.get)
                    inherited = self._counts.pop(victim)
                    self._counts[key] = inherited + int(c)
                self._total += int(c)
        return int(cnts.sum())

    @property
    def total(self) -> int:
        return self._total

    def top(self, n=None) -> list:
        """[(value, count)] sorted by count desc (counts are
        space-saving upper bounds)."""
        with self._lock:
            items = sorted(self._counts.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        return items[:n] if n else items

    def to_dict(self) -> dict:
        with self._lock:
            return {"k": self.k, "total": int(self._total),
                    "counts": dict(self._counts)}

    def merge(self, other) -> "CategoricalSketch":
        snap = other.to_dict() if isinstance(other, CategoricalSketch) \
            else other
        with self._lock:
            for key, c in snap["counts"].items():
                if key in self._counts:
                    self._counts[key] += int(c)
                elif len(self._counts) < self.k:
                    self._counts[key] = int(c)
                else:
                    victim = min(self._counts, key=self._counts.get)
                    self._counts[key] = self._counts.pop(victim) + int(c)
            self._total += int(snap["total"])
        return self
