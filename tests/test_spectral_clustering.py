"""SpectralClustering tests (ref: tests/test_spectral_clustering.py)."""

import numpy as np
import pytest
from sklearn.datasets import make_circles
from sklearn.metrics import adjusted_rand_score

from dask_ml_tpu.cluster import KMeans, SpectralClustering
from dask_ml_tpu.datasets import make_blobs


def test_spectral_blobs():
    X, y = make_blobs(n_samples=300, n_features=4, centers=3, random_state=0,
                      cluster_std=0.5)
    sc = SpectralClustering(n_clusters=3, n_components=80, gamma=0.5,
                            random_state=0).fit(X)
    ari = adjusted_rand_score(y.to_numpy(), sc.labels_.to_numpy())
    assert ari > 0.9, ari


def test_spectral_circles_beats_kmeans():
    """Non-convex clusters: spectral must separate what kmeans cannot."""
    Xh, y = make_circles(n_samples=400, factor=0.4, noise=0.04,
                         random_state=0)
    sc = SpectralClustering(n_clusters=2, n_components=150, gamma=40.0,
                            random_state=0).fit(Xh)
    ari_spectral = adjusted_rand_score(y, sc.labels_.to_numpy())
    ari_kmeans = adjusted_rand_score(
        y, KMeans(n_clusters=2, random_state=0).fit(Xh).labels_.to_numpy()
    )
    assert ari_spectral > 0.85, ari_spectral
    assert ari_spectral > ari_kmeans


def test_spectral_assign_labels_validation():
    X, _ = make_blobs(n_samples=50, n_features=3, centers=2, random_state=1)
    with pytest.raises(ValueError, match="assign_labels"):
        SpectralClustering(n_clusters=2, assign_labels="discretize").fit(X)


def test_spectral_affinity_validation():
    X, _ = make_blobs(n_samples=50, n_features=3, centers=2, random_state=1)
    with pytest.raises(ValueError, match="affinity"):
        SpectralClustering(n_clusters=2, affinity="bogus").fit(X)


def test_spectral_linear_affinity_runs():
    X, y = make_blobs(n_samples=120, n_features=4, centers=2, random_state=2)
    sc = SpectralClustering(n_clusters=2, affinity="rbf", gamma=0.3,
                            n_components=60, random_state=0).fit(X)
    assert len(np.unique(sc.labels_.to_numpy())) == 2
