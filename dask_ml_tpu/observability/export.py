"""Chrome-trace / Perfetto export of recorded span JSONL.

``python -m dask_ml_tpu.observability.report trace.jsonl --perfetto
out.json`` converts a recorded run into the Chrome trace-event JSON
format, viewable in ``ui.perfetto.dev`` (or ``chrome://tracing``):

- span records become complete ("X") track events, laned by the thread
  that closed them (span trees nest by containment, exactly how the
  span stack produced them); a merged multi-process input (``report
  --merge``) lanes by (pid, thread) — the pid rides each span id's
  high bits;
- per-span counter deltas (``ctr_*``) become cumulative counter ("C")
  tracks — program FLOPs, h2d bytes, recompiles over time;
- explicit counter snapshots (``log_counters`` records) set the same
  tracks to their absolute totals;
- per-step solver records contribute ``<component>.<metric>`` counter
  tracks (loss / inertia / residual trajectories on the timeline);
- watchdog stall records become instant ("i") events so a stall dump is
  visible at the moment it fired; alert-firing transitions and incident
  captures (``observability/alerts.py``/``incidents.py``) lane the same
  way, so "what was running when the pager went off" is one glance;
- sampled request traces (``req_trace`` records) become per-stage "X"
  slices — queue wait on the admission thread's lane, pack/execute/
  demux on the worker's — linked by flow events ("s"/"f") sharing the
  pid-prefixed trace id, so a request is drawn hopping threads from
  admission to completion.

Timestamps: span records carry absolute ``t_unix``; step records only
carry the sink-relative ``time``. The exporter estimates each sink's
origin PER COMPONENT as the median of (t_unix - time) over span records
carrying both (each fit's MetricsLogger has its own zero-point), with a
global-median fallback, so mixed records land on one consistent
timeline (microsecond ts relative to the earliest event).
"""

from __future__ import annotations

import json

# step-record metrics worth a counter track (same preference list the
# report's convergence column reads)
_STEP_KEYS = ("loss", "inertia", "center_shift2", "primal_residual",
              "score", "opt_residual", "grad_norm")

# span attributes that are structural, not user payload
_SPAN_META = {"span", "span_id", "parent_id", "depth", "time", "t_unix",
              "wall_s", "sync_s", "thread"}

# request-trace stage order (mirrors observability/_requests.STAGES)
# and the names of the consecutive stage-pair slices
_REQ_STAGES = ("admit", "queue_pop", "pack", "dispatch", "execute_done",
               "demux", "complete")
_REQ_DUR = {
    ("admit", "queue_pop"): "queue_wait",
    ("queue_pop", "pack"): "pack",
    ("pack", "dispatch"): "dispatch",
    ("dispatch", "execute_done"): "execute",
    ("execute_done", "demux"): "demux",
    ("demux", "complete"): "resolve",
}


def _origins(records):
    """Per-component estimates of each sink's t=0 (median of
    t_unix - time over span records carrying both), plus a global
    fallback under the ``None`` key. Per-component because one JSONL
    file can hold records from SEVERAL sinks with different zero-points
    (each fit's MetricsLogger stamps ``time`` relative to its own
    creation) — a single global origin would shift the later fit's
    step records by the gap between the fits' start times."""
    by_comp = {}
    for r in records:
        if "t_unix" in r and "time" in r:
            by_comp.setdefault(r.get("component"), []).append(
                float(r["t_unix"]) - float(r["time"])
            )
    out = {}
    all_deltas = []
    for comp, deltas in by_comp.items():
        deltas.sort()
        out[comp] = deltas[len(deltas) // 2]
        all_deltas.extend(deltas)
    all_deltas.sort()
    out.setdefault(None,
                   all_deltas[len(all_deltas) // 2] if all_deltas
                   else 0.0)
    return out


def _abs_time(r, origins):
    if "t_unix" in r:
        return float(r["t_unix"])
    origin = origins.get(r.get("component"), origins[None])
    return origin + float(r.get("time", 0.0))


def to_chrome_trace(records) -> dict:
    """Records (list of dicts, as ``report.load_records`` returns) ->
    Chrome trace-event JSON object."""
    records = [r for r in records if isinstance(r, dict)]
    origins = _origins(records)
    if records:
        # a span's record time is its CLOSE — the earliest event on the
        # timeline is the earliest span START, so subtract durations
        # when establishing the zero point (ts must never go negative)
        base = min(
            _abs_time(r, origins) - float(r.get("wall_s", 0.0) or 0.0)
            for r in records
        )
    else:
        base = 0.0

    def ts(r):
        # clamped at 0: base/abs subtract ~1e9-scale floats whose ulp
        # (~µs) can push the earliest span start epsilon-negative
        return max((_abs_time(r, origins) - base) * 1e6, 0.0)  # µs

    events = []
    tids = {}

    # span ids carry their process in the high bits (_spans pid-prefixes
    # the id counter); a MERGED multi-process trace (report --merge)
    # lanes by (pid, thread) so two processes' "MainThread" spans don't
    # interleave on one lane — single-process traces keep the plain
    # thread name
    span_pids = {r["span_id"] >> 24 for r in records
                 if isinstance(r.get("span_id"), int)}
    span_pids |= {int(r["pid"]) & 0xFFFFFF for r in records
                  if r.get("req_trace") and isinstance(r.get("pid"), int)}
    multi_proc = len(span_pids) > 1

    def lane_of(r):
        name = r.get("thread", "main")
        sid = r.get("span_id")
        if multi_proc and isinstance(sid, int):
            return f"pid{sid >> 24}.{name}"
        return name

    def tid_of(name):
        if name not in tids:
            tids[name] = len(tids) + 1
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1,
                "tid": tids[name], "args": {"name": str(name)},
            })
        return tids[name]

    counters = {}  # counter name -> cumulative value

    def counter_event(name, value, t):
        events.append({
            "name": name, "ph": "C", "pid": 1, "ts": round(t, 3),
            "args": {name: value},
        })

    # cross-process trace joins: federation propagates one trace id
    # through every process a request touches (router + worker, plus
    # reroute survivors), so SEVERAL req_trace records can share an id.
    # Order each id's legs by admit time and chain the flow: the very
    # first leg starts ("s"), middles step ("t"), the very last
    # terminates ("f") — one arrow threading router lane -> worker lane
    # -> survivor lane on the Perfetto timeline.
    req_groups = {}
    for r in records:
        if r.get("req_trace") and isinstance(r.get("trace_id"), int):
            req_groups.setdefault(r["trace_id"], []).append(r)
    flow_pos = {}
    for rs in req_groups.values():
        rs.sort(key=lambda r: _abs_time(r, origins))
        for i, r in enumerate(rs):
            flow_pos[id(r)] = (i == 0, i == len(rs) - 1, len(rs))

    for r in sorted(records, key=lambda r: _abs_time(r, origins)):
        t = ts(r)
        if r.get("drift"):
            # drift-alert instants: the moment a feature crossed the
            # PSI threshold (or a canary flagged a version delta) lands
            # on the timeline next to the spans that served it; quiet
            # drift records stay out of the trace (they would swamp it)
            if r.get("alert"):
                if r.get("pair") == "canary":
                    name = (f"canary alert: {r.get('model')} "
                            f"v{r.get('version_from')}->"
                            f"v{r.get('version_to')}")
                    args = {
                        "disagreement": r.get("disagreement"),
                        "max_quantile_shift":
                            r.get("max_quantile_shift"),
                    }
                else:
                    name = (f"drift alert: {r.get('model')} "
                            f"{r.get('feature')} ({r.get('pair')})")
                    args = {"psi": r.get("psi"), "ks": r.get("ks"),
                            "version": r.get("version")}
                events.append({
                    "name": name, "ph": "i", "s": "g", "pid": 1,
                    "tid": tid_of(lane_of(r)), "ts": round(t, 3),
                    "args": args,
                })
            continue
        if r.get("watchdog"):
            events.append({
                "name": f"watchdog: {r.get('span', '?')} stalled",
                "ph": "i", "s": "g", "pid": 1,
                "tid": tid_of(lane_of(r)),
                "ts": round(t, 3),
                "args": {"age_s": r.get("age_s"),
                         "timeout_s": r.get("timeout_s")},
            })
            continue
        if r.get("alert") and not r.get("drift"):
            # rules-engine transitions (ISSUE 20): firing instants land
            # on the timeline; resolved transitions stay out (the
            # firing mark plus span context already tells the story)
            if r.get("state") == "firing":
                events.append({
                    "name": f"alert firing: {r.get('rule', '?')}",
                    "ph": "i", "s": "g", "pid": 1,
                    "tid": tid_of(lane_of(r)), "ts": round(t, 3),
                    "args": {"metric": r.get("metric"),
                             "value": r.get("value")},
                })
            continue
        if r.get("incident"):
            # black-box captures: the moment a bundle was frozen
            events.append({
                "name": f"incident: {r.get('reason', '?')}",
                "ph": "i", "s": "g", "pid": 1,
                "tid": tid_of(lane_of(r)), "ts": round(t, 3),
                "args": {"path": r.get("path"), "rule": r.get("rule")},
            })
            continue
        if r.get("req_trace"):
            # one request's lifecycle: per-stage "X" slices (queue wait
            # on the ADMISSION thread's lane, everything from queue_pop
            # on the worker's) linked by a flow arrow sharing the
            # pid-prefixed trace id — ui.perfetto.dev draws the request
            # hopping threads
            st = r.get("stages") or {}
            if "admit" not in st:
                continue
            threads = r.get("threads") or {}
            adm = threads.get("admit", "main")
            wrk = threads.get("worker", adm)
            if multi_proc:
                p = int(r.get("pid", 0)) & 0xFFFFFF
                adm = f"pid{p}.{adm}"
                wrk = f"pid{p}.{wrk}"
            rid = r.get("trace_id")
            label = f"req {r.get('method')}#{rid}"
            args = {k: v for k, v in r.items()
                    if k not in ("req_trace", "stages", "durations",
                                 "threads", "time", "t_unix")
                    and isinstance(v, (int, float, str, bool))}
            order = [s for s in _REQ_STAGES if s in st]
            for a, b in zip(order, order[1:]):
                d_us = (float(st[b]) - float(st[a])) * 1e6
                lane = adm if a == "admit" else wrk
                events.append({
                    "name": f"{label}:{_REQ_DUR.get((a, b), f'{a}>{b}')}",
                    "ph": "X", "pid": 1, "tid": tid_of(lane),
                    "ts": round(t + float(st[a]) * 1e6, 3),
                    "dur": round(max(d_us, 0.0), 3),
                    "cat": "request", "args": args,
                })
            first, last, n_legs = flow_pos.get(id(r), (True, True, 1))
            if isinstance(rid, int) and (len(order) > 1 or n_legs > 1):
                start = {
                    "name": label, "ph": "s" if first else "t",
                    "id": rid, "cat": "request", "pid": 1,
                    "tid": tid_of(adm), "ts": round(t, 3),
                }
                end = {
                    "name": label, "ph": "f" if last else "t",
                    "id": rid, "cat": "request", "pid": 1,
                    "tid": tid_of(wrk),
                    "ts": round(t + float(st[order[-1]]) * 1e6, 3),
                }
                if last:
                    end["bp"] = "e"
                events.append(start)
                events.append(end)
            continue
        if "span" in r:
            dur = float(r.get("wall_s", 0.0)) * 1e6
            name = r["span"]
            if r.get("component"):
                name = f"{r['component']}.{name}"
            args = {k: v for k, v in r.items()
                    if k not in _SPAN_META and not k.startswith("ctr_")
                    and isinstance(v, (int, float, str, bool))}
            events.append({
                "name": name, "ph": "X", "pid": 1,
                "tid": tid_of(lane_of(r)),
                "ts": round(max(t - dur, 0.0), 3), "dur": round(dur, 3),
                "args": args,
            })
            # counter deltas: TOP-LEVEL spans only — a parent span's
            # delta already contains every nested child's (one global
            # accumulator), so summing both would double the track
            # (same rule as report.final_counters)
            if r.get("parent_id") is None:
                for k, v in r.items():
                    if k.startswith("ctr_") and isinstance(v,
                                                           (int, float)):
                        cname = k[4:]
                        counters[cname] = counters.get(cname, 0) + v
                        counter_event(cname, counters[cname], t)
            continue
        if r.get("counters"):
            for k, v in r.items():
                if k in ("counters", "time", "t_unix", "step",
                         "component"):
                    continue
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                counters[k] = v  # absolute snapshot overrides the sum
                counter_event(k, v, t)
            continue
        if r.get("component") is not None and r.get("step") is not None:
            for k in _STEP_KEYS:
                if k in r and isinstance(r[k], (int, float)):
                    counter_event(f"{r['component']}.{k}", float(r[k]), t)
                    break

    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(records, path) -> dict:
    """Serialize :func:`to_chrome_trace` to ``path``; returns the trace
    object (tests schema-check it)."""
    trace = to_chrome_trace(records)
    with open(path, "w") as fh:
        json.dump(trace, fh)
    return trace
