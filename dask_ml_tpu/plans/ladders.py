"""Shape policies: the ladders every compiled specialization draws from.

XLA programs are shape-specialized, so every subsystem that feeds
ragged work into compiled entry points needs the same three decisions:
which fixed shapes exist (the rungs), which rung a given workload takes,
and how the padding it pays is masked back out. Before ISSUE 15 those
decisions lived in three hand-maintained copies — serving's geometric
``BucketLadder``, the sparse staging ``_nnz_rung`` ladder, and the
adaptive-search ``_cohort_rungs`` slot ladder. This module is the one
home: each policy keeps its documented semantics as a
:class:`ShapeLadder` subclass, and the padding/mask construction lives
NEXT to the rung choice so a rung and its validity mask can never
diverge.

The three policies differ exactly where their workloads do:

- :class:`GeometricLadder` (serving rows): geometric rungs CLAMPED to
  ``max_rows`` — per-request padding waste matters, and batches taller
  than the top rung are the caller's chunking problem;
- :class:`NnzLadder` (sparse staging): pure geometric rungs, NEVER
  clamped to the observed maximum — clamping the staging capacity to a
  corpus's exact nnz would key the compiled scan shape to the corpus
  instead of its bucket, minting a fresh specialization per corpus;
- :class:`SlotRungLadder` (search cohorts): powers of two below the
  candidate count plus the full count, dropping a power within 25% of
  the full count — warming a near-duplicate rung costs more than its
  padding ever saves.
"""

from __future__ import annotations

import math

import numpy as np

__all__ = ["ShapeLadder", "GeometricLadder", "NnzLadder",
           "SlotRungLadder"]


class ShapeLadder:
    """Base shape policy: a named family of compiled-shape rungs.

    Subclasses implement ``rung_for`` (and usually an iterable rung
    set); the base class co-locates the padding/mask helpers so callers
    never hand-build a mask that disagrees with the rung they chose.
    """

    kind = "shape"

    def rung_for(self, n: int, **kw) -> int:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__

    # -- padding/mask co-location -----------------------------------------
    @staticmethod
    def pad_rows(X, rung: int):
        """``X`` (n, ...) zero-padded up to ``rung`` rows (a no-copy
        passthrough at exact fit). Pairs with :meth:`row_mask`."""
        X = np.asarray(X)
        n = X.shape[0]
        if n == rung:
            return X
        if n > rung:
            raise ValueError(f"{n} rows exceed the rung {rung}")
        out = np.zeros((rung,) + X.shape[1:], X.dtype)
        out[:n] = X
        return out

    @staticmethod
    def row_mask(n: int, rung: int, dtype=np.float32):
        """The validity mask matching :meth:`pad_rows`: 1.0 for the
        ``n`` real rows, 0.0 for the rung's padding tail."""
        m = np.zeros(rung, dtype)
        m[:n] = 1
        return m


class GeometricLadder(ShapeLadder):
    """The geometric sequence of padded batch heights
    (min, min*g, min*g^2, ..., max) — serving's shape policy.

    ``rung_for(n)`` returns the smallest rung >= n; callers chunk
    requests taller than the top rung (``max_rows``) first. The last
    rung CLAMPS to ``max_rows`` exactly: padding waste is paid per
    request here, so the top rung must not overshoot the configured
    maximum. Geometric (not linear) spacing is the padding/compile
    trade: with growth ``g`` the padded rows waste less than
    ``(g-1)/g`` of any batch while the rung count stays logarithmic in
    ``max/min``.
    """

    kind = "rows"

    __slots__ = ("buckets",)

    def __init__(self, min_rows=8, max_rows=1024, growth=2.0):
        if min_rows < 1:
            raise ValueError(f"min_rows must be >= 1, got {min_rows}")
        if max_rows < min_rows:
            raise ValueError(
                f"max_rows={max_rows} < min_rows={min_rows}"
            )
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {growth}")
        rungs = [int(min_rows)]
        while rungs[-1] < max_rows:
            nxt = max(int(math.ceil(rungs[-1] * growth)), rungs[-1] + 1)
            rungs.append(min(nxt, int(max_rows)))
        self.buckets = tuple(rungs)

    @property
    def max_rows(self) -> int:
        return self.buckets[-1]

    def __len__(self):
        return len(self.buckets)

    def __iter__(self):
        return iter(self.buckets)

    def __repr__(self):
        return f"{type(self).__name__}{self.buckets}"

    def describe(self) -> str:
        return f"{self.kind}{self.buckets}"

    def rung_for(self, n_rows: int) -> int:
        """Smallest rung >= n_rows. Raises for batches taller than the
        top rung — the caller must chunk those, padding DOWN would drop
        rows and padding up past max would mint a novel shape."""
        if n_rows > self.buckets[-1]:
            raise ValueError(
                f"batch of {n_rows} rows exceeds the top bucket "
                f"{self.buckets[-1]}; chunk before bucketing"
            )
        for b in self.buckets:
            if b >= n_rows:
                return b
        raise AssertionError("unreachable")  # pragma: no cover

    # serving's historical spelling (BucketLadder API)
    def bucket_for(self, n_rows: int) -> int:
        return self.rung_for(n_rows)

    def padding_for(self, n_rows: int) -> int:
        """Rows of padding the ladder charges a batch of ``n_rows``."""
        return self.rung_for(n_rows) - n_rows


class NnzLadder(ShapeLadder):
    """The sparse-staging nnz policy: geometric from ``min_nnz``,
    deliberately NEVER clamped to an observed maximum.

    ``rung_for(nnz, top=...)`` clips to ``top`` — callers pass the max
    RUNG any block of their plan needs (itself computed with
    ``top=0``), so the staging capacity always stays a geometric rung:
    keying the compiled scan shape to a corpus's exact nnz would mint a
    fresh specialization per corpus (the exact failure mode the serving
    ladder's clamp is harmless against, and this one is not).
    """

    kind = "nnz"

    __slots__ = ("min_nnz", "growth")

    def __init__(self, min_nnz=128, growth=2.0):
        if min_nnz < 1:
            raise ValueError(f"min_nnz must be >= 1, got {min_nnz}")
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {growth}")
        self.min_nnz = int(min_nnz)
        self.growth = float(growth)

    def __repr__(self):
        return (f"NnzLadder(min_nnz={self.min_nnz}, "
                f"growth={self.growth})")

    def describe(self) -> str:
        return f"nnz(geometric {self.min_nnz}x{self.growth}, no clamp)"

    def rung_for(self, nnz: int, top: int = 0) -> int:
        """Smallest geometric rung >= nnz, clipped to ``top``'s own
        rung when ``top`` is given (0 = unclipped)."""
        r = self.min_nnz
        while r < nnz:
            r = int(np.ceil(r * self.growth))
        return min(r, max(top, 1)) if top else r

    def rungs_to(self, top: int) -> tuple:
        """Every rung up to (and including) ``top``'s rung — the grid a
        warmer walks."""
        out, r = [], self.min_nnz
        cap = self.rung_for(top)
        while r < cap:
            out.append(r)
            r = int(np.ceil(r * self.growth))
        out.append(cap)
        return tuple(out)

    @staticmethod
    def pad_triple(data, cols, rows, rung: int):
        """The COO-expanded triple zero-padded to ``rung`` entries —
        the sparse twin of :meth:`ShapeLadder.pad_rows` (zero values /
        zero row-ids: padding entries contribute nothing to a
        segment_sum)."""
        nnz = len(data)
        if nnz > rung:
            raise ValueError(f"{nnz} nonzeros exceed the rung {rung}")
        d = np.zeros(rung, np.float32)
        c = np.zeros(rung, np.int32)
        r = np.zeros(rung, np.int32)
        d[:nnz] = data
        c[:nnz] = cols
        r[:nnz] = rows
        return d, c, r


class SlotRungLadder(ShapeLadder):
    """The search-cohort slot-width policy: powers of two below the
    candidate count, then the full count; a power within 25% of the
    full count is dropped (warming a near-duplicate rung costs more
    than its padding ever saves). Every rung compiles during a
    search's first round, so a shrinking bracket later picks its rung
    at zero new compiles."""

    kind = "slots"

    __slots__ = ()

    def __repr__(self):
        return "SlotRungLadder()"

    def describe(self) -> str:
        return "slots(pow2 + full, 25% dedup)"

    def rungs_for(self, n_slots: int) -> list:
        n_slots = max(int(n_slots), 1)
        out, r = [], 1
        while r < n_slots:
            out.append(r)
            r *= 2
        if out and out[-1] * 4 >= n_slots * 3:
            out.pop()
        out.append(n_slots)
        return out

    def rung_for(self, n_active: int, n_slots: int) -> int:
        for r in self.rungs_for(n_slots):
            if r >= n_active:
                return r
        return max(int(n_slots), 1)
