"""Shape-bucket ladder for the online serving path.

XLA programs are shape-specialized: a naive server that pads each
micro-batch to its exact row count compiles a fresh program per novel
size — unbounded compile debt under ragged traffic. The ladder fixes a
small geometric set of batch heights (min, min*g, min*g^2, ..., max);
every emitted batch is padded UP to the smallest rung that fits, so
steady-state serving touches at most ``len(ladder)`` compiled programs
per method, all of which ``ModelServer.warmup()`` can compile before the
first request arrives.

Geometric (not linear) spacing is the padding/compile trade: with growth
``g`` the padded rows waste less than ``(g-1)/g`` of any batch while the
rung count stays logarithmic in ``max/min``.
"""

from __future__ import annotations

import math

__all__ = ["BucketLadder"]


class BucketLadder:
    """The geometric sequence of padded batch heights.

    ``bucket_for(n)`` returns the smallest rung >= n; callers chunk
    requests taller than the top rung (``max_rows``) before asking.
    """

    __slots__ = ("buckets",)

    def __init__(self, min_rows=8, max_rows=1024, growth=2.0):
        if min_rows < 1:
            raise ValueError(f"min_rows must be >= 1, got {min_rows}")
        if max_rows < min_rows:
            raise ValueError(
                f"max_rows={max_rows} < min_rows={min_rows}"
            )
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {growth}")
        rungs = [int(min_rows)]
        while rungs[-1] < max_rows:
            nxt = max(int(math.ceil(rungs[-1] * growth)), rungs[-1] + 1)
            rungs.append(min(nxt, int(max_rows)))
        self.buckets = tuple(rungs)

    @classmethod
    def from_config(cls):
        from ..config import get_config

        cfg = get_config()
        return cls(
            min_rows=cfg.serving_min_batch,
            max_rows=cfg.serving_max_batch,
            growth=cfg.serving_bucket_growth,
        )

    @property
    def max_rows(self) -> int:
        return self.buckets[-1]

    def __len__(self):
        return len(self.buckets)

    def __iter__(self):
        return iter(self.buckets)

    def __repr__(self):
        return f"BucketLadder{self.buckets}"

    def bucket_for(self, n_rows: int) -> int:
        """Smallest rung >= n_rows. Raises for batches taller than the
        top rung — the batcher must chunk those, padding DOWN would drop
        rows and padding up past max would mint a novel shape."""
        if n_rows > self.buckets[-1]:
            raise ValueError(
                f"batch of {n_rows} rows exceeds the top bucket "
                f"{self.buckets[-1]}; chunk before bucketing"
            )
        for b in self.buckets:
            if b >= n_rows:
                return b
        raise AssertionError("unreachable")  # pragma: no cover

    def padding_for(self, n_rows: int) -> int:
        """Rows of padding the ladder charges a batch of ``n_rows``."""
        return self.bucket_for(n_rows) - n_rows
