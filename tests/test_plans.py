"""The plans subsystem (ISSUE 15): shape ladders, ProgramPlan cache
keying, the WarmupRegistry, jaxpr byte-identity for every migrated
client (serving dense/sparse/int8, the stacked C-grid/OvR solves, the
superblock scan builders), and the naive_bayes onboarding (streamed fit
+ warmed serving at zero steady-state compiles)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dask_ml_tpu import config, plans
from dask_ml_tpu import observability as obs
from dask_ml_tpu.plans import (GeometricLadder, NnzLadder, ProgramPlan,
                               SlotRungLadder, warmups)


# -- shape ladders -----------------------------------------------------------

def test_geometric_ladder_rungs_and_clamp():
    lad = GeometricLadder(8, 100, 2.0)
    assert lad.buckets == (8, 16, 32, 64, 100)   # top rung CLAMPS
    assert lad.rung_for(1) == 8
    assert lad.rung_for(17) == 32
    assert lad.rung_for(100) == 100
    assert lad.padding_for(17) == 15
    with pytest.raises(ValueError):
        lad.rung_for(101)                        # chunk, don't pad down
    with pytest.raises(ValueError):
        GeometricLadder(0, 10)
    with pytest.raises(ValueError):
        GeometricLadder(16, 8)
    with pytest.raises(ValueError):
        GeometricLadder(8, 64, growth=1.0)


def test_bucket_ladder_is_the_plans_geometric_ladder():
    from dask_ml_tpu.serving._buckets import BucketLadder

    lad = BucketLadder(8, 128, 2.0)
    assert isinstance(lad, GeometricLadder)
    assert lad.bucket_for(9) == lad.rung_for(9) == 16
    assert repr(lad).startswith("BucketLadder")


def test_nnz_ladder_never_clamps_to_observed_max():
    lad = NnzLadder(min_nnz=128, growth=2.0)
    # a corpus peaking at 5000 nnz stages at the PURE rung 8192 — never
    # the observed max (clamping would mint a shape per corpus)
    assert lad.rung_for(5000) == 8192
    assert lad.rung_for(1) == 128
    assert lad.rung_for(128) == 128
    # callers pass an already-rung top (the max rung any block needs);
    # the clip is to that value, not a fresh clamp policy
    assert lad.rung_for(5000, top=512) == 512
    assert lad.rungs_to(1000) == (128, 256, 512, 1024)
    # ... and matches the sparse staging ladder exactly
    from dask_ml_tpu.parallel.sparse_stream import _nnz_rung

    for nnz in (1, 100, 128, 129, 5000, 100_000):
        assert _nnz_rung(nnz, 0) == lad.rung_for(nnz)


def test_slot_rung_ladder_matches_cohort_policy():
    lad = SlotRungLadder()
    assert lad.rungs_for(8) == [1, 2, 4, 8]
    assert lad.rungs_for(12) == [1, 2, 4, 8, 12]
    # near-duplicate top power dropped: 4 is within 25% of 5
    assert lad.rungs_for(5) == [1, 2, 5]
    assert lad.rung_for(3, 8) == 4
    assert lad.rung_for(8, 8) == 8
    from dask_ml_tpu.models.sgd import _cohort_rung_of, _cohort_rungs

    for n in (1, 2, 5, 8, 12, 33):
        assert _cohort_rungs(n) == lad.rungs_for(n)
        assert _cohort_rung_of(max(n // 2, 1), n) == \
            lad.rung_for(max(n // 2, 1), n)


def test_pad_rows_and_mask_colocated():
    lad = GeometricLadder(4, 64, 2.0)
    X = np.arange(12, dtype=np.float32).reshape(6, 2)
    rung = lad.rung_for(6)
    Xp = lad.pad_rows(X, rung)
    m = lad.row_mask(6, rung)
    assert Xp.shape == (rung, 2) and m.shape == (rung,)
    assert np.all(Xp[:6] == X) and np.all(Xp[6:] == 0)
    assert m.sum() == 6 and np.all(m[:6] == 1)
    # exact fit passes through without a copy
    assert lad.pad_rows(X, 6) is X
    with pytest.raises(ValueError):
        lad.pad_rows(X, 4)


def test_nnz_pad_triple():
    d, c, r = NnzLadder.pad_triple(
        np.ones(3, np.float32), np.arange(3), np.arange(3), 8
    )
    assert d.shape == c.shape == r.shape == (8,)
    assert d[:3].sum() == 3 and d[3:].sum() == 0
    with pytest.raises(ValueError):
        NnzLadder.pad_triple(np.ones(9), np.arange(9), np.arange(9), 8)


# -- ProgramPlan cache keying ------------------------------------------------

def _body(a, b):
    return a + b


def test_plan_cache_identical_specs_hit():
    p1 = ProgramPlan(name="test.plan.hit", body=_body,
                     key=("k", 1)).build()
    p2 = ProgramPlan(name="test.plan.hit", body=_body,
                     key=("k", 1)).build()
    assert p1 is p2
    x = jnp.ones(3)
    np.testing.assert_allclose(np.asarray(p1(x, x)), 2.0)


def test_plan_cache_differing_specs_miss():
    base = dict(name="test.plan.miss", body=_body)
    p = ProgramPlan(key=("mesh1", "f32", (), 8), **base).build()
    # differing mesh / dtype-mxu / donation / ladder rung all MISS
    assert ProgramPlan(key=("mesh2", "f32", (), 8), **base).build() \
        is not p
    assert ProgramPlan(key=("mesh1", "bf16", (), 8), **base).build() \
        is not p
    assert ProgramPlan(key=("mesh1", "f32", (), 8), donate=(0,),
                       **base).build() is not p
    assert ProgramPlan(key=("mesh1", "f32", (), 16), **base).build() \
        is not p
    # and a differing program name misses even at an equal key
    assert ProgramPlan(name="test.plan.miss2", body=_body,
                       key=("mesh1", "f32", (), 8)).build() is not p


def test_plan_cache_off_builds_fresh():
    with config.set(plan_cache=False):
        p1 = ProgramPlan(name="test.plan.off", body=_body,
                         key=("k",)).build()
        p2 = ProgramPlan(name="test.plan.off", body=_body,
                         key=("k",)).build()
    assert p1 is not p2


def test_plan_build_counters_move():
    obs.counters_reset()
    ProgramPlan(name="test.plan.ctr", body=_body, key=("c", 1)).build()
    ProgramPlan(name="test.plan.ctr", body=_body, key=("c", 1)).build()
    snap = obs.counters_snapshot()
    assert snap.get("plan_builds", 0) >= 1
    assert snap.get("plan_cache_hits", 0) >= 1


# -- WarmupRegistry ----------------------------------------------------------

def test_warmup_registry_idempotent_and_attributable():
    calls = []
    key = ("test-warm", id(test_warmup_registry_idempotent_and_attributable))
    obs.counters_reset()
    ran = warmups.warm(key, lambda: calls.append(1),
                       program="test.warm.prog", ladder="test-rows",
                       rung=32)
    assert ran and calls == [1]
    ran2 = warmups.warm(key, lambda: calls.append(1),
                        program="test.warm.prog", ladder="test-rows",
                        rung=32)
    assert not ran2 and calls == [1]          # idempotent
    snap = obs.counters_snapshot()
    assert snap.get("plan_warmups", 0) >= 1
    assert snap.get("plan_cache_hits", 0) >= 1
    rows = [r for r in warmups.snapshot()
            if r["program"] == "test.warm.prog"]
    assert rows and rows[0]["rungs"] == "32" \
        and rows[0]["warmups"] == 1 and rows[0]["warm_hits"] == 1
    # plan_rewarm forces re-execution
    with config.set(plan_rewarm=True):
        assert warmups.warm(key, lambda: calls.append(1))
    assert calls == [1, 1]


# -- jaxpr byte-identity for the migrated clients ----------------------------

def _jaxprs_match(tracked_fn, jit_kwargs, args, static_kwargs=None):
    """The plan-built entry point's jaxpr vs a hand-assembled
    ``jax.jit(raw_body, <the pre-migration flags>)`` — byte equality
    proves the plan layer changed plumbing only, never the traced
    computation."""
    static_kwargs = static_kwargs or {}
    ref = jax.jit(tracked_fn.__wrapped__, **jit_kwargs)

    def call_plan(*xs):
        return tracked_fn.__wrapped_jit__(*xs, **static_kwargs)

    def call_ref(*xs):
        return ref(*xs, **static_kwargs)

    a = str(jax.make_jaxpr(call_plan)(*args))
    b = str(jax.make_jaxpr(call_ref)(*args))
    return a == b


def test_jaxpr_identity_serving_dense_and_int8():
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.wrappers import compiled_batch_fn, _donate_spec

    rng = np.random.RandomState(0)
    X = rng.randn(64, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    clf = SGDClassifier(max_iter=2, random_state=0).fit(X, y)
    donate = _donate_spec()
    kw = {"donate_argnums": donate} if donate else {}
    for quant in (None, "int8"):
        fn = compiled_batch_fn(clf, "predict", quantize=quant)
        params, _post = fn._state
        assert _jaxprs_match(fn._fn, kw, (params, X[:8]))


def test_jaxpr_identity_serving_sparse():
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.wrappers import sparse_batch_fn

    rng = np.random.RandomState(1)
    X = rng.randn(64, 16).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    clf = SGDClassifier(max_iter=2, random_state=0).fit(X, y)
    fn = sparse_batch_fn(clf, "predict")
    assert fn is not None
    params, _post = fn._state
    nnz = 32
    args = (params, np.zeros(nnz, np.float32),
            np.zeros(nnz, np.int32), np.zeros(nnz, np.int32))
    # static n_rows: bind it on both sides
    tracked = fn._fn
    ref = jax.jit(tracked.__wrapped__, static_argnums=(4,))
    a = str(jax.make_jaxpr(
        lambda *xs: tracked.__wrapped_jit__(*xs, 8))(*args))
    b = str(jax.make_jaxpr(lambda *xs: ref(*xs, 8))(*args))
    assert a == b


def test_jaxpr_identity_stacked_c_grid_solves():
    import optax

    from dask_ml_tpu.models.solvers import solvers as S

    n, d, k, C = 32, 4, 2, 3
    rng = np.random.RandomState(2)
    X = jnp.asarray(rng.randn(n, d), jnp.float32)
    y = jnp.asarray((rng.randn(n) > 0), jnp.float32)
    Y = jnp.asarray(rng.rand(C, n) > 0.5, jnp.float32)
    mask = jnp.ones(n, jnp.float32)
    pmask = jnp.ones(d, jnp.float32)
    lams = jnp.asarray(np.logspace(-3, -1, k), jnp.float32)
    opt = optax.lbfgs(memory_size=10)

    def carry_of(width):
        b0 = jnp.zeros((width,), jnp.float32)
        return (b0, opt.init(b0), jnp.asarray(jnp.inf, b0.dtype), 0)

    stop_it = jnp.asarray(3)
    tol = jnp.asarray(1e-6, jnp.float32)
    cases = [
        (S._lam_grid_chunk,
         {"static_argnames": ("family", "reg", "k", "memory")},
         (X, y, mask, n, carry_of(k * d), lams, pmask, stop_it, tol),
         {"family": "logistic", "reg": "l2", "k": k}),
        (S._lam_grid_multi_chunk,
         {"static_argnames": ("family", "reg", "k", "C", "memory")},
         (X, Y, mask, n, carry_of(k * C * d), lams, pmask, stop_it,
          tol),
         {"family": "logistic", "reg": "l2", "k": k, "C": C}),
        (S._multi_stacked_chunk,
         {"static_argnames": ("family", "reg", "C", "memory")},
         (X, Y, mask, n, carry_of(C * d), jnp.asarray(0.1), pmask,
          jnp.asarray(0.0), stop_it, tol),
         {"family": "logistic", "reg": "l2", "C": C}),
    ]
    for tracked, kw, args, statics in cases:
        assert _jaxprs_match(tracked, kw, args, static_kwargs=statics), \
            tracked.program_name


def test_jaxpr_identity_superblock_scan():
    from dask_ml_tpu.models.solvers.streamed import _sb_reducer

    tracked = _sb_reducer("vg", "normal", True, None)
    K, S, d = 2, 16, 4
    rng = np.random.RandomState(3)
    Xs = jnp.asarray(rng.randn(K, S, d), jnp.float32)
    ys = jnp.asarray(rng.randn(K, S), jnp.float32)
    counts = jnp.full((K,), S, jnp.int32)
    beta = jnp.zeros(d + 1, jnp.float32)        # intercept slot
    acc = (jnp.zeros((), jnp.float32), jnp.zeros(d + 1, jnp.float32))
    assert _jaxprs_match(tracked, {"donate_argnums": (0,)},
                         (acc, beta, Xs, ys, counts))


# -- plans table / attribution ----------------------------------------------

def test_programs_snapshot_carries_plan_attribution():
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.serving import ModelServer
    from dask_ml_tpu.serving._buckets import BucketLadder

    rng = np.random.RandomState(4)
    X = rng.randn(128, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    clf = SGDClassifier(max_iter=1, random_state=0).fit(X, y)
    obs.programs_reset()
    with config.set(obs_programs=True):
        srv = ModelServer(clf, methods=("predict",),
                          ladder=BucketLadder(8, 16, 2.0))
        srv.warmup()
    rows = {p["program"]: p for p in obs.programs_snapshot()}
    row = rows.get("serving.SGDClassifier.predict")
    assert row is not None
    assert row.get("plan") == "serving"
    assert str(row.get("ladder_rung", "")).startswith("serving-rows:")
    # the plans snapshot names the rungs that minted specializations
    prow = [r for r in plans.plans_snapshot()
            if r["program"] == "serving.SGDClassifier.predict"]
    assert prow and "8" in prow[0]["rungs"]


def test_report_renders_plan_column_and_plans_table(tmp_path):
    from dask_ml_tpu.observability.report import (build_report,
                                                  report_data)

    records = [
        {"programs": [
            {"program": "serving.SGDClassifier.predict", "compiles": 2,
             "compile_s": 0.1, "calls": 4, "flops_per_call": 1e6,
             "flops_total": 4e6, "exec_s": 0.01,
             "hbm_peak_bytes": 1 << 20, "plan": "serving",
             "ladder_rung": "serving-rows:8,16"}],
         "plans": [
            {"program": "serving.SGDClassifier.predict",
             "plan": "serving", "ladder": "serving-rows",
             "rungs": "8,16", "warmups": 2, "warm_hits": 1}]},
    ]
    out = build_report(records)
    assert "plan" in out and "serving-rows:8,16" in out
    assert "plans (execution plans: ladder rungs / warmups)" in out
    data = report_data(records)
    assert data["plans"][0]["rungs"] == "8,16"        # --json mirrors
    assert data["programs"][0]["ladder_rung"] == "serving-rows:8,16"


def test_report_without_plans_is_unchanged():
    from dask_ml_tpu.observability.report import build_report

    records = [{"programs": [
        {"program": "glm.lbfgs", "compiles": 1, "compile_s": 0.1,
         "calls": 1, "flops_per_call": 1e6, "flops_total": 1e6,
         "exec_s": 0.0, "hbm_peak_bytes": 1 << 20}]}]
    out = build_report(records)
    assert "programs (XLA cost/memory per compiled entry point)" in out
    # no plan attribution anywhere -> the legacy table shape (no plan
    # column header on the programs table)
    header = [ln for ln in out.splitlines()
              if ln.startswith("program ")][0]
    assert "plan" not in header


# -- the onboarded estimator: streamed fit + warmed serving ------------------

def test_naive_bayes_streamed_fit_and_served_predict_zero_compiles():
    from dask_ml_tpu.naive_bayes import GaussianNB
    from dask_ml_tpu.serving import ModelServer
    from dask_ml_tpu.serving._buckets import BucketLadder
    from dask_ml_tpu.wrappers import Incremental

    rng = np.random.RandomState(5)
    X = np.concatenate([rng.randn(2000, 6) + 2,
                        rng.randn(2000, 6) - 2]).astype(np.float32)
    y = np.concatenate([np.zeros(2000), np.ones(2000)])
    p = rng.permutation(len(y))
    X, y = X[p], y[p]

    ref = GaussianNB().fit(X, y)
    inc = Incremental(GaussianNB(), shuffle_blocks=True, random_state=0)
    inc.fit(X, y)                       # pass 1 mints the block rungs
    obs.counters_reset()
    inc.partial_fit(X, y)               # pass 2: zero new compiles
    assert obs.counters_snapshot().get("recompiles", 0) == 0
    est = inc.estimator_
    np.testing.assert_allclose(est.theta_, ref.theta_, atol=1e-3)
    np.testing.assert_allclose(est.class_prior_, ref.class_prior_,
                               atol=1e-6)
    assert est.score(X, y) > 0.95

    srv = ModelServer(est, methods=("predict", "predict_proba"),
                      ladder=BucketLadder(8, 64, 2.0))
    srv.warmup()
    # the reference outputs run BEFORE the counter reset: each direct
    # predict at a novel request shape pays its own (off-ladder) compile
    sizes = (3, 17, 60, 9, 64)
    expect = {n: est.predict(X[:n]) for n in sizes}
    expect_proba = est.predict_proba(X[:33])
    obs.counters_reset()
    with srv:
        for n in sizes:
            np.testing.assert_array_equal(srv.predict(X[:n]),
                                          expect[n])
        proba = srv.predict_proba(X[:33])
    assert obs.counters_snapshot().get("recompiles", 0) == 0
    np.testing.assert_allclose(proba, expect_proba, atol=1e-4)


def test_naive_bayes_partial_fit_contract():
    from dask_ml_tpu.naive_bayes import GaussianNB

    rng = np.random.RandomState(6)
    X = rng.randn(100, 3).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    nb = GaussianNB()
    with pytest.raises(ValueError):
        nb.partial_fit(X, y)            # first call needs classes=
    nb.partial_fit(X[:50], y[:50], classes=[0.0, 1.0])
    nb.partial_fit(X[50:], y[50:])
    ref = GaussianNB().fit(X, y)
    np.testing.assert_allclose(nb.theta_, ref.theta_, atol=1e-4)
    with pytest.raises(ValueError):
        nb.partial_fit(X[:4], np.full(4, 7.0))   # unseen label refuses
    with pytest.raises(ValueError):
        nb.partial_fit(X[:4, :2], y[:4])         # width change refuses


def test_naive_bayes_hot_swap_through_serving():
    from dask_ml_tpu.naive_bayes import GaussianNB
    from dask_ml_tpu.serving import ModelServer
    from dask_ml_tpu.serving._buckets import BucketLadder

    rng = np.random.RandomState(7)
    X = rng.randn(300, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    a = GaussianNB().fit(X, y)
    b = GaussianNB().fit(X + 0.5, y)
    srv = ModelServer(a, methods=("predict",),
                      ladder=BucketLadder(8, 32, 2.0))
    srv.warmup()
    obs.counters_reset()
    with srv:
        srv.swap_model(b)
        out = srv.predict(X[:16])
    assert obs.counters_snapshot().get("recompiles", 0) == 0
    np.testing.assert_array_equal(out, b.predict(X[:16]))
