from . import glm
