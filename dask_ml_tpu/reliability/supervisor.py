"""Replica supervision: dead fleet replicas get REBUILT, not mourned.

Before this module the fleet's answer to a dead replica was "stop
routing to it" (``serving/fleet.py::NoHealthyReplicas``: "this needs
replicas restarted, not a retry" — and nothing restarted them).
:class:`ReplicaSupervisor` is the background daemon that closes the
loop:

- it polls the fleet's replicas (``config.serving_supervise_interval_s``)
  and, for each one whose worker thread died, builds a FRESH
  :class:`~dask_ml_tpu.serving.ModelServer` at the registry's CURRENT
  version **off the serving path** — the replacement compiles and warms
  its (method, bucket) grid on the supervisor thread while the
  survivors keep answering traffic — and only then swaps it into the
  routing tuple;
- the dead replica's still-queued requests are drained onto the fresh
  replica (counted as reroutes), so a worker crash loses ZERO admitted
  requests — in-flight protection is the worker's own batch guard;
- restarts are budgeted per replica slot
  (``config.serving_restart_budget``): a crash-looping replica degrades
  to PERMANENT failover (its stale gauges dropped, its queue failed
  typed) instead of burning the fleet's compute on rebuild loops;
- a publish racing the rebuild converges: after installation the fresh
  replica is re-checked against the registry's current version and
  swapped forward if a newer one landed mid-rebuild.

Armed by ``FleetServer.start()`` when ``config.serving_supervise`` is
on (default off: restart-on-death is an operational policy, not a
universal default — failover-only fleets keep today's behavior).
"""

from __future__ import annotations

import dataclasses
import threading

__all__ = ["ReplicaSupervisor"]


class ReplicaSupervisor:
    """Watch one fleet; rebuild dead replicas off the serving path."""

    def __init__(self, fleet, interval_s=None, budget=None):
        from ..config import get_config

        cfg = get_config()
        self.fleet = fleet
        self.interval_s = float(
            cfg.serving_supervise_interval_s if interval_s is None
            else interval_s
        )
        self.budget = int(
            cfg.serving_restart_budget if budget is None else budget
        )
        self._cfg = cfg          # the supervisor thread re-applies it
        self._restarts: dict[int, int] = {}   # replica slot -> restarts
        self._failed: set[int] = set()        # permanently failed slots
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dask-ml-tpu-replica-supervisor",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def restarts(self) -> dict:
        return dict(self._restarts)

    # -- loop --------------------------------------------------------------
    def _run(self):
        from .. import config

        # thread-local config: warmup compiles, counters and fault
        # gates on this thread must follow the fleet creator's config,
        # not daemon-thread defaults
        with config.set(**dataclasses.asdict(self._cfg)):
            while not self._stop.wait(self.interval_s):
                try:
                    self._sweep()
                except Exception:
                    # supervision must never take the process down; the
                    # next tick retries
                    pass

    def _sweep(self):
        fleet = self.fleet
        if not getattr(fleet, "_started", False):
            return
        for idx, r in enumerate(fleet.replicas):
            if r.healthy or idx in self._failed:
                continue
            used = self._restarts.get(idx, 0)
            if used >= self.budget:
                self._permanent_failure(idx, r)
                continue
            self._restarts[idx] = used + 1
            self._restart(idx, r)

    # -- actions -----------------------------------------------------------
    def _restart(self, idx, dead):
        """Rebuild replica slot ``idx`` at the registry's current
        version, warmed BEFORE it rejoins routing."""
        from ..observability._counters import record_replica_restart
        from ..serving import metrics as smetrics

        fleet = self.fleet
        dead._accepting = False     # new traffic routes around it now
        try:
            mv = fleet.registry.get(fleet.name)
        except KeyError:
            return
        fresh = fleet._make_replica(idx, mv.estimator, mv.version)
        q = getattr(mv, "quantize", None)
        if q:
            # the ctor builds the f32 flavor; a quantized current
            # version installs via the paid rebuild path — we are off
            # the serving path by construction here
            fresh.rebuild_model(mv.estimator, version=mv.version,
                                warm=False, quantize=q)
        if getattr(dead, "_warmed", False):
            fresh.warmup()          # compiles land HERE, not on traffic
        fresh.start()
        with fleet._lock:
            if not fleet._started:
                fresh.stop(drain=False)
                return
            reps = list(fleet.replicas)
            reps[idx] = fresh
            fleet.replicas = tuple(reps)
        record_replica_restart()
        smetrics.set_replica_gauges(fresh.replica_id,
                                    version=fresh.model_version,
                                    healthy=True)
        self._requeue(dead, fresh)
        # a publish may have landed while the rebuild ran; converge to
        # the registry's CURRENT version like fleet._on_publish does
        try:
            cur = fleet.registry.get(fleet.name)
        except KeyError:
            cur = None
        if cur is not None and cur.version != fresh.model_version:
            from ..wrappers import ParamSwapError

            qv = getattr(cur, "quantize", None)
            try:
                fresh.swap_model(cur.estimator, version=cur.version,
                                 quantize=qv)
            except ParamSwapError:
                fresh.rebuild_model(cur.estimator, version=cur.version,
                                    quantize=qv)

    def _requeue(self, dead, fresh):
        """Drain the dead replica's admitted-but-unserved requests onto
        the fresh one — zero admitted requests lost to a worker crash."""
        from ..serving import metrics as smetrics
        from ..serving._batching import fail_requests

        try:
            reqs = dead._queue.drain_all()
        except Exception:
            return
        if not reqs:
            return
        for r in reqs:
            # requeued traces record the corpse they drained off of —
            # the same rerouted_from tag the fleet's failover loop sets
            if r.trace is not None:
                r.trace.tag(rerouted_from=dead.replica_id,
                            replica=fresh.replica_id)
        verdict = fresh._queue.put_many(reqs)
        if verdict == "ok":
            for _ in reqs:
                smetrics.record_reroute()
            return
        from ..serving._server import ServerClosed

        fail_requests(reqs, ServerClosed(
            "replica died and its replacement could not absorb the "
            "backlog"
        ), outcome="closed")

    def _permanent_failure(self, idx, dead):
        """Budget exhausted: the slot degrades to permanent failover —
        queue failed typed, stale per-replica gauges dropped so /metrics
        stops advertising a corpse."""
        from ..observability._counters import record_replica_failure
        from ..serving import metrics as smetrics
        from ..serving._batching import fail_requests
        from ..serving._server import ServerClosed

        self._failed.add(idx)
        dead._accepting = False
        try:
            fail_requests(dead._queue.drain_all(), ServerClosed(
                f"replica {dead.replica_id} exceeded its restart budget "
                f"({self.budget}); permanently failed over"
            ), outcome="closed")
        except Exception:
            pass
        record_replica_failure()
        smetrics.drop_replica_gauges(dead.replica_id)
