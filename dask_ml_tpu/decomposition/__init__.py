"""Ref: dask_ml/decomposition/__init__.py."""
from ..models.pca import PCA, IncrementalPCA, TruncatedSVD

__all__ = ["PCA", "TruncatedSVD", "IncrementalPCA"]
