"""Adaptive hyperparameter search: HyperbandSearchCV over device-resident
SGD trials. Homogeneous surviving trials advance as ONE vmapped program
(N models per step); under jax.distributed, brackets distribute across
hosts automatically.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

N = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 50_000))

from dask_ml_tpu.model_selection import HyperbandSearchCV
from dask_ml_tpu.models.sgd import SGDClassifier

rng = np.random.RandomState(0)
X = rng.randn(N, 32).astype(np.float32)
w = rng.randn(32)
y = (X @ w > 0).astype(np.float32)

search = HyperbandSearchCV(
    SGDClassifier(tol=1e-3, random_state=0),
    {"alpha": [1e-5, 1e-4, 1e-3, 1e-2], "eta0": [0.01, 0.1, 0.5]},
    max_iter=9, aggressiveness=3, random_state=0,
)
search.fit(X, y, classes=[0.0, 1.0])
print("best params:", search.best_params_)
print("best score:", round(search.best_score_, 4))
print("models trained:", search.metadata_["n_models"],
      "| total partial_fit calls:", search.metadata_["partial_fit_calls"])
