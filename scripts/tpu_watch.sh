#!/bin/bash
# Probe the axon TPU tunnel until it answers, then run the smoke suite.
# The tunnel hangs (rather than raises) when wedged, so every probe runs
# in a killable subprocess. Logs to /tmp/tpu_watch.log.
LOG=/tmp/tpu_watch.log
: > "$LOG"
STATE=/tmp/smoke_r5_state.json
REPO=$(dirname "$0")/..
for i in $(seq 1 60); do
  echo "[$(date +%H:%M:%S)] probe $i" >> "$LOG"
  if timeout 150 python -c "import jax; d=jax.devices(); assert d" \
      >> "$LOG" 2>&1; then
    # the resumable-smoke state is only valid for the code it passed
    # on: re-check HEAD at EVERY launch (commits land while the loop
    # probes) so changed code re-runs every surface
    SHA=$(git -C "$REPO" rev-parse HEAD 2>/dev/null)
    if [ -f "$STATE.sha" ] && [ "$(cat "$STATE.sha")" != "$SHA" ]; then
      rm -f "$STATE"
    fi
    echo "$SHA" > "$STATE.sha"
    echo "[$(date +%H:%M:%S)] tunnel UP — launching smoke" >> "$LOG"
    TPU_SMOKE_STATE="$STATE" \
      timeout 3300 python -u scripts/tpu_smoke.py > /tmp/smoke_r5.log 2>&1
    rc=$?
    echo "rc=$rc" >> /tmp/smoke_r5.log
    echo "[$(date +%H:%M:%S)] smoke rc=$rc" >> "$LOG"
    if [ $rc -eq 0 ]; then
      # the state has served its purpose — clear it so the NEXT launch
      # re-runs everything instead of reporting green without executing
      rm -f "$STATE" "$STATE.sha"
      # bank TPU bench numbers while the tunnel window is open
      echo "[$(date +%H:%M:%S)] smoke green — running bench" >> "$LOG"
      BENCH_CHILD=1 BENCH_SKIP_PROBE=1 timeout 2000 \
        python bench.py > /tmp/bench_r5_tpu.json 2> /tmp/bench_r5_tpu.err
      echo "[$(date +%H:%M:%S)] bench rc=$?" >> "$LOG"
      exit 0
    fi
    # rc=124 is the timeout kill: the tunnel wedged at init or mid-run
    # (even after some OK lines) — loop back to probing either way.
    # Any other nonzero rc with surface results (incl. a resumed run
    # that only printed SKIPs before a native crash) is a genuine FAIL:
    # stop for triage rather than burning tunnel windows on broken code.
    if [ $rc -ne 124 ] && grep -qE "OK|FAIL|SKIP" /tmp/smoke_r5.log; then
      exit $rc
    fi
  fi
  sleep 90
done
echo "[$(date +%H:%M:%S)] giving up" >> "$LOG"
exit 1
