"""GLM error paths and degenerate inputs (ref: the reference's
tests/linear_model/test_glm.py error cases and sklearn's validation
behavior, which dask_ml/linear_model/glm.py inherits via check_X_y).

Solvers must fail loudly on invalid configurations and stay finite on
degenerate-but-legal inputs — a NaN that silently satisfies a
``gnorm > tol`` while_loop would otherwise read as convergence
(SURVEY.md §5 sanitizer row).
"""

import numpy as np
import pytest

from dask_ml_tpu.linear_model import (
    LinearRegression, LogisticRegression, PoissonRegression,
)

rng = np.random.RandomState(0)
X = rng.randn(80, 6).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)


def test_unknown_solver_raises():
    with pytest.raises(ValueError, match="solver"):
        LogisticRegression(solver="sgdqn").fit(X, y)


def test_l1_with_lbfgs_raises():
    # smooth solvers cannot honor a non-smooth penalty
    with pytest.raises(ValueError, match="penalty|l1"):
        LogisticRegression(solver="lbfgs", penalty="l1").fit(X, y)


def test_unknown_penalty_raises():
    with pytest.raises(ValueError, match="penalty"):
        LogisticRegression(penalty="l7").fit(X, y)


def test_mismatched_lengths_raise():
    with pytest.raises(ValueError):
        LogisticRegression().fit(X, y[:-5])


def test_1d_X_rejected():
    with pytest.raises(ValueError):
        LogisticRegression().fit(X[:, 0], y)


def test_predict_before_fit_raises():
    with pytest.raises((ValueError, AttributeError)):
        LogisticRegression().predict(X)


def test_more_than_two_classes_fits_ovr():
    # beyond the reference: >2 classes dispatch to the one-vs-rest path
    y3 = rng.randint(0, 3, len(X)).astype(np.float32)
    clf = LogisticRegression(solver="lbfgs", max_iter=10).fit(X, y3)
    assert clf.coef_.shape == (3, X.shape[1])


def test_single_class_raises():
    y1 = np.zeros(len(X), np.float32)
    with pytest.raises(ValueError, match="class"):
        LogisticRegression(solver="lbfgs", max_iter=10).fit(X, y1)


@pytest.mark.parametrize("solver", ["lbfgs", "newton", "gradient_descent"])
def test_underdetermined_fit_stays_finite(solver):
    # n < d: the normal equations are rank-deficient; coefficients must
    # still come back finite (newton falls back to lstsq)
    Xu = rng.randn(8, 20).astype(np.float32)
    yu = (Xu[:, 0] > 0).astype(np.float32)
    clf = LogisticRegression(solver=solver, max_iter=10).fit(Xu, yu)
    assert np.isfinite(clf.coef_).all()
    assert np.isfinite(clf.intercept_).all()


def test_nonfinite_input_rejected():
    Xbad = X.copy()
    Xbad[3, 2] = np.inf
    with pytest.raises(ValueError, match="finite|NaN|inf"):
        LogisticRegression().fit(Xbad, y)


def test_poisson_negative_targets_rejected():
    with pytest.raises(ValueError, match="negative|non-negative"):
        PoissonRegression(max_iter=5).fit(X, -np.abs(y) - 1.0)


def test_linear_regression_constant_column_finite():
    Xc = X.copy()
    Xc[:, 1] = 3.0  # collinear with the intercept column
    m = LinearRegression(solver="newton", max_iter=10).fit(Xc, X[:, 0])
    assert np.isfinite(m.coef_).all()


def test_float32_overflow_detected():
    # finite in float64, inf after the float32 cast: must be rejected
    # (validation runs post-conversion, as sklearn's check_array does)
    Xo = X.astype(np.float64).copy()
    Xo[0, 0] = 1e40
    with pytest.raises(ValueError, match="infinity"):
        LogisticRegression().fit(Xo, y)
