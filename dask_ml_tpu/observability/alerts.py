"""Alert rules engine: declarative host-side rules over the live
registry.

After the fleet PRs this repo could *watch* failures but not *respond*
to them: alert state was scattered across a latched SLO burn deque in
the fleet block, the ``drift_alerts_total`` counter, and the watchdog
stall ring, with no unified surface an operator or autoscaler could
consume. This module is that surface:

- **declarative rules** (``config.obs_alert_rules``, ","/";"
  separated)::

      serving_slo_violations:rate>5/60s    counter delta per window
      drift_score_max:gauge>0.2            worst series of the family
      fit_eta_seconds:gauge>1800           (ops: > < >= <=)

  evaluated by ONE ticker thread over the existing counter/gauge
  snapshots — pure host dicts, zero device syncs, nothing in any
  jaxpr;
- **built-in rules**, always included once the engine is armed:
  ``builtin:watchdog_stall`` (event-fed by the watchdog's stall
  report), ``builtin:recompiles`` (any XLA compile after the engine's
  first evaluation window — the post-warmup recompile tripwire),
  ``builtin:fleet_slo_burn`` (event-fed by the metrics federator when
  a window burns error budget faster than 1.0), ``builtin:drift``
  (event-fed by the drift engine's below→above latch crossings) and
  ``builtin:typed_error`` (event-fed by the reliability hook on typed
  serving/streaming failures);
- a **firing/resolved state machine** per rule with hysteresis: a rule
  fires on its first breaching evaluation and resolves only after
  ``CLEAR_TICKS`` consecutive clean ones (event rules age out after
  ``EVENT_RESOLVE_TICKS`` tick intervals without a fresh event) — a
  flapping signal cannot strobe pages;
- ``alerts_firing{rule=}`` gauges + ``alerts_fired_total`` /
  ``alerts_resolved_total`` counters, JSONL ``alert`` transition
  records through the ambient trace sink, a ``/alerts`` JSON endpoint
  and the ``alerts`` block/table on ``/status`` + the report CLI;
- every transition to firing triggers black-box capture
  (:mod:`.incidents`) — rate-limited, bounded, atomic.

Arming: the engine starts when ``obs_alert_rules`` is non-empty OR
``incident_dir`` is set (built-ins only in the latter case), via
:func:`ensure_engine` on the same entry paths as the telemetry
exporter. Both knobs at their "" defaults = no engine object, no
ticker thread, and every ``note_event`` call one module-global check —
the package-wide zero-overhead contract.

Crossing dedupe (ISSUE 20 satellite): the drift latch and the fleet
burn latch now ROUTE through :func:`note_event` — one crossing mints
one event record (returned to the caller so the old deque surfaces can
keep re-exporting it) and at most one firing transition; the built-in
rules are purely event-driven, so the engine never double-counts a
crossing it was also told about.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque

from ._counters import counter_add, counters_enabled, counters_snapshot

__all__ = [
    "AlertRule", "AlertRuleError", "AlertEngine", "parse_rules",
    "ensure_engine", "engine", "stop_engine", "note_event",
    "note_error", "events", "alerts_data", "reset",
]

# consecutive clean evaluations before a firing polled rule resolves
# (hysteresis: one good tick between two bad ones must not flap)
CLEAR_TICKS = 2
# tick intervals an event rule stays firing after its LAST event
EVENT_RESOLVE_TICKS = 3
# transition ring (firing/resolved history on /alerts)
_TRANSITION_KEEP = 64
# passive event ledger (works with OR without an engine — the drift /
# fleet / watchdog crossings land here either way, replacing the old
# private deques as the one creation point)
_EVENT_KEEP = 64

_OPS = {
    ">": lambda v, t: v > t,
    "<": lambda v, t: v < t,
    ">=": lambda v, t: v >= t,
    "<=": lambda v, t: v <= t,
}

_GRAMMAR = (
    "accepted forms: '<counter>:rate<op><N>/<W>s' (counter delta <op> N "
    "per W-second window, e.g. 'serving_slo_violations:rate>5/60s'), "
    "'<gauge>:gauge<op><X>' (worst series of the gauge family, e.g. "
    "'drift_score_max:gauge>0.2'), '<counter>:counter<op><N>' (absolute "
    "total); ops: > < >= <=; several rules join with ',' or ';'; the "
    "special value 'builtin' arms only the built-in rules"
)

_RULE_RE = re.compile(
    r"^(?P<metric>[A-Za-z_][A-Za-z0-9_]*)"
    r":(?P<kind>rate|gauge|counter)"
    r"(?P<op>>=|<=|>|<)"
    r"(?P<value>[+-]?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)"
    r"(?:/(?P<window>\d+(?:\.\d+)?)s)?$"
)


class AlertRuleError(ValueError):
    """A rule spec the grammar rejects — the message always carries the
    full accepted-forms vocabulary so the config error is
    self-documenting."""

    def __init__(self, spec, why):
        super().__init__(
            f"bad alert rule {spec!r}: {why}; {_GRAMMAR}"
        )
        self.spec = spec


class AlertRule:
    """One parsed rule + its firing/resolved state machine. ``kind`` is
    ``rate`` (counter delta over a trailing window), ``gauge`` (worst
    current series of the family), ``counter`` (absolute total) or
    ``event`` (built-in, fed by :func:`note_event`)."""

    def __init__(self, metric, kind, op, threshold, window_s=None,
                 name=None, builtin=False):
        self.metric = metric
        self.kind = kind
        self.op = op
        self.threshold = float(threshold)
        self.window_s = float(window_s) if window_s else None
        self.name = name or f"{metric}:{kind}{op}{threshold}" + (
            f"/{window_s:g}s" if window_s else ""
        )
        self.builtin = builtin
        # state machine
        self.state = "ok"
        self.since = None           # unix time of the last transition
        self.value = None           # last evaluated / event value
        self.fired_total = 0
        self._clean_ticks = 0
        self._samples: deque = deque()   # (t, counter_total) for rate
        self._last_event_t = None        # event rules: freshness clock

    def _breach(self, value) -> bool:
        return _OPS[self.op](value, self.threshold)

    def evaluate(self, now, counters, gauges):
        """One polled evaluation → "firing"/"resolved"/None transition.
        Event rules only age out here (they fire inside
        :meth:`AlertEngine.notify`, at event time)."""
        if self.kind == "event":
            if self.state == "firing" and self._last_event_t is not None \
                    and now - self._last_event_t > self._resolve_after:
                return self._to_ok(now)
            return None
        if self.kind == "gauge":
            series = [v for (n, _ls), v in gauges.items()
                      if n == self.metric]
            if not series:
                return self._tick_ok(now)   # no data = not breaching
            # the WORST value for this op direction: any one series
            # over a ">" line (or under a "<" line) breaches the family
            value = max(series) if self.op in (">", ">=") else min(series)
        elif self.kind == "counter":
            value = counters.get(self.metric)
            if not isinstance(value, (int, float)):
                return self._tick_ok(now)
            value = float(value)
        else:  # rate
            total = counters.get(self.metric)
            if not isinstance(total, (int, float)):
                return self._tick_ok(now)
            total = float(total)
            self._samples.append((now, total))
            # keep one sample older than the window so the delta spans
            # the FULL window, not window-minus-one-tick
            while len(self._samples) > 1 \
                    and now - self._samples[1][0] >= self.window_s:
                self._samples.popleft()
            if len(self._samples) < 2:
                return self._tick_ok(now)   # first sample = baseline:
                # compiles/violations from BEFORE the engine armed
                # (warmup) can never fire a rate rule
            value = max(total - self._samples[0][1], 0.0)
        self.value = value
        if self._breach(value):
            self._clean_ticks = 0
            if self.state != "firing":
                self.state = "firing"
                self.since = now
                self.fired_total += 1
                return "firing"
            return None
        return self._tick_ok(now)

    def _tick_ok(self, now):
        """One clean evaluation; resolves only past the hysteresis."""
        if self.state != "firing":
            return None
        self._clean_ticks += 1
        if self._clean_ticks >= CLEAR_TICKS:
            return self._to_ok(now)
        return None

    def _to_ok(self, now):
        self.state = "ok"
        self.since = now
        self._clean_ticks = 0
        return "resolved"

    def fire_event(self, now, value):
        """An event landed for this rule (engine lock held). Returns
        "firing" on the ok→firing transition, None while already
        firing (the event just refreshes the age-out clock)."""
        self._last_event_t = now
        self.value = value
        if self.state != "firing":
            self.state = "firing"
            self.since = now
            self.fired_total += 1
            return "firing"
        return None

    @property
    def _resolve_after(self):
        return EVENT_RESOLVE_TICKS * (self._interval or 1.0)

    _interval = None  # set by the owning engine

    def row(self) -> dict:
        """One table-ready row (the /status + report ``alerts``
        shape)."""
        return {
            "rule": self.name, "kind": self.kind, "metric": self.metric,
            "op": self.op if self.kind != "event" else None,
            "threshold": self.threshold if self.kind != "event" else None,
            "window_s": self.window_s,
            "state": self.state,
            "value": (round(self.value, 6)
                      if isinstance(self.value, float) else self.value),
            "since": round(self.since, 3) if self.since else None,
            "fired": self.fired_total,
            "builtin": self.builtin,
        }


def parse_rules(spec: str):
    """``config.obs_alert_rules`` → list of :class:`AlertRule`. Raises
    :class:`AlertRuleError` (a ``ValueError``) on anything outside the
    grammar, with the accepted-forms vocabulary in the message."""
    rules = []
    for part in re.split(r"[,;]", spec or ""):
        part = part.strip()
        if not part or part == "builtin":
            continue  # "builtin" arms the engine with built-ins only
        m = _RULE_RE.match(part)
        if m is None:
            if ":" not in part:
                raise AlertRuleError(part, "missing ':<kind>' separator")
            kind = part.split(":", 1)[1]
            if not re.match(r"^(rate|gauge|counter)", kind):
                raise AlertRuleError(
                    part, "kind must be rate, gauge or counter"
                )
            raise AlertRuleError(part, "unparseable op/threshold/window")
        kind = m.group("kind")
        window = m.group("window")
        if kind == "rate" and window is None:
            raise AlertRuleError(
                part, "rate rules need a '/<W>s' window"
            )
        if kind != "rate" and window is not None:
            raise AlertRuleError(
                part, f"'/{window}s' windows only apply to rate rules"
            )
        if window is not None and float(window) <= 0:
            raise AlertRuleError(part, "window must be > 0 seconds")
        rules.append(AlertRule(
            m.group("metric"), kind, m.group("op"),
            float(m.group("value")), float(window) if window else None,
        ))
    return rules


def _builtin_rules():
    """The always-on rules once the engine is armed. Event rules carry
    no threshold — their sources (watchdog / federator / drift /
    reliability hook) already decided the crossing; the engine owns the
    state machine and dedupe."""
    return [
        AlertRule("watchdog_stalls", "event", ">", 0.0,
                  name="builtin:watchdog_stall", builtin=True),
        # post-warmup recompiles: a rate rule's first sample is its
        # baseline, so compiles from before the engine armed (warmup)
        # never count — any fresh XLA compile after that fires
        AlertRule("recompiles", "rate", ">", 0.0, window_s=60.0,
                  name="builtin:recompiles", builtin=True),
        AlertRule("fleet_slo_burn", "event", ">", 1.0,
                  name="builtin:fleet_slo_burn", builtin=True),
        AlertRule("drift_alerts", "event", ">", 0.0,
                  name="builtin:drift", builtin=True),
        AlertRule("typed_errors", "event", ">", 0.0,
                  name="builtin:typed_error", builtin=True),
    ]


# event name (note_event's first arg) -> built-in rule name
_EVENT_RULES = {
    "watchdog_stall": "builtin:watchdog_stall",
    "fleet_slo_burn": "builtin:fleet_slo_burn",
    "drift": "builtin:drift",
    "typed_error": "builtin:typed_error",
}


class AlertEngine:
    """The single ticker: every ``interval_s`` it snapshots the counter
    and gauge registries (host dicts — the evaluation path can never
    compile or sync) and advances every rule's state machine. Owns the
    transition ring, the ``alerts_firing`` gauges, and the capture
    hand-off to :mod:`.incidents`."""

    def __init__(self, rules, interval_s, cfg=None):
        self.rules = list(rules)
        self.interval_s = max(float(interval_s), 0.05)
        for r in self.rules:
            r._interval = self.interval_s
        self._by_name = {r.name: r for r in self.rules}
        if cfg is None:
            from ..config import get_config

            cfg = get_config()
        self._cfg = cfg
        self._lock = threading.Lock()
        self._transitions: deque = deque(maxlen=_TRANSITION_KEEP)
        self._stop = threading.Event()
        self._thread = None
        self._t_start = time.time()
        self.ticks = 0

    # -- lifecycle --------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dask-ml-tpu-alerts", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(5.0)
        self._thread = None

    def _run(self):
        import dataclasses

        from .. import config as _config

        # the ticker must see the ARMING caller's thread-local config
        # (trace sink, incident_dir, thresholds) — the drift-monitor /
        # watchdog idiom
        with _config.set(**dataclasses.asdict(self._cfg)):
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    pass  # the engine must never die mid-run

    # -- evaluation -------------------------------------------------------
    def tick(self, now=None):
        """One evaluation pass; returns the transitions it caused as
        ``[(rule, "firing"|"resolved"), ...]`` (tests drive this
        directly)."""
        from .live import gauges_snapshot

        now = time.time() if now is None else now
        counters = counters_snapshot()
        gauges = gauges_snapshot()
        out = []
        with self._lock:
            for rule in self.rules:
                tr = rule.evaluate(now, counters, gauges)
                if tr is not None:
                    out.append((rule, tr))
            self.ticks += 1
        for rule, tr in out:
            self._on_transition(rule, tr, now)
        return out

    def notify(self, event: str, value, meta) -> None:
        """An external crossing (watchdog / federator / drift /
        reliability hook) — drives the matching event rule NOW, at
        event time, so incident capture sees the freshest context."""
        name = _EVENT_RULES.get(event)
        rule = self._by_name.get(name) if name else None
        if rule is None:
            return
        now = time.time()
        with self._lock:
            tr = rule.fire_event(now, value)
        if tr is not None:
            self._on_transition(rule, tr, now, meta=meta)

    def _on_transition(self, rule, transition, now, meta=None):
        from .live import gauge_set

        firing = transition == "firing"
        gauge_set("alerts_firing", 1.0 if firing else 0.0,
                  (("rule", rule.name),))
        if counters_enabled():
            counter_add("alerts_fired" if firing else "alerts_resolved",
                        1)
        rec = {
            "alert": True, "rule": rule.name, "kind": rule.kind,
            "metric": rule.metric, "state": transition,
            "value": rule.value, "t_unix": round(now, 6),
        }
        if meta:
            rec.update({k: v for k, v in meta.items()
                        if k not in rec})
        with self._lock:
            self._transitions.append(rec)
        _emit(rec)
        if firing:
            try:
                from . import incidents

                incidents.capture_incident(
                    reason=f"alert:{rule.name}", rule=rule.name,
                    meta=meta, cfg=self._cfg,
                )
            except Exception:
                pass  # capture failures never break evaluation

    # -- read surfaces ----------------------------------------------------
    def rows(self):
        with self._lock:
            return [r.row() for r in self.rules]

    def data(self) -> dict:
        with self._lock:
            rows = [r.row() for r in self.rules]
            transitions = list(self._transitions)
        return {
            "armed": True,
            "interval_s": self.interval_s,
            "ticks": self.ticks,
            "t_start_unix": round(self._t_start, 3),
            "rules": rows,
            "firing": [r["rule"] for r in rows if r["state"] == "firing"],
            "transitions": transitions,
            "events": events(),
        }


def _emit(rec) -> None:
    """One JSONL record through the ambient trace sink (the drift
    engine's idiom) — the report CLI's alerts table reads these."""
    try:
        from ._spans import _trace_sink

        sink = _trace_sink()
        if sink is not None:
            sink.log(**rec)
    except Exception:
        pass


# -- passive event ledger + module singleton ---------------------------------

_events: deque = deque(maxlen=_EVENT_KEEP)
_events_lock = threading.Lock()
_engine: AlertEngine | None = None
_engine_lock = threading.Lock()


def note_event(event: str, value=None, meta=None) -> dict:
    """Record one crossing from another subsystem (drift latch, fleet
    burn, watchdog stall, reliability typed error) and drive the
    matching built-in rule when an engine is armed. Returns the event
    record so legacy surfaces (the federator's alert deque) can keep
    holding the SAME object — one crossing, one record, at most one
    firing transition."""
    rec = {"event": str(event), "t_unix": round(time.time(), 3)}
    if value is not None:
        try:
            rec["value"] = round(float(value), 6)
        except (TypeError, ValueError):
            rec["value"] = value
    if meta:
        rec.update({k: v for k, v in dict(meta).items()
                    if k not in rec})
    with _events_lock:
        _events.append(rec)
    eng = _engine
    if eng is not None:
        try:
            eng.notify(event, rec.get("value"), meta)
        except Exception:
            pass
    return rec


def note_error(exc, site: str) -> None:
    """The reliability opt-in hook: a typed error surfaced on an error
    path (serving batch failure, streaming retries exhausted). One
    module-global check when nothing is armed; with an engine it drives
    ``builtin:typed_error`` and captures an incident."""
    if _engine is None and not _armed_by_config():
        return
    note_event("typed_error", value=1.0,
               meta={"error": type(exc).__name__, "site": str(site),
                     "detail": str(exc)[:200]})


def events(event=None) -> list:
    """The crossing ledger, oldest first (``event`` filters by
    source)."""
    with _events_lock:
        out = list(_events)
    if event is not None:
        out = [r for r in out if r.get("event") == event]
    return out


def _armed_by_config(cfg=None) -> bool:
    from ..config import get_config

    cfg = cfg or get_config()
    return bool(str(cfg.obs_alert_rules).strip()) \
        or bool(str(cfg.incident_dir).strip())


def engine() -> AlertEngine | None:
    """The live singleton engine, or None."""
    return _engine


def ensure_engine(cfg=None) -> AlertEngine | None:
    """Start the process-wide engine if the config asks for one
    (``obs_alert_rules`` non-empty OR ``incident_dir`` set) and none is
    running. Idempotent; called from the same hot-path entries as
    ``live.ensure_telemetry`` — with both knobs at their "" defaults
    this is one None check + one config read, and a bad rule spec
    raises the typed :class:`AlertRuleError` into the arming caller
    (config errors must not be swallowed by a daemon)."""
    global _engine
    if _engine is not None:
        return _engine
    from ..config import get_config

    cfg = cfg or get_config()
    if not _armed_by_config(cfg):
        return None
    with _engine_lock:
        if _engine is not None:
            return _engine
        rules = parse_rules(cfg.obs_alert_rules)
        rules.extend(_builtin_rules())
        eng = AlertEngine(rules, cfg.obs_alert_interval_s, cfg=cfg)
        eng.start()
        _engine = eng
    return _engine


def stop_engine() -> None:
    """Stop the singleton (tests / graceful shutdown)."""
    global _engine
    with _engine_lock:
        eng, _engine = _engine, None
    if eng is not None:
        eng.stop()


def alerts_data() -> dict:
    """The ``/alerts`` JSON document (and the /status ``alerts``
    block): engine state when armed, just the passive event ledger
    when not."""
    eng = _engine
    if eng is not None:
        return eng.data()
    return {"armed": False, "rules": [], "firing": [],
            "transitions": [], "events": events()}


def reset() -> None:
    """Stop the engine and clear the ledger — test isolation."""
    stop_engine()
    with _events_lock:
        _events.clear()
