"""Peak-FLOPs table: the ONE denominator every MFU number divides by.

Grown out of ``bench.py`` (which now imports it) so the report CLI's
measured per-span MFU and the benchmark's analytic MFU are computed
against the same peak: datasheet bf16 matmul peaks for known TPU
generations, a measured large-matmul peak everywhere else (the only
honest option on CPU fallback).
"""

from __future__ import annotations

import time

# bf16 datasheet peaks per chip (TFLOP/s) by device_kind substring. The
# MXU runs f32-input matmuls at bf16-pass rate under default precision,
# so the bf16 peak is the honest denominator for BOTH dtypes (using it
# for f32 yields a conservative MFU, never an inflated one).
DATASHEET_PEAKS = {
    "v6": 918e12,       # Trillium / v6e
    "v5p": 459e12,
    "v5 lite": 197e12,  # v5e reports device_kind "TPU v5 lite"
    "v5e": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}

_cached_peak = None


def resolve_peak(matmul_dim=None, use_cache=True) -> dict:
    """Per-chip peak matmul FLOP/s: datasheet when the device_kind is
    known, else MEASURED with a large square matmul. Returns
    ``{"flops", "source", "device_kind"}``. The measured path is cached
    per process (it burns a few GFLOPs); pass ``use_cache=False`` to
    re-measure."""
    global _cached_peak
    if use_cache and matmul_dim is None and _cached_peak is not None:
        return dict(_cached_peak)
    import jax

    backend = jax.default_backend()
    kind = getattr(jax.devices()[0], "device_kind", backend) or backend
    if backend == "tpu":
        for sub, peak in DATASHEET_PEAKS.items():
            if sub in kind.lower():
                out = {"flops": peak, "source": "datasheet",
                       "device_kind": kind}
                _cached_peak = dict(out)
                return out
    import jax.numpy as jnp

    m = matmul_dim or (4096 if backend == "tpu" else 1024)
    a = jnp.ones((m, m), jnp.bfloat16 if backend == "tpu" else jnp.float32)
    f = jax.jit(lambda x: x @ x)
    jax.block_until_ready(f(a))  # compile
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        a = jax.block_until_ready(f(a))
    dt = time.perf_counter() - t0
    out = {"flops": 2.0 * m ** 3 * reps / dt, "source": "measured",
           "device_kind": kind}
    if matmul_dim is None:
        _cached_peak = dict(out)
    return out


def mfu_fields(model_flops, elapsed, n_chips, peak) -> dict:
    """Achieved model FLOP/s and MFU vs per-chip peak (absolute perf
    measures; model_flops counts the algorithm's useful matmul FLOPs)."""
    fps = model_flops / elapsed
    return {
        "model_flops": round(model_flops),
        "model_flop_per_s": round(fps, 1),
        "mfu": round(fps / (peak["flops"] * n_chips), 5),
        "peak": {"flop_per_s_per_chip": round(peak["flops"], 1),
                 "source": peak["source"],
                 "device_kind": peak["device_kind"]},
    }
