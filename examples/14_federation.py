"""Federation: one router over N fleet processes, scaled to the SLO.

`examples/10_fleet.py` scaled a model across replicas INSIDE one
process. The federation plane is the layer above — the last hop of the
"serves heavy traffic from millions of users" north star:

- ``FederatedFleet``    — predicted-completion routing over fleet
  PROCESSES (each process's ``/status`` snapshot rebuilds the local
  admission predictor remotely via ``policy.exec_from_snapshot``);
- **failover**          — a process dying mid-request loses NOTHING:
  the whole request re-issues on the next-ranked survivor, whose
  trace carries ``rerouted_from_process``;
- **publish fan-out**   — one ``fed.publish()`` pins the control
  registry's version id into every process (stale fan-outs drop, so
  back-to-back publishes converge no matter the arrival order);
- ``ReplicaAutoscaler`` — the SLO admission signal ADDS/RETIRES
  replicas under hysteresis bands, spin-up warmed off the serving
  path;
- ``replay_load_test``  — recorded traffic in, pass/fail SLO verdict
  out.

This example federates two in-process fleets through
``LocalEndpoint``s (the virtual-process transport — swap in
``"http://host:port"`` strings against real processes running a
``TelemetryServer``), kills one mid-traffic, publishes a retrained
version to the survivor, scales under a synthetic burst, and verdicts
a replayed load test. ``scripts/federation_smoke.py`` proves the same
story with real subprocesses and a SIGKILL.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dask_ml_tpu import config
from dask_ml_tpu import observability as obs
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.serving import (
    BucketLadder,
    FederatedFleet,
    FleetServer,
    LocalEndpoint,
    ReplicaAutoscaler,
    replay_load_test,
    synthesize_records,
)

n = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 20_000))
X, y = make_classification(n_samples=n, n_features=16, n_informative=8,
                           random_state=0)
X2, y2 = make_classification(n_samples=n, n_features=16, n_informative=8,
                             random_state=7)
a = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
b = LogisticRegression(solver="lbfgs", max_iter=30).fit(X2, y2)
Xh = X.to_numpy().astype(np.float32)

ladder = BucketLadder(8, 256, 2.0)

# -- two "processes": each fleet owns its registry, workers, devices.
#    Against real remote processes these would be HttpEndpoint URLs.
f0 = FleetServer(a, name="clf", replicas=1, ladder=ladder,
                 batch_window_ms=1.0, timeout_ms=0).warmup().start()
f1 = FleetServer(a, name="clf", replicas=1, ladder=ladder,
                 batch_window_ms=1.0, timeout_ms=0).warmup().start()

with FederatedFleet([LocalEndpoint(f0, "p0"), LocalEndpoint(f1, "p1")],
                    name="clf", ladder=ladder, poll_s=0.2) as fed:
    # align version numbering fleet-wide: control v1 pins over each
    # process's construction-time version
    v1 = fed.publish(a)
    print(f"published v{v1} to {fed.stats()['live_processes']} processes")

    # -- routed traffic ----------------------------------------------------
    got = fed.predict(Xh[:32])
    assert np.array_equal(got, np.asarray(a.predict(Xh[:32])))
    print(f"routed predict ok; router view: {fed.stats()['processes']}")

    # -- failover: p0 dies mid-stream, nothing is lost ---------------------
    c0 = obs.counters_snapshot()
    f0.stop(drain=False)                 # the "SIGKILL"
    for i in range(6):                   # every request still resolves
        got = fed.predict(Xh[i * 8:(i + 1) * 8])
        assert np.array_equal(got, np.asarray(a.predict(Xh[i * 8:(i + 1) * 8])))
    c1 = obs.counters_snapshot()
    print(f"p0 killed: {fed.stats()['live_processes']}/2 live, "
          f"reroutes +{c1.get('serving_process_reroutes', 0) - c0.get('serving_process_reroutes', 0)}, "
          f"failovers +{c1.get('serving_process_failovers', 0) - c0.get('serving_process_failovers', 0)}, "
          "0 requests lost")

    # -- publish fan-out converges the survivor ----------------------------
    v2 = fed.publish(b)
    assert f1.version == v2 == f1.registry.current_version("clf")
    got = fed.predict(Xh[:32])
    assert np.array_equal(got, np.asarray(b.predict(Xh[:32])))
    print(f"published v{v2}: survivor registry pinned to control version")

with config.set(serving_slo_ms=5000.0):
    # -- autoscale: the admission signal grows the fleet -------------------
    fleet = FleetServer(a, name="clf-as", replicas=1, ladder=ladder,
                        batch_window_ms=1.0, timeout_ms=0).warmup()
    with fleet:
        # pretend yesterday's window showed the top bucket at 90% of the
        # SLO — above the 80% up band, below the shedding door
        for _ in range(50):
            fleet.replicas[0]._exec.observe("predict", ladder.max_rows, 4.5)
        scaler = ReplicaAutoscaler(fleet, min_replicas=1, max_replicas=2,
                                   interval_s=0.05, patience=2,
                                   cooldown_s=5.0)
        scaler.start()

        # -- replayed load test against the scaling fleet ------------------
        report = replay_load_test(
            fleet, Xh,
            records=synthesize_records(150, rows=(1, 64), rate_rps=300.0),
            slo_ms=5000.0, quantile=99.0,
        )
        scaler.stop()
        ups = [e for e in scaler.events if e[0] == "up"]
        print(f"autoscale: {len(fleet.replicas)} replicas "
              f"(spin-up {ups[0][2] * 1e3:.1f} ms, warmed off-path)")
        print(f"load test: {report['ok']}/{report['requests']} ok, "
              f"p99 {report['latency_ms']['p99']:.1f} ms "
              f"<= SLO {report['slo_ms']:.0f} ms, "
              f"passed={report['passed']}")
        assert ups and report["passed"]

print("federation example done")
