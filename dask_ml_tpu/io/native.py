"""ctypes bindings for the native data loader (native/fast_loader.cpp).

Compiled on demand with g++ (the image has the toolchain but no
pybind11 — SURVEY.md environment notes); falls back to numpy text parsing
when compilation is unavailable. The loader feeds
``parallel/streaming.BlockStream`` — parse into pinned host memory, then
stream blocks to the mesh.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_lock = threading.Lock()
_lib = None
_lib_failed = False

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_ROOT, "native", "fast_loader.cpp")
_SO = os.path.join(_ROOT, "native", "_fast_loader.so")


def _build_and_load(src, so, configure):
    """Shared compile-if-stale + dlopen + symbol-config flow for every
    native helper; returns the configured library or None. A prebuilt
    .so next to a MISSING source still loads (no getmtime on a path
    that isn't there)."""
    if not os.path.exists(so) or (
        os.path.exists(src) and os.path.getmtime(so) < os.path.getmtime(src)
    ):
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
             "-o", so, src],
            check=True, capture_output=True,
        )
    lib = ctypes.CDLL(so)
    configure(lib)
    return lib


def _configure_fast_loader(lib):
    lib.csv_dims.restype = ctypes.c_int64
    lib.csv_dims.argtypes = [ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_int64)]
    lib.csv_parse_f32.restype = ctypes.c_int64
    lib.csv_parse_f32.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
    ]


def load_library():
    """The compiled library, building it if needed; None if unavailable."""
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            _lib = _build_and_load(_SRC, _SO, _configure_fast_loader)
        except Exception:
            _lib_failed = True
        return _lib


def read_csv_f32(path, n_threads=None) -> np.ndarray:
    """Parse a numeric CSV (comma/space/tab separated, no header) into a
    float32 array with the native multithreaded parser; numpy fallback."""
    path = os.path.abspath(path)
    lib = load_library()
    if lib is None:
        return np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)
    if n_threads is None:
        n_threads = min(os.cpu_count() or 1, 16)
    n_cols = ctypes.c_int64(0)
    n_rows = lib.csv_dims(path.encode(), ctypes.byref(n_cols))
    if n_rows < 0:
        raise IOError(f"cannot read {path!r} (code {n_rows})")
    out = np.empty((n_rows, n_cols.value), np.float32)
    got = lib.csv_parse_f32(
        path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_rows, n_cols.value, n_threads,
    )
    if got < 0:
        raise ValueError(
            f"malformed CSV {path!r} (code {got}); expected "
            f"{n_cols.value} numeric columns per row"
        )
    return out[:got]


def read_csv_sharded(path, mesh=None, n_threads=None):
    """CSV straight onto the mesh: native parse -> ShardedArray."""
    from ..parallel.sharded import as_sharded

    return as_sharded(read_csv_f32(path, n_threads=n_threads), mesh=mesh)


# -- native block reader (native/block_reader.cpp) --------------------------

_SRC_BR = os.path.join(_ROOT, "native", "block_reader.cpp")
_SO_BR = os.path.join(_ROOT, "native", "_block_reader.so")
_lib_br = None
_lib_br_failed = False


def _configure_block_reader(lib):
    lib.br_open.restype = ctypes.c_void_p
    lib.br_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.br_next.restype = ctypes.c_int64
    lib.br_next.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.br_close.restype = None
    lib.br_close.argtypes = [ctypes.c_void_p]


def load_block_reader():
    """The threaded-readahead reader library; None if unavailable."""
    global _lib_br, _lib_br_failed
    with _lock:
        if _lib_br is not None or _lib_br_failed:
            return _lib_br
        try:
            _lib_br = _build_and_load(_SRC_BR, _SO_BR,
                                      _configure_block_reader)
        except Exception:
            _lib_br_failed = True
        return _lib_br


class NativeBlockReader:
    """Sequential fixed-size row blocks of a memmap-backed file, read
    AHEAD by a C++ thread into a buffer ring (native/block_reader.cpp) —
    disk latency overlaps the previous block's device_put + compute even
    with a cold page cache."""

    def __init__(self, mm: np.memmap, block_rows: int, depth: int = 2):
        lib = load_block_reader()
        if lib is None:
            raise RuntimeError("native block reader unavailable")
        self._lib = lib
        self._shape_tail = mm.shape[1:]
        self._dtype = mm.dtype
        row_items = int(np.prod(self._shape_tail, dtype=np.int64) or 1)
        self._row_bytes = int(mm.dtype.itemsize) * row_items
        self._block_rows = int(block_rows)
        self.n_rows = int(mm.shape[0])
        self._buf = np.empty((self._block_rows,) + tuple(self._shape_tail),
                             mm.dtype)
        self._h = lib.br_open(
            str(mm.filename).encode(), int(mm.offset), self._row_bytes,
            self.n_rows, self._block_rows, int(depth),
        )
        if not self._h:
            raise RuntimeError(f"br_open failed for {mm.filename}")

    def next(self):
        """Next block as an ndarray VIEW of the internal buffer (valid
        until the following call), or None at end-of-stream."""
        rows = self._lib.br_next(
            self._h, self._buf.ctypes.data_as(ctypes.c_char_p)
        )
        if rows < 0:
            raise IOError("native block reader failed mid-stream")
        if rows == 0:
            return None
        return self._buf[: int(rows)]

    def close(self):
        if getattr(self, "_h", None):
            self._lib.br_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC path
        try:
            self.close()
        except Exception:
            pass
