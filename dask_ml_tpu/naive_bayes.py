"""GaussianNB on sharded arrays.

Reference: ``dask_ml/naive_bayes.py`` (SURVEY.md §2a Naive Bayes row) —
per-class mean/var via masked reductions. Here the per-class statistics
are one jitted program (class masks × masked reductions, psum under
sharding) and the joint log-likelihood predict is a fused elementwise +
matmul program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import BaseEstimator, ClassifierMixin, to_host
from .metrics import accuracy_score
from .parallel.sharded import ShardedArray
from .plans import GeometricLadder, ProgramPlan, warmups
from .utils.validation import check_X_y, check_array, check_is_fitted

__all__ = ["GaussianNB"]

# -- execution-plan declarations (ISSUE 15) ---------------------------------
# GaussianNB is the "any new estimator gets streaming + serving for
# free" proof: ONE ProgramPlan (the donated-carry per-block class-stats
# reducer below) + one shape ladder is the whole streamed-fit story —
# `Incremental(GaussianNB())` then streams blocks through it with zero
# steady-state compiles, and `wrappers._nb_extract` serves the fitted
# model through the same plan-built zero-recompile serving entry points
# as the linear family (warmable via ModelServer.warmup()).

# block heights pad up this ladder so a whole streamed fit touches at
# most two compiled rungs (full blocks + the ragged tail)
_STREAM_LADDER = GeometricLadder(min_rows=256, max_rows=1 << 22,
                                 growth=2.0)


def _nb_partial_stats_body(carry, Xp, codes, mask, k):
    """One padded block folded into the running per-class
    (count, sum, sum-of-squares) stats — Gaussian NB's whole sufficient
    statistic, so the streamed fit is one masked matmul pair per
    block. ``codes`` are class indices (host-encoded, so label dtype
    never enters the trace); ``k`` is static."""
    counts, sums, sqs = carry
    cm = (codes[None, :] == jnp.arange(k, dtype=Xp.dtype)[:, None]) \
        .astype(Xp.dtype) * mask[None, :]
    counts = counts + jnp.sum(cm, axis=1)
    sums = sums + cm @ Xp                                # (k, d) on MXU
    sqs = sqs + cm @ (Xp * Xp)
    return counts, sums, sqs


# the fitted attributes the streamed path publishes lazily from the
# device-resident running stats (see GaussianNB.__getattr__)
_NB_STAT_ATTRS = ("theta_", "var_", "class_prior_", "class_count_")

_NB_STATS_PLAN = ProgramPlan(
    name="plans.nb.partial_stats", body=_nb_partial_stats_body,
    donate=(0,), static_argnames=("k",), ladder="nb-rows",
    group="naive-bayes",
)
_NB_STATS = None


def _nb_stats():
    global _NB_STATS
    if _NB_STATS is None:
        _NB_STATS = _NB_STATS_PLAN.build()
    return _NB_STATS


@jax.jit
def _class_stats(X, y, mask, classes):
    """Per-class count/mean/var in one pass. classes: (k,) values."""
    cmask = (y[None, :] == classes[:, None]).astype(X.dtype) * mask[None, :]
    counts = jnp.sum(cmask, axis=1)                      # (k,)
    sums = cmask @ X                                     # (k, d) on MXU
    means = sums / jnp.maximum(counts[:, None], 1.0)
    sq = cmask @ (X * X)
    var = sq / jnp.maximum(counts[:, None], 1.0) - means ** 2
    return counts, means, jnp.maximum(var, 0.0)


def _jll_math(X, theta, var, log_prior):
    """The one joint-log-likelihood definition — the in-core predict
    below AND the plan-built serving core (wrappers._nb_core) both
    trace THIS function, so a numerical change can never diverge the
    served predictions from GaussianNB.predict."""
    # -0.5 * sum((x-mu)^2/var) - 0.5*sum(log 2 pi var) + log prior
    prec = 1.0 / var                                     # (k, d)
    x2 = (X * X) @ prec.T                                # (n, k)
    xm = X @ (theta * prec).T
    m2 = jnp.sum(theta * theta * prec, axis=1)
    quad = x2 - 2.0 * xm + m2[None, :]
    logdet = jnp.sum(jnp.log(2.0 * jnp.pi * var), axis=1)
    return -0.5 * (quad + logdet[None, :]) + log_prior[None, :]


_joint_log_likelihood = jax.jit(_jll_math)


class GaussianNB(ClassifierMixin, BaseEstimator):
    """Ref: dask_ml/naive_bayes.py::GaussianNB."""

    def __init__(self, priors=None, var_smoothing=1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing

    def fit(self, X, y):
        X, y = check_X_y(X, y, dtype=np.float32)
        mask = X.row_mask(X.dtype)
        classes = np.unique(y.to_numpy())
        counts, means, var = _class_stats(
            X.data, y.data, mask, jnp.asarray(classes, X.dtype)
        )
        # sklearn's numerical floor on variances
        from .ops.reductions import masked_mean_var

        _, gvar = masked_mean_var(X.data, mask, X.n_rows)
        eps = self.var_smoothing * float(jnp.max(gvar))
        self.classes_ = classes
        self.class_count_ = to_host(counts).astype(np.float64)
        self.theta_ = to_host(means).astype(np.float64)
        self.var_ = to_host(var).astype(np.float64) + eps
        if self.priors is not None:
            self.class_prior_ = np.asarray(self.priors, np.float64)
        else:
            self.class_prior_ = self.class_count_ / self.class_count_.sum()
        self.n_features_in_ = X.shape[1]
        return self

    # -- streamed out-of-core fit (ISSUE 15) ------------------------------
    def partial_fit(self, X, y, classes=None):
        """Fold one block of rows into the running per-class stats via
        the plan-built donated-carry reducer — the streamed fit
        ``Incremental(GaussianNB())`` drives block by block. Blocks pad
        up the plans GeometricLadder (mask co-located with the rung
        choice), so a whole multi-pass fit touches a bounded compiled
        set and pays zero XLA compiles after pass 1."""
        import scipy.sparse as sp

        if isinstance(X, ShardedArray):
            Xh = X.to_numpy()
        elif sp.issparse(X):
            Xh = X.toarray()
        else:
            Xh = X
        Xh = np.asarray(Xh, np.float32)
        if Xh.ndim == 1:
            Xh = Xh[None, :]
        yh = np.asarray(y.to_numpy() if isinstance(y, ShardedArray)
                        else y).ravel()
        if getattr(self, "_stats_", None) is None:
            if classes is None:
                raise ValueError(
                    "classes= is required on the first partial_fit"
                )
            self.classes_ = np.unique(np.asarray(classes))
            k, d = len(self.classes_), int(Xh.shape[1])
            self._stats_ = (jnp.zeros((k,), jnp.float32),
                            jnp.zeros((k, d), jnp.float32),
                            jnp.zeros((k, d), jnp.float32))
            self.n_features_in_ = d
        if Xh.shape[1] != self.n_features_in_:
            raise ValueError(
                f"block has {Xh.shape[1]} features; this fit started "
                f"with {self.n_features_in_}"
            )
        k = len(self.classes_)
        idx = np.searchsorted(self.classes_, yh)
        ok = (idx < k) & (self.classes_[np.minimum(idx, k - 1)] == yh)
        if not np.all(ok):
            raise ValueError(
                f"y contains labels outside classes= "
                f"({np.asarray(yh)[~ok][:3]!r} ...)"
            )
        codes = idx.astype(np.float32)
        # fold in top-rung chunks: a block taller than the ladder's top
        # is the caller's batch, not a reason to refuse a fit
        top = _STREAM_LADDER.max_rows
        for lo in range(0, Xh.shape[0], top):
            xb, cb = Xh[lo:lo + top], codes[lo:lo + top]
            n = xb.shape[0]
            rung = _STREAM_LADDER.rung_for(n)
            Xp = _STREAM_LADDER.pad_rows(xb, rung)
            cp = _STREAM_LADDER.pad_rows(cb, rung)
            mask = _STREAM_LADDER.row_mask(n, rung)
            self._stats_ = _nb_stats()(self._stats_, Xp, cp, mask, k=k)
            # attribution: the real dispatch minted (or reused) this
            # rung's specialization — the plans table names it
            warmups.note(("nb-stats", k, self.n_features_in_, rung),
                         program="plans.nb.partial_stats",
                         ladder="nb-rows", rung=rung)
        # publishing is LAZY (see __getattr__): pulling the stats to
        # host here would synchronize every streamed block's device
        # computation with the host loop; dropping the published attrs
        # instead keeps the fitted-attribute contract (any read
        # publishes first) without the per-block sync
        for a in _NB_STAT_ATTRS:
            self.__dict__.pop(a, None)
        return self

    def __getattr__(self, name):
        # fitted-stat attributes materialize on first read after a
        # partial_fit (the streamed path defers the device->host pull)
        if name in _NB_STAT_ATTRS \
                and self.__dict__.get("_stats_") is not None:
            self._publish_from_stats()
            return self.__dict__[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}"
        )

    def __getstate__(self):
        # pickle the PUBLISHED view (host numpy stats): a restored
        # estimator predicts immediately and can keep partial_fitting —
        # jnp re-adopts numpy carries on the next block
        if self.__dict__.get("_stats_") is not None:
            self._publish_from_stats()
        state = dict(self.__dict__)
        st = state.get("_stats_")
        if st is not None:
            state["_stats_"] = tuple(np.asarray(a) for a in st)
        return state

    def _publish_from_stats(self):
        counts, sums, sqs = (np.asarray(a, np.float64)
                             for a in self._stats_)
        tot = max(float(counts.sum()), 1.0)
        means = sums / np.maximum(counts[:, None], 1.0)
        var = np.maximum(
            sqs / np.maximum(counts[:, None], 1.0) - means ** 2, 0.0
        )
        gmean = sums.sum(axis=0) / tot
        gvar = np.maximum(sqs.sum(axis=0) / tot - gmean ** 2, 0.0)
        eps = self.var_smoothing * float(np.max(gvar)) \
            if gvar.size else 0.0
        self.class_count_ = counts
        self.theta_ = means
        self.var_ = var + eps
        if self.priors is not None:
            self.class_prior_ = np.asarray(self.priors, np.float64)
        else:
            self.class_prior_ = counts / tot

    def _jll(self, X):
        X = check_array(X, dtype=np.float32)
        return X, _joint_log_likelihood(
            X.data,
            jnp.asarray(self.theta_, X.dtype),
            jnp.asarray(self.var_, X.dtype),
            jnp.asarray(np.log(self.class_prior_), X.dtype),
        )

    def predict(self, X):
        check_is_fitted(self, "theta_")
        X, jll = self._jll(X)
        idx = to_host(jnp.argmax(jll, axis=1))[: X.n_rows]
        return self.classes_[idx]

    def predict_proba(self, X):
        check_is_fitted(self, "theta_")
        X, jll = self._jll(X)
        p = to_host(jax.nn.softmax(jll, axis=1))[: X.n_rows]
        return p

    def predict_log_proba(self, X):
        from .base import log_proba

        return log_proba(self.predict_proba(X))

    def score(self, X, y):
        y = y.to_numpy() if isinstance(y, ShardedArray) else np.asarray(y)
        return accuracy_score(y, self.predict(X))
