"""Observability subsystem: JSONL metrics, hierarchical span tracing,
runtime counters, and the run-report CLI.

The dask-ml reference leaned on dask's diagnostics stack (task-stream
dashboard, progress bars, profilers — SURVEY.md §5); the TPU rebuild's
equivalent is this package (grown from the flat per-step logger in
``utils/observability.py``, which remains as a re-export shim):

- ``_metrics``  — ``MetricsLogger`` (JSONL sink), the ambient
  ``active_logger`` jit-step sink + ``emit_jit_step`` debug-callback
  bridge, the host-callback capability probe, profiler wrappers;
- ``_spans``    — ``span(name, **attrs)``: nested span records (fit →
  pass → solve) with wall time, device-sync time, parent ids, and
  counter deltas;
- ``_counters`` — flat counter/gauge registry: recompiles (via
  ``jax.monitoring``, with a jit-cache fallback), host↔device transfer
  bytes, donated-buffer reuse, per-device memory gauges;
- ``_programs`` — compiled-program registry: per-program compile time,
  XLA cost/memory analysis (FLOPs, bytes, HBM peak) and invocation
  counts for every tracked jit entry point (``config.obs_programs``);
- ``_watchdog`` — opt-in slow-span watchdog
  (``config.watchdog_timeout_s``): spans open past their deadline dump
  all-thread tracebacks + device memory gauges + the open-span stack to
  the trace sink without touching the fit;
- ``_peak``     — the peak-FLOPs table (datasheet TPU peaks / measured
  matmul fallback) the report's measured MFU and bench.py's analytic
  MFU both divide by;
- ``export``    — span JSONL -> Chrome-trace/Perfetto JSON
  (``report ... --perfetto out.json``);
- ``report``    — ``python -m dask_ml_tpu.observability.report
  metrics.jsonl`` aggregates a recorded run into per-component tables
  (``--json`` for the machine-readable form; ``--merge`` folds several
  processes' trace files into ONE timeline/report);
- ``_hist``     — thread-safe fixed-boundary log-spaced histograms (the
  serving latency quantile core and /metrics histogram series);
- ``sketch``    — streaming data sketches (per-feature moments +
  fixed-boundary histograms, top-k categoricals): host-only, mergeable,
  JSON-safe — the training profiles streamed fits attach and the
  serving sketches the quality plane scores;
- ``drift``     — train-serve/window/version drift scoring (PSI/KS),
  hot-swap shadow canaries, the drift-alert counter, the background
  drift monitor (``config.obs_drift``);
- ``_requests`` — the per-REQUEST trace plane
  (``config.obs_trace_sample``): stage-stamped lifecycle traces through
  the serving queue/pack/execute/demux pipeline, tail sampling of
  interesting traces, per-stage exemplar histograms, the ``/traces``
  surface, and the admitted-traffic capture/replay substrate (ROADMAP
  4(c));
- ``live``      — the LIVE telemetry plane (``config.obs_http_port``):
  a process-wide gauge/histogram registry over the counter registry,
  fit-progress publication via span-close observers, and a background
  HTTP exporter serving Prometheus ``/metrics``, ``/healthz`` and a
  JSON ``/status`` (open-span stack, report tables, serving windows,
  watchdog stalls) while the run is still going;
- ``fleet``     — fleet-scope metrics federation
  (``config.obs_fleet_federate``): ``MetricsFederator`` rides the
  federation status poller, folds every process's scraped counters/
  gauges/histograms into one fleet registry (counters sum, gauges get
  a ``{process=}`` label, histograms merge bucket-for-bucket), and
  exposes it on the router's ``/metrics`` (``dask_ml_tpu_fleet_*``
  families) and ``/status/fleet`` with a fleet-wide SLO burn-rate and
  latched alerts;
- ``alerts``    — the alert rules engine (``config.obs_alert_rules``):
  declarative counter-rate/gauge-threshold rules plus built-ins
  (watchdog stalls, post-warmup recompiles, fleet SLO burn, drift,
  typed errors) evaluated by one ticker over the live registry, with
  firing/resolved state machines, ``alerts_firing{rule=}`` gauges, the
  ``/alerts`` endpoint, and the crossing ledger the drift/fleet latches
  route through;
- ``incidents`` — black-box incident capture (``config.incident_dir``):
  every firing transition freezes one rate-limited, bounded, atomic
  JSON bundle (open spans, counter/gauge/histogram snapshots, programs,
  device memory, fault plan, config fingerprint), plus on-demand deep
  profiling (``POST /profile?seconds=N``; jax.profiler windows on TPU,
  no-op-with-reason off it).

Everything is ambient and zero-overhead when disabled: no
``metrics_path``/``trace_dir`` configured means spans are no-ops and no
callback is ever traced into jitted code (asserted by
``tests/test_observability.py``).
"""

from ._counters import (
    count_recompiles,
    counter_add,
    counters_enabled,
    counters_reset,
    counters_snapshot,
    device_memory_gauges,
    install_recompile_tracking,
    log_counters,
    record_donation,
    record_fault_injected,
    record_gspmd_reduce,
    record_registry_publish,
    record_replica_failure,
    record_replica_restart,
    record_serving_batch,
    record_serving_drop,
    record_serving_request,
    record_serving_reroute,
    record_serving_slo_violation,
    record_serving_swap,
    record_shard_staging,
    record_sparse_spill,
    record_sparse_staging,
    record_stream_checkpoint,
    record_stream_quarantine,
    record_stream_retry,
    record_superblock,
    record_superblock_donation,
    record_transfer,
    record_zero_copy,
)
from ._metrics import (
    MetricsLogger,
    _active_lock,
    _active_loggers,
    active_logger,
    emit_jit_step,
    fit_logger,
    jit_callbacks_supported,
    profile_trace,
    reset_jit_callbacks_probe,
    start_profiler_server,
    timed,
)
from ._programs import (
    log_programs,
    programs_enabled,
    programs_reset,
    programs_snapshot,
    track_program,
)
from ._hist import Histogram, merge_snapshots
from .fleet import SLO_BURN_BUDGET, MetricsFederator
from .sketch import CategoricalSketch, FeatureSketch, merge_profiles
from ._spans import (
    NOOP_SPAN,
    add_span_observer,
    current_span_id,
    open_spans_snapshot,
    remove_span_observer,
    span,
)
from ._requests import (
    load_capture,
    replay,
    tracing_enabled,
    traces_data,
    traces_reset,
)
from ._watchdog import Watchdog, watchdog, watchdog_active
from .alerts import (
    AlertEngine,
    AlertRule,
    AlertRuleError,
    alerts_data,
    ensure_engine,
    note_event,
    parse_rules,
    stop_engine,
)
from .incidents import (
    capture_incident,
    deep_profile,
    incidents_data,
    load_bundles,
)
from .live import (
    TelemetryServer,
    ensure_telemetry,
    gauge_set,
    live_publishing,
    publish_progress,
    render_prometheus,
    status_data,
    stop_telemetry,
    telemetry_server,
)

# recompile telemetry is passive and cheap (a no-op listener call per
# compile when counters are disabled) — install at import so the counter
# covers warmup compiles too
install_recompile_tracking()

__all__ = [
    "AlertEngine",
    "AlertRule",
    "AlertRuleError",
    "CategoricalSketch",
    "FeatureSketch",
    "Histogram",
    "MetricsFederator",
    "MetricsLogger",
    "SLO_BURN_BUDGET",
    "merge_profiles",
    "merge_snapshots",
    "NOOP_SPAN",
    "TelemetryServer",
    "Watchdog",
    "active_logger",
    "add_span_observer",
    "alerts_data",
    "capture_incident",
    "deep_profile",
    "ensure_engine",
    "ensure_telemetry",
    "gauge_set",
    "live_publishing",
    "publish_progress",
    "remove_span_observer",
    "render_prometheus",
    "status_data",
    "stop_telemetry",
    "telemetry_server",
    "count_recompiles",
    "counter_add",
    "counters_enabled",
    "counters_reset",
    "counters_snapshot",
    "current_span_id",
    "device_memory_gauges",
    "emit_jit_step",
    "fit_logger",
    "install_recompile_tracking",
    "incidents_data",
    "jit_callbacks_supported",
    "load_bundles",
    "load_capture",
    "log_counters",
    "log_programs",
    "note_event",
    "parse_rules",
    "replay",
    "traces_data",
    "traces_reset",
    "tracing_enabled",
    "open_spans_snapshot",
    "profile_trace",
    "programs_enabled",
    "programs_reset",
    "programs_snapshot",
    "record_donation",
    "record_fault_injected",
    "record_gspmd_reduce",
    "record_registry_publish",
    "record_replica_failure",
    "record_replica_restart",
    "record_serving_batch",
    "record_serving_drop",
    "record_serving_request",
    "record_serving_reroute",
    "record_serving_slo_violation",
    "record_serving_swap",
    "record_shard_staging",
    "record_sparse_spill",
    "record_sparse_staging",
    "record_stream_checkpoint",
    "record_stream_quarantine",
    "record_stream_retry",
    "record_superblock",
    "record_superblock_donation",
    "record_transfer",
    "record_zero_copy",
    "reset_jit_callbacks_probe",
    "span",
    "start_profiler_server",
    "stop_engine",
    "timed",
    "track_program",
    "watchdog",
    "watchdog_active",
]
