"""sklearn-parity namespace. Ref: dask_ml/linear_model/__init__.py."""
from ..models.glm import (LinearRegression, LogisticRegression,
                          PoissonRegression, add_intercept)
from ..models.sgd import SGDClassifier, SGDRegressor

__all__ = ["LinearRegression", "LogisticRegression", "PoissonRegression",
           "SGDClassifier", "SGDRegressor", "add_intercept"]
