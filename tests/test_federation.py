"""Federation plane (dask_ml_tpu/serving/federation.py): predicted-
completion routing over N fleet processes, whole-request failover with
``rerouted_from_process`` tagging, seq-guarded + version-pinned
cross-process publish fan-out, the ``POST /fleet`` HTTP surface, and
the policy predictor's admit-friendly edge cases the router ranks by.

The load-bearing assertions: a process death loses ZERO admitted
requests (the survivor's trace names the corpse process), back-to-back
fan-outs converge EVERY process to the control registry's CURRENT
version (stale seqs dropped, version ids pinned equal), a dead
process's gauge series are dropped from the live registry, and warmed
federated traffic across a publish fan-out mints zero XLA compiles.
"""

import math

import numpy as np
import pytest

from dask_ml_tpu import config, observability as obs
from dask_ml_tpu.observability import _requests as rtrace
from dask_ml_tpu.serving import (
    BucketLadder,
    FederatedFleet,
    FleetServer,
    HttpEndpoint,
    LocalEndpoint,
    ModelRegistry,
    NoLiveProcesses,
    ProcessDown,
)
from dask_ml_tpu.serving.federation import apply_publish
from dask_ml_tpu.serving.policy import (
    ExecStats,
    admission_verdict,
    exec_from_snapshot,
    predict_completion_s,
)


@pytest.fixture(scope="module")
def two_logregs():
    """Two same-shape fitted models (the swap pair) + host data."""
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(
        n_samples=600, n_features=12, n_informative=6, random_state=0
    )
    X2, y2 = make_classification(
        n_samples=600, n_features=12, n_informative=6, random_state=7
    )
    a = LogisticRegression(solver="lbfgs", max_iter=30).fit(X, y)
    b = LogisticRegression(solver="lbfgs", max_iter=30).fit(X2, y2)
    return a, b, X.to_numpy().astype(np.float32)


@pytest.fixture(autouse=True)
def _trace_isolation():
    rtrace.traces_reset()
    yield
    rtrace.traces_reset()


def _ladder():
    return BucketLadder(8, 64, 2.0)


def _pair(a, name="clf"):
    """Two started in-process fleets (own registries — separate
    'processes') + their endpoints + the router."""
    f1 = FleetServer(a, name=name, replicas=1, ladder=_ladder(),
                     batch_window_ms=1.0).warmup().start()
    f2 = FleetServer(a, name=name, replicas=1, ladder=_ladder(),
                     batch_window_ms=1.0).warmup().start()
    fed = FederatedFleet(
        [LocalEndpoint(f1, "p0"), LocalEndpoint(f2, "p1")],
        name=name, ladder=_ladder(),
    ).start()
    return f1, f2, fed


# -- policy edge cases (the router's prediction substrate) -------------------

def test_predict_s_empty_window_admits():
    """A never-observed predictor yields None, and None ADMITS — an
    empty window must not shed (or admit) with false confidence."""
    ex = ExecStats()
    assert ex.predict_s("predict", 64) is None
    pred = predict_completion_s(1000, 8, 64,
                                ex.predict_s("predict", 64))
    assert pred is None
    assert admission_verdict(pred, 0.001) is True


def test_predict_s_single_sample_stays_usable():
    """One observation is a usable (positive, finite) estimate — the
    deadline-release and admission paths rely on early predictions."""
    ex = ExecStats()
    ex.observe("predict", 64, 0.25)
    v = ex.predict_s("predict", 64)
    assert v is not None and math.isfinite(v) and v > 0
    # an unmeasured sibling bucket borrows it
    assert ex.predict_s("predict", 8) == pytest.approx(v)


def test_predict_s_degenerate_mass_collapses_to_none():
    """All-zero observations (a sub-resolution clock) collapse to None
    instead of a 0.0 that admission would read as 'instant'."""
    ex = ExecStats()
    for _ in range(20):
        ex.observe("predict", 64, 0.0)
    assert ex.predict_s("predict", 64) is None
    assert ex.predict_s("predict", 8) is None   # sibling equally bad


def test_completion_and_verdict_guards():
    assert predict_completion_s(100, 8, 64, None) is None
    assert predict_completion_s(100, 8, 64, 0.0) is None
    assert predict_completion_s(100, 8, 64, float("nan")) is None
    assert predict_completion_s(100, 8, 64, -1.0) is None
    assert predict_completion_s(0, 8, 64, 0.5) == pytest.approx(0.5)
    assert admission_verdict(None, 1.0) is True
    assert admission_verdict(float("nan"), 1.0) is True
    assert admission_verdict(2.0, 1.0) is False
    assert admission_verdict(0.5, 1.0) is True
    assert admission_verdict(99.0, 0.0) is True   # no SLO, no shed


def test_exec_from_snapshot_heterogeneous_windows():
    """The remote-twin predictor over heterogeneous replica windows:
    thin windows skipped, degenerate quantiles skipped, other methods
    ignored, nearest bucket by log-distance wins."""
    snap = {
        "predict:64": {"count": 30, "p50_s": 0.01, "p90_s": 0.02},
        "predict:8": {"count": 2, "p50_s": 5.0, "p90_s": 5.0},
        "predict:16": {"count": 30, "p50_s": 0.0, "p90_s": 0.0},
        "transform:64": {"count": 30, "p50_s": 9.0, "p90_s": 9.0},
    }
    assert exec_from_snapshot(snap, "predict", 64) == 0.02
    # 8 and 16 are closer by log-distance but thin/degenerate: the
    # warm 64 window answers for them too
    assert exec_from_snapshot(snap, "predict", 8) == 0.02
    assert exec_from_snapshot(snap, "transform", 8) == 9.0
    assert exec_from_snapshot(snap, "decision_function", 64) is None
    assert exec_from_snapshot({}, "predict", 64) is None
    assert exec_from_snapshot(None, "predict", 64) is None


# -- registry version pinning ------------------------------------------------

def test_registry_pinned_publish(two_logregs):
    """publish(version=) stores at the exact id, points current at it,
    advances the local counter past it, and overwrites idempotently —
    the fan-out's version-convergence substrate."""
    a, b, _ = two_logregs
    reg = ModelRegistry(keep=8)
    assert reg.publish("m", a) == 1
    assert reg.publish("m", b, version=5) == 5
    assert reg.current_version("m") == 5
    assert reg.publish("m", a) == 6          # never collides with pin
    assert reg.publish("m", b, version=5) == 5   # replayed fan-out
    assert reg.current_version("m") == 5
    assert reg.versions("m") == (1, 5, 6)
    with pytest.raises(ValueError):
        reg.publish("m", a, version=0)


def test_apply_publish_stale_seq_dropped(two_logregs):
    """Out-of-order fan-out delivery: the newer seq wins no matter the
    arrival order (last-writer-wins, the cross-process generalization
    of the fleet's converge-to-current contract)."""
    a, b, _ = two_logregs
    fleet = FleetServer(a, name="clf", ladder=_ladder(), replicas=1)
    try:
        assert apply_publish(fleet, b, version=7, seq=5) is True
        assert fleet.version == 7
        # seq 4 arrives late: dropped, version stays
        assert apply_publish(fleet, a, version=6, seq=4) is False
        assert fleet.version == 7
        assert fleet.registry.current_version("clf") == 7
        # local publishes mint ids past the pin
        assert fleet.registry.publish("clf", a) == 8
    finally:
        fleet.stop(drain=False)


# -- routing -----------------------------------------------------------------

def test_ranked_prefers_predicted_fast(two_logregs):
    """The router orders processes by predicted completion out of the
    cached /status windows; cold (no-prediction) processes rank after
    warm-fast ones but stay routable."""
    a, _, _ = two_logregs
    fed = FederatedFleet(
        [HttpEndpoint("http://127.0.0.1:1", name="clf",
                      process_id=p) for p in ("p0", "p1", "p2")],
        name="clf", ladder=_ladder(),
    )
    warm = {"count": 30, "p50_s": 0.01, "p90_s": 0.01}
    slow = {"count": 30, "p50_s": 1.0, "p90_s": 1.0}
    fed._procs[0].stats = {"queue_rows": 640,
                           "replicas": [{"exec_s": {"predict:64": slow}}]}
    fed._procs[1].stats = {"queue_rows": 0,
                           "replicas": [{"exec_s": {"predict:64": warm}}]}
    fed._procs[2].stats = {"queue_rows": 0, "replicas": [{"exec_s": {}}]}
    order = [p.endpoint.process_id for p in fed._ranked("predict", 8)]
    assert order == ["p1", "p0", "p2"]


def test_federated_failover_zero_lost_with_process_tag(two_logregs):
    """Kill one process mid-traffic: every admitted request still
    resolves (whole-request re-issue on the survivor), the survivor's
    trace names the corpse process, and the hop/failover counters
    move."""
    a, _, Xh = two_logregs
    with config.set(obs_trace_sample=1.0):
        f1, f2, fed = _pair(a)
        try:
            want = np.asarray(a.predict(Xh[:6]))
            before = obs.counters_snapshot()
            np.testing.assert_array_equal(fed.predict(Xh[:6]), want)
            # p0 dies (no drain — a SIGKILL stand-in); the router finds
            # out mid-request and re-issues on p1
            f1.stop(drain=False)
            futs = [fed.submit(Xh[i:i + 4]) for i in range(0, 24, 4)]
            for i, fut in enumerate(futs):
                got = fut.result(30)
                np.testing.assert_array_equal(
                    got, np.asarray(a.predict(Xh[4 * i:4 * i + 4])))
            after = obs.counters_snapshot()
            assert after.get("serving_process_reroutes", 0) \
                > before.get("serving_process_reroutes", 0)
            assert after.get("serving_process_failovers", 0) \
                > before.get("serving_process_failovers", 0)
            st = fed.stats()
            assert st["live_processes"] == 1
        finally:
            fed.stop()
            f1.stop(drain=False)
            f2.stop()
    d = obs.traces_data()
    tagged = [t for t in d["traces"]
              if t.get("rerouted_from_process") == "p0"
              and t["outcome"] == "ok"]
    assert tagged, "no survivor trace carried rerouted_from_process"


def test_all_processes_down_is_typed(two_logregs):
    a, _, Xh = two_logregs
    f1, f2, fed = _pair(a)
    try:
        f1.stop(drain=False)
        f2.stop(drain=False)
        with pytest.raises(NoLiveProcesses):
            fed.submit(Xh[:4]).result(30)
    finally:
        fed.stop()


# -- publish fan-out ---------------------------------------------------------

def test_fanout_back_to_back_converges_and_zero_compiles(two_logregs):
    """Back-to-back cross-process publishes: every process lands on the
    control registry's CURRENT version with EQUAL version ids, and the
    whole sequence (same-shape swaps) mints zero XLA compiles on the
    warmed fleets."""
    a, b, Xh = two_logregs
    f1, f2, fed = _pair(a)
    try:
        before = obs.counters_snapshot().get("recompiles", 0)
        for est in (b, a, b, a):
            v = fed.publish(est)
        assert fed.registry.current_version("clf") == v
        assert f1.version == v and f2.version == v
        assert f1.registry.current_version("clf") == v
        assert f2.registry.current_version("clf") == v
        # the converged fleets actually serve the last-published model
        want = np.asarray(a.predict(Xh[:8]))
        np.testing.assert_array_equal(fed.predict(Xh[:8]), want)
        after = obs.counters_snapshot().get("recompiles", 0)
        assert after - before == 0, (
            f"{after - before} recompiles across 4 fan-outs"
        )
    finally:
        fed.stop()
        f1.stop()
        f2.stop()


def test_fanout_skips_dead_and_reconverges_on_next_publish(two_logregs):
    """A publish while a process is down skips it; after it returns,
    the NEXT publish re-converges its registry (the smoke's
    re-convergence contract in miniature)."""
    a, b, _ = two_logregs
    f1, f2, fed = _pair(a)
    try:
        fed._poll_once()
        v0 = fed.publish(a)       # everyone on control v1
        f2.stop(drain=False)
        v1 = fed.publish(b)       # p1 dead: only p0 converges
        assert v1 > v0
        assert f1.version == v1
        assert f2.version == v0   # stale: missed the fan-out
        # p1 comes back (fresh fleet on the same endpoint object)
        f2b = FleetServer(a, name="clf", replicas=1, ladder=_ladder(),
                          batch_window_ms=1.0).warmup().start()
        fed._procs[1].endpoint.fleet = f2b
        fed._procs[1].alive = True
        v2 = fed.publish(a)
        assert f1.version == v2 and f2b.version == v2
        assert f2b.registry.current_version("clf") == v2
        f2b.stop()
    finally:
        fed.stop()
        f1.stop(drain=False)
        f2.stop(drain=False)


# -- live-gauge hygiene ------------------------------------------------------

def test_process_failover_drops_process_gauges(two_logregs):
    """A process marked dead must not leave serving_process_* series
    latched on /metrics (the federation twin of the replica-gauge
    drop)."""
    from dask_ml_tpu.observability.live import (
        TelemetryServer,
        gauges_snapshot,
    )

    a, _, _ = two_logregs
    with TelemetryServer(port=0):
        f1, f2, fed = _pair(a)
        try:
            fed._poll_once()
            have = {(n, dict(ls).get("process"))
                    for (n, ls) in gauges_snapshot()}
            assert ("serving_process_healthy", "p0") in have
            assert ("serving_process_healthy", "p1") in have
            f1.stop(drain=False)
            fed._poll_once()
            have = {(n, dict(ls).get("process"))
                    for (n, ls) in gauges_snapshot()}
            assert ("serving_process_healthy", "p0") not in have
            assert ("serving_process_healthy", "p1") in have
        finally:
            fed.stop()
            f1.stop(drain=False)
            f2.stop()


# -- HTTP surface ------------------------------------------------------------

def test_http_endpoint_roundtrip_publish_and_errors(two_logregs):
    """The POST /fleet surface end-to-end against a real telemetry
    server: status, npy submit round-trip, version-pinned publish,
    typed unknown-fleet refusal, and dead-server ProcessDown."""
    from dask_ml_tpu.observability.live import TelemetryServer

    a, b, Xh = two_logregs
    ts = TelemetryServer(port=0).start()
    fleet = FleetServer(a, name="clf", replicas=1, ladder=_ladder(),
                        batch_window_ms=1.0).warmup().start()
    try:
        ep = HttpEndpoint(ts.url, name="clf", process_id="h0",
                          timeout_s=30.0)
        assert ep.status()["fleet"] == "clf"
        got = ep.submit(Xh[:7])
        np.testing.assert_array_equal(got,
                                      np.asarray(a.predict(Xh[:7])))
        assert ep.apply_publish(b, version=9, seq=1) is True
        assert fleet.version == 9
        assert fleet.registry.current_version("clf") == 9
        assert ep.apply_publish(a, version=8, seq=1) is False  # stale
        assert fleet.version == 9
        with pytest.raises(ProcessDown):
            HttpEndpoint(ts.url, name="ghost").submit(Xh[:2])
    finally:
        fleet.stop()
        ts.stop()
    with pytest.raises(ProcessDown):
        ep.status()


def test_http_truncated_response_is_process_down(two_logregs, monkeypatch):
    """A SIGKILL landing mid-RESPONSE surfaces as IncompleteRead — an
    http.client.HTTPException, NOT an OSError — and must still map to
    ProcessDown so the router re-issues the request whole (the zero-
    lost contract covers deaths at any point in the round-trip)."""
    import http.client
    import urllib.request as _ur

    _, _, Xh = two_logregs

    def boom(*args, **kwargs):
        raise http.client.IncompleteRead(b"", 464)

    monkeypatch.setattr(_ur, "urlopen", boom)
    ep = HttpEndpoint("http://127.0.0.1:1", name="clf",
                      process_id="h0", timeout_s=1.0)
    with pytest.raises(ProcessDown):
        ep.submit(Xh[:2])
    with pytest.raises(ProcessDown):
        ep.status()


def test_http_reroute_header_tags_survivor_trace(two_logregs):
    """X-Fed-Reroute propagates the corpse process's id into the
    SURVIVOR process's trace — the cross-process reroute audit trail."""
    from dask_ml_tpu.observability.live import TelemetryServer

    a, _, Xh = two_logregs
    with config.set(obs_trace_sample=1.0):
        with TelemetryServer(port=0) as ts:
            fleet = FleetServer(a, name="clf", replicas=1,
                                ladder=_ladder(),
                                batch_window_ms=1.0).warmup().start()
            try:
                ep = HttpEndpoint(ts.url, name="clf", timeout_s=30.0)
                got = ep.submit(Xh[:3], rerouted_from="proc-dead")
                assert got.shape == (3,)
            finally:
                fleet.stop()
    d = obs.traces_data()
    tagged = [t for t in d["traces"]
              if t.get("rerouted_from_process") == "proc-dead"]
    assert tagged and tagged[-1]["outcome"] == "ok"


# -- virtual-rank harness ----------------------------------------------------

def test_virtual_rank_federation_roundtrip(two_logregs):
    """Multi-process federation logic without real fabric: each
    virtual rank builds its own fleet (own registry — the process
    stand-in), the router federates the ranks' endpoints, and a
    publish converges every rank's registry to the pinned version."""
    from dask_ml_tpu.parallel.distributed import run_virtual_processes

    a, b, Xh = two_logregs

    def build(rank):
        fleet = FleetServer(
            a, name="clf", replicas=1, ladder=_ladder(),
            batch_window_ms=1.0,
        ).warmup().start()
        return LocalEndpoint(fleet, f"rank{rank}")

    eps = run_virtual_processes(build, world=2)
    fed = FederatedFleet(eps, name="clf", ladder=_ladder()).start()
    try:
        want = np.asarray(a.predict(Xh[:10]))
        np.testing.assert_array_equal(fed.predict(Xh[:10]), want)
        v = fed.publish(b)
        assert all(ep.fleet.version == v for ep in eps)
        assert all(ep.fleet.registry.current_version("clf") == v
                   for ep in eps)
    finally:
        fed.stop()
        for ep in eps:
            ep.fleet.stop()
