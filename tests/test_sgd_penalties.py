"""SGD penalty semantics + contract guards (VERDICT r1 weak #7, ADVICE
r1 #4): penalty/l1_ratio/fit_intercept actually change the update, and
the sklearn classes contract is enforced across partial_fit calls."""

import numpy as np
import pytest

from dask_ml_tpu.linear_model import SGDClassifier, SGDRegressor


def _data(seed=0, n=400, d=20, informative=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    beta = np.zeros(d, np.float32)
    beta[:informative] = 2.0
    y = (X @ beta + 0.1 * rng.randn(n) > 0).astype(np.float64)
    return X, y


@pytest.mark.slow
def test_l1_sparsifies_vs_l2():
    X, y = _data()
    l2 = SGDClassifier(penalty="l2", alpha=0.05, eta0=0.5, max_iter=40,
                       random_state=0).fit(X, y)
    l1 = SGDClassifier(penalty="l1", alpha=0.05, eta0=0.5, max_iter=40,
                       random_state=0).fit(X, y)
    n_zero_l2 = int((np.abs(l2.coef_) < 1e-7).sum())
    n_zero_l1 = int((np.abs(l1.coef_) < 1e-7).sum())
    assert n_zero_l1 > n_zero_l2  # soft-threshold produces exact zeros
    assert n_zero_l1 >= 10  # uninformative features killed
    assert l1.score(X, y) > 0.8


@pytest.mark.slow
def test_elasticnet_between_l1_l2():
    X, y = _data(1)
    kw = dict(alpha=0.05, eta0=0.5, max_iter=40, random_state=0)
    zeros = {}
    for pen, l1r in (("l2", 0.0), ("elasticnet", 0.5), ("l1", 1.0)):
        m = SGDClassifier(penalty=pen, l1_ratio=l1r, **kw).fit(X, y)
        zeros[pen] = int((np.abs(m.coef_) < 1e-7).sum())
    assert zeros["l2"] <= zeros["elasticnet"] <= zeros["l1"]
    assert zeros["l1"] > zeros["l2"]


def test_none_penalty_is_unregularized():
    X, y = _data(2)
    dense = SGDClassifier(penalty=None, alpha=10.0, eta0=0.5, max_iter=20,
                          random_state=0).fit(X, y)
    # huge alpha with penalty=None must have no effect at all
    ref = SGDClassifier(penalty=None, alpha=1e-4, eta0=0.5, max_iter=20,
                        random_state=0).fit(X, y)
    np.testing.assert_allclose(dense.coef_, ref.coef_, rtol=1e-6)


def test_invalid_penalty_raises():
    X, y = _data()
    with pytest.raises(ValueError, match="penalty"):
        SGDClassifier(penalty="l3").fit(X, y)
    with pytest.raises(ValueError, match="penalty"):
        SGDClassifier(penalty="l3").partial_fit(X, y, classes=[0.0, 1.0])


def test_fit_intercept_false_keeps_zero():
    X, y = _data(3)
    m = SGDClassifier(fit_intercept=False, eta0=0.5, max_iter=20,
                      random_state=0).fit(X, y)
    assert m.intercept_[0] == 0.0
    m2 = SGDClassifier(fit_intercept=True, eta0=0.5, max_iter=20,
                       random_state=0).fit(X, y + 0)  # biased data below
    assert isinstance(float(m2.intercept_[0]), float)


@pytest.mark.slow
def test_regressor_l1_sparsifies():
    rng = np.random.RandomState(4)
    X = rng.randn(300, 15).astype(np.float32)
    beta = np.zeros(15, np.float32)
    beta[:2] = 3.0
    yr = X @ beta + 0.05 * rng.randn(300).astype(np.float32)
    m = SGDRegressor(penalty="l1", alpha=0.1, eta0=0.05, max_iter=60,
                     random_state=0).fit(X, yr)
    assert int((np.abs(m.coef_) < 1e-7).sum()) >= 8
    assert m.score(X, yr) > 0.7


def test_classes_mismatch_raises():
    """ADVICE r1 #4: re-passing different classes must raise, not
    silently re-encode labels mid-training (sklearn contract)."""
    X, y = _data()
    clf = SGDClassifier()
    clf.partial_fit(X, y, classes=[0.0, 1.0])
    with pytest.raises(ValueError, match="classes"):
        clf.partial_fit(X, y, classes=[1.0, 2.0])
    # same classes again is fine
    clf.partial_fit(X, y, classes=[0.0, 1.0])
    # a fresh fit() resets classes
    clf.fit(X, (y + 1))
    np.testing.assert_array_equal(clf.classes_, [1.0, 2.0])
