"""GLM parity tests vs scikit-learn (SURVEY.md §4: sklearn is the oracle).

Mirrors the reference's ``tests/linear_model/test_glm.py`` strategy: fit the
distributed estimator on sharded data, fit sklearn in memory, compare
coefficients / predictions.
"""

import numpy as np
import pytest
import sklearn.linear_model as sklm

from dask_ml_tpu.linear_model import (
    LinearRegression,
    LogisticRegression,
    PoissonRegression,
)

SOLVERS_SMOOTH = ["lbfgs", "newton", "gradient_descent", "admm", "proximal_grad"]


@pytest.mark.parametrize("solver", SOLVERS_SMOOTH)
def test_logistic_l2_parity(xy_classification, solver):
    X, y = xy_classification
    ours = LogisticRegression(solver=solver, C=1.0, max_iter=500, tol=1e-7)
    ours.fit(X, y)
    ref = sklm.LogisticRegression(C=1.0, solver="lbfgs", max_iter=2000, tol=1e-10)
    ref.fit(X, y)
    atol = 0.03 if solver in ("admm", "gradient_descent", "proximal_grad") else 0.01
    np.testing.assert_allclose(ours.coef_, ref.coef_, atol=atol)
    np.testing.assert_allclose(ours.intercept_, ref.intercept_, atol=atol)
    assert ours.score(X, y) == pytest.approx(ref.score(X, y), abs=0.02)


def test_logistic_predict_api(xy_classification):
    X, y = xy_classification
    clf = LogisticRegression(solver="lbfgs", max_iter=200).fit(X, y)
    proba = clf.predict_proba(X)
    assert proba.shape == (len(y), 2)
    np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-5)
    pred = clf.predict(X)
    assert set(np.unique(pred)) <= set(clf.classes_)
    assert clf.score(X, y) > 0.8


def test_logistic_l1_sparsity(xy_classification):
    X, y = xy_classification
    clf = LogisticRegression(
        solver="proximal_grad", penalty="l1", C=0.01, max_iter=2000, tol=1e-9
    ).fit(X, y)
    # penalty="l1" must be explicit: modern sklearn IGNORES l1_ratio
    # under the default penalty="l2" (with only a warning), silently
    # turning the oracle into a dense L2 fit
    ref = sklm.LogisticRegression(
        penalty="l1", C=0.01, solver="saga", max_iter=5000, tol=1e-10
    ).fit(X, y)
    np.testing.assert_allclose(ours_zero := (np.abs(clf.coef_) < 1e-6),
                               np.abs(ref.coef_) < 1e-6)
    np.testing.assert_allclose(clf.coef_, ref.coef_, atol=0.02)


def test_logistic_admm_l1(xy_classification):
    X, y = xy_classification
    clf = LogisticRegression(
        solver="admm", penalty="l1", C=0.01, max_iter=400, tol=1e-5
    ).fit(X, y)
    # explicit penalty="l1" — see test_logistic_l1_sparsity
    ref = sklm.LogisticRegression(
        penalty="l1", C=0.01, solver="saga", max_iter=5000, tol=1e-10
    ).fit(X, y)
    np.testing.assert_allclose(clf.coef_, ref.coef_, atol=0.03)


@pytest.mark.parametrize("solver", ["lbfgs", "newton"])
def test_linear_regression_parity(xy_regression, solver):
    X, y = xy_regression
    ours = LinearRegression(
        solver=solver, penalty="none", max_iter=500, tol=1e-8
    ).fit(X, y)
    ref = sklm.LinearRegression().fit(X, y)
    np.testing.assert_allclose(ours.coef_, ref.coef_, atol=0.05, rtol=1e-3)
    np.testing.assert_allclose(ours.intercept_, ref.intercept_, atol=0.05)
    assert ours.score(X, y) == pytest.approx(ref.score(X, y), abs=1e-3)


def test_poisson_parity():
    rng = np.random.RandomState(0)
    X = rng.randn(400, 5)
    beta = np.array([0.3, -0.2, 0.1, 0.0, 0.4])
    y = rng.poisson(np.exp(X @ beta + 0.5)).astype(np.float64)
    alpha = 1e-4
    ours = PoissonRegression(
        solver="lbfgs", C=1.0 / (alpha * len(y)), max_iter=500, tol=1e-8
    ).fit(X, y)
    ref = sklm.PoissonRegressor(alpha=alpha, max_iter=2000, tol=1e-10).fit(X, y)
    np.testing.assert_allclose(ours.coef_, ref.coef_, atol=0.01)
    np.testing.assert_allclose(ours.intercept_, ref.intercept_, atol=0.01)


def test_clone_and_get_params():
    from sklearn.base import clone

    clf = LogisticRegression(C=2.0, solver="lbfgs")
    p = clf.get_params()
    assert p["C"] == 2.0
    c2 = clone(clf)
    assert c2.get_params()["C"] == 2.0


def test_warm_start(xy_classification):
    X, y = xy_classification
    clf = LogisticRegression(solver="lbfgs", max_iter=300, warm_start=True)
    clf.fit(X, y)
    c1 = clf.coef_.copy()
    clf.fit(X, y)  # warm restart from optimum: should stay there
    np.testing.assert_allclose(clf.coef_, c1, atol=1e-3)


def test_bfloat16_config_parity(xy_classification):
    """config.dtype='bfloat16' (MXU fast path) must match the f32 fit to
    within bf16 rounding on a well-conditioned problem."""
    from dask_ml_tpu import config

    X, y = xy_classification
    f32 = LogisticRegression(solver="lbfgs", max_iter=100).fit(X, y)
    with config.set(dtype="bfloat16"):
        bf16 = LogisticRegression(solver="lbfgs", max_iter=100).fit(X, y)
    assert abs(f32.score(X, y) - bf16.score(X, y)) < 0.02
    denom = np.linalg.norm(f32.coef_) + 1e-12
    assert np.linalg.norm(f32.coef_ - bf16.coef_) / denom < 0.15


def test_class_weight_raises_not_silently_ignored():
    from dask_ml_tpu.datasets import make_classification
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = make_classification(n_samples=500, n_features=5, random_state=0)
    with pytest.raises(ValueError, match="class_weight"):
        LogisticRegression(solver="lbfgs",
                           class_weight="balanced").fit(X, y)
    # None stays allowed
    LogisticRegression(solver="lbfgs", max_iter=5).fit(X, y)
