"""Pass-granular checkpoint / auto-resume for streamed fits.

``utils/checkpoint.py`` states the recovery contract (TPU slices fail
whole: checkpoint-restart, no lineage recompute) but before this module
only KMeans Lloyd (``models/kmeans.py::_LloydCheckpoint``) and the
adaptive searches honored it — a killed streamed GLM/SGD/Incremental
fit restarted from scratch. :class:`StreamCheckpoint` generalizes the
Lloyd contract:

- **fingerprint-keyed identity**: the checkpoint carries a token over
  the fit's hyperparameters, partition, and a data-content fingerprint
  (``utils.validation.data_fingerprint``); a checkpoint written by a
  DIFFERENT fit (other data, other knobs, other shapes) is ignored, not
  silently resumed;
- **pass granularity**: consumers save their carry pytree + pass /
  lr-clock state after each streamed pass (``stream_checkpoint_every``
  thins the cadence) via orbax, through ``utils.checkpoint``'s atomic
  temp-sibling-fsync-rename writer — a kill mid-save leaves the
  previous checkpoint intact;
- **cleared on completion**: a finished fit removes its checkpoint so
  it can never be resumed into a new one;
- **multihost refusal**: under a >1-process runtime resume must be a
  COLLECTIVE decision (every process restarts from the same pass or
  none does — the same refusal ``models/kmeans.py`` documents), so the
  builder returns None there and the fit simply runs uncheckpointed.

Knobs: ``config.stream_checkpoint_path`` ("" = off) and
``config.stream_checkpoint_every`` (passes between saves).
"""

from __future__ import annotations

import hashlib
import os
import shutil

import numpy as np

__all__ = ["StreamCheckpoint", "stream_checkpoint"]

_TOKEN_BYTES = 40  # sha1 hex digest length, padded like _LloydCheckpoint


class StreamCheckpoint:
    """One streamed fit's checkpoint slot: a directory holding the
    carry pytree + host clocks under an identity token."""

    def __init__(self, path, token: str, every: int = 1):
        self.path = os.path.abspath(path)
        self.token = np.frombuffer(
            token.encode()[:_TOKEN_BYTES].ljust(_TOKEN_BYTES), np.uint8
        )
        self.every = max(int(every), 1)

    def due(self, pass_no: int) -> bool:
        """Save after this pass? (every N-th, counting from 1)."""
        return pass_no % self.every == 0

    def restore(self):
        """The saved state dict (numpy leaves) when a checkpoint with a
        MATCHING token exists, else None — wrong-fingerprint / corrupt /
        absent checkpoints all mean "start fresh", never an error."""
        from ..utils import checkpoint as ckpt

        if not ckpt.checkpoint_exists(self.path):
            return None
        try:
            state = ckpt.restore_pytree(self.path)
        except Exception:
            return None
        try:
            tok = np.asarray(state.get("token"))
            if tok.shape != self.token.shape or \
                    not np.array_equal(tok, self.token):
                return None
        except Exception:
            return None
        return {k: v for k, v in state.items() if k != "token"}

    def save(self, **state) -> None:
        """Persist ``state`` (numpy-able leaves) under the token. Rides
        ``utils.checkpoint.save_pytree``'s atomic rename, so a kill at
        ANY point leaves either the previous or the new checkpoint
        restorable."""
        from ..observability._counters import record_stream_checkpoint
        from ..utils import checkpoint as ckpt

        tree = {"token": self.token}
        for k, v in state.items():
            if v is None:
                continue
            tree[k] = np.asarray(v)
        ckpt.save_pytree(self.path, tree)
        record_stream_checkpoint()

    def clear(self) -> None:
        """Remove the checkpoint (called on successful completion)."""
        for suffix in ("", ".old", ".tmp"):
            shutil.rmtree(self.path + suffix, ignore_errors=True)


def fit_token(kind, token_parts, arrays=()) -> str:
    """The identity token: fit kind + stringified hyperparameter parts
    + a content fingerprint of every data array."""
    from ..utils.validation import data_fingerprint

    parts = [str(kind)] + [repr(p) for p in token_parts]
    for a in arrays:
        parts.append(data_fingerprint(a))
    return hashlib.sha1("|".join(parts).encode()).hexdigest()


def stream_checkpoint(kind, token_parts, arrays=()):
    """A :class:`StreamCheckpoint` for one streamed fit, or None when
    checkpointing is off (``stream_checkpoint_path`` unset) or refused
    (multi-process / virtual-world runtime — resume must be collective).
    ``kind`` ("sgd" / "glm" / "incremental") namespaces the slot so
    concurrent fits of different kinds under one path don't clobber."""
    from ..config import get_config

    cfg = get_config()
    if not cfg.stream_checkpoint_path:
        return None
    from ..parallel import distributed as dist

    if dist.process_count() > 1 or dist.in_virtual_world():
        return None
    path = os.path.join(cfg.stream_checkpoint_path, str(kind))
    return StreamCheckpoint(
        path, fit_token(kind, token_parts, arrays),
        every=cfg.stream_checkpoint_every,
    )
