"""Fit from a dataset that never fits on device: np.memmap streams
through the solver in fixed-shape blocks (double-buffered host->device).

The reference streams dask chunks between workers; here blocks stream
host RAM -> HBM with the optimizer state resident on device.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

import numpy as np

from dask_ml_tpu import config
from dask_ml_tpu.linear_model import LogisticRegression

n, d = int(os.environ.get("DASK_ML_TPU_EXAMPLE_N", 500_000)), 32
rng = np.random.RandomState(0)
w = rng.randn(d).astype(np.float32)

path = os.path.join(tempfile.mkdtemp(), "example_X.f32")
X = np.memmap(path, dtype=np.float32, mode="w+", shape=(n, d))
for lo in range(0, n, 100_000):  # write in chunks: no full matrix in RAM
    X[lo:lo + 100_000] = rng.randn(min(100_000, n - lo), d)
X.flush()
y = (np.asarray(X) @ w > 0).astype(np.float32)

X_ro = np.memmap(path, dtype=np.float32, mode="r", shape=(n, d))
with config.set(stream_block_rows=min(100_000, n // 4)):
    clf = LogisticRegression(solver="lbfgs", max_iter=50).fit(X_ro, y)
print("train accuracy:", (clf.predict(X_ro) == y).mean())
