"""Live-scrape verify gate (ISSUE 5): a SUBPROCESS streamed fit with
``obs_http_port`` set must be scrapable while it runs.

The parent picks a free port, launches a child that runs a streamed SGD
fit with ``DASK_ML_TPU_OBS_HTTP_PORT`` pointing at it (then lingers
briefly so a slow scraper still sees the final state), and asserts:

- ``/healthz`` answers 200;
- ``/metrics`` parses as Prometheus text and contains >= 1 histogram
  series and >= 1 fit progress gauge (``fit_pass``);
- ``/status`` is valid JSON naming this child's pid;
- (ISSUE 16) after the child turns the fit into a TRACED serving phase
  under an artificially tight SLO, ``/traces`` shows the violating
  requests tail-sampled with a COMPLETE stage breakdown (every
  lifecycle stage stamped, slo_violation tagged) while the process is
  still up.

Prints one JSON line: {"ok": true, "fit_pass": ..., "histograms": ...,
"slo_traces": ...}.
Run: ``python scripts/live_smoke.py`` (exit 0 = gate holds).
"""

import json
import os
import re
import socket
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHILD = r"""
import os, time
import numpy as np
from dask_ml_tpu import config
from dask_ml_tpu.models.sgd import SGDClassifier

rng = np.random.RandomState(0)
X = rng.randn(120_000, 16).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)
with config.set(stream_block_rows=4096):
    SGDClassifier(max_iter=8, random_state=0).fit(X, y)
print("FIT_DONE", flush=True)
# serving phase under the same exporter: tracing ON, SLO artificially
# tight (1us) so every executed request violates it — the tail sampler
# must keep ALL of them with a complete stage breakdown on /traces
from dask_ml_tpu.datasets import make_classification
from dask_ml_tpu.linear_model import LogisticRegression
from dask_ml_tpu.serving import BucketLadder, ModelServer

Xs, ys = make_classification(
    n_samples=300, n_features=6, n_informative=4, random_state=0
)
clf = LogisticRegression(solver="lbfgs", max_iter=20).fit(Xs, ys)
Xh = Xs.to_numpy().astype(np.float32)
with config.set(obs_trace_sample=1.0, serving_slo_ms=0.001):
    with ModelServer(clf, ladder=BucketLadder(8, 64, 2.0)) as srv:
        srv.warmup()
        for i in range(6):
            srv.submit(Xh[: 4 + i]).result(30)
        print("SERVE_DONE", flush=True)
        # keep the exporter (and sampler state) up so the parent's
        # final scrape can't race the exit
        time.sleep(float(os.environ.get("LIVE_SMOKE_LINGER", "20")))
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url, timeout=2.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def main():
    out = {"ok": False}
    port = _free_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "DASK_ML_TPU_OBS_HTTP_PORT": str(port)}
    child = subprocess.Popen(
        [sys.executable, "-c", CHILD], env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )
    base = f"http://127.0.0.1:{port}"
    deadline = time.time() + 120
    try:
        # 1) liveness comes up with the fit
        while True:
            try:
                status, body = _get(base + "/healthz")
                assert status == 200 and body == "ok\n"
                break
            except AssertionError:
                raise
            except Exception:
                if child.poll() is not None or time.time() > deadline:
                    # stderr.read() on a LIVE child blocks until EOF —
                    # kill it first so the diagnostic actually prints
                    if child.poll() is None:
                        child.kill()
                        child.wait(10)
                    raise RuntimeError(
                        "child exited or deadline passed before "
                        "/healthz answered: "
                        + child.stderr.read().decode()[-2000:]
                    )
                time.sleep(0.05)
        # 2) scrape until the progress gauge and a histogram series show
        #    (the fit may still be mid-flight — that is the point)
        fit_pass = None
        n_hist = 0
        while time.time() < deadline:
            _, text = _get(base + "/metrics")
            m = re.search(r"^dask_ml_tpu_fit_pass (\d+)", text,
                          re.MULTILINE)
            hists = set(re.findall(
                r"^# TYPE (dask_ml_tpu_\w+) histogram$", text,
                re.MULTILINE,
            ))
            if m and hists:
                fit_pass, n_hist = int(m.group(1)), len(hists)
                break
            if child.poll() is not None:
                raise RuntimeError(
                    "child exited before /metrics showed a progress "
                    "gauge + histogram"
                )
            time.sleep(0.05)
        if fit_pass is None:
            raise RuntimeError("deadline: no progress gauge/histogram")
        # every sample line must be grammar-clean
        for line in text.rstrip("\n").split("\n"):
            assert line.startswith("#") or re.match(
                r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
                r"(-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$", line
            ), f"bad exposition line: {line!r}"
        # 3) /status belongs to the child
        _, body = _get(base + "/status")
        status_doc = json.loads(body)
        assert status_doc["pid"] == child.pid, (status_doc["pid"],
                                                child.pid)
        # 4) the serving phase's SLO-violating requests are on /traces,
        #    tail-sampled with a COMPLETE stage breakdown
        full_stages = {"admit", "queue_pop", "pack", "dispatch",
                       "execute_done", "demux", "complete"}
        slo_traces = 0
        while time.time() < deadline:
            _, body = _get(base + "/traces")
            doc = json.loads(body)
            slo = [t for t in doc.get("traces", [])
                   if t.get("slo_violation")
                   and set(t.get("stages", {})) == full_stages
                   and t.get("outcome") == "ok"]
            if len(slo) >= 6:
                slo_traces = len(slo)
                break
            if child.poll() is not None:
                raise RuntimeError(
                    "child exited before /traces sampled the "
                    "SLO-violating requests"
                )
            time.sleep(0.05)
        if not slo_traces:
            raise RuntimeError(
                "deadline: /traces never showed the SLO-violating "
                "requests with complete breakdowns"
            )
        # the trace-fed queue-wait family reached /metrics too
        _, text = _get(base + "/metrics")
        assert re.search(
            r"^dask_ml_tpu_serving_queue_wait_seconds_bucket\{", text,
            re.MULTILINE,
        ), "serving_queue_wait_seconds missing from /metrics"
        out.update(ok=True, fit_pass=fit_pass, histograms=n_hist,
                   slo_traces=slo_traces, port=port)
    except Exception as exc:
        out["error"] = f"{type(exc).__name__}: {exc}"
    finally:
        child.terminate()
        try:
            child.wait(10)
        except Exception:
            child.kill()
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
