"""SimpleImputer parity vs sklearn (ref: dask_ml/impute.py; SURVEY.md §2a
Imputation row — strategies mean/median/most_frequent/constant)."""

import numpy as np
import pytest
from sklearn.impute import SimpleImputer as SkImputer

from dask_ml_tpu.impute import SimpleImputer


@pytest.fixture(scope="module")
def data_nan():
    rng = np.random.RandomState(0)
    X = rng.randn(300, 6) * 3 + 1
    miss = rng.uniform(size=X.shape) < 0.15
    X[miss] = np.nan
    return X


def _np(a):
    return a.to_numpy() if hasattr(a, "to_numpy") else np.asarray(a)


@pytest.mark.parametrize("strategy", ["mean", "median", "most_frequent"])
def test_strategy_parity(data_nan, strategy):
    X = data_nan
    ours = SimpleImputer(strategy=strategy).fit(X)
    sk = SkImputer(strategy=strategy).fit(X)
    rtol = 1e-4 if strategy != "median" else 2e-2  # device quantile interp
    np.testing.assert_allclose(
        np.asarray(ours.statistics_), sk.statistics_, rtol=rtol, atol=1e-3
    )
    out = _np(ours.transform(X))
    assert not np.isnan(out).any()
    np.testing.assert_allclose(out, sk.transform(X), rtol=rtol, atol=1e-3)


def test_constant_strategy(data_nan):
    X = data_nan
    ours = SimpleImputer(strategy="constant", fill_value=-7.0).fit(X)
    out = _np(ours.transform(X))
    sk_out = SkImputer(strategy="constant", fill_value=-7.0).fit_transform(X)
    np.testing.assert_allclose(out, sk_out, rtol=1e-5)


def test_custom_missing_value():
    X = np.array([[1.0, -1.0], [3.0, 4.0], [-1.0, 6.0]])
    ours = SimpleImputer(missing_values=-1.0, strategy="mean").fit(X)
    sk = SkImputer(missing_values=-1.0, strategy="mean").fit(X)
    np.testing.assert_allclose(
        np.asarray(ours.statistics_), sk.statistics_, rtol=1e-5
    )
    np.testing.assert_allclose(_np(ours.transform(X)), sk.transform(X),
                               rtol=1e-5)


def test_bad_strategy_raises():
    with pytest.raises(ValueError):
        SimpleImputer(strategy="nope").fit(np.ones((4, 2)))


def test_imputer_rejects_infinity():
    # NaN is the imputer's job; infinity is still invalid (sklearn's
    # 'allow-nan' mode)
    import pytest

    from dask_ml_tpu.impute import SimpleImputer

    X = np.array([[1.0, np.nan], [np.inf, 2.0]], np.float32)
    with pytest.raises(ValueError, match="infinity"):
        SimpleImputer(strategy="mean").fit(X)


def test_quantile_scalers_accept_nan():
    from dask_ml_tpu.preprocessing import QuantileTransformer, RobustScaler

    rng = np.random.RandomState(0)
    X = rng.randn(200, 3).astype(np.float32)
    X[::11, 1] = np.nan
    for est in (RobustScaler(), QuantileTransformer(n_quantiles=20)):
        est.fit(X)  # NaN-skipping statistics: must not raise


def test_imputer_on_partitioned_frame():
    """SimpleImputer consumes frames through the ShardedArray bridge and
    matches sklearn's statistics."""
    import pandas as pd

    from dask_ml_tpu.parallel import from_pandas

    rng = np.random.RandomState(0)
    df = pd.DataFrame({"a": rng.randn(120), "b": rng.rand(120)})
    df.iloc[::7, 0] = np.nan
    pf = from_pandas(df, npartitions=4)
    Xs = pf.to_sharded()
    imp = SimpleImputer(strategy="mean").fit(Xs)
    ref = SkImputer(strategy="mean").fit(df)
    np.testing.assert_allclose(imp.statistics_, ref.statistics_, rtol=1e-5)
    out = imp.transform(Xs).to_numpy()
    np.testing.assert_allclose(out, ref.transform(df), rtol=1e-5)
