"""Row-sharded array substrate — the TPU-native replacement for the
reference's chunked ``dask.array`` data model (SURVEY.md §2b, row 1:
``dask/array/core.py`` blockwise collections).

Design (SURVEY.md §7 B0): a :class:`ShardedArray` is a padded ``jax.Array``
laid out with ``NamedSharding(P("data", ...))`` over a device mesh, plus the
*logical* row count. Global-view GSPMD programming replaces dask's per-block
task graphs: ``jnp`` ops on the padded array are traced once under ``jit``
and XLA inserts the ICI collectives that dask would have expressed as
tree-reduce task graphs.

Padding: XLA needs equal shards, so rows are padded to a multiple of the
data-axis size. Padded rows are zero; every reduction in ``ops/`` is
mask-aware (``row_mask``) so they never contribute. This replaces dask's
ragged-final-chunk handling.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import (
    DATA_AXIS, MODEL_AXIS, data_shards, logical_axis_spec, resolve_mesh,
)


def _padded_rows(n_rows: int, n_shards: int) -> int:
    return max(n_shards, math.ceil(n_rows / n_shards) * n_shards)


def _scatter(x, mesh: Mesh, spec) -> jax.Array:
    """Place an array onto ``mesh`` with ``spec`` — the ONE placement
    primitive for host and device inputs, single- and multi-host meshes.

    Multi-host meshes can't be reached by ``device_put`` (it only places
    onto this process's devices): every process holds the same full array
    (SPMD discipline) and materializes ONLY its addressable shards via
    ``make_array_from_callback`` — the reference's scatter step with no
    bytes over sockets beyond the runtime's own control plane.
    """
    sharding = NamedSharding(mesh, spec)
    if not sharding.is_fully_addressable:  # mesh spans other processes
        if isinstance(x, jax.Array):
            if x.sharding == sharding:  # already placed as requested
                return x
            if not x.is_fully_addressable:
                raise NotImplementedError(
                    "re-placing an already cross-process array onto a "
                    "different multi-host sharding is not supported; "
                    "gather to host first (to_numpy)"
                )
            x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx]
        )
    return jax.device_put(x, sharding)


@functools.lru_cache(maxsize=32)
def _replicator(mesh: Mesh):
    """Cached replicating identity per mesh: the cross-host all-gather
    program ``to_numpy`` uses — a fresh lambda per call would retrace and
    recompile every time."""
    return jax.jit(lambda v: v, out_shardings=NamedSharding(mesh, P()))


class ShardedArray:
    """A logically (n_rows, *feature_dims) array, row-sharded over a mesh.

    Parameters
    ----------
    data : jax.Array
        Padded device array, leading axis divisible by the mesh's data size.
    n_rows : int
        Logical (unpadded) number of rows.
    mesh : Mesh
    """

    __slots__ = ("data", "n_rows", "mesh")

    def __init__(self, data: jax.Array, n_rows: int, mesh: Mesh):
        self.data = data
        self.n_rows = int(n_rows)
        self.mesh = mesh

    # -- construction -----------------------------------------------------
    @classmethod
    def from_array(cls, x, mesh: Mesh | None = None, dtype=None,
                   shard_features: bool = False) -> "ShardedArray":
        """Place a host (numpy) or device array onto the mesh, row-sharded.

        Equivalent of ``da.from_array`` + scatter in the reference; here it
        is one ``device_put`` with a NamedSharding (no serialization layer —
        SURVEY.md §5 comm row).

        ``shard_features=True`` additionally shards axis 1 over the mesh's
        ``"model"`` axis (2-D tensor-parallel layout for wide-feature
        problems, SURVEY.md §2c TP row) — GSPMD then inserts the psum for
        feature-contracted matmuls automatically.
        """
        if isinstance(x, ShardedArray):
            return x if dtype is None else cls(x.data.astype(dtype), x.n_rows, x.mesh)
        import scipy.sparse as sp

        if sp.issparse(x):
            # densify-on-placement: correct for BLOCK-sized sparse inputs
            # (an Incremental partial_fit block). Whole-corpus sparse fits
            # never reach here — estimator fit paths route sparse through
            # stream_plan/BlockStream, which densifies one block at a time
            from .streaming import _csr_dense

            x = _csr_dense(x.tocsr(), 0, x.shape[0],
                           x.dtype if dtype is None else dtype)
        mesh = resolve_mesh(mesh)
        on_device = isinstance(x, jax.Array) and not isinstance(
            x, jax.core.Tracer
        )
        if on_device:
            # pad + reshard on device — never round-trip through host
            # memory (the tunnel/PCIe hop dominates at scale)
            xp = jnp
            if dtype is not None:
                x = x.astype(dtype)
        else:
            xp = np
            x = np.asarray(x)
            if dtype is not None:
                x = x.astype(dtype, copy=False)
        n = x.shape[0]
        n_pad = _padded_rows(n, data_shards(mesh))
        if n_pad != n:
            pad_widths = [(0, n_pad - n)] + [(0, 0)] * (x.ndim - 1)
            x = xp.pad(x, pad_widths)
        feat = "feature" if shard_features and x.ndim >= 2 else None
        axes = (("batch", feat) + (None,) * (x.ndim - 2))[: x.ndim]
        spec = logical_axis_spec(axes, mesh)
        data = _scatter(x, mesh, spec)
        return cls(data, n, mesh)

    # -- basic properties -------------------------------------------------
    @property
    def shape(self):
        return (self.n_rows,) + tuple(self.data.shape[1:])

    @property
    def padded_shape(self):
        return tuple(self.data.shape)

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def sharding(self) -> NamedSharding:
        return self.data.sharding

    def __len__(self):
        return self.n_rows

    def __repr__(self):
        return (
            f"ShardedArray(shape={self.shape}, padded={self.padded_shape}, "
            f"dtype={self.dtype}, shards={data_shards(self.mesh)})"
        )

    # -- masks ------------------------------------------------------------
    def row_mask(self, dtype=jnp.float32) -> jax.Array:
        """(n_padded,) mask: 1 for logical rows, 0 for padding. Sharded the
        same way as ``data``'s rows so masked reductions stay local."""
        return row_mask(self.padded_shape[0], self.n_rows, self.mesh, dtype)

    # -- host round-trip --------------------------------------------------
    def to_numpy(self) -> np.ndarray:
        if not self.data.is_fully_addressable:
            # multi-host mesh: replicate via an in-program all-gather
            # (ICI/DCN), then read the local copy — np.asarray on a
            # cross-process array would raise
            rep = _replicator(self.mesh)(self.data)
            return np.asarray(rep)[: self.n_rows]
        return np.asarray(self.data)[: self.n_rows]

    def astype(self, dtype) -> "ShardedArray":
        return ShardedArray(self.data.astype(dtype), self.n_rows, self.mesh)

    # -- pickling ---------------------------------------------------------
    def __getstate__(self):
        """Pickle as the logical HOST array (devices and meshes don't
        pickle); unpickling re-shards onto the ambient mesh — a model
        saved on an 8-chip slice loads on a 1-chip box and vice versa.
        Fitted estimators holding ShardedArray attributes (KMeans.labels_
        et al) become persistable exactly like the reference's estimators
        holding dask arrays."""
        if not self.data.is_fully_addressable:
            # to_numpy on a multi-host array launches a COLLECTIVE; a
            # rank-0-only pickle (the normal save pattern) would deadlock
            # waiting for peers mid-pickle. Make the caller gather first,
            # where every process can participate.
            raise ValueError(
                "cannot pickle a cross-process ShardedArray directly: "
                "call to_numpy() on ALL processes first and pickle the "
                "host array"
            )
        from .mesh import MODEL_AXIS

        spec = getattr(self.data.sharding, "spec", ())
        model_sharded = len(spec) > 1 and spec[1] == MODEL_AXIS
        return {"host": self.to_numpy(), "n_rows": self.n_rows,
                "model_sharded": model_sharded}

    def __setstate__(self, state):
        restored = ShardedArray.from_array(
            state["host"], shard_features=state.get("model_sharded", False)
        )
        self.data = restored.data
        self.n_rows = int(state["n_rows"])
        self.mesh = restored.mesh




@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _row_mask(n_padded: int, n_rows: int, sharding, dtype) -> jax.Array:
    idx = jnp.arange(n_padded)
    return jax.lax.with_sharding_constraint((idx < n_rows).astype(dtype), sharding)


# result cache is bounded by SIZE, not just count: a cached (n,) f32 mask
# pins n*4 bytes of device memory for the process lifetime
_MASK_CACHE_MAX_ROWS = 4_194_304  # <= 16 MB per entry, 8 entries


@functools.lru_cache(maxsize=8)
def _row_mask_cached(n_padded: int, n_rows: int, mesh: Mesh, dtype):
    return _row_mask(n_padded, n_rows, NamedSharding(mesh, P(DATA_AXIS)), dtype)


def row_mask(n_padded: int, n_rows: int, mesh: Mesh, dtype=jnp.float32) -> jax.Array:
    # RESULT-cached for small/medium masks (they are requested several
    # times per fit, and on tunneled runtimes every program launch costs
    # a round trip); huge masks are rebuilt rather than pinned in HBM
    if n_padded <= _MASK_CACHE_MAX_ROWS:
        return _row_mask_cached(n_padded, n_rows, mesh, dtype)
    return _row_mask(n_padded, n_rows, NamedSharding(mesh, P(DATA_AXIS)), dtype)


def as_sharded(x, mesh: Mesh | None = None, dtype=None) -> ShardedArray:
    """Canonicalize numpy / jax / ShardedArray input to ShardedArray."""
    return ShardedArray.from_array(x, mesh=mesh, dtype=dtype)


def reshard(x: ShardedArray, mesh: Mesh | None = None) -> ShardedArray:
    """Move a ShardedArray onto a different mesh — the rechunk-parity
    primitive (ref ``dask/array/rechunk.py``, SURVEY.md §5 long-context
    row). The repartition lowers to XLA collective-permute/all-to-all over
    ICI when the device sets overlap; no task graph, no serialization.

    Padding is recomputed for the target mesh's data-axis size (old
    padding rows are zero, so slicing/padding on device preserves the
    masked-reduction invariant).
    """
    mesh = resolve_mesh(mesh)
    if mesh is x.mesh or mesh == x.mesh:
        return x
    # slice off the old padding on device, then reuse from_array's
    # on-device pad + placement path for the target mesh
    return ShardedArray.from_array(x.data[: x.n_rows], mesh=mesh)


def take_rows(x: ShardedArray, idx) -> ShardedArray:
    """New ShardedArray of x's rows at (host) integer indices ``idx``.

    The resharding primitive behind train/test splits and CV fold
    extraction — the reference's rechunk/shuffle task graphs
    (``dask/array/rechunk.py``, SURVEY.md §5 long-context row) become one
    gather that XLA lowers to an all-to-all over ICI."""
    idx = np.asarray(idx)
    if idx.ndim != 1:
        raise ValueError(f"idx must be 1-D, got shape {idx.shape}")
    if idx.size and ((idx < 0).any() or (idx >= x.n_rows).any()):
        raise IndexError(
            f"indices out of bounds for {x.n_rows} rows: "
            f"[{idx.min()}, {idx.max()}] (jnp.take would clamp silently)"
        )
    n_out = idx.shape[0]
    shards = data_shards(x.mesh)
    n_pad = _padded_rows(n_out, shards)
    # pad with index 0 (any valid row): padded rows are masked by n_rows
    idx_padded = np.zeros(n_pad, np.int32)
    idx_padded[:n_out] = idx
    spec = P(*((DATA_AXIS,) + (None,) * (x.ndim - 1)))
    sharding = NamedSharding(x.mesh, spec)
    idx_dev = _scatter(idx_padded, x.mesh, P(DATA_AXIS))

    @jax.jit
    def gather(data, indices):
        out = jnp.take(data, indices, axis=0)
        return jax.lax.with_sharding_constraint(out, sharding)

    out = gather(x.data, idx_dev)
    # re-zero rows that came from padding of the source or of the output
    out_arr = ShardedArray(out, n_out, x.mesh)
    mask = out_arr.row_mask(out.dtype)
    out_arr.data = out * (mask.reshape((n_pad,) + (1,) * (x.ndim - 1)))
    return out_arr
