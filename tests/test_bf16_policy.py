"""config.dtype="bfloat16" beyond the GLMs (VERDICT r4 missing #5):
KMeans distances, the PCA streamed Gram, and the SGD epoch grid run
their matmuls at bf16 with f32 accumulation. Parity tolerances here
document the expected bf16 input-rounding error (~1e-2 relative)."""

import numpy as np
import pytest

import dask_ml_tpu.config as config

rng = np.random.RandomState(0)


def test_kmeans_bf16_parity():
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.parallel import as_sharded

    # two well-separated blobs + near-true init: the converged partition
    # is unambiguous, so parity isolates bf16 distance rounding (not
    # Lloyd's local-minimum sensitivity)
    X = rng.randn(4000, 16).astype(np.float32)
    X[:2000] += 6.0
    Xs = as_sharded(X)
    init = np.stack([np.full(16, 5.5, np.float32),
                     np.full(16, 0.5, np.float32)])
    f32 = KMeans(n_clusters=2, init=init, max_iter=20, random_state=0,
                 use_pallas=False).fit(Xs)
    with config.set(dtype="bfloat16"):
        b16 = KMeans(n_clusters=2, init=init, max_iter=20,
                     random_state=0, use_pallas=False).fit(Xs)
    np.testing.assert_allclose(
        b16.cluster_centers_, f32.cluster_centers_, rtol=2e-2, atol=2e-2
    )
    # inertia within bf16 rounding of distances
    assert abs(b16.inertia_ - f32.inertia_) / f32.inertia_ < 2e-2


def test_kmeans_streamed_bf16_parity():
    """The out-of-core Lloyd honors the dtype policy too — the policy
    must not silently depend on whether the data fit in memory."""
    from dask_ml_tpu.cluster import KMeans

    X = rng.randn(4000, 8).astype(np.float32)
    X[:2000] += 6.0
    init = np.stack([np.full(8, 5.5, np.float32),
                     np.full(8, 0.5, np.float32)])
    with config.set(stream_block_rows=512):
        f32 = KMeans(n_clusters=2, init=init, max_iter=10,
                     random_state=0).fit(X)
        with config.set(dtype="bfloat16"):
            b16 = KMeans(n_clusters=2, init=init, max_iter=10,
                         random_state=0).fit(X)
    np.testing.assert_allclose(
        b16.cluster_centers_, f32.cluster_centers_, rtol=2e-2, atol=2e-2
    )


def test_pca_streamed_gram_bf16_parity():
    from dask_ml_tpu.decomposition import PCA

    X = rng.randn(5000, 12).astype(np.float32)
    with config.set(stream_block_rows=1024):
        f32 = PCA(n_components=4).fit(X)
        with config.set(dtype="bfloat16"):
            b16 = PCA(n_components=4).fit(X)
    np.testing.assert_allclose(b16.mean_, f32.mean_, atol=1e-3)
    np.testing.assert_allclose(
        np.abs(b16.components_ @ f32.components_.T), np.eye(4), atol=5e-2
    )
    np.testing.assert_allclose(
        b16.explained_variance_ratio_, f32.explained_variance_ratio_,
        rtol=5e-2,
    )


def test_sgd_fused_epoch_bf16_parity():
    from dask_ml_tpu.models.sgd import SGDClassifier
    from dask_ml_tpu.parallel import as_sharded
    from dask_ml_tpu.wrappers import Incremental

    X = rng.randn(2000, 10).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    Xs, ys = as_sharded(X), as_sharded(y)
    kw = dict(loss="log_loss", random_state=0, max_iter=2)
    f32 = Incremental(SGDClassifier(**kw), shuffle_blocks=False)
    f32.fit(Xs, ys)
    with config.set(dtype="bfloat16"):
        b16 = Incremental(SGDClassifier(**kw), shuffle_blocks=False)
        b16.fit(Xs, ys)
    np.testing.assert_allclose(
        b16.estimator_.coef_, f32.estimator_.coef_, rtol=5e-2, atol=1e-3
    )
    agree = (b16.estimator_.predict(Xs) == f32.estimator_.predict(Xs))
    assert agree.mean() > 0.99


def test_unknown_dtype_raises_and_pallas_warns():
    from dask_ml_tpu.config import mxu_dtype

    # "bf16" is an accepted ALIAS since ISSUE 8 (it used to be the
    # canonical example typo); a real typo still raises
    with config.set(dtype="bf16"):
        import jax.numpy as jnp

        assert mxu_dtype() is jnp.bfloat16
    with config.set(dtype="b16"):
        with pytest.raises(ValueError, match="not supported"):
            mxu_dtype()
    # explicit Pallas + bf16: warned, not silently dropped
    from dask_ml_tpu.cluster import KMeans
    from dask_ml_tpu.parallel import as_sharded

    X = as_sharded(rng.randn(200, 4).astype(np.float32))
    with config.set(dtype="bfloat16"):
        with pytest.warns(RuntimeWarning, match="Pallas"):
            KMeans(n_clusters=2, random_state=0, max_iter=1,
                   use_pallas=True).fit(X)


def test_bf16_leaves_f32_defaults_untouched():
    """Default config must not change dtypes anywhere (guards against a
    latched global)."""
    from dask_ml_tpu.models.sgd import _grid_builders
    from dask_ml_tpu.parallel import as_sharded

    # default policy is "auto" — which must resolve to f32 dtypes
    # everywhere on this CPU backend
    assert config.get_config().dtype == "auto"
    assert config.mxu_dtype() is None
    X = rng.randn(64, 4).astype(np.float32)
    Xs = as_sharded(X)
    fX, _ = _grid_builders(Xs.mesh, 8, 8, None)
    assert fX(Xs.data).dtype == np.float32
