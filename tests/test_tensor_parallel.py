"""2-D ("data", "model") mesh: the tensor-parallel layout for
wide-feature problems (SURVEY.md §2c TP row). shard_features=True places
the feature axis over the model axis; GSPMD inserts the psums for
feature-contracted matmuls. These tests close VERDICT r2 weak #6: the TP
path was previously untested end-to-end."""

import numpy as np
import pytest

from dask_ml_tpu.parallel import as_sharded
from dask_ml_tpu.parallel.mesh import MODEL_AXIS, device_mesh, use_mesh
from dask_ml_tpu.parallel.sharded import ShardedArray


@pytest.fixture(scope="module")
def mesh2d():
    return device_mesh((4, 2), ("data", "model"))


@pytest.fixture(scope="module")
def clf_data():
    rng = np.random.RandomState(0)
    X = rng.randn(400, 16).astype(np.float32)
    beta = rng.randn(16) / 4
    y = (X @ beta + 0.1 * rng.randn(400) > 0).astype(np.float32)
    return X, y


def test_feature_sharded_roundtrip(mesh2d):
    rng = np.random.RandomState(1)
    X = rng.randn(100, 8).astype(np.float32)
    Xs = ShardedArray.from_array(X, mesh=mesh2d, shard_features=True)
    spec = Xs.data.sharding.spec
    assert spec[1] == MODEL_AXIS, spec  # feature axis IS model-sharded
    np.testing.assert_array_equal(Xs.to_numpy(), X)
    # reductions stay exact with padding on a 2-D mesh
    from dask_ml_tpu.ops.reductions import masked_mean_var

    mean, var = masked_mean_var(Xs.data, Xs.row_mask(np.float32), Xs.n_rows)
    np.testing.assert_allclose(np.asarray(mean), X.mean(0), atol=1e-5)


@pytest.mark.parametrize("solver", ["lbfgs", "newton"])
def test_glm_fit_parity_tensor_parallel(mesh2d, clf_data, solver):
    """LogisticRegression over a feature-sharded design matrix must match
    the pure data-parallel fit — the psum GSPMD inserts for the
    feature-contracted matvec changes layout, not math."""
    from dask_ml_tpu.linear_model import LogisticRegression

    X, y = clf_data
    ref = LogisticRegression(solver=solver, max_iter=100).fit(
        as_sharded(X), as_sharded(y)
    )
    Xtp = ShardedArray.from_array(X, mesh=mesh2d, shard_features=True)
    ytp = ShardedArray.from_array(y, mesh=mesh2d)
    with use_mesh(mesh2d):
        tp = LogisticRegression(solver=solver, max_iter=100).fit(Xtp, ytp)
    np.testing.assert_allclose(tp.coef_, ref.coef_, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(tp.intercept_, ref.intercept_,
                               rtol=1e-3, atol=1e-4)
    assert tp.score(Xtp, ytp) == pytest.approx(ref.score(X, y), abs=1e-6)


@pytest.mark.slow
def test_pca_fit_parity_tensor_parallel(mesh2d):
    from dask_ml_tpu.decomposition import PCA

    rng = np.random.RandomState(2)
    X = (rng.randn(300, 12) * np.linspace(4, 0.2, 12)).astype(np.float32)
    ref = PCA(n_components=4, svd_solver="full").fit(as_sharded(X))
    Xtp = ShardedArray.from_array(X, mesh=mesh2d, shard_features=True)
    with use_mesh(mesh2d):
        tp = PCA(n_components=4, svd_solver="full").fit(Xtp)
    np.testing.assert_allclose(tp.explained_variance_,
                               ref.explained_variance_, rtol=1e-4)
    np.testing.assert_allclose(tp.components_, ref.components_,
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(tp.mean_, ref.mean_, atol=1e-5)


def test_kmeans_fit_parity_tensor_parallel(mesh2d):
    from dask_ml_tpu.cluster import KMeans

    rng = np.random.RandomState(3)
    centers_true = rng.randn(3, 8).astype(np.float32) * 4
    X = np.concatenate([
        centers_true[i] + 0.3 * rng.randn(150, 8).astype(np.float32)
        for i in range(3)
    ])
    init = centers_true + 0.5
    ref = KMeans(n_clusters=3, init=init, max_iter=40).fit(as_sharded(X))
    Xtp = ShardedArray.from_array(X, mesh=mesh2d, shard_features=True)
    with use_mesh(mesh2d):
        tp = KMeans(n_clusters=3, init=init, max_iter=40,
                    use_pallas=False).fit(Xtp)
    np.testing.assert_allclose(tp.cluster_centers_, ref.cluster_centers_,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(tp.inertia_, ref.inertia_, rtol=1e-4)
