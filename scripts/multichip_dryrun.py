"""8-device CPU multichip dryrun with a recorded flight-recorder trace.

Extends the MULTICHIP_r*.json dryrun (8 virtual XLA:CPU devices via
``--xla_force_host_platform_device_count``) beyond "does the sharded
path run": the run records a span trace + program registry under
``config.trace_dir`` (spans) plus a separate counters/programs file and
ASSERTS ``report --merge`` folds both into ONE timeline rendering spans
AND a programs table for the sharded L-BFGS and ADMM fit paths — the
observability the next wedged-TPU round will need, proven on the same
virtual mesh the tier-1 suite uses.

Prints one JSON line (MULTICHIP_r*.json shape, plus the trace fields):

    {"n_devices": 8, "ok": true, "rc": 0, "trace_records": ...,
     "report_spans": [...], "report_programs": [...]}

Run: ``python scripts/multichip_dryrun.py``.
"""

import json
import os
import sys
import tempfile
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8 virtual devices BEFORE jax initializes; never downgrade an explicit
# operator setting
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_DEVICES = 8


def main():
    out = {"n_devices": None, "rc": 0, "ok": False, "skipped": False,
           "tail": ""}
    trace_dir = tempfile.mkdtemp(prefix="multichip_trace_")
    try:
        import jax
        import numpy as np

        out["n_devices"] = len(jax.devices())
        if out["n_devices"] < N_DEVICES:
            raise RuntimeError(
                f"expected {N_DEVICES} virtual devices, got "
                f"{out['n_devices']} (XLA_FLAGS not honored?)"
            )
        from dask_ml_tpu import config
        from dask_ml_tpu import observability as obs
        from dask_ml_tpu.linear_model import LogisticRegression
        from dask_ml_tpu.observability.report import (build_report,
                                                      load_records,
                                                      report_data)
        from dask_ml_tpu.parallel import as_sharded

        rng = np.random.RandomState(0)
        n, d = 16_384, 32
        X = rng.randn(n, d).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        Xs, ys = as_sharded(X), as_sharded(y)
        obs.programs_reset()
        with config.set(trace_dir=trace_dir, obs_programs=True):
            # the two sharded solve flavors: one-program L-BFGS
            # (per-shard matmuls + psum) and shard_map consensus ADMM
            lb = LogisticRegression(solver="lbfgs", max_iter=20).fit(Xs, ys)
            ad = LogisticRegression(solver="admm", max_iter=20).fit(Xs, ys)
            assert lb.score(Xs, ys) > 0.6 and ad.score(Xs, ys) > 0.6
            # sharded STREAMED fits (ISSUE 9): host data, super-blocks
            # batch-sharded over the 8-device mesh, psum-bearing
            # shard_map scan programs — SGD (per-step gradient psum)
            # and the streamed GLM vg reducer (one psum per super-block)
            from dask_ml_tpu.models.sgd import SGDClassifier

            with config.set(stream_block_rows=n // 8,
                            trace_dir=trace_dir, obs_programs=True):
                ssgd = SGDClassifier(max_iter=2, random_state=0,
                                     shuffle=False).fit(X, y)
                sglm = LogisticRegression(solver="lbfgs",
                                          max_iter=10).fit(X, y)
            sgd_st = dict(getattr(ssgd, "_last_stream_stats", None)
                          or {})
            assert sgd_st.get("sb_shards") == 8, sgd_st
            assert ssgd.score(X, y) > 0.6
            assert sglm.solver_info_.get("stream_shards") == 8, \
                sglm.solver_info_
            trace = os.path.join(trace_dir, "trace.jsonl")
            # counters/programs land in a SEPARATE file, the shape a
            # multi-process run produces (bench child + serving worker
            # each append their own sink) — report --merge below must
            # fold both into one timeline
            aux = os.path.join(trace_dir, "aux.jsonl")
            with obs.MetricsLogger(aux) as lg:
                obs.log_counters(lg)
                obs.log_programs(lg)
        from dask_ml_tpu.observability.report import merge_records

        # `report --merge`: the span trace and the aux counters/programs
        # file fold into ONE timeline — the 8-device run renders as a
        # single report exactly like a multi-file multi-process round
        records = merge_records([load_records(trace), load_records(aux)])
        report = build_report(records, path=f"{trace} + {aux}")
        data = report_data(records)
        spans = [r["span"] for r in data["spans"]]
        programs = [p["program"] for p in data["programs"]]
        # the merged report must render the sharded fits' spans AND
        # their compiled programs — the assertion the dryrun exists for
        assert "LogisticRegression.fit" in spans, spans
        assert "spans (time by component)" in report
        assert "programs (XLA cost/memory per compiled entry point)" \
            in report
        assert any(p == "glm.lbfgs" for p in programs), programs
        assert any(p == "glm.admm" for p in programs), programs
        # the psum-bearing SHARDED superblock scan programs (ISSUE 9)
        # must rank in the same programs table — per-device attribution
        # of the streamed hot loop
        assert any(p == "superblock.sgd_scan.psum" for p in programs), \
            programs
        assert any(p == "superblock.glm.vg.psum" for p in programs), \
            programs
        # counters came from the aux file: the merge really folded both
        assert data["counters"].get("recompiles", 0) > 0, data["counters"]
        # the CLI flag itself renders the same merged timeline
        import contextlib
        import io

        from dask_ml_tpu.observability import report as report_cli

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = report_cli.main(["--merge", "--json", trace, aux])
        assert rc == 0, rc
        cli_data = json.loads(buf.getvalue())
        assert cli_data["merged_files"] == 2
        assert any(r["span"] == "LogisticRegression.fit"
                   for r in cli_data["spans"])
        out.update(
            ok=True,
            trace_records=len(records),
            merged_files=2,
            report_spans=spans,
            report_programs=programs,
        )
    except Exception:
        out["rc"] = 1
        out["tail"] = traceback.format_exc()[-2000:]
    print(json.dumps(out))
    return out["rc"]


if __name__ == "__main__":
    sys.exit(main())
