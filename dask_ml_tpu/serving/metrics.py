"""Serving telemetry: per-batch spans, counters, and latency quantiles.

Everything funnels through ``dask_ml_tpu/observability/`` — the same
JSONL sinks, span tree, and counter registry the fit paths use, so a
recorded serving run and a recorded fit aggregate under one report CLI.
Per batch the server emits one ``serving.batch`` span carrying bucket,
occupancy, and padding attributes (plus the counter deltas it caused —
recompiles paid mid-serving show up HERE, on the batch that paid them).
Counters accumulate the run totals:

- ``serving_requests`` / ``serving_rows``   — admitted work
- ``serving_batches`` / ``serving_padded_rows`` — batching efficiency
  (padding waste = padded_rows / (rows + padded_rows))
- ``serving_shed`` / ``serving_timeouts`` / ``serving_errors`` —
  backpressure outcomes

Latency quantiles come from a fixed-size ring of recent request
latencies — O(1) memory for a long-lived server, exact percentiles over
the retained window.
"""

from __future__ import annotations

import threading

import numpy as np

from ..observability import span
from ..observability._counters import (
    record_serving_batch,
    record_serving_drop,
    record_serving_request,
)

__all__ = ["LatencyWindow", "batch_span", "record_batch",
           "record_request", "record_drop"]

# counter recording lives in observability/_counters.py (the shared
# registry the report CLI and span deltas read); these are the serving
# package's local names for it
record_request = record_serving_request
record_batch = record_serving_batch
record_drop = record_serving_drop


def batch_span(method: str, bucket: int, rows: int, n_requests: int,
               queue_depth: int):
    """The per-batch span: one JSONL record per executed micro-batch
    with the occupancy/padding signals a capacity review needs. Cheap
    no-op when no sink is configured (same contract as every other
    span)."""
    return span(
        "serving.batch", method=method, bucket=bucket, rows=rows,
        n_requests=n_requests, queue_depth=queue_depth,
        occupancy=round(rows / bucket, 4),
    )


class LatencyWindow:
    """Lock-guarded ring buffer of recent per-request latencies
    (seconds). ``percentiles()`` computes exact quantiles over the
    retained window — a million-request day keeps memory flat while p50
    and p99 track the live distribution."""

    __slots__ = ("_lock", "_buf", "_n", "_i", "count")

    def __init__(self, size=4096):
        self._lock = threading.Lock()
        self._buf = np.zeros(int(size), np.float64)
        self._n = 0      # filled entries (<= size)
        self._i = 0      # next write slot
        self.count = 0   # total observations ever

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._buf[self._i] = seconds
            self._i = (self._i + 1) % len(self._buf)
            self._n = min(self._n + 1, len(self._buf))
            self.count += 1

    def percentiles(self, qs=(50, 99)) -> dict:
        with self._lock:
            if self._n == 0:
                return {f"p{q}": float("nan") for q in qs}
            window = self._buf[: self._n].copy()
        vals = np.percentile(window, qs)
        return {f"p{q}": float(v) for q, v in zip(qs, vals)}
