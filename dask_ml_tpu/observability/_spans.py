"""Hierarchical span tracing.

``span(name, **attrs)`` is the ambient, nesting-aware timer the rest of
the package wraps its hot paths in (fit → epoch/pass → solve): each
closed span appends one JSONL record carrying wall time, accumulated
device-sync time (``Span.sync`` barriers), its id/parent id/depth, the
caller's attributes, and the counter deltas it caused (``ctr_*`` fields
from the registry in ``_counters``). The parent chain is per-thread, so
concurrent fits trace independent trees into the shared sink.

Sink resolution, per span open (cheap: one list peek + one config read):

1. the innermost ``active_logger`` binding OF THIS THREAD — spans
   inside a fit land in that fit's logger with its ``component``
   extras (another thread's concurrent binding is never borrowed: its
   extras would mislabel this thread's records);
2. ``config.trace_dir`` → a shared append-only ``trace.jsonl`` there;
3. ``config.metrics_path`` → the same file the step metrics use;
4. none of those set → the span is the singleton no-op: no record, no
   id allocation, no counter snapshot. The disabled path is a dict
   lookup and a None check — nothing is ever traced into jitted code.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

from ._counters import counters_enabled, counters_snapshot
from ._metrics import thread_bound_logger

# span ids carry the pid in their high bits: config.trace_dir is a
# persistent knob and _FileSink APPENDS, so two processes recording
# into one trace.jsonl must not collide ids — the report's parent-chain
# walk (nested-of-group dedup) would silently cross runs. 16M spans per
# process before ranges could touch.
_ids = itertools.count(((os.getpid() & 0xFFFFFF) << 24) | 1)
_tls = threading.local()

# live view of every OPEN span (id -> start time/name/thread): the stall
# watchdog's working set. Maintained only on the recording path — the
# disabled (no-sink) path never touches it.
_open_lock = threading.Lock()
_open_spans: dict[int, dict] = {}

# live tracker count (armed by _watchdog.Watchdog.start/stop and by the
# telemetry plane's span observers): while a watchdog polls or a live
# observer listens, spans register in the open-span registry even when
# NO sink is configured — otherwise a run without metrics_path/
# trace_dir (bench's timed fits, the wedged-tunnel scenario) would be
# invisible to the very threads meant to watch it. Sinkless tracked
# spans write no JSONL record; the disabled path (no sink, no tracker)
# stays the zero-cost no-op.
_armed_trackers = 0


def _track_arm(delta: int) -> None:
    global _armed_trackers
    with _open_lock:
        _armed_trackers += delta


# span-close observers (the live telemetry plane subscribes while its
# HTTP server runs): each gets the SAME record dict the sink receives —
# for sinkless tracked spans, a record without counter deltas. The list
# is empty unless something subscribed, so the default path never
# builds a record it won't use.
_span_observers: list = []


def add_span_observer(fn) -> None:
    """Subscribe ``fn(record)`` to every span close; arms span tracking
    (like a watchdog) so observers see spans even with no sink
    configured."""
    with _open_lock:
        _span_observers.append(fn)
    _track_arm(+1)


def remove_span_observer(fn) -> None:
    with _open_lock:
        try:
            _span_observers.remove(fn)
        except ValueError:
            return
    _track_arm(-1)


def open_spans_snapshot():
    """[{span_id, span, thread, t_open_unix, parent_id, ...}] for every
    span currently open anywhere in the process, oldest first."""
    with _open_lock:
        out = [dict(v) for v in _open_spans.values()]
    out.sort(key=lambda r: r["t_open_unix"])
    return out

# "time" origin for fallback-sink span records (relative to process
# start, matching MetricsLogger's fit-relative convention in spirit)
_T0 = time.time()
_trace_lock = threading.Lock()


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span_id():
    """Id of the innermost open span on this thread (None outside any)."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


class _FileSink:
    """Open-per-record append sink: no file descriptor outlives the
    write (a long-lived process tracing many distinct paths must not
    accumulate open handles), and each record gets a fresh timestamp.
    Spans are per-fit/pass frequency, so the open cost is noise."""

    __slots__ = ("path",)

    def __init__(self, path):
        self.path = path

    def log(self, **rec):
        line = json.dumps(
            {"time": round(time.time() - _T0, 6), **rec}
        ) + "\n"
        with _trace_lock, open(self.path, "a") as fh:
            fh.write(line)


def _trace_sink():
    lg = thread_bound_logger()
    if lg is not None:
        return lg
    from ..config import get_config

    cfg = get_config()
    if cfg.trace_dir:
        try:
            os.makedirs(cfg.trace_dir, exist_ok=True)
        except OSError:
            return None  # unusable sink disables the span, never the fit
        return _FileSink(os.path.join(cfg.trace_dir, "trace.jsonl"))
    if cfg.metrics_path:
        return _FileSink(cfg.metrics_path)
    return None


class _NoopSpan:
    """Shared zero-cost stand-in when no sink is configured."""

    __slots__ = ()

    recording = False

    def add(self, **attrs):
        return self

    def sync(self, value):
        return value


NOOP_SPAN = _NoopSpan()


class span:
    """Context manager producing one nested JSONL span record.

    ``with span("fit", component="KMeans", n_rows=n) as sp:`` — the
    yielded object accepts late attributes (``sp.add(n_iter=7)``) and
    device barriers (``out = sp.sync(out)`` runs ``block_until_ready``
    and accumulates the stall into the record's ``sync_s``). With no
    sink configured the context yields the shared no-op span.
    """

    __slots__ = ("name", "attrs", "span_id", "parent_id", "sync_s",
                 "_sink", "_t0", "_ctr0", "_tracked")

    def __init__(self, name, **attrs):
        self.name = name
        self.attrs = attrs
        self.sync_s = 0.0
        self._sink = None
        self._tracked = False

    @property
    def recording(self):
        """True when this span will emit a record at close — False for
        spans tracked only for the watchdog (armed timeout, no sink).
        The public signal call sites gate record-dependent work on
        (e.g. the stream's wait_s readiness syncs)."""
        return self._sink is not None

    def add(self, **attrs):
        self.attrs.update(attrs)
        return self

    def sync(self, value):
        """block_until_ready barrier whose wall time is charged to this
        span's ``sync_s`` — the honest "time the host stalled on the
        device" number under async dispatch."""
        import jax

        t0 = time.perf_counter()
        out = jax.block_until_ready(value)
        self.sync_s += time.perf_counter() - t0
        return out

    def __enter__(self):
        sink = _trace_sink()
        if sink is None and not _armed_trackers:
            return NOOP_SPAN
        # sink None but a watchdog/observer armed: track the span
        # (open-span registry + id stack); close emits to observers
        # only, no JSONL record
        self._sink = sink
        self._tracked = True
        st = _stack()
        self.parent_id = st[-1] if st else None
        self.span_id = next(_ids)
        st.append(self.span_id)
        with _open_lock:
            _open_spans[self.span_id] = {
                "span_id": self.span_id,
                "span": self.name,
                "parent_id": self.parent_id,
                "thread": threading.current_thread().name,
                # the ident disambiguates same-named threads (every
                # ModelServer worker is "dask-ml-tpu-serving") so the
                # watchdog dumps THIS thread's stack, not a namesake's
                "thread_id": threading.get_ident(),
                "t_open_unix": time.time(),
            }
        self._ctr0 = (counters_snapshot()
                      if sink is not None and counters_enabled() else None)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if not self._tracked:
            return False
        wall = time.perf_counter() - self._t0
        st = _stack()
        # pop down to (and including) OUR frame: frames above ours are
        # spans abandoned mid-block (a generator dropped between yields)
        # — leaving them would corrupt every later span's parent id
        abandoned = []
        if self.span_id in st:
            while st and st[-1] != self.span_id:
                abandoned.append(st.pop())
            if st:
                st.pop()
        with _open_lock:
            _open_spans.pop(self.span_id, None)
            for sid in abandoned:  # their __exit__ will never run
                _open_spans.pop(sid, None)
            observers = list(_span_observers)
        if self._sink is None and not observers:
            return False  # watchdog-only tracking: no record to emit
        rec = {
            "span": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "depth": len(st),
            # absolute close time: the relative "time" field's origin
            # differs by sink (fit logger's t0 vs process start), so
            # cross-record correlation uses this one
            "t_unix": round(time.time(), 6),
            "wall_s": round(wall, 6),
            "sync_s": round(self.sync_s, 6),
            # which OS thread closed the span — Perfetto export lanes
            # spans by it, and the watchdog correlates stall dumps to it
            "thread": threading.current_thread().name,
        }
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        rec.update(self.attrs)
        if self._ctr0 is not None:
            now = counters_snapshot()
            for k, v in now.items():
                d = v - self._ctr0.get(k, 0)
                if d:
                    rec[f"ctr_{k}"] = round(d, 6) if isinstance(
                        d, float) else d
        for fn in observers:
            # the live plane sees every closed span, recorded or not —
            # a failing observer must never surface into the fit
            try:
                fn(rec)
            except Exception:
                pass
        if self._sink is not None:
            try:
                self._sink.log(**rec)
            except Exception:
                # telemetry must never kill the fit it observes (a full
                # disk mid-run would otherwise raise out of this
                # __exit__ — replacing the in-flight exception when one
                # is unwinding)
                pass
        return False
