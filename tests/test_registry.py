"""ModelRegistry eviction / rollback / notification coverage (ISSUE 7
satellite): the archival eviction policy (`serving_registry_keep`) had
no direct tests — keep bounds, the current-version guard, typed errors
on rollback to an evicted version, and subscriber notification ordering
under rapid publish."""

import threading

import numpy as np
import pytest

from dask_ml_tpu.serving.registry import (
    ModelRegistry,
    ModelVersion,
    UnknownModelError,
)


class _Est:
    """Minimal 'fitted estimator' stand-in (deep-copyable)."""

    def __init__(self, tag):
        self.tag = tag
        self.coef_ = np.asarray([float(tag)])


# -- eviction ----------------------------------------------------------------

def test_keep_zero_rejected():
    with pytest.raises(ValueError):
        ModelRegistry(keep=0)
    with pytest.raises(ValueError):
        ModelRegistry(keep=-3)


def test_keep_one_holds_only_current():
    reg = ModelRegistry(keep=1)
    for i in range(1, 4):
        reg.publish("m", _Est(i))
    assert reg.versions("m") == (3,)
    assert reg.current_version("m") == 3
    assert reg.get("m").estimator.tag == 3


def test_keep_n_evicts_oldest_first():
    reg = ModelRegistry(keep=3)
    for i in range(1, 6):
        reg.publish("m", _Est(i))
    assert reg.versions("m") == (3, 4, 5)
    # ids never reused: the next publish continues the sequence
    assert reg.publish("m", _Est(6)) == 6
    assert reg.versions("m") == (4, 5, 6)


def test_current_version_never_evicted():
    # make an OLD version current via rollback, then publish past the
    # keep bound: eviction must step around the rolled-back current
    # until the new publish re-points it
    reg = ModelRegistry(keep=2)
    for i in range(1, 4):
        reg.publish("m", _Est(i))
    assert reg.versions("m") == (2, 3)
    reg.rollback("m")           # current -> v2
    assert reg.current_version("m") == 2
    reg.publish("m", _Est(4))   # current -> v4; keep=2 evicts oldest
    assert reg.current_version("m") == 4
    assert reg.current_version("m") in reg.versions("m")
    assert reg.get("m").estimator.tag == 4


def test_rollback_to_evicted_version_raises_typed():
    reg = ModelRegistry(keep=2)
    for i in range(1, 5):
        reg.publish("m", _Est(i))
    assert reg.versions("m") == (3, 4)
    with pytest.raises(UnknownModelError):
        reg.rollback("m", version=1)     # evicted
    with pytest.raises(UnknownModelError):
        reg.get("m", version=1)
    with pytest.raises(UnknownModelError):
        reg.rollback("nope")             # unknown name
    # registry state untouched by the refusals
    assert reg.current_version("m") == 4


def test_rollback_default_steps_one_back_and_is_typed_at_floor():
    reg = ModelRegistry(keep=4)
    reg.publish("m", _Est(1))
    with pytest.raises(UnknownModelError):
        reg.rollback("m")                # nothing older than v1
    reg.publish("m", _Est(2))
    assert reg.rollback("m") == 1
    assert reg.current_version("m") == 1


# -- subscriber notification ordering ----------------------------------------

def test_notifications_in_order_under_rapid_publish():
    reg = ModelRegistry(keep=4)
    seen = []
    reg.subscribe("m", lambda mv: seen.append(mv.version))
    for i in range(1, 21):
        reg.publish("m", _Est(i))
    assert seen == list(range(1, 21))
    # rollback notifies too, with the re-pointed version
    reg.rollback("m", version=19)
    assert seen[-1] == 19


def test_concurrent_publishers_deliver_every_version_once():
    reg = ModelRegistry(keep=64)
    seen = []
    lock = threading.Lock()

    def cb(mv):
        with lock:
            seen.append(mv.version)

    reg.subscribe("m", cb)
    n_threads, per = 4, 10

    def publisher(t):
        for _ in range(per):
            reg.publish("m", _Est(t))

    threads = [threading.Thread(target=publisher, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per
    # every publish notified exactly once, version ids unique and dense
    assert sorted(seen) == list(range(1, total + 1))
    assert reg.current_version("m") in seen


def test_late_subscriber_gets_current_immediately():
    reg = ModelRegistry(keep=4)
    reg.publish("m", _Est(1))
    reg.publish("m", _Est(2))
    seen = []
    reg.subscribe("m", lambda mv: seen.append(mv.version))
    assert seen == [2]


# -- version metadata (publisher / profile / status snapshot) ----------------

def test_version_carries_publisher_and_profile():
    est = _Est(1)
    est.training_profile_ = {"n_features": 1, "rows": 10}
    reg = ModelRegistry(keep=4)
    reg.publish("m", est, publisher="trainer-7", tag="nightly")
    mv = reg.get("m")
    assert mv.publisher == "trainer-7"
    assert mv.tag == "nightly"
    # the drift baseline is archived WITH the version
    assert mv.profile == {"n_features": 1, "rows": 10}
    # default publisher: the publishing thread's name
    reg.publish("m", _Est(2))
    assert reg.get("m").publisher == threading.current_thread().name


def test_status_snapshot_shape():
    reg = ModelRegistry(keep=2)
    for i in range(1, 4):
        reg.publish("a", _Est(i))
    reg.publish("b", _Est(1), publisher="svc")
    snap = reg.status_snapshot()
    assert set(snap) == {"a", "b"}
    assert snap["a"]["current"] == 3
    assert snap["a"]["versions"] == [2, 3]
    assert snap["b"]["publisher"] == "svc"
    assert snap["a"]["t_publish"] is not None


def test_model_version_repr():
    mv = ModelVersion("m", 3, _Est(3), tag="x")
    assert "v3" in repr(mv) and "'x'" in repr(mv)
