"""SpectralClustering via Nyström approximation.

Reference: ``dask_ml/cluster/spectral.py`` (SURVEY.md §2a
SpectralClustering row): exact affinity on an ``n_components``-row sample,
cross-affinity to the rest, orthogonalize, embed, then KMeans on the
embedding.

TPU formulation: with inducing set Z (c rows, uniform sample) and
B = affinity(X, Z) (n × c, row-sharded), the Nyström normalized affinity is
D^{-1/2} B A⁺ Bᵀ D^{-1/2} = G Gᵀ for G = D^{-1/2} B A^{-1/2} — so the
spectral embedding is the top-k left singular vectors of the TALL matrix G,
computed with the distributed TSQR SVD (``ops/linalg.py``). One psum-matvec
for the approximate degrees, one TSQR — no n×n affinity ever materialized,
matching the reference's algorithmic complexity with single-program
execution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import BaseEstimator, ClusterMixin, to_host
from ..ops import linalg, pairwise
from ..parallel.sharded import ShardedArray
from ..utils.validation import check_array, check_is_fitted
from .kmeans import KMeans, _gumbel_top_l


def _affinity(name, x, z, gamma, degree, coef0, kernel_params=None):
    if callable(name):  # user kernel(X, Z, **kernel_params), ref contract
        return name(x, z, **(kernel_params or {}))
    if name == "rbf":
        return pairwise.rbf_kernel(x, z, gamma=gamma)
    if name == "polynomial":
        return pairwise.polynomial_kernel(x, z, degree=degree, gamma=gamma,
                                          coef0=coef0)
    if name == "sigmoid":
        return pairwise.sigmoid_kernel(x, z, gamma=gamma, coef0=coef0)
    if name == "linear":
        return pairwise.linear_kernel(x, z)
    raise ValueError(f"Unknown affinity {name!r}")


class SpectralClustering(ClusterMixin, BaseEstimator):
    """Ref: dask_ml/cluster/spectral.py::SpectralClustering."""

    def __init__(self, n_clusters=8, eigen_solver=None, random_state=None,
                 n_init=10, gamma=1.0, affinity="rbf", n_neighbors=10,
                 eigen_tol=0.0, assign_labels="kmeans", degree=3, coef0=1,
                 kernel_params=None, n_jobs=1, n_components=100,
                 persist_embedding=False, kmeans_params=None):
        self.n_clusters = n_clusters
        self.eigen_solver = eigen_solver
        self.random_state = random_state
        self.n_init = n_init
        self.gamma = gamma
        self.affinity = affinity
        self.n_neighbors = n_neighbors
        self.eigen_tol = eigen_tol
        self.assign_labels = assign_labels
        self.degree = degree
        self.coef0 = coef0
        self.kernel_params = kernel_params
        self.n_jobs = n_jobs
        self.n_components = n_components
        self.persist_embedding = persist_embedding
        self.kmeans_params = kmeans_params

    def fit(self, X, y=None):
        X = check_array(X, dtype=np.float32)
        n, d = X.shape
        c = min(self.n_components, n)
        if self.assign_labels != "kmeans":
            raise ValueError("only assign_labels='kmeans' is supported")
        # honest parameter surface: params the TSQR/Nyström formulation
        # cannot honor RAISE instead of silently no-oping
        if self.eigen_solver not in (None, "tsqr"):
            raise ValueError(
                f"eigen_solver={self.eigen_solver!r} is not supported: the "
                "embedding is computed by an exact distributed TSQR SVD "
                "(pass None or 'tsqr')"
            )
        if self.eigen_tol not in (0.0, 0, "auto"):
            raise ValueError(
                "eigen_tol is not supported: the TSQR SVD is exact, not "
                "iterative (pass 0.0 or 'auto')"
            )
        if self.affinity == "nearest_neighbors":
            raise ValueError(
                "affinity='nearest_neighbors' (and hence n_neighbors) is "
                "not supported; use 'rbf', 'polynomial', 'sigmoid', "
                "'linear', or a callable"
            )
        mask = X.row_mask(X.dtype)
        key = jax.random.PRNGKey(
            0 if self.random_state is None else int(self.random_state)
        )
        idx = _gumbel_top_l(mask, key, c)  # uniform inducing sample
        Z = jnp.take(X.data, idx, axis=0)  # (c, d) replicated

        B = _affinity(self.affinity, X.data, Z, self.gamma, self.degree,
                      self.coef0, self.kernel_params) * mask[:, None]
        A = _affinity(self.affinity, Z, Z, self.gamma, self.degree,
                      self.coef0, self.kernel_params)      # (c, c) replicated

        # A^{-1/2} via eigh with jitter (A is a PSD Gram matrix)
        w, V = jnp.linalg.eigh(A + 1e-6 * jnp.eye(c, dtype=A.dtype))
        inv_sqrt = V @ jnp.diag(1.0 / jnp.sqrt(jnp.maximum(w, 1e-6))) @ V.T
        a_pinv = V @ jnp.diag(1.0 / jnp.maximum(w, 1e-6)) @ V.T

        # approximate degrees: d = B A⁺ (Bᵀ 1) — two psum matvecs
        colsum = B.T @ mask
        deg = B @ (a_pinv @ colsum)
        deg = jnp.where(deg > 1e-12, deg, 1.0)
        G = (B / jnp.sqrt(deg)[:, None]) @ inv_sqrt     # (n, c) sharded

        u, s, _ = linalg.svd_tall_jit(G, X.mesh)
        emb = u[:, : self.n_clusters]
        norms = jnp.linalg.norm(emb, axis=1, keepdims=True)
        emb = emb / jnp.where(norms > 1e-12, norms, 1.0)
        emb = emb * mask[:, None]
        embedding = ShardedArray(emb, X.n_rows, X.mesh)

        km_params = dict(self.kmeans_params or {})
        base_seed = (0 if self.random_state is None
                     else int(self.random_state))
        km_params.setdefault("random_state", base_seed)
        # n_init restarts of the assignment KMeans (sklearn semantics:
        # keep the run with the lowest inertia) — the embedding is (n, k)
        # so restarts are cheap relative to building G. Restart seeds
        # derive from the RESOLVED r=0 seed (which may come from
        # kmeans_params) so no restart duplicates it.
        seed0 = km_params["random_state"]
        seed0 = 0 if seed0 is None else int(seed0)
        n_init = max(int(self.n_init), 1)
        best = None
        for r in range(n_init):
            params_r = dict(km_params)
            if r > 0:
                params_r["random_state"] = seed0 + r
            km = KMeans(n_clusters=self.n_clusters, **params_r)
            km.fit(embedding)
            if best is None or km.inertia_ < best.inertia_:
                best = km
        km = best
        self.assign_labels_ = km
        self.labels_ = km.labels_
        self.eigenvalues_ = to_host(s[: self.n_clusters]).astype(np.float64)
        if self.persist_embedding:
            # reference persists the embedding in cluster memory; the
            # analog here is keeping the device-resident ShardedArray on
            # the fitted estimator instead of letting it free
            self.embedding_ = embedding
        self.n_features_in_ = d
        return self

    def fit_predict(self, X, y=None):
        return self.fit(X).labels_
