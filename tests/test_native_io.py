"""Native loader tests (native/fast_loader.cpp via ctypes)."""

import numpy as np
import pytest

from dask_ml_tpu.io import load_library, read_csv_f32, read_csv_sharded


def test_native_library_builds():
    assert load_library() is not None, "g++ build of fast_loader failed"


def test_read_csv_matches_numpy(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(1000, 7).astype(np.float32)
    p = tmp_path / "data.csv"
    np.savetxt(p, X, delimiter=",", fmt="%.6f")
    got = read_csv_f32(str(p))
    ref = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_read_csv_multithreaded_consistent(tmp_path):
    rng = np.random.RandomState(1)
    X = rng.randn(5000, 3).astype(np.float32)
    p = tmp_path / "big.csv"
    np.savetxt(p, X, delimiter=",", fmt="%.5f")
    a = read_csv_f32(str(p), n_threads=1)
    b = read_csv_f32(str(p), n_threads=8)
    np.testing.assert_array_equal(a, b)


def test_read_csv_malformed(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("1.0,2.0\n3.0\n")
    with pytest.raises(ValueError, match="malformed"):
        read_csv_f32(str(p))


def test_read_csv_missing():
    with pytest.raises(IOError):
        read_csv_f32("/nonexistent/file.csv")


def test_read_csv_sharded(tmp_path):
    X = np.arange(24, dtype=np.float32).reshape(12, 2)
    p = tmp_path / "s.csv"
    np.savetxt(p, X, delimiter=",", fmt="%.1f")
    sx = read_csv_sharded(str(p))
    np.testing.assert_allclose(sx.to_numpy(), X)


def test_native_block_reader_matches_numpy(tmp_path):
    """The C++ readahead reader yields byte-identical blocks to numpy
    slicing, including the ragged tail, and BlockStream picks it for
    sequential memmap passes."""
    import numpy as np

    from dask_ml_tpu.io.native import NativeBlockReader, load_block_reader
    from dask_ml_tpu.parallel.streaming import BlockStream

    if load_block_reader() is None:
        import pytest

        pytest.skip("native toolchain unavailable")
    rng = np.random.RandomState(0)
    X = rng.randn(1003, 7).astype(np.float32)
    path = str(tmp_path / "X.f32")
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=X.shape)
    mm[:] = X
    mm.flush()
    mm = np.memmap(path, dtype=np.float32, mode="r", shape=X.shape)

    r = NativeBlockReader(mm, block_rows=100)
    got = []
    while True:
        blk = r.next()
        if blk is None:
            break
        got.append(blk.copy())
    r.close()
    np.testing.assert_array_equal(np.concatenate(got), X)

    # BlockStream parity: native path (sequential) == numpy slicing
    stream = BlockStream((mm,), block_rows=96)
    assert any(stream._verify_native())
    blocks = [np.asarray(b.arrays[0])[: b.n_rows] for b in stream]
    np.testing.assert_allclose(np.concatenate(blocks), X, rtol=1e-6)

    # sliced memmap views (offset no longer authoritative) are detected
    # by the block-0 verification and fall back to numpy slicing
    view = mm[100:]
    s2 = BlockStream((view,), block_rows=96)
    assert not any(s2._verify_native())
    blocks2 = [np.asarray(b.arrays[0])[: b.n_rows] for b in s2]
    np.testing.assert_allclose(np.concatenate(blocks2), X[100:], rtol=1e-6)


@pytest.mark.slow
def test_streamed_fit_with_native_reader(tmp_path):
    """End-to-end: an out-of-core GLM fit through the native readahead
    path matches the in-memory fit."""
    import numpy as np

    from dask_ml_tpu import config
    from dask_ml_tpu.io.native import load_block_reader
    from dask_ml_tpu.linear_model import LinearRegression

    if load_block_reader() is None:
        import pytest

        pytest.skip("native toolchain unavailable")
    rng = np.random.RandomState(1)
    X = rng.randn(2400, 6).astype(np.float32)
    w = rng.randn(6)
    y = (X @ w + 0.3).astype(np.float32)
    path = str(tmp_path / "Xn.f32")
    mm = np.memmap(path, dtype=np.float32, mode="w+", shape=X.shape)
    mm[:] = X
    mm.flush()
    mm = np.memmap(path, dtype=np.float32, mode="r", shape=X.shape)

    ref = LinearRegression(solver="lbfgs", max_iter=60, tol=1e-7).fit(X, y)
    with config.set(stream_block_rows=500):
        streamed = LinearRegression(solver="lbfgs", max_iter=60,
                                    tol=1e-7).fit(mm, y)
    np.testing.assert_allclose(streamed.coef_, ref.coef_, rtol=1e-2,
                               atol=1e-3)
