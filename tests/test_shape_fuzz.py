"""Shape/padding fuzz: random (n, d) combinations — including n not
divisible by the shard count, n < shards, and d == 1 — through the core
estimators. The padded-shard substrate must be invisible at every size
(ref: the reference's ragged-final-chunk handling, SURVEY.md §1 L2;
here padding + masks replace it, and an unmasked reduction would show up
exactly in these off-size cases)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # hypothesis fuzz: full-suite only

SIZES = [(5, 3), (9, 1), (17, 3), (64, 5), (101, 7), (256, 2)]


@pytest.mark.parametrize("n,d", SIZES)
def test_glm_any_shape(n, d):
    from dask_ml_tpu.linear_model import LogisticRegression

    rng = np.random.RandomState(n * 31 + d)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    if len(np.unique(y)) < 2:
        y[0] = 1.0 - y[0]
    clf = LogisticRegression(solver="lbfgs", max_iter=25).fit(X, y)
    assert np.isfinite(clf.coef_).all()
    pred = clf.predict(X)
    assert pred.shape == (n,)
    proba = clf.predict_proba(X)
    assert proba.shape == (n, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)


@pytest.mark.parametrize("n,d", SIZES)
def test_scaler_roundtrip_any_shape(n, d):
    from dask_ml_tpu.preprocessing import StandardScaler

    rng = np.random.RandomState(n + d)
    X = (rng.randn(n, d) * 3 + 1).astype(np.float64)
    sc = StandardScaler().fit(X)
    out = sc.transform(X).to_numpy()
    assert out.shape == (n, d)
    assert np.abs(out.mean(axis=0)).max() < 1e-4
    back = sc.inverse_transform(out).to_numpy()
    np.testing.assert_allclose(back, X, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("n,k", [(6, 2), (10, 2), (33, 3), (70, 5)])
def test_kmeans_any_shape(n, k):
    from dask_ml_tpu.cluster import KMeans

    rng = np.random.RandomState(n)
    X = rng.randn(n, 4).astype(np.float32)
    km = KMeans(n_clusters=k, max_iter=10, random_state=0).fit(X)
    labels = np.asarray(km.labels_.to_numpy())
    assert labels.shape == (n,)
    assert set(np.unique(labels)) <= set(range(k))
    assert np.isfinite(km.inertia_)
    assert km.transform(X).shape == (n, k)


@pytest.mark.parametrize("n,d", [(7, 3), (12, 3), (65, 9)])
def test_pca_any_shape(n, d):
    from dask_ml_tpu.decomposition import PCA

    rng = np.random.RandomState(d)
    X = rng.randn(n, d).astype(np.float32)
    k = min(n, d) - 1
    p = PCA(n_components=k, svd_solver="full").fit(X)
    t = p.transform(X)
    assert t.shape == (n, k)
    back = p.inverse_transform(t)
    arr = back.to_numpy() if hasattr(back, "to_numpy") else np.asarray(back)
    assert arr.shape == (n, d)
